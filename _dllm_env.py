"""Shared environment fixup for CPU-mesh child processes.

This image's sitecustomize eagerly registers a TPU PJRT plugin when
``PALLAS_AXON_POOL_IPS`` is set, which makes ``import jax`` hang or grab
the TPU in processes that want a virtual CPU mesh.  Every entry point that
spawns (or re-execs into) a CPU-mesh process must apply the same fixup —
keep the logic in exactly one place.

Used by ``dllm_test_bootstrap.py`` (pytest re-exec) and
``__graft_entry__.py`` (driver dryrun subprocess).
"""

from __future__ import annotations


def cpu_mesh_env(env: dict, n_devices: int = 8) -> dict:
    """A copy of ``env`` corrected for an n-device virtual CPU mesh."""
    env = dict(env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    keep = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    env["XLA_FLAGS"] = " ".join([*keep, flag])
    return env
