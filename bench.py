"""Framework benchmark: seq2seq fine-tune train-step throughput on TPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

Workload: the reference's headline recipe — bart-large-cnn-class seq2seq
fine-tuning, source 1024 / target 128 (reference train-accelerator.py:115-127),
AdamW + linear schedule — as our SPMD train step (bf16 compute, fp32
params/optimizer, remat) on all locally available chips.  Throughput
counts non-pad source+target tokens per optimizer step.

Baseline: the reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured against a documented estimate of its strongest
variant (A: HF Trainer fp32 DDP on modern data-center GPUs):
~6 * n_params FLOPs/token training compute at ~35% utilization of a
312 TFLOP/s bf16 A100 ≈ 4000 tokens/sec/GPU for a 406M-param model.
We report per-chip so the comparison is per-accelerator.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

_BENCH_CHILD = "_DLLM_BENCH_CHILD"


def _is_json(line: str) -> bool:
    try:
        json.loads(line)
        return True
    except ValueError:
        return False


def _supervise() -> int:
    """Run the real benchmark in child processes with retry + backoff.

    Round-1 failure mode: the tunneled TPU backend can fail to initialize
    transiently (``UNAVAILABLE: TPU backend setup/compile error``), and JAX
    caches backend-init failure per process — so retry means a fresh
    process.  On final failure print ONE parseable JSON error line (never a
    bare traceback) and exit 0 so the driver records a parseable artifact.
    """
    attempts = int(os.environ.get("BENCH_RETRIES", "3"))
    backoff = float(os.environ.get("BENCH_BACKOFF", "10"))
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "600"))
    # hard wall-clock ceiling so a hanging backend can't outlive the
    # driver's own timeout with no JSON printed (round-1 rc=124 mode)
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "1400"))
    here = os.path.abspath(__file__)
    env = dict(os.environ)
    env[_BENCH_CHILD] = "1"
    t_start = time.monotonic()
    tail = ""
    for i in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, here],
                env=env,
                cwd=os.path.dirname(here),
                capture_output=True,
                text=True,
                timeout=attempt_timeout,
            )
        except subprocess.TimeoutExpired as e:
            tail = f"attempt {i + 1} timed out: {e}"
            print(tail, file=sys.stderr)
            transient = True
        else:
            if proc.returncode == 0:
                result = next(
                    (ln for ln in reversed(proc.stdout.strip().splitlines()) if _is_json(ln)),
                    None,
                )
                if result is not None:
                    sys.stderr.write(proc.stderr)
                    print(result)
                    return 0
            tail = "\n".join((proc.stderr or proc.stdout or "").strip().splitlines()[-8:])
            print(f"bench attempt {i + 1}/{attempts} failed rc={proc.returncode}:\n{tail}", file=sys.stderr)
            # retry only failures that look like transient backend trouble;
            # a deterministic crash (bad model name, shape error) won't heal
            transient = any(s in tail for s in ("UNAVAILABLE", "DEADLINE_EXCEEDED", "Unable to initialize"))
        if not transient:
            break
        if i < attempts - 1:
            if time.monotonic() - t_start + attempt_timeout > budget:
                print("bench: total budget exhausted, giving up", file=sys.stderr)
                break
            time.sleep(backoff * (2**i))
    print(
        json.dumps(
            {
                "metric": "seq2seq fine-tune train-step throughput",
                "value": None,
                "unit": "tokens/sec/chip",
                "vs_baseline": None,
                "error": "benchmark did not produce a result (see detail)",
                "detail": tail[-500:],
            }
        )
    )
    return 0



# The reference's documented estimate for its strongest variant
# (BASELINE.md: ~4,000 tok/s per A100-class GPU on bart-large-cnn,
# src 1024 / tgt 128).  NOTE: bench defaults have evolved across rounds
# (round 1: batch 8/chip, remat on; round 2+: batch 16/chip, remat off for
# <1B-param models) — the baseline constant describes the REFERENCE and is
# config-independent, but vs_baseline values in BENCH_r{N}.json files are
# only comparable across rounds when the metric string reports the same
# bench config (it always names batch/remat/attention).
BASELINE_TOKENS_PER_SEC_PER_CHIP = 4000.0


def _flagship():
    import jax

    from distributed_llms_example_tpu.models.registry import load_model

    attention = os.environ.get("BENCH_ATTENTION", "") or None
    if attention not in (None, "auto", "flash", "ring", "xla"):
        # validate up front: the except below is for unknown registry names,
        # and a typo'd env var must not masquerade as "no model found"
        raise SystemExit(f"BENCH_ATTENTION={attention!r}: must be auto/flash/ring/xla")
    for name in (os.environ.get("BENCH_MODEL", ""), "bart-large-cnn", "t5-small"):
        if not name:
            continue
        try:
            lm = load_model(name, dtype=jax.numpy.bfloat16, attention_impl=attention)
        except ValueError:
            continue
        # remat trades ~27% measured throughput for activation memory — only
        # worth it when the model might not fit (7B-class); the 406M flagship
        # at the default batch uses a fraction of 16 GB HBM without it
        shapes = jax.eval_shape(lambda: lm.init_params(0))
        n_params = sum(int(math.prod(x.shape)) for x in jax.tree.leaves(shapes))
        remat_env = os.environ.get("BENCH_REMAT", "")
        remat = (n_params > 1_000_000_000) if remat_env == "" else remat_env != "0"
        if remat:
            # rebuild just the module with remat on — the already-loaded
            # weights (if any) don't depend on the flag, so no second
            # checkpoint read/convert for the 7B-class models
            import dataclasses

            lm = dataclasses.replace(
                lm,
                module=type(lm.module)(
                    lm.config, dtype=jax.numpy.bfloat16, remat=True,
                    remat_policy=os.environ.get("BENCH_REMAT_POLICY", "full"),
                ),
            )
        return name, lm, remat
    raise SystemExit("no benchmarkable model in registry")


def main() -> None:
    import jax
    import numpy as np

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.optim import make_optimizer
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    name, lm, remat = _flagship()
    n_chips = jax.device_count()
    mesh = build_mesh(MeshConfig(data=-1))

    src_len, tgt_len = 1024, 128
    batch = int(os.environ.get("BENCH_BATCH", "16")) * n_chips
    steps = max(1, int(os.environ.get("BENCH_STEPS", "5")))

    rng = np.random.RandomState(0)
    vocab = lm.config.vocab_size
    b = {
        "input_ids": rng.randint(2, min(vocab, 30000), (batch, src_len)).astype(np.int32),
        "attention_mask": np.ones((batch, src_len), np.int32),
        "labels": rng.randint(2, min(vocab, 30000), (batch, tgt_len)).astype(np.int32),
    }
    b["labels"][:, -8:] = LABEL_PAD

    tx, schedule = make_optimizer(learning_rate=5e-5, warmup_steps=0, total_steps=1000)
    params = lm.params if lm.params is not None else jax.device_get(lm.init_params(0))
    params = shard_params(params, mesh)
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    build = make_train_step(lm.module, lm.config, tx, schedule, mesh)
    step_fn, _ = build(state)
    gb = put_batch(b, mesh)

    # Sync via host readbacks: on tunneled/experimental PJRT backends
    # block_until_ready can return before execution finishes, which would
    # report absurd throughput.  A scalar device_get of the loss plus one
    # updated parameter element forces the full step chain.
    def sync(state, metrics) -> float:
        leaf = jax.tree.leaves(state.params)[0]
        _ = jax.device_get(leaf.ravel()[0])
        return float(jax.device_get(metrics["loss"]))

    tokens_per_step = int(np.sum(b["attention_mask"])) + int(np.sum(b["labels"] != LABEL_PAD))
    n_params = int(sum(x.size for x in jax.tree.leaves(params)))

    # Per-step FLOPs: compiler cost analysis of the actual program when the
    # backend reports it, else the standard 6*N*tokens training estimate
    # (fwd 2N + bwd 4N matmul FLOPs per token; attention excluded, so MFU
    # is slightly conservative relative to true utilization).
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    flops_per_step = 0.0
    try:
        # HLO-level analysis on the Lowered stage: no second backend compile.
        # Must lower under the mesh context — jit caches the traced jaxpr,
        # and a trace made without the ambient mesh would bake constraint
        # no-ops into the very program the benchmark then measures.
        with activation_mesh(step_fn.mesh):
            ca = step_fn.jitted.lower(state, gb).cost_analysis()
        if isinstance(ca, list):  # some backends return one dict per device
            ca = ca[0] if ca else {}
        flops_per_step = float((ca or {}).get("flops", 0.0))
    except Exception as e:
        print(f"bench: cost_analysis unavailable ({e}); using 6*N*tokens", file=sys.stderr)
    if flops_per_step <= 0.0:
        flops_per_step = 6.0 * n_params * tokens_per_step

    # warmup/compile
    for _ in range(2):
        state, metrics = step_fn(state, gb)
    sync(state, metrics)

    # throughput: one sync at the end so async dispatch can overlap steps —
    # the same pipelining the trainer gets (a per-step readback here would
    # deflate tokens/sec by the host round-trip)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, gb)
    loss = sync(state, metrics)
    dt = time.perf_counter() - t0
    assert loss == loss, "non-finite loss"

    # step-time distribution: a separate pass with a readback per step
    # (sync-inclusive — upper bounds on single-step latency, not 1/throughput)
    times = []
    for _ in range(steps):
        t1 = time.perf_counter()
        state, metrics = step_fn(state, gb)
        sync(state, metrics)
        times.append(time.perf_counter() - t1)

    peak_flops = float(os.environ.get("BENCH_PEAK_TFLOPS", "197")) * 1e12  # v5e bf16
    order = sorted(times)
    tps = tokens_per_step * steps / dt
    tps_chip = tps / n_chips
    mfu = flops_per_step * steps / dt / (n_chips * peak_flops)
    print(
        json.dumps(
            {
                "metric": f"{name} seq2seq fine-tune train-step throughput "
                          f"(src1024/tgt128, bf16{'+remat' if remat else ''}, batch {batch})",
                "value": round(tps_chip, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(tps_chip / BASELINE_TOKENS_PER_SEC_PER_CHIP, 3),
                "mfu": round(mfu, 4),
                "model_flops_per_token": round(flops_per_step / tokens_per_step),
                "params": n_params,
                "chips": n_chips,
                "backend": jax.default_backend(),
                "step_time_ms_sync_inclusive": {
                    "p50": round(order[len(order) // 2] * 1e3, 1),
                    "p90": round(order[min(len(order) - 1, int(0.9 * len(order)))] * 1e3, 1),
                    "min": round(order[0] * 1e3, 1),
                    "max": round(order[-1] * 1e3, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get(_BENCH_CHILD) == "1":
        main()
    else:
        raise SystemExit(_supervise())
