"""Framework benchmark: seq2seq fine-tune train-step throughput on TPU.

Output contract: the LAST result line on stdout is the benchmark record —
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}
The supervisor entry point (`python bench.py`) prints exactly one.  A
direct child run (`_DLLM_BENCH_CHILD=1 python bench.py`) re-prints the
record as each add-on measurement lands (headline first, then enriched
with grad-accum/dropout/rbg/trainer fields) so a kill at any point loses
only the not-yet-measured fields — always take the last line.  Add-ons
that the adaptive time budget skips are named in ``skipped_passes``.

Workload: the reference's headline recipe — bart-large-cnn-class seq2seq
fine-tuning, source 1024 / target 128 (reference train-accelerator.py:115-127),
AdamW + linear schedule — as our SPMD train step (bf16 compute, fp32
params/optimizer, remat) on all locally available chips.  Throughput
counts non-pad source+target tokens per optimizer step.

Baseline: the reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is measured against a documented estimate of its strongest
variant (A: HF Trainer fp32 DDP on modern data-center GPUs):
~6 * n_params FLOPs/token training compute at ~35% utilization of a
312 TFLOP/s bf16 A100 ≈ 4000 tokens/sec/GPU for a 406M-param model.
We report per-chip so the comparison is per-accelerator.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time
from typing import Callable

_BENCH_CHILD = "_DLLM_BENCH_CHILD"

# Persistent XLA compilation cache, shared by supervisor children and direct
# runs.  Round-4 failure mode: a slow remote-compile service pushed the three
# child compiles past the 900 s attempt timeout — with the cache, any compile
# that ever finished (this run or a previous one) is a disk hit next time,
# so retries and re-runs spend their budget measuring, not compiling.
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_compile_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")


def _is_result_json(line: str) -> bool:
    """True only for the bench RESULT line — the child's stdout also carries
    JSON-lines training logs ({"step":...}) and events ({"event":...}), and
    salvaging one of those as the round artifact would be worse than no
    number at all."""
    try:
        rec = json.loads(line)
    except ValueError:
        return False
    return isinstance(rec, dict) and "metric" in rec and "value" in rec and "unit" in rec


def _salvage_result(stdout, stderr, note: str, extra: dict | None = None) -> bool:
    """Shared salvage policy for a child that already printed its result
    line (the child emits the headline the moment it is measured): forward
    the child's stderr, print ``note``, re-emit the result line (merged
    with ``extra`` fields — e.g. the corrupt-cache reset marker).  Returns
    False when no result line is present.  ``stdout``/``stderr`` may be
    bytes (TimeoutExpired carries raw captures) or str."""
    def to_text(x):
        return x.decode(errors="replace") if isinstance(x, bytes) else (x or "")

    line = next(
        (ln for ln in reversed(to_text(stdout).strip().splitlines()) if _is_result_json(ln)),
        None,
    )
    if line is None:
        return False
    sys.stderr.write(to_text(stderr))
    if note:
        print(note, file=sys.stderr)
    if extra:
        rec = json.loads(line)
        rec.update(extra)
        line = json.dumps(rec)
    print(line)
    return True


# Corrupt persistent-cache abort detection (the known failure mode on this
# container since PR 7: the headline bench dies inside XLA deserializing a
# poisoned .jax_compile_cache entry — byte-identical reproduction at an
# older clean HEAD, and a fresh cache dir runs clean end-to-end).  Text
# signatures first; an abort-style exit (SIGABRT / XLA check-fail) with a
# non-empty persistent cache present is treated as the same suspect —
# wrong at worst once, because the reset fires a single retry against a
# fresh cache dir and a genuine crash reproduces there.
_CACHE_SIG_TEXTS = (
    "compilation cache", "persistent cache", "jax_compile_cache",
    "deserializ", "cache entry", "corrupt",
)


def _corrupt_cache_suspect(rc: int | None, tail: str, cache_dir: str) -> bool:
    t = (tail or "").lower()
    if any(s in t for s in _CACHE_SIG_TEXTS) and ("cache" in t):
        return True
    abortish = rc in (-6, 134) or "check failed" in t or "aborted" in t
    try:
        populated = os.path.isdir(cache_dir) and bool(os.listdir(cache_dir))
    except OSError:
        populated = False
    return bool(abortish and populated)


def _reset_compile_cache(env: dict) -> str:
    """Redirect JAX_COMPILATION_CACHE_DIR to a fresh empty dir (the old
    one is left in place for forensics) and return the new path."""
    import shutil

    fresh = _CACHE_DIR + ".fresh"
    shutil.rmtree(fresh, ignore_errors=True)
    os.makedirs(fresh, exist_ok=True)
    env["JAX_COMPILATION_CACHE_DIR"] = fresh
    return fresh


def _latest_local_result() -> str:
    """Quote the newest committed BENCH_LOCAL_r*.json headline, if any.

    When the shared backend is wedged the official artifact carries no
    number; naming the preserved same-hardware measurement in ``detail``
    keeps the error line self-contained for the reader of BENCH_r{N}.json.
    """
    import glob
    import re

    best = None
    for path in glob.glob(os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_LOCAL_r*.json")):
        m = re.search(r"BENCH_LOCAL_r(\d+)\.json$", path)
        if not m:
            continue
        if best is None or int(m.group(1)) > best[0]:
            best = (int(m.group(1)), path)
    if best is None:
        return ""
    try:
        with open(best[1]) as f:
            rec = json.load(f)
        res = rec.get("result", rec)
        return (
            f"; latest in-repo on-chip measurement {os.path.basename(best[1])}: "
            f"{res.get('value')} {res.get('unit', '')} ({res.get('metric', '')[:120]})"
        )
    except Exception:
        return ""


def _probe_backend(env: dict, timeout: float) -> str | None:
    """Cheap pre-flight: can a fresh process see devices at all?

    Round-3 failure mode: the backend's remote-compile service wedged and
    ``jax.devices()`` hung *indefinitely* during init — each full bench
    attempt then burned its entire timeout inside backend setup, and the
    supervisor exhausted its 1400 s budget without ever reaching user code.
    A ~2-minute subprocess that only calls ``jax.device_count()`` turns
    that hang into a fast, diagnosable failure.  Returns None when healthy,
    else a one-line diagnosis.
    """
    code = "import jax; print('PROBE_OK', jax.device_count())"
    penv = {k: v for k, v in env.items() if k != _BENCH_CHILD}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=penv,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return f"backend probe (jax.device_count) hung >{timeout:.0f}s — backend init wedged"
    if proc.returncode != 0 or "PROBE_OK" not in proc.stdout:
        tail = "\n".join((proc.stderr or proc.stdout or "").strip().splitlines()[-3:])
        return f"backend probe failed rc={proc.returncode}: {tail}"
    return None


def _supervise() -> int:
    """Run the real benchmark in child processes with retry + backoff.

    Round-1 failure mode: the tunneled TPU backend can fail to initialize
    transiently (``UNAVAILABLE: TPU backend setup/compile error``), and JAX
    caches backend-init failure per process — so retry means a fresh
    process.  Round-3 failure mode: backend init *hangs* rather than
    failing, so each attempt is gated on a cheap device-count probe first.
    On final failure print ONE parseable JSON error line (never a bare
    traceback) and exit 0 so the driver records a parseable artifact.
    """
    attempts = int(os.environ.get("BENCH_RETRIES", "3"))
    backoff = float(os.environ.get("BENCH_BACKOFF", "10"))
    # generous per-attempt ceiling: the child now compiles three programs
    # (headline step, with-dropout step, trainer loop) before measuring
    attempt_timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "900"))
    # hard wall-clock ceiling so a hanging backend can't outlive the
    # driver's own timeout with no JSON printed (round-1 rc=124 mode)
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "1400"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "110"))
    here = os.path.abspath(__file__)
    env = dict(os.environ)
    env[_BENCH_CHILD] = "1"
    t_start = time.monotonic()
    tail = ""
    cache_reset = False  # corrupt-compile-cache recovery fired (once)
    for i in range(attempts):
        if probe_timeout > 0:
            # cap the probe at the remaining budget (minus slack to print
            # the final JSON line) so it can never push total wall-clock
            # past BENCH_TOTAL_BUDGET — the driver killing us mid-probe
            # would reproduce the round-1 no-artifact mode
            remaining = budget - (time.monotonic() - t_start)
            if i > 0 and remaining < 90:
                print("bench: total budget exhausted, giving up", file=sys.stderr)
                break
            diag = _probe_backend(env, min(probe_timeout, max(30.0, remaining - 60)))
            if diag is not None:
                # wedged backend: fail THIS attempt in ~2 min, not 900 s.
                # Retrying the probe (with backoff) still covers genuinely
                # transient init errors; a dead backend exits in minutes.
                tail = f"attempt {i + 1} pre-flight: {diag}"
                print(tail, file=sys.stderr)
                # budget break BEFORE the backoff sleep: sleeping and then
                # immediately giving up would only delay the error line
                if budget - (time.monotonic() - t_start) < probe_timeout + 60:
                    break
                if i < attempts - 1:
                    time.sleep(min(backoff * (2**i), max(0.0, budget - (time.monotonic() - t_start))))
                continue
        if i > 0:
            # degrade gracefully: retries drop the add-on measurements
            # (trainer loop, dropout pass) so a slow/recovering backend
            # still produces the headline number within the budget
            env["BENCH_TRAINER"] = "0"
            env["BENCH_DROPOUT"] = "0"
        # cap each attempt at the remaining budget, so a first-attempt hang
        # at the full attempt_timeout still leaves room for the degraded
        # (headline-only) retry instead of exhausting the budget outright
        remaining = budget - (time.monotonic() - t_start)
        if i > 0 and remaining < 120:  # always give attempt 1 its shot
            print("bench: total budget exhausted, giving up", file=sys.stderr)
            break
        remaining = max(remaining, 60.0)
        this_timeout = min(attempt_timeout, remaining)
        # tell the child the timeout it actually runs under, so its add-on
        # budget gate scales with the supervisor instead of assuming 900 s
        env["BENCH_CHILD_TIMEOUT"] = str(this_timeout)
        try:
            proc = subprocess.run(
                [sys.executable, here],
                env=env,
                cwd=os.path.dirname(here),
                capture_output=True,
                text=True,
                timeout=this_timeout,
            )
        except subprocess.TimeoutExpired as e:
            # an add-on measurement overrunning the kill must not cost the
            # already-captured headline
            if _salvage_result(
                e.stdout, e.stderr,
                f"attempt {i + 1} timed out after the headline was measured; "
                "salvaging the child's early JSON line",
                extra={"compile_cache_reset": True} if cache_reset else None,
            ):
                return 0
            tail = f"attempt {i + 1} timed out: {e}"
            print(tail, file=sys.stderr)
            transient = True
        else:
            # salvage regardless of exit code: an add-on crashing the
            # process after the headline printed (rc!=0, e.g. an XLA
            # check-fail in the trainer-loop pass) must not cost it either
            note = (
                "" if proc.returncode == 0 else
                f"bench attempt {i + 1} exited rc={proc.returncode} after "
                "the headline was measured; salvaging its JSON line"
            )
            if _salvage_result(
                proc.stdout, proc.stderr, note,
                extra={"compile_cache_reset": True} if cache_reset else None,
            ):
                return 0
            full_err = (proc.stderr or "") + "\n" + (proc.stdout or "")
            tail = "\n".join((proc.stderr or proc.stdout or "").strip().splitlines()[-8:])
            print(f"bench attempt {i + 1}/{attempts} failed rc={proc.returncode}:\n{tail}", file=sys.stderr)
            # retry only failures that look like transient backend trouble;
            # a deterministic crash (bad model name, shape error) won't heal
            transient = any(s in tail for s in ("UNAVAILABLE", "DEADLINE_EXCEEDED", "Unable to initialize"))
            if not cache_reset and _corrupt_cache_suspect(
                proc.returncode, full_err,
                env.get("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR),
            ):
                # the known corrupt-persistent-cache abort: redirect to a
                # fresh cache dir and retry ONCE — the recovery the round-7
                # failure note asked for, instead of dying with no artifact
                fresh = _reset_compile_cache(env)
                cache_reset = True
                transient = True
                print(
                    "bench: corrupt compile-cache abort signature detected; "
                    f"redirected JAX_COMPILATION_CACHE_DIR to {fresh} and "
                    "retrying once (compile_cache_reset will be stamped)",
                    file=sys.stderr,
                )
        if not transient:
            break
        if i < attempts - 1:
            # the remaining-budget cap above bounds the next attempt; only
            # the backoff sleep needs to fit here
            time.sleep(min(backoff * (2**i), max(0.0, budget - (time.monotonic() - t_start))))
    print(
        json.dumps(
            {
                "metric": "seq2seq fine-tune train-step throughput",
                "value": None,
                "unit": "tokens/sec/chip",
                "vs_baseline": None,
                "error": "benchmark did not produce a result (see detail)",
                "detail": (tail[-500:] + _latest_local_result())[:900],
                **({"compile_cache_reset": True} if cache_reset else {}),
            }
        )
    )
    return 0



# The reference's documented estimate for its strongest variant
# (BASELINE.md: ~4,000 tok/s per A100-class GPU on bart-large-cnn,
# src 1024 / tgt 128).  NOTE: bench defaults have evolved across rounds
# (round 1: batch 8/chip, remat on; round 2+: batch 16/chip, remat off for
# <1B-param models) — the baseline constant describes the REFERENCE and is
# config-independent, but vs_baseline values in BENCH_r{N}.json files are
# only comparable across rounds when the metric string reports the same
# bench config (it always names batch/remat/attention).
BASELINE_TOKENS_PER_SEC_PER_CHIP = 4000.0


def _flagship():
    import jax

    from distributed_llms_example_tpu.models.registry import load_model

    attention = os.environ.get("BENCH_ATTENTION", "") or None
    if attention not in (None, "auto", "flash", "ring", "xla"):
        # validate up front: the except below is for unknown registry names,
        # and a typo'd env var must not masquerade as "no model found"
        raise SystemExit(f"BENCH_ATTENTION={attention!r}: must be auto/flash/ring/xla")
    for name in (os.environ.get("BENCH_MODEL", ""), "bart-large-cnn", "t5-small"):
        if not name:
            continue
        try:
            lm = load_model(name, dtype=jax.numpy.bfloat16, attention_impl=attention)
        except ValueError as e:
            if name == os.environ.get("BENCH_MODEL", ""):
                # an explicitly requested model must never silently fall
                # back to a different one — the headline would be misleading
                raise SystemExit(f"BENCH_MODEL={name!r} failed to load: {e}")
            if name == "bart-large-cnn":
                # the default flagship failing to load is a registry
                # regression — silently benching t5-small (60M) would report
                # a misleading headline number for the round
                raise SystemExit("flagship bart-large-cnn failed to load from registry")
            continue
        # remat trades ~27% measured throughput for activation memory — only
        # worth it when the model might not fit (7B-class); the 406M flagship
        # at the default batch uses a fraction of 16 GB HBM without it
        shapes = jax.eval_shape(lambda: lm.init_params(0))
        n_params = sum(int(math.prod(x.shape)) for x in jax.tree.leaves(shapes))
        remat_env = os.environ.get("BENCH_REMAT", "")
        remat = (n_params > 1_000_000_000) if remat_env == "" else remat_env != "0"
        if remat:
            # rebuild just the module with remat on — the already-loaded
            # weights (if any) don't depend on the flag, so no second
            # checkpoint read/convert for the 7B-class models
            import dataclasses

            lm = dataclasses.replace(
                lm,
                module=type(lm.module)(
                    lm.config, dtype=jax.numpy.bfloat16, remat=True,
                    remat_policy=os.environ.get("BENCH_REMAT_POLICY", "full"),
                ),
            )
        return name, lm, remat
    raise SystemExit("no benchmarkable model in registry")


def _trainer_loop_bench(model_name: str, n_chips: int, *, remat: bool,
                        attention: str | None,
                        rbg_ok: Callable[[float], bool] = lambda est: True) -> dict:
    """Measure the REAL Trainer loop (bucketed batching + prefetch +
    logging cadence + put_batch on the critical path), not just the jitted
    step — the round-2 bench only timed synthetic fixed batches, so input-
    pipeline regressions were invisible.  Returns tok/s/chip with the
    prefetcher on (depth 2) and off (0): their gap quantifies how much
    host input work the background thread actually hides.

    Checkpoint/export IO is stubbed out (this measures the training loop,
    not artifact writes), and each timed pass re-runs the SAME Trainer so
    compilation stays out of the window."""
    import tempfile

    import jax
    import numpy as np

    from distributed_llms_example_tpu.core.config import (
        CheckpointConfig,
        MeshConfig,
        TrainConfig,
    )
    from distributed_llms_example_tpu.train.trainer import Trainer

    steps = max(2, int(os.environ.get("BENCH_TRAINER_STEPS", "6")))
    batch = int(os.environ.get("BENCH_BATCH", "16")) * n_chips
    rng = np.random.RandomState(7)

    def text(n_chars: int) -> str:
        # byte tokenizer ≈ 1 token/char: sources fill the 1024 bucket,
        # targets the 128 bucket, mirroring the synthetic workload
        words = []
        total = 0
        while total < n_chars:
            w = "".join(chr(97 + rng.randint(26)) for _ in range(3 + rng.randint(6)))
            words.append(w)
            total += len(w) + 1
        return " ".join(words)[:n_chars]

    records = [{"dialogue": text(1016), "summary": text(120)} for _ in range(batch * steps)]
    # device-time attribution: one PROFILED (untimed) pass captures a
    # 1-step jax.profiler window, parsed into the device_account that is
    # stamped below — the gauges compile supplies the instruction→bucket
    # index and the byte account the bandwidth join needs.  "auto" =
    # accelerators only: the CPU thunk-runtime profiler multiplies a
    # bench-sized (src 1024) step's wall ~20× and overflows the session
    # into an EMPTY trace (measured on this container); the CPU parse
    # path is pinned by tests/test_devprof.py on CLI-sized windows
    # instead.  BENCH_DEVICE_PROFILE=1 forces it anywhere, 0 disables.
    dev_profile_env = os.environ.get("BENCH_DEVICE_PROFILE", "auto")
    dev_profile = dev_profile_env != "0" and (
        dev_profile_env == "1" or jax.default_backend() != "cpu"
    )
    with tempfile.TemporaryDirectory() as tmp:
        cfg = TrainConfig(
            model_ckpt=model_name,
            output_dir=tmp,
            obs_gauges="on" if dev_profile else "auto",
            batch_size=batch,
            num_epochs=1,
            warmup_steps=0,
            evaluation_steps=0,
            learning_rate=5e-5,
            max_source_length=1024,
            max_target_length=128,
            pad_to_multiple=128,
            prefetch_batches=2,
            log_every_steps=steps,
            tokenizer="byte",
            # mirror the synthetic step's BENCH_REMAT / BENCH_ATTENTION
            # overrides so vs_synthetic compares identically-built programs
            remat=remat,
            attention_impl=attention or "",
            # pin the baseline stream: with the new "auto" defaults a TPU
            # trainer would silently start on rbg+fused and the rbg A/B
            # pass below would compare like against like
            prng_impl="threefry",
            dropout_impl="xla",
            mesh=MeshConfig(data=-1),
            checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        )
        trainer = Trainer(cfg, train_records=records)
        trainer.checkpointer.save = lambda *a, **k: None
        trainer.checkpointer.wait = lambda: None
        trainer.save_final = lambda: None
        tokens = sum(trainer._batch_tokens(b) for b in trainer.batches.epoch(0))

        # capture the span windows each pass emits (data_wait /
        # step_dispatch / device_sync) — BENCH_r05 showed prefetch2 ≈
        # prefetch0 with no way to tell WHY from the artifact; the span
        # totals are the answer (device-bound loop: data_wait ≪
        # step_dispatch at depth 0 already)
        captured_windows: list[dict] = []
        orig_summary = trainer.obs.spans.summary

        def capturing_summary():
            s = orig_summary()
            if s is not None:
                captured_windows.append(s)
            return s

        trainer.obs.spans.summary = capturing_summary

        def pass_budget() -> dict | None:
            """Drain the pass's step_budget accounts (obs/budget.py) into
            one aggregate: the additive component breakdown plus the
            wall-weighted dispatch_efficiency — the same-session A/B
            artifact the ROADMAP's vs_synthetic_step >= 0.95 attack needs
            (which component to shrink, not just that a gap exists)."""
            from distributed_llms_example_tpu.obs.budget import aggregate_accounts

            bud = getattr(trainer.obs, "budget", None)
            if bud is None or not bud.history:
                return None
            accounts = bud.history[:]
            bud.history.clear()
            return aggregate_accounts(accounts)

        def timed_pass() -> float:
            t0 = time.perf_counter()
            trainer.train()
            # force completion: train() can return with steps still in
            # flight (async dispatch; block_until_ready is unreliable on
            # the tunneled backend, so read a param element back)
            _ = jax.device_get(jax.tree.leaves(trainer.state.params)[0].ravel()[0])
            return time.perf_counter() - t0

        def pass_spans() -> dict:
            """Aggregate this pass's captured windows into per-span totals."""
            agg: dict[str, float] = {}
            n_steps = 0
            for w in captured_windows:
                n_steps += int(w.get("window_steps", 0))
                for name, slot in w.get("spans", {}).items():
                    agg[name] = agg.get(name, 0.0) + float(slot["total_ms"])
            captured_windows.clear()
            return {"steps": n_steps, **{f"{k}_ms": round(v, 1) for k, v in sorted(agg.items())}}

        dt_first = timed_pass()  # compile + warmup
        captured_windows.clear()
        pass_budget()  # drop the warmup pass's accounts
        out = {}
        for prefetch in (2, 0):
            trainer.cfg = cfg.replace(prefetch_batches=prefetch)
            # COLD tokenizer cache each pass: the dataset memoizes encoded
            # examples, and a warm cache would exclude tokenization from
            # the timed window entirely — the prefetch 2-vs-0 gap is
            # precisely "does the background thread hide tokenize+pad"
            trainer.train_ds.clear_cache()
            dt = timed_pass()
            out[f"tokens_per_sec_chip_prefetch{prefetch}"] = round(tokens / dt / n_chips, 1)
            out[f"spans_prefetch{prefetch}"] = pass_spans()
            budget = pass_budget()
            if budget is not None:
                out[f"budget_prefetch{prefetch}"] = budget
        if "budget_prefetch2" in out:
            # the headline gauge: the fraction of trainer-loop wall the
            # device was fed or drained (vs host-side stalls) on the
            # default prefetch config
            out["dispatch_efficiency"] = out["budget_prefetch2"][
                "dispatch_efficiency"
            ]
        if dev_profile and rbg_ok(dt + 25.0):
            # one profiled (UNTIMED — the profiler start/stop syncs would
            # pollute a timed window) pass: touch the trainer's own
            # profile trigger, let the capture land mid-pass, and read
            # back the parsed device account (per-bucket device time,
            # achieved collective bandwidth, overlap) the capture emitted
            try:
                trainer.cfg = cfg.replace(prefetch_batches=2)
                trigger = trainer.obs._trigger
                os.makedirs(os.path.dirname(trigger), exist_ok=True)
                with open(trigger, "w") as f:
                    f.write("1")  # one profiled step bounds the overhead
                trainer.train_ds.clear_cache()
                trainer.train()
                acct = (
                    trainer.obs.budget.last_device_account
                    if trainer.obs.budget is not None
                    else None
                )
                if acct is not None:
                    out["device_account"] = {
                        k: v for k, v in acct.items()
                        if k not in ("lanes", "lane_slices_dropped", "event")
                    }
                else:
                    out["device_account"] = {"error": "no capture landed"}
            except Exception as e:
                out["device_account"] = {"error": str(e)[:300]}
            captured_windows.clear()
            pass_budget()  # drop the profiled pass's accounts
        # adaptive cost estimate for the rbg pass: one warm pass (includes
        # the typed-key retrace — bounded by the compile-inclusive first
        # pass) plus one timed pass
        rbg_est = dt_first + dt + 30.0
        if trainer.use_dropout and os.environ.get("BENCH_TRAINER_RBG", "1") != "0" and rbg_ok(rbg_est):
            # the --prng-impl rbg trainer path: hardware-RNG dropout masks.
            # Swap the key impl via the Trainer's own knob and warm once
            # (the step retraces for the typed-key argument) before timing.
            trainer.cfg = cfg.replace(prefetch_batches=2)
            trainer.set_prng_impl("rbg")
            timed_pass()
            trainer.train_ds.clear_cache()
            dt = timed_pass()
            out["tokens_per_sec_chip_rbg"] = round(tokens / dt / n_chips, 1)
        out["steps"] = steps
        out["prng_impl"] = trainer.prng_impl  # resolved (not the "auto" alias)
        out["dropout_impl"] = trainer.cfg.dropout_impl
        # resolved optimizer path; the budget_prefetch* aggregates above
        # carry its per-window optimizer_apply_ms gauge (the cadenced
        # stand-alone apply sample) when budget accounting ran
        out["optim_impl"] = trainer.optim_impl
        return out


def _llama_depth_main() -> None:
    """BENCH_MODE=llama-depth: measured 7B-class remat step time by depth
    extrapolation.  One v5e chip cannot hold llama-2-7b's optimizer state,
    so this measures the full-width model (hidden 4096 / inter 11008, GQA,
    bf16, remat ON — the BASELINE.json config-5 recipe) truncated to
    2 and 4 layers, fits time = overhead + per_layer · L, and extrapolates
    to the real 32-layer depth.  Transformer step time is linear in depth
    (identical layers, remat recompute included per layer), so the fit has
    exactly the two degrees of freedom the two measurements pin down."""
    import dataclasses

    import jax
    import numpy as np

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import LlamaForCausalLM
    from distributed_llms_example_tpu.models.registry import LLAMA_CONFIGS
    from distributed_llms_example_tpu.ops.fused_optim import (
        resolve_impl as resolve_optim_impl,
    )
    from distributed_llms_example_tpu.train.optim import make_optimizer_bundle
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    policy = os.environ.get("BENCH_REMAT_POLICY", "full")
    batch = int(os.environ.get("BENCH_BATCH_7B", "4"))
    seq = int(os.environ.get("BENCH_SEQ_7B", "1024"))
    depths = [int(x) for x in os.environ.get("BENCH_DEPTHS", "2,4").split(",")]
    steps = max(2, int(os.environ.get("BENCH_STEPS", "4")))
    base = LLAMA_CONFIGS["llama-2-7b"]
    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = jax.device_count()
    # the HEADLINE runs the production default optimizer path (--optim-impl
    # auto = the fused Pallas clip+AdamW apply on TPU, optax elsewhere);
    # same-session variants below re-measure the OTHER impl and the fused
    # blockwise CE so the non-layer-overhead delta is attributed per
    # component in one session (the ROADMAP acceptance shape)
    optim_impl = os.environ.get("BENCH_OPTIM_IMPL", "auto")
    resolved_optim = resolve_optim_impl(optim_impl)
    # this mode measures depth scaling only and always runs uncompressed;
    # a silently-ignored BENCH_GRAD_COMPRESSION here would be the exact
    # config-loss failure obs_gate exists to catch — say so loudly
    if os.environ.get("BENCH_GRAD_COMPRESSION", "off") != "off":
        print(
            "bench: BENCH_GRAD_COMPRESSION is ignored in llama-depth mode "
            "(record stamps grad_compression=off); the compression A/B "
            "lives in the main bench",
            file=sys.stderr,
        )
    variant_names = [
        v for v in os.environ.get(
            "BENCH_7B_VARIANTS", "optim_xla,fused_ce"
        ).split(",") if v
    ]

    rng = np.random.RandomState(0)
    ids = rng.randint(2, base.vocab_size, (batch * n_chips, seq)).astype(np.int32)
    labels = ids.copy()
    labels[:, : seq // 4] = LABEL_PAD
    b = {"input_ids": ids, "attention_mask": np.ones_like(ids), "labels": labels}
    tokens_per_step = int(np.sum(b["attention_mask"]))

    from distributed_llms_example_tpu.parallel.sharding import infer_param_shardings

    fused_ce = os.environ.get("BENCH_FUSED_CE", "0") == "1"
    step_ms = {}
    variant_ms: dict = {v: {} for v in variant_names}
    optim_probe_ms: dict = {}
    accum_report = None
    for L in depths:
        cfg = dataclasses.replace(base, num_hidden_layers=L, fused_ce=fused_ce)
        module = LlamaForCausalLM(cfg, dtype=jax.numpy.bfloat16, remat=True, remat_policy=policy)

        # init ON-DEVICE with output shardings: a host round-trip of these
        # multi-GB trees through the tunneled backend takes minutes and
        # times the bench out
        def init_params():
            return module.init(
                jax.random.PRNGKey(0), jax.numpy.ones((1, 8), jax.numpy.int32)
            )["params"]

        shapes = jax.eval_shape(init_params)
        params = jax.jit(
            init_params, out_shardings=infer_param_shardings(shapes, mesh)
        )()
        tx, schedule, optim_spec = make_optimizer_bundle(
            learning_rate=5e-5, warmup_steps=0, total_steps=1000
        )
        state = create_train_state(params, tx)
        sh = state_shardings(state, mesh)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
        build = make_train_step(
            module, cfg, tx, schedule, mesh, is_seq2seq=False,
            optim_spec=optim_spec, optim_impl=optim_impl,
        )
        step_fn, _ = build(state)
        gb = put_batch(b, mesh)

        def timed_median(fn, state):
            """warm twice, then per-step sync-inclusive times, MEDIAN over
            the window: the tunneled backend's host latency is spiky, and
            one stall inside a single aggregate window once turned a
            2-layer measurement slower than the 4-layer one (negative
            per-layer fit).  Returns (median_ms, state)."""
            for _ in range(2):
                state, metrics = fn(state, gb)
            _ = float(jax.device_get(metrics["loss"]))
            times = []
            for _ in range(steps):
                t0 = time.perf_counter()
                state, metrics = fn(state, gb)
                _ = float(jax.device_get(metrics["loss"]))
                times.append(time.perf_counter() - t0)
            return sorted(times)[len(times) // 2] * 1e3, state

        step_ms[L], state = timed_median(step_fn, state)

        # same-session component A/Bs at every depth (both depths feed the
        # per-variant intercept fit, so the non-layer-overhead delta is
        # attributed to the optimizer / CE component it came from):
        # "optim_xla" re-measures the step on the optax chain;
        # "fused_ce" measures the vocab-chunked LM-head+CE path.
        for v in variant_names:
            try:
                if v == "optim_xla":
                    if resolved_optim == "xla":
                        variant_ms[v][L] = {"skipped": "headline already xla"}
                        continue
                    build_v = make_train_step(
                        module, cfg, tx, schedule, mesh, is_seq2seq=False,
                        optim_spec=optim_spec, optim_impl="xla",
                    )
                elif v == "fused_ce":
                    if fused_ce:
                        # BENCH_FUSED_CE=1: the headline already runs the
                        # fused CE — re-measuring would stamp run-to-run
                        # jitter as a component delta
                        variant_ms[v][L] = {"skipped": "headline already fused_ce"}
                        continue
                    ce_cfg = dataclasses.replace(cfg, fused_ce=True)
                    ce_module = LlamaForCausalLM(
                        ce_cfg, dtype=jax.numpy.bfloat16, remat=True,
                        remat_policy=policy,
                    )
                    build_v = make_train_step(
                        ce_module, ce_cfg, tx, schedule, mesh,
                        is_seq2seq=False,
                        optim_spec=optim_spec, optim_impl=optim_impl,
                    )
                else:
                    variant_ms[v][L] = {"skipped": f"unknown variant {v!r}"}
                    continue
                sv, _ = build_v(state)
                ms, state = timed_median(sv, state)
                variant_ms[v][L] = ms
            except Exception as e:
                variant_ms[v][L] = {"error": str(e)[:300]}

        # direct optimizer-apply wall sample per impl (the step-budget
        # layer's optimizer_apply_ms, stand-alone): the component-level
        # evidence for WHICH slice of the intercept the fused apply moved
        if L == max(depths) and os.environ.get("BENCH_OPTIM_PROBE_7B", "1") != "0":
            from distributed_llms_example_tpu.train.step import (
                make_optimizer_probe,
            )

            probe_impls = ["xla"] + (
                [resolved_optim] if resolved_optim != "xla" else []
            )
            for impl_name in probe_impls:
                try:
                    probe = make_optimizer_probe(
                        tx, schedule, sh, mesh,
                        optim_spec=optim_spec, optim_impl=impl_name,
                    )
                    _ = float(jax.device_get(probe(state)))  # compile+warm
                    pts = []
                    for _ in range(steps):
                        t0 = time.perf_counter()
                        _ = float(jax.device_get(probe(state)))
                        pts.append(time.perf_counter() - t0)
                    optim_probe_ms[impl_name] = round(
                        sorted(pts)[len(pts) // 2] * 1e3, 2
                    )
                except Exception as e:
                    optim_probe_ms[impl_name] = f"error: {str(e)[:200]}"

        # In-step grad-accumulation sweep at the deepest measured config:
        # effective batch = microbatch(=BENCH_BATCH_7B) × N at the SAME
        # peak activation memory as the batch-4 step (the scan holds one
        # microbatch's activations + the param-sharded fp32 accumulators)
        # — this is how batch 8+ becomes reachable on one v5e chip after
        # the round-5 batch8_oom.  Ideal linear scaling is N × the accum=1
        # step time; the per-microbatch overhead fraction is the cost of
        # the scan + the (amortized-away) once-per-step tail.
        if L == max(depths) and os.environ.get("BENCH_ACCUM_7B", "1") != "0":
            from distributed_llms_example_tpu.obs import memprof

            # peak_bytes_in_use is the allocator's PROCESS-LIFETIME
            # high-water mark (never reset), so every field derived from
            # it is named *_cumulative and each accumN entry also reports
            # the watermark delta vs its own pre-pass mark(): delta 0
            # proves the pass stayed under the historical peak (the
            # memory-flatness claim), delta > 0 is the new high water
            # this pass alone set
            watermark = memprof.Watermark()

            def peak_gib():
                p = watermark.peak_bytes()
                return round(p / memprof.GIB, 2) if p else None

            accum_list = [
                int(x)
                for x in os.environ.get("BENCH_ACCUM_7B_STEPS", "4,16").split(",")
            ]
            accum_report = {
                "note": (
                    f"measured at depth {L} (full-width layers, the same "
                    "truncated-depth methodology as the headline): in-step "
                    "scan accumulation, microbatch "
                    f"{batch * n_chips}, one optimizer apply per step"
                ),
                "microbatch": batch * n_chips,
                "accum1_step_ms": round(step_ms[L], 1),
            }
            p = peak_gib()
            if p is not None:
                accum_report["accum1_peak_hbm_gib_cumulative"] = p
            for N in accum_list:
                watermark.mark()
                rows = batch * n_chips * N
                idsN = rng.randint(2, base.vocab_size, (rows, seq)).astype(np.int32)
                labelsN = idsN.copy()
                labelsN[:, : seq // 4] = LABEL_PAD
                bN = {
                    "input_ids": idsN,
                    "attention_mask": np.ones_like(idsN),
                    "labels": labelsN,
                }
                try:
                    buildN = make_train_step(
                        module, cfg, tx, schedule, mesh,
                        is_seq2seq=False, grad_accum_steps=N,
                        optim_spec=optim_spec, optim_impl=optim_impl,
                    )
                    stepN, _ = buildN(state)
                    gbN = put_batch(bN, mesh)
                    state, mN = stepN(state, gbN)  # compile + warmup
                    _ = float(jax.device_get(mN["loss"]))
                    tN = []
                    for _ in range(steps):
                        t0 = time.perf_counter()
                        state, mN = stepN(state, gbN)
                        _ = float(jax.device_get(mN["loss"]))
                        tN.append(time.perf_counter() - t0)
                    tN_ms = sorted(tN)[len(tN) // 2] * 1e3
                    ideal = N * step_ms[L]
                    entry = {
                        "effective_batch": rows,
                        "step_ms": round(tN_ms, 1),
                        "per_microbatch_ms": round(tN_ms / N, 2),
                        # tokens/sec/chip ratio vs accum=1 at equal token
                        # throughput accounting == ideal/actual; the
                        # acceptance bar is >= 0.95 at accum=4
                        "tokens_per_sec_vs_accum1": round(ideal / tN_ms, 3),
                        "overhead_frac_vs_ideal_linear": round(tN_ms / ideal - 1.0, 4),
                    }
                    if N == 4:
                        entry["ok_95pct"] = bool(ideal / tN_ms >= 0.95)
                    p = peak_gib()
                    if p is not None:
                        entry["peak_hbm_gib_cumulative"] = p
                        delta = watermark.delta_bytes()
                        if delta is not None:
                            # 0.0 == this pass stayed under the lifetime
                            # peak: the constant-memory acceptance signal
                            entry["peak_hbm_new_high_water_gib"] = round(
                                delta / memprof.GIB, 2
                            )
                    accum_report[f"accum{N}"] = entry
                    del gbN, mN
                except Exception as e:
                    accum_report[f"accum{N}"] = {"error": str(e)[:300]}
                    # a failure mid-step may have consumed the donated
                    # state; rebuild it so the next N measures (or OOMs)
                    # on its own terms instead of 'Array has been deleted'.
                    # Drop the dead tree and this N's batch FIRST — on an
                    # OOM before donation, old + replacement living at
                    # once would OOM the rebuild too
                    state = None
                    gbN = None
                    state = jax.tree.map(
                        lambda x, s: jax.device_put(x, s),
                        create_train_state(
                            jax.jit(
                                init_params,
                                out_shardings=infer_param_shardings(shapes, mesh),
                            )(),
                            tx,
                        ),
                        sh,
                    )
        del state, params, gb  # free ~11 GB before the next depth

    l_lo, l_hi = min(depths), max(depths)
    per_layer = (step_ms[l_hi] - step_ms[l_lo]) / (l_hi - l_lo)
    overhead = step_ms[l_lo] - l_lo * per_layer
    if per_layer <= 0:
        # a non-positive slope means a polluted measurement, not physics —
        # refuse to extrapolate garbage into the artifact
        print(json.dumps({
            "metric": "llama-2-7b depth-extrapolated throughput",
            "value": None,
            "unit": "tokens/sec/chip (extrapolated)",
            "vs_baseline": None,
            "error": "non-positive per-layer slope: measurement polluted, re-run",
            "measured_step_ms": {str(k): round(v, 1) for k, v in step_ms.items()},
        }))
        return
    t_full_ms = overhead + base.num_hidden_layers * per_layer
    tps_chip = tokens_per_step / (t_full_ms / 1e3) / n_chips
    # same analytic method as the 406M baseline constant: 6·N FLOPs/token at
    # 35% utilization of a 312 TFLOP/s bf16 A100 → ~2,700 tok/s/GPU at 6.74B
    baseline_7b = 312e12 * 0.35 / (6.0 * 6.74e9)
    # per-variant intercept fits: the same two-point depth fit as the
    # headline, so each variant's non_layer_overhead_ms delta attributes
    # the headline's intercept move to its component (optimizer impl / CE)
    variants_out: dict = {}
    for v, per_depth in variant_ms.items():
        ok = {k: x for k, x in per_depth.items() if isinstance(x, (int, float))}
        if l_lo in ok and l_hi in ok:
            vl = (ok[l_hi] - ok[l_lo]) / (l_hi - l_lo)
            vo = ok[l_lo] - l_lo * vl
            variants_out[v] = {
                "measured_step_ms": {str(k): round(x, 1) for k, x in ok.items()},
                "per_layer_ms": round(vl, 2),
                "non_layer_overhead_ms": round(vo, 2),
                "overhead_delta_ms_vs_headline": round(vo - overhead, 2),
            }
        elif per_depth:
            variants_out[v] = {
                "measured": {str(k): x for k, x in per_depth.items()}
            }
    print(
        json.dumps(
            {
                "grad_compression": "off",
                "metric": f"llama-2-7b causal-LM fine-tune throughput, depth-extrapolated "
                          f"from measured {depths}-layer full-width steps "
                          f"(seq {seq}, bf16+remat[{policy}]"
                          f"{'+fused_ce' if fused_ce else ''}, batch {batch}, "
                          f"optim {resolved_optim})",
                "value": round(tps_chip, 1),
                "unit": "tokens/sec/chip (extrapolated)",
                "vs_baseline": round(tps_chip / baseline_7b, 3),
                "extrapolated_step_ms": round(t_full_ms, 1),
                "per_layer_ms": round(per_layer, 2),
                "non_layer_overhead_ms": round(overhead, 2),
                "measured_step_ms": {str(k): round(v, 1) for k, v in step_ms.items()},
                "chips": n_chips,
                "backend": jax.default_backend(),
                # the headline's optimizer impl (--optim-impl auto resolves
                # to the fused Pallas apply on TPU) + the same-session
                # component A/Bs: per-variant intercept fits and the
                # stand-alone optimizer-apply wall per impl
                "optim_impl": resolved_optim,
                **({"optimizer_apply_ms": optim_probe_ms} if optim_probe_ms else {}),
                **({"variants": variants_out} if variants_out else {}),
                # stamped even when the sweep is disabled/failed, so the
                # record always says which accumulation config it measured
                "grad_accum_steps": 1,
                **({"grad_accum": accum_report} if accum_report else {}),
            }
        )
    )


def _host_input_main() -> None:
    """BENCH_MODE=host-input: batch-assembly throughput, host only.

    A v5e-8 host must feed 8 chips at the measured per-chip rate
    (~60k tok/s each ⇒ ~483k tok/s of assembled batches) through ONE
    prefetch thread running tokenize + pad + bucket.  This measures that
    assembly path in isolation — no devices touched — for both the
    dependency-free byte tokenizer and a real HF fast (byte-level BPE)
    tokenizer trained in-process (no egress), at the headline shape
    (src 1024 / tgt 128 buckets, host batch = 8 chips × 16/chip).
    Token counting matches Trainer._batch_tokens (non-pad source +
    target), so the margin vs the device rate is apples-to-apples."""
    import tempfile

    import numpy as np

    from distributed_llms_example_tpu.data.batching import LABEL_PAD, BatchIterator
    from distributed_llms_example_tpu.data.dataset import SummarizationDataset
    from distributed_llms_example_tpu.data.tokenizer import ByteTokenizer, HFTokenizer

    steps = max(4, int(os.environ.get("BENCH_HOST_STEPS", "12")))
    batch = int(os.environ.get("BENCH_HOST_BATCH", str(16 * 8)))
    chip_rate = float(os.environ.get("BENCH_HOST_CHIP_RATE", "60343"))  # BENCH_r04
    n_chips = int(os.environ.get("BENCH_HOST_CHIPS", "8"))
    target = chip_rate * n_chips
    rng = np.random.RandomState(11)

    def text(n_chars: int) -> str:
        words = []
        total = 0
        while total < n_chars:
            w = "".join(chr(97 + rng.randint(26)) for _ in range(3 + rng.randint(6)))
            words.append(w)
            total += len(w) + 1
        return " ".join(words)[:n_chars]

    records = [{"dialogue": text(1016), "summary": text(120)} for _ in range(batch * steps)]

    def build_bpe(tmp: str):
        # a real transformers fast tokenizer (rust BPE), trained on the
        # fixture corpus so no assets are needed — same construction as
        # tests/test_tokenizer_parity.py
        from tokenizers import Tokenizer as TK, models, pre_tokenizers, processors
        from tokenizers.trainers import BpeTrainer
        from transformers import PreTrainedTokenizerFast

        tok = TK(models.BPE(unk_token="<unk>"))
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        trainer = BpeTrainer(
            special_tokens=["<s>", "<pad>", "</s>", "<unk>"],
            vocab_size=int(os.environ.get("BENCH_HOST_BPE_VOCAB", "8000")),
        )
        corpus = (r["dialogue"] + " " + r["summary"] for r in records)
        tok.train_from_iterator(corpus, trainer)
        bos, eos = tok.token_to_id("<s>"), tok.token_to_id("</s>")
        tok.post_processor = processors.TemplateProcessing(
            single="<s> $A </s>", pair="<s> $A </s> $B </s>",
            special_tokens=[("<s>", bos), ("</s>", eos)],
        )
        fast = PreTrainedTokenizerFast(
            tokenizer_object=tok, bos_token="<s>", eos_token="</s>",
            pad_token="<pad>", unk_token="<unk>",
        )
        fast.save_pretrained(tmp)
        return HFTokenizer(tmp)

    result = {
        "grad_compression": "off",
        "metric": f"host batch-assembly throughput (tokenize+pad+bucket, no devices; "
                  f"host batch {batch}, src1024/tgt128) vs the ~{target / 1e3:.0f}k tok/s "
                  f"a v5e-{n_chips} host must feed at {chip_rate / 1e3:.1f}k tok/s/chip",
        "unit": "host tokens/sec",
        "vs_baseline": None,
        "target_tokens_per_sec": round(target),
        "chips_assumed": n_chips,
        # the HF number scales with cores: encode_batch fans across them
        # (rayon), and this machine is the FLOOR — a real v5e-8 host has
        # ~100 vCPUs where one batch call parallelizes
        "host_cpus": os.cpu_count(),
    }
    with tempfile.TemporaryDirectory() as tmp:
        for label, tokzr in (("byte", ByteTokenizer()), ("hf_bpe", build_bpe(tmp))):
            ds = SummarizationDataset(
                records, tokzr, max_source_length=1024, max_target_length=128
            )
            it = BatchIterator(
                ds, global_batch=batch, seed=0,
                bucket_multiple=128, max_source_length=1024, max_target_length=128,
            )
            for warm in range(2):
                ds._cache = [None] * len(ds)  # cold tokenizer cache each pass
                t0 = time.perf_counter()
                tokens = 0
                for b in it.epoch(0):
                    tokens += int(np.sum(b["attention_mask"]))
                    tokens += int(np.sum(b["labels"] != LABEL_PAD))
                dt = time.perf_counter() - t0
            rate = tokens / dt
            result[f"{label}_tokens_per_sec"] = round(rate)
            result[f"{label}_margin_vs_target"] = round(rate / target, 2)
    # headline value = the slower (realistic HF) tokenizer's rate
    result["value"] = result["hf_bpe_tokens_per_sec"]
    print(json.dumps(result))


def _generate_main() -> None:
    """BENCH_MODE=generate: jitted eval-generation throughput on the
    flagship seq2seq model.  The reference's live eval loop spends roughly
    half its wall clock inside beam-2 ``generate()`` (reference
    train-accelerator.py:245-249); this measures that exact contract
    on-chip — beam-2, src 1024 / max_new 128 — reporting generated
    tokens/sec/chip plus the prefill(encode)/decode split.  Weights are
    randomly initialized (no egress): the decode loop is a fixed-trip-count
    ``fori_loop``, so throughput is content-independent."""
    import jax
    import numpy as np

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.evaluation.generation import (
        make_beam_search,
        make_greedy_generate,
    )
    from distributed_llms_example_tpu.parallel.activation import activation_mesh
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    name, lm, _ = _flagship()
    n_chips = jax.device_count()
    mesh = build_mesh(MeshConfig(data=-1))
    src_len = int(os.environ.get("BENCH_GEN_SRC", "1024"))
    new_tokens = int(os.environ.get("BENCH_GEN_NEW", "128"))
    beams = int(os.environ.get("BENCH_GEN_BEAMS", "2"))
    batch = int(os.environ.get("BENCH_GEN_BATCH", "16")) * n_chips
    reps = max(1, int(os.environ.get("BENCH_STEPS", "3")))

    params = lm.params if lm.params is not None else jax.device_get(lm.init_params(0))
    params = shard_params(params, mesh)
    if beams > 1:
        gen = make_beam_search(lm.module, lm.config, new_tokens, beams)
    else:
        gen = make_greedy_generate(lm.module, lm.config, new_tokens)
    jgen = jax.jit(gen)
    jenc = jax.jit(
        lambda p, ids, m: lm.module.apply({"params": p}, ids, m, method="encode")
    )

    rng = np.random.RandomState(0)
    ids = jax.numpy.asarray(
        rng.randint(2, min(lm.config.vocab_size, 30000), (batch, src_len)).astype(np.int32)
    )
    mask = jax.numpy.ones((batch, src_len), jax.numpy.int32)

    with activation_mesh(mesh):
        out = jgen(params, ids, mask)  # compile + warmup
        _ = np.asarray(out)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = jgen(params, ids, mask)
        _ = np.asarray(out)
        dt_total = (time.perf_counter() - t0) / reps

        enc = jenc(params, ids, mask)  # compile + warmup
        _ = np.asarray(jax.device_get(enc.ravel()[0]))
        t0 = time.perf_counter()
        for _ in range(reps):
            enc = jenc(params, ids, mask)
        _ = np.asarray(jax.device_get(enc.ravel()[0]))
        dt_prefill = (time.perf_counter() - t0) / reps

    dt_decode = max(dt_total - dt_prefill, 1e-9)
    gen_tokens = batch * new_tokens  # fixed trip count: every row decodes L steps
    tps_chip = gen_tokens / dt_total / n_chips
    print(json.dumps({
        "grad_compression": "off",
        "metric": f"{name} eval generation throughput (beam {beams}, src {src_len} "
                  f"/ max_new {new_tokens}, bf16, batch {batch}) — the reference's "
                  "live eval contract (train-accelerator.py:245-249); no reference "
                  "number exists to compare against (BASELINE.md: none published)",
        "value": round(tps_chip, 1),
        "unit": "generated tokens/sec/chip",
        "vs_baseline": None,
        "examples_per_sec_chip": round(batch / dt_total / n_chips, 2),
        "prefill_ms": round(dt_prefill * 1e3, 1),
        "decode_ms": round(dt_decode * 1e3, 1),
        "decode_ms_per_token": round(dt_decode * 1e3 / new_tokens, 3),
        "decode_tokens_per_sec_chip": round(gen_tokens / dt_decode / n_chips, 1),
        "chips": n_chips,
        "backend": jax.default_backend(),
    }))


def _serve_measure(
    lm, mesh, sharded, *,
    slots: int, src: int, new_tokens: int, n_req: int, eval_beams: int,
) -> dict:
    """The serving measurements, shared by BENCH_MODE=serve and the main
    bench's ``serve`` add-on: continuous-batching decode tokens/sec/chip
    and TTFT (serving/engine.py), the continuous-vs-static utilization A/B
    at per-request token budgets, the ROUGE-eval-path A/B (OLD contract:
    params replicated onto one device, whole-batch generate — vs the
    sharded prefill/decode split the Evaluator now rides), and the decode
    composition-matrix rows for fsdp/tensor/stage/sequence mesh shapes.
    Same session, same requests; weights are randomly initialized —
    greedy/beam decode is deterministic and throughput content-independent."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_llms_example_tpu.analysis.composition import failing_combos
    from distributed_llms_example_tpu.evaluation.generation import (
        CausalGenerator,
        Seq2SeqGenerator,
    )
    from distributed_llms_example_tpu.parallel.activation import activation_mesh
    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
        make_static_runner,
    )

    n_chips = jax.device_count()
    rng = np.random.RandomState(0)
    vocab_hi = min(lm.config.vocab_size, 30000)
    requests = [
        list(rng.randint(4, vocab_hi, rng.randint(max(src // 2, 8), src + 1)))
        for _ in range(n_req)
    ]
    # per-request token budgets (the serving max_tokens knob): varied, so
    # continuous batching's slot refill has something to exploit and the
    # static path's pay-max-L-per-row cost is visible
    budgets = [int(b) for b in rng.randint(max(new_tokens // 4, 1), new_tokens + 1, n_req)]

    # the goodput SLO the router tier dispatches on: useful tokens/sec +
    # attainment at this first-token threshold ride the serve block (and
    # the serve_summary event) — BENCH_TTFT_SLO_MS overrides per round
    ttft_slo_ms = float(os.environ.get("BENCH_TTFT_SLO_MS", "500"))
    engine = ServingEngine(
        lm.module, lm.config, mesh,
        ServeConfig(
            max_slots=slots, prefill_batch=slots,
            max_new_tokens=new_tokens, max_source_length=src,
            log_every_steps=0, ttft_slo_ms=ttft_slo_ms,
        ),
        is_seq2seq=lm.is_seq2seq,
    )
    engine.generate(sharded, requests[: slots], max_new=budgets[: slots])  # compile+warm
    t0 = time.perf_counter()
    headline_outs = engine.generate(sharded, requests, max_new=budgets)
    serve_s = time.perf_counter() - t0
    stats = engine.last_stats

    # static contract on the SAME workload: every chunk row decodes the
    # full max_new_tokens no matter when its budget is met — timed through
    # the very runner the determinism test pins (serving/engine.py)
    static_all = make_static_runner(
        lm.module, lm.config, mesh,
        max_new_tokens=new_tokens, width=src, batch=slots,
        is_seq2seq=lm.is_seq2seq,
    )

    def run_static() -> float:
        t0 = time.perf_counter()
        static_all(sharded, requests)
        return time.perf_counter() - t0

    run_static()  # compile+warm
    static_s = run_static()
    useful_tokens = sum(budgets)
    static_rows = slots * math.ceil(n_req / slots)
    serve_tps_chip = stats.tokens_per_sec() / n_chips
    ttft_p50, ttft_p95 = stats.ttft_percentiles()

    # ROUGE-eval-path A/B (the Evaluator's generation cost): OLD = params
    # replicated onto ONE device (host copy → default placement), the
    # whole-batch program traced with no mesh — the seed's single-device
    # decode; NEW = the sharded prefill/decode split the Evaluator uses.
    eval_batch = slots
    ids = np.full((eval_batch, src), lm.config.pad_token_id, np.int32)
    mask = np.zeros((eval_batch, src), np.int32)
    for r in range(eval_batch):
        req = requests[r % n_req][:src]
        ids[r, : len(req)] = req
        mask[r, : len(req)] = 1
    gen_cls = Seq2SeqGenerator if lm.is_seq2seq else CausalGenerator
    gen = gen_cls(lm.module, lm.config, new_tokens, num_beams=eval_beams)
    rouge_ab = {}
    try:
        # the whole tree RESIDENT on device 0 before timing — numpy args
        # would re-transfer every param on each call and bill the H2D copy
        # to the "single-device" leg
        old_params = jax.device_put(jax.device_get(sharded), jax.devices()[0])
        old_run = jax.jit(gen.run)
        with activation_mesh(None):
            np.asarray(old_run(old_params, jnp.asarray(ids), jnp.asarray(mask)))
            t0 = time.perf_counter()
            np.asarray(old_run(old_params, jnp.asarray(ids), jnp.asarray(mask)))
            old_s = time.perf_counter() - t0
        del old_params
        prefill = jax.jit(gen.prefill)
        decode = jax.jit(gen.decode_loop)
        finalize = jax.jit(gen.finalize)

        def run_new() -> float:
            with activation_mesh(mesh):
                carry = prefill(sharded, jnp.asarray(ids), jnp.asarray(mask))
                out = finalize(decode(sharded, carry))
            np.asarray(out)
            return 0.0

        run_new()
        t0 = time.perf_counter()
        run_new()
        new_s = time.perf_counter() - t0
        rouge_ab = {
            "beams": eval_beams,
            "batch": eval_batch,
            "old_single_device_s": round(old_s, 3),
            "sharded_split_s": round(new_s, 3),
            "speedup": round(old_s / max(new_s, 1e-9), 2),
        }
        if jax.default_backend() == "cpu":
            # forced host devices share ONE machine's cores: the
            # "single-device" leg still uses every thread via XLA intra-op
            # parallelism, so this A/B only separates on real accelerators
            rouge_ab["note"] = (
                "cpu backend: virtual devices share one host's cores — the "
                "single-device leg is not resource-constrained here"
            )
    except Exception as e:
        print(f"bench: rouge-eval A/B failed ({e})", file=sys.stderr)
        rouge_ab = {"error": str(e)[:300]}

    # decode × mesh composition rows — pure table evaluation, every shape
    # stamped whether or not this host can build the mesh
    flags = ("decode", "seq2seq" if lm.is_seq2seq else "causal")
    compo = {}
    for label, axes in (
        ("data", {"data": n_chips}),
        ("fsdp", {"fsdp": n_chips}),
        ("fsdp_tensor", {"fsdp": max(n_chips // 2, 1), "tensor": 2}),
        ("tensor", {"tensor": n_chips}),
        ("stage", {"stage": 2, "data": max(n_chips // 2, 1)}),
        ("sequence", {"sequence": 2, "data": max(n_chips // 2, 1)}),
    ):
        bad = failing_combos(flags=flags, mesh_axes=axes)
        compo[label] = "ok" if not bad else [row.id for row in bad]

    # decode-capacity block (ISSUE 13): int8 KV A/B on this model (token
    # parity at a tolerance + static footprint ratio), paged A/B when the
    # family is causal, capacity headline fields — all at the same mixed
    # prompt lengths as the headline run
    capacity = {}
    try:
        capacity = _serve_capacity(
            lm, mesh, sharded, requests, budgets,
            slots=slots, src=src, new_tokens=new_tokens,
            f32_stats=stats, f32_outs=headline_outs,
        )
    except Exception as e:
        print(f"bench: serve capacity block failed ({e})", file=sys.stderr)
        capacity = {"error": str(e)[:300]}

    return {
        "decode_tokens_per_sec_chip": round(serve_tps_chip, 1),
        "ttft_p50_ms": round(ttft_p50 * 1e3, 1),
        "ttft_p95_ms": round(ttft_p95 * 1e3, 1),
        # queue-wait vs prefill share of TTFT (serving request spans):
        # the explainable-p95 fields the serve_summary event also carries
        **stats.ttft_decomposition(),
        # goodput at the TTFT SLO (useful tokens/sec + attainment) — the
        # serve_summary fields the router open item dispatches on
        **stats.goodput,
        "slot_occupancy": round(stats.slot_occupancy, 4),
        "decode_steps": stats.decode_steps,
        "wall_s": round(serve_s, 2),
        "static_wall_s": round(static_s, 2),
        # useful tokens (the budget sum) per second, both paths — the
        # utilization A/B: static decodes max_new for EVERY padded row
        "continuous_useful_tokens_per_sec_chip": round(useful_tokens / serve_s / n_chips, 1),
        "static_useful_tokens_per_sec_chip": round(useful_tokens / static_s / n_chips, 1),
        "continuous_vs_static": round(static_s / max(serve_s, 1e-9), 2),
        "static_row_utilization": round(useful_tokens / (static_rows * new_tokens), 4),
        "rouge_eval_ab": rouge_ab,
        "decode_composition": compo,
        "capacity": capacity,
        "slots": slots,
        "src_len": src,
        "max_new_tokens": new_tokens,
        "requests": n_req,
    }


def _token_match_rate(a_rows, b_rows, eos, pad) -> float:
    """Greedy prefix agreement between two decode paths: positionwise
    match over the eos-trimmed common prefix length.  A single near-tie
    argmax flip cascades (every later token conditions on it), so this is
    the CONSERVATIVE tolerance metric — per-step teacher-forced agreement
    is strictly higher."""
    from distributed_llms_example_tpu.serving.engine import trim_eos

    match = total = 0
    for a, b in zip(a_rows, b_rows):
        ta, tb = trim_eos(a, eos, pad), trim_eos(b, eos, pad)
        n = min(len(ta), len(tb))
        total += max(len(ta), len(tb))
        match += sum(x == y for x, y in zip(ta[:n], tb[:n]))
    return match / max(total, 1)


def _serve_capacity(
    lm, mesh, sharded, requests, budgets, *,
    slots: int, src: int, new_tokens: int, f32_stats, f32_outs,
) -> dict:
    """The decode-capacity A/Bs: int8 KV vs the f32 headline engine
    (token-parity at a tolerance + >= 3.5x static footprint reduction),
    and — causal families — paged vs flat (BIT-identical tokens,
    bytes-per-token scaling with actual prompt length).  Static byte
    accounting throughout (serving/cache_pool.py tree_bytes): capacity
    claims are measured off the state trees, not inferred; HBM/bandwidth
    wall-clock verdicts land on the TPU round."""
    import jax

    from distributed_llms_example_tpu.serving import cache_pool
    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
    )

    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    base_kw = dict(
        max_slots=slots, prefill_batch=slots, max_new_tokens=new_tokens,
        max_source_length=src, log_every_steps=0, request_spans=False,
    )

    def run(**kw):
        eng = ServingEngine(
            lm.module, lm.config, mesh, ServeConfig(**base_kw, **kw),
            is_seq2seq=lm.is_seq2seq,
        )
        outs = eng.generate(sharded, requests, max_new=budgets)
        return eng, outs

    out = {
        # the f32 flat baseline's capacity headline: a full-width slot set
        "max_sustained_slots": slots,
        "cache_bytes_per_token": round(f32_stats.bytes_per_live_token, 1),
        "cache_bytes_resident": f32_stats.cache_bytes_resident,
    }

    i8_eng, i8_outs = run(kv_cache_dtype="int8")
    out["int8_vs_f32_kv"] = {
        "token_match_rate": round(
            _token_match_rate(f32_outs, i8_outs, eos, pad), 4
        ),
        "cache_bytes_ratio": round(
            f32_stats.cache_bytes_resident
            / max(i8_eng.last_stats.cache_bytes_resident, 1),
            3,
        ),
        "cache_bytes_per_token_f32": round(
            f32_stats.bytes_per_live_token, 1
        ),
        "cache_bytes_per_token_int8": round(
            i8_eng.last_stats.bytes_per_live_token, 1
        ),
        "decode_tokens_per_sec_chip_int8": round(
            i8_eng.last_stats.tokens_per_sec() / max(jax.device_count(), 1), 1
        ),
    }
    if lm.is_seq2seq:
        out["paged_vs_flat"] = {
            "note": (
                "paged_kv applies to the causal KV cache; the seq2seq "
                "slot state is encoder output + cross-KV — see the "
                "standalone causal paged record"
            )
        }
        return out

    # kv_block_size=0: the engine picks the largest valid block — it must
    # tile the cache width AND the admission bucket, a constraint the
    # engine owns (gcd-based auto default)
    pg_eng, pg_outs = run(paged_kv=True)
    bs = pg_eng.block_size
    mean_blocks = sum(
        cache_pool.blocks_needed(min(len(r), src), b, bs)
        for r, b in zip(requests, budgets)
    ) / max(len(requests), 1)
    out["paged_vs_flat"] = {
        # the acceptance pin: paged tokens are BIT-identical to flat
        "bit_identical": list(pg_outs) == list(f32_outs),
        "kv_block_size": bs,
        "pool_blocks": pg_eng.pool.num_blocks,
        "cache_bytes_per_token_flat": round(
            f32_stats.bytes_per_live_token, 1
        ),
        # scales with ACTUAL prompt length: live blocks / live tokens
        "cache_bytes_per_token_paged": round(
            pg_eng.last_stats.bytes_per_live_token, 1
        ),
        "admit_deferrals": pg_eng.last_stats.admit_deferrals,
        # what the SAME pool memory sustains at this workload's mix —
        # the concurrency headroom paging converts padding into
        "max_sustained_slots": int(pg_eng.pool.num_blocks // max(mean_blocks, 1)),
    }
    out["max_sustained_slots"] = max(
        out["max_sustained_slots"], out["paged_vs_flat"]["max_sustained_slots"]
    )
    return out


def _serve_main() -> None:
    """BENCH_MODE=serve: the full-size standalone serving record on the
    flagship seq2seq model (see ``_serve_measure``)."""
    import jax

    from distributed_llms_example_tpu.core.config import MeshConfig, parse_mesh_arg
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    name, lm, _ = _flagship()
    n_chips = jax.device_count()
    mesh_spec = os.environ.get("BENCH_SERVE_MESH", "")
    mesh = build_mesh(parse_mesh_arg(mesh_spec) if mesh_spec else MeshConfig(data=-1))
    batch_shards = 1
    for a in ("data", "fsdp", "expert"):
        batch_shards *= mesh.shape.get(a, 1)
    src = int(os.environ.get("BENCH_SERVE_SRC", "1024"))
    new_tokens = int(os.environ.get("BENCH_SERVE_NEW", "64"))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS_PER_SHARD", "4")) * batch_shards
    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", str(3 * slots)))
    eval_beams = int(os.environ.get("BENCH_SERVE_EVAL_BEAMS", "2"))
    params = lm.params if lm.params is not None else jax.device_get(lm.init_params(0))
    sharded = shard_params(params, mesh)
    serve = _serve_measure(
        lm, mesh, sharded,
        slots=slots, src=src, new_tokens=new_tokens, n_req=n_req,
        eval_beams=eval_beams,
    )
    # the flagship is seq2seq, whose slot state has no causal cache to
    # page — run the paged_vs_flat acceptance A/B on a causal model at the
    # same mixed prompt lengths (random init: greedy decode is
    # deterministic and the bit-identity/footprint claims are
    # weight-independent)
    if lm.is_seq2seq and os.environ.get("BENCH_SERVE_PAGED_AB", "1") != "0":
        try:
            causal_name = os.environ.get("BENCH_SERVE_CAUSAL", "llama-test")
            from distributed_llms_example_tpu.models.registry import load_model

            clm = load_model(causal_name)
            cparams = shard_params(
                clm.params if clm.params is not None else clm.init_params(0),
                mesh,
            )
            crng = __import__("numpy").random.RandomState(1)
            c_src, c_new = 64, 16
            c_slots = max(2, batch_shards)
            c_reqs = [
                list(crng.randint(4, min(clm.config.vocab_size, 1000),
                                  crng.randint(max(c_src // 4, 4), c_src + 1)))
                for _ in range(3 * c_slots)
            ]
            c_budgets = [int(b) for b in crng.randint(c_new // 2, c_new + 1, len(c_reqs))]
            from distributed_llms_example_tpu.serving.engine import (
                ServeConfig as _SC,
                ServingEngine as _SE,
            )

            base = dict(max_slots=c_slots, prefill_batch=c_slots,
                        max_new_tokens=c_new, max_source_length=c_src,
                        log_every_steps=0, request_spans=False)
            flat_eng = _SE(clm.module, clm.config, mesh, _SC(**base),
                           is_seq2seq=False)
            flat_outs = flat_eng.generate(cparams, c_reqs, max_new=c_budgets)
            serve["paged_vs_flat_causal"] = {
                "model": causal_name,
                **_serve_capacity(
                    clm, mesh, cparams, c_reqs, c_budgets,
                    slots=c_slots, src=c_src, new_tokens=c_new,
                    f32_stats=flat_eng.last_stats, f32_outs=flat_outs,
                ),
            }
        except Exception as e:
            print(f"bench: causal paged A/B failed ({e})", file=sys.stderr)
            serve["paged_vs_flat_causal"] = {"error": str(e)[:300]}
    print(json.dumps({
        "grad_compression": "off",
        "metric": f"{name} continuous-batching serving decode (slots {slots}, "
                  f"src {src} / max_new {new_tokens}, {n_req} requests with "
                  "varied per-request budgets) — serving/engine.py on mesh "
                  f"{mesh_spec or 'data=-1'}; no reference number exists "
                  "(BASELINE.md: none published)",
        "value": serve["decode_tokens_per_sec_chip"],
        "unit": "decode tokens/sec/chip",
        "vs_baseline": None,
        **{k: v for k, v in serve.items() if k != "decode_tokens_per_sec_chip"},
        "chips": n_chips,
        "backend": jax.default_backend(),
    }))


def _router_measure(
    lm, mesh, sharded, *,
    replicas: int, slots: int, src: int, new_tokens: int, n_req: int,
) -> dict:
    """Degraded-mode serving throughput (ISSUE 15): the same workload
    through the replica router twice — an unfailed ORACLE pass, then a
    pass with ``replica_crash`` injected at the oracle's halfway tick —
    stamping p99 TTFT and goodput BEFORE / DURING / AFTER the kill
    (phases cut at the router's failure / recovered instants), the
    request-level MTTR and retry counts, and the bit-identity verdict
    (greedy tokens of the failed run == the unfailed oracle's).  Engines
    are built once and reused across both passes (compiled programs are
    per-engine; a router 'crash' discards only session state)."""
    import numpy as np

    from distributed_llms_example_tpu.obs.chaos import parse_chaos
    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
    )
    from distributed_llms_example_tpu.serving.router import (
        ReplicaRouter,
        RouterConfig,
    )

    rng = np.random.RandomState(0)
    vocab_hi = min(lm.config.vocab_size, 30000)
    requests = [
        list(rng.randint(4, vocab_hi, rng.randint(max(src // 2, 8), src + 1)))
        for _ in range(n_req)
    ]
    budgets = [
        int(b)
        for b in rng.randint(max(new_tokens // 4, 1), new_tokens + 1, n_req)
    ]
    engines = [
        ServingEngine(
            lm.module, lm.config, mesh,
            ServeConfig(
                max_slots=slots, prefill_batch=slots,
                max_new_tokens=new_tokens, max_source_length=src,
                log_every_steps=0, request_spans=False,
            ),
            is_seq2seq=lm.is_seq2seq,
        )
        for _ in range(replicas)
    ]
    # oracle pass: unfailed run — the bit-identity reference AND the
    # compile/warm pass (both routers share the engines' programs)
    oracle = ReplicaRouter(engines, sharded, RouterConfig(log_every_ticks=0))
    oracle_outs = oracle.serve(requests, max_new=budgets)
    kill_tick = max(2, oracle.ticks // 2)
    for r in oracle.replicas:
        # only ticks + outputs are needed past this point: drop the
        # oracle sessions' serving state so the injected pass doesn't
        # hold 2x replicas worth of KV cache resident
        r.session = None
    injected = ReplicaRouter(
        engines, sharded,
        RouterConfig(
            log_every_ticks=0,
            chaos=parse_chaos(f"replica_crash@{kill_tick}"),
        ),
    )
    t0 = time.perf_counter()
    outs = injected.serve(requests, max_new=budgets)
    wall = time.perf_counter() - t0
    summary = injected.last_stats or {}
    rows = [r for r in injected.request_rows() if not r["synthetic"]]
    t_fail = summary.get("t_fail_s")
    t_rec = summary.get("t_recovered_s", t_fail)

    def phase_stats(lo: float, hi: float) -> dict:
        from distributed_llms_example_tpu.obs.spans import percentiles

        done = [
            r for r in rows
            if r["done_s"] is not None and lo <= r["done_s"] < hi
        ]
        ttfts = [r["ttft_s"] for r in done if r["ttft_s"] is not None]
        dur = max(hi - lo, 1e-9)
        (p99,) = percentiles(ttfts, (0.99,))
        return {
            "requests": len(done),
            "ttft_p99_ms": round(p99 * 1e3, 1) if ttfts else None,
            "goodput_tokens_per_sec": round(
                sum(r["tokens"] for r in done) / dur, 1
            ),
        }

    out: dict = {
        "replicas": replicas,
        "kill_tick": kill_tick,
        "retries": summary.get("retries"),
        "request_retry_rate": summary.get("request_retry_rate"),
        "request_mttr_s": summary.get("request_mttr_s"),
        "goodput_frac": summary.get("goodput_frac"),
        "completed": summary.get("completed"),
        "shed": summary.get("shed"),
        # the acceptance verdict: a mid-decode replica kill loses nothing
        # and changes no tokens (greedy re-prefill == unfailed oracle)
        "tokens_identical": outs == oracle_outs,
        "requests_lost": sum(
            1 for r in rows if r["done_s"] is None and not r["shed"]
        ),
        "wall_s": round(wall, 3),
    }
    if t_fail is not None:
        out["degraded"] = {
            "t_fail_s": t_fail,
            "t_recovered_s": t_rec,
            "before": phase_stats(0.0, t_fail),
            "during": phase_stats(t_fail, t_rec if t_rec > t_fail else t_fail),
            "after": phase_stats(t_rec, wall + 1e-9),
        }
    return out


def _router_main() -> None:
    """BENCH_MODE=serve-router: the standalone degraded-mode serving
    record — replica router over the flagship model, p99 TTFT + goodput
    before/during/after an injected replica kill."""
    import jax

    from distributed_llms_example_tpu.core.config import MeshConfig, parse_mesh_arg
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    name, lm, _ = _flagship()
    n_chips = jax.device_count()
    mesh_spec = os.environ.get("BENCH_SERVE_MESH", "")
    mesh = build_mesh(parse_mesh_arg(mesh_spec) if mesh_spec else MeshConfig(data=-1))
    batch_shards = 1
    for a in ("data", "fsdp", "expert"):
        batch_shards *= mesh.shape.get(a, 1)
    src = int(os.environ.get("BENCH_ROUTER_SRC", "256"))
    new_tokens = int(os.environ.get("BENCH_ROUTER_NEW", "32"))
    slots = int(os.environ.get("BENCH_ROUTER_SLOTS_PER_SHARD", "2")) * batch_shards
    replicas = int(os.environ.get("BENCH_ROUTER_REPLICAS", "2"))
    n_req = int(os.environ.get("BENCH_ROUTER_REQUESTS", str(4 * replicas * slots)))
    params = lm.params if lm.params is not None else jax.device_get(lm.init_params(0))
    sharded = shard_params(params, mesh)
    record = _router_measure(
        lm, mesh, sharded,
        replicas=replicas, slots=slots, src=src, new_tokens=new_tokens,
        n_req=n_req,
    )
    print(json.dumps({
        "grad_compression": "off",
        "metric": f"{name} serve-router degraded-mode serving "
                  f"({replicas} replicas x {slots} slots, src {src} / "
                  f"max_new {new_tokens}, {n_req} requests, one replica "
                  "killed mid-decode) — serving/router.py on mesh "
                  f"{mesh_spec or 'data=-1'}; no reference number exists",
        "value": (record.get("degraded") or {}).get("after", {}).get(
            "goodput_tokens_per_sec"
        ),
        "unit": "goodput tokens/sec after recovery",
        "vs_baseline": None,
        **record,
        "chips": n_chips,
        "backend": jax.default_backend(),
    }))


def _loadgen_measure(
    lm, mesh, sharded, *,
    slots: int, src: int, new_tokens: int, n_req: int,
    process: str, seed: int, qps_grid: tuple, slo_ms: float,
    max_wall_s: float, replicas: int, chaos_spec: str,
) -> dict:
    """Open-loop QPS sweep (ISSUE 17) vs the closed-loop measurement of
    the SAME engine config.  The closed-loop pass (generate: submit all,
    drain) is what every previous serving bench reported — its offered
    rate is capped by the service rate, so it reads healthy even when
    the config would collapse under real traffic.  The open-loop sweep
    offers seeded arrivals that never wait for completions, so the same
    config gains a saturation knee, per-rate goodput/SLO-attainment, and
    TTFT-from-arrival percentiles.  Two extra stamps: the determinism
    pin (open-loop tokens at the top of the grid == the closed-loop
    oracle's — arrival timing moves latency, never tokens) and, when
    ``replicas >= 1``, a second sweep through the replica router with
    ``chaos_spec`` injected per point (degraded-mode numbers AT a
    stated offered load)."""
    import numpy as np

    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
    )
    from distributed_llms_example_tpu.serving.loadgen import (
        EngineTarget,
        LoadgenConfig,
        RouterTarget,
        arrival_schedule,
        drive_open_loop,
        sweep_qps,
    )

    rng = np.random.RandomState(0)
    vocab_hi = min(lm.config.vocab_size, 30000)
    requests = [
        list(rng.randint(4, vocab_hi, rng.randint(max(src // 2, 8), src + 1)))
        for _ in range(n_req)
    ]
    budgets = [
        int(b)
        for b in rng.randint(max(new_tokens // 4, 1), new_tokens + 1, n_req)
    ]
    serve_cfg = ServeConfig(
        max_slots=slots, prefill_batch=slots,
        max_new_tokens=new_tokens, max_source_length=src,
        log_every_steps=0, request_spans=False, ttft_slo_ms=slo_ms,
    )
    engine = ServingEngine(
        lm.module, lm.config, mesh, serve_cfg, is_seq2seq=lm.is_seq2seq
    )
    # closed-loop measurement of the same config — the number that can
    # NEVER expose queueing collapse (and the determinism oracle)
    t0 = time.perf_counter()
    oracle_outs = engine.generate(sharded, requests, max_new=budgets)
    closed_wall = max(time.perf_counter() - t0, 1e-9)
    closed_stats = engine.last_stats
    cfg = LoadgenConfig(
        process=process, seed=seed, qps_grid=qps_grid,
        ttft_slo_ms=slo_ms, max_wall_s=max_wall_s,
    )
    summary = sweep_qps(
        lambda: EngineTarget(engine.open(sharded)),
        requests, cfg, budgets=budgets,
    )
    # determinism pin: an uncapped open-loop run at the top of the grid
    # must produce the oracle's tokens bit-for-bit
    sess = engine.open(sharded)
    sched = arrival_schedule(
        process, qps=float(qps_grid[-1]), n=n_req, seed=seed,
    )
    drive_open_loop(EngineTarget(sess), requests, sched, budgets=budgets)
    open_outs = [sess.output(r) for r in range(n_req)]
    out: dict = {
        "closed_loop": {
            "wall_s": round(closed_wall, 3),
            "decode_tokens_per_sec": round(
                sum(len(o) for o in oracle_outs) / closed_wall, 1
            ),
            "slo_attainment": (
                (closed_stats.goodput or {}).get("slo_attainment")
                if closed_stats else None
            ),
        },
        "loadgen": summary,
        "tokens_identical_to_closed_loop": open_outs == oracle_outs,
    }
    if replicas >= 1:
        from distributed_llms_example_tpu.obs.chaos import parse_chaos
        from distributed_llms_example_tpu.serving.router import (
            ReplicaRouter,
            RouterConfig,
        )

        engines = [
            ServingEngine(
                lm.module, lm.config, mesh, serve_cfg,
                is_seq2seq=lm.is_seq2seq,
            )
            for _ in range(replicas)
        ]
        router_cfg = RouterConfig(
            log_every_ticks=0,
            chaos=parse_chaos(chaos_spec) if chaos_spec else None,
        )
        chaos_summary = sweep_qps(
            lambda: RouterTarget(ReplicaRouter(engines, sharded, router_cfg)),
            requests, cfg, budgets=budgets,
        )
        out["router_sweep"] = {
            "replicas": replicas,
            "chaos": chaos_spec or None,
            **chaos_summary,
        }
    return out


def _loadgen_main() -> None:
    """BENCH_MODE=serve-loadgen: the standalone open-loop load record —
    offered-QPS sweep over the flagship model with the closed-loop
    measurement of the same config stamped beside it."""
    import jax

    from distributed_llms_example_tpu.core.config import MeshConfig, parse_mesh_arg
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    name, lm, _ = _flagship()
    n_chips = jax.device_count()
    mesh_spec = os.environ.get("BENCH_SERVE_MESH", "")
    mesh = build_mesh(parse_mesh_arg(mesh_spec) if mesh_spec else MeshConfig(data=-1))
    batch_shards = 1
    for a in ("data", "fsdp", "expert"):
        batch_shards *= mesh.shape.get(a, 1)
    src = int(os.environ.get("BENCH_LOADGEN_SRC", "256"))
    new_tokens = int(os.environ.get("BENCH_LOADGEN_NEW", "32"))
    slots = int(os.environ.get("BENCH_LOADGEN_SLOTS_PER_SHARD", "2")) * batch_shards
    n_req = int(os.environ.get("BENCH_LOADGEN_REQUESTS", str(4 * slots)))
    process = os.environ.get("BENCH_LOADGEN_PROCESS", "poisson")
    seed = int(os.environ.get("BENCH_LOADGEN_SEED", "0"))
    qps_grid = tuple(
        float(q)
        for q in os.environ.get("BENCH_LOADGEN_QPS_GRID", "0.5,1,2,4,8").split(",")
        if q.strip()
    )
    slo_ms = float(os.environ.get("BENCH_LOADGEN_SLO_MS", "500"))
    max_wall_s = float(os.environ.get("BENCH_LOADGEN_MAX_WALL_S", "120"))
    replicas = int(os.environ.get("BENCH_LOADGEN_REPLICAS", "0"))
    chaos_spec = os.environ.get("BENCH_LOADGEN_CHAOS", "")
    params = lm.params if lm.params is not None else jax.device_get(lm.init_params(0))
    sharded = shard_params(params, mesh)
    record = _loadgen_measure(
        lm, mesh, sharded,
        slots=slots, src=src, new_tokens=new_tokens, n_req=n_req,
        process=process, seed=seed, qps_grid=qps_grid, slo_ms=slo_ms,
        max_wall_s=max_wall_s, replicas=replicas, chaos_spec=chaos_spec,
    )
    print(json.dumps({
        "grad_compression": "off",
        "metric": f"{name} open-loop load sweep ({process} arrivals, "
                  f"QPS grid {list(qps_grid)}, {n_req} requests/point, "
                  f"slots {slots}, src {src} / max_new {new_tokens}, "
                  f"TTFT SLO {slo_ms:.0f} ms) — serving/loadgen.py on "
                  f"mesh {mesh_spec or 'data=-1'}; no reference number "
                  "exists",
        "value": record["loadgen"].get("knee_qps"),
        "unit": "offered QPS at the saturation knee",
        "vs_baseline": None,
        **record,
        "chips": n_chips,
        "backend": jax.default_backend(),
    }))


def _prefix_measure(
    clm, mesh, cparams, *,
    slots: int, src: int, new_tokens: int,
    sessions: int, turns: int, seed: int, budget_gib: float,
) -> dict:
    """The prefix-cache A/B (ISSUE 19): the seeded chatbot shared-prefix
    mix (serving/loadgen.py ``chatbot_requests`` — shared system prompt,
    multi-turn growing histories, turn-major arrival) through the SAME
    paged engine config twice — cold (prefix cache off, the baseline
    every previous serving bench measured) and warm (``--prefix-cache``
    with an LRU warm-retention budget).  Stamps the acceptance pins:
    tokens bit-identical to cold, hit_rate, prefill_tokens_saved_frac,
    tokens/sec/chip and p95 TTFT for both legs."""
    import jax

    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
    )
    from distributed_llms_example_tpu.serving.loadgen import chatbot_requests

    requests, _keys = chatbot_requests(
        sessions=sessions, turns=turns, seed=seed,
        vocab=min(clm.config.vocab_size, 1000),
        system_len=max(src * 3 // 4, 8), user_len=(2, 4), reply_len=(2, 4),
        max_len=src,
    )
    base = dict(
        max_slots=slots, prefill_batch=slots, max_new_tokens=new_tokens,
        max_source_length=src, log_every_steps=0, request_spans=False,
        # block size 8, not the auto (largest-valid) default: match
        # granularity IS the block size — a turn's uncached delta is a
        # handful of tokens, and coarse blocks round every chain down.
        # Pool at 4x the slots' worst case: warm retention lives in the
        # pool's free headroom (evicted strictly at refcount 0 under
        # allocation pressure), and a worst-case-exact pool has none
        paged_kv=True, kv_block_size=8,
        pool_blocks=4 * slots * ((src + new_tokens) // 8),
    )
    n_chips = max(jax.device_count(), 1)

    def run(**kw):
        eng = ServingEngine(
            clm.module, clm.config, mesh, ServeConfig(**base, **kw),
            is_seq2seq=False,
        )
        t0 = time.perf_counter()
        outs = eng.generate(cparams, requests)
        return eng, outs, max(time.perf_counter() - t0, 1e-9)

    cold_eng, cold_outs, cold_wall = run()
    cs = cold_eng.last_stats
    warm_eng, warm_outs, warm_wall = run(
        prefix_cache=True, prefix_cache_budget_gib=budget_gib,
    )
    ws = warm_eng.last_stats
    _, c95 = cs.ttft_percentiles()
    _, w95 = ws.ttft_percentiles()
    return {
        "requests": len(requests),
        "chat_sessions": sessions,
        "chat_turns": turns,
        "kv_block_size": warm_eng.block_size,
        "prefix_cache_budget": budget_gib,
        # the acceptance pin: warm-path tokens == cold-start tokens
        "bit_identical": list(warm_outs) == list(cold_outs),
        "hit_rate": round(ws.prefix_hits / max(ws.prefix_lookups, 1), 4),
        "prefill_tokens_total": ws.prefill_tokens_total,
        "prefill_tokens_saved": ws.prefill_tokens_saved,
        "prefill_tokens_saved_frac": round(
            ws.prefill_tokens_saved / max(ws.prefill_tokens_total, 1), 4
        ),
        "decode_tokens_per_sec_chip": round(ws.tokens_per_sec() / n_chips, 1),
        "decode_tokens_per_sec_chip_cold": round(
            cs.tokens_per_sec() / n_chips, 1
        ),
        "ttft_p95_ms": round(w95 * 1e3, 1),
        "ttft_p95_ms_cold": round(c95 * 1e3, 1),
        "prefill_seconds": round(ws.prefill_seconds, 3),
        "prefill_seconds_cold": round(cs.prefill_seconds, 3),
        "wall_s": round(warm_wall, 3),
        "wall_s_cold": round(cold_wall, 3),
    }


def _prefix_main() -> None:
    """BENCH_MODE=serve-prefix: the standalone prefix-caching record —
    chatbot shared-prefix mix, warm vs cold, on a causal paged engine
    (the flagship is seq2seq; prefix caching shares the causal paged
    pool, so the record runs on BENCH_PREFIX_MODEL, default the
    registry's causal test model — random init is fine: greedy decode is
    deterministic and every claim here is weight-independent)."""
    import jax

    from distributed_llms_example_tpu.core.config import MeshConfig, parse_mesh_arg
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    name = os.environ.get("BENCH_PREFIX_MODEL", "llama-test")
    clm = load_model(name)
    if clm.is_seq2seq:
        raise SystemExit(
            f"BENCH_PREFIX_MODEL={name!r} is seq2seq; the prefix cache "
            "shares the causal paged pool — pick a causal model"
        )
    n_chips = jax.device_count()
    mesh_spec = os.environ.get("BENCH_SERVE_MESH", "")
    mesh = build_mesh(parse_mesh_arg(mesh_spec) if mesh_spec else MeshConfig(data=-1))
    batch_shards = 1
    for a in ("data", "fsdp", "expert"):
        batch_shards *= mesh.shape.get(a, 1)
    src = int(os.environ.get("BENCH_PREFIX_SRC", "64"))
    new_tokens = int(os.environ.get("BENCH_PREFIX_NEW", "16"))
    slots = int(os.environ.get("BENCH_PREFIX_SLOTS_PER_SHARD", "2")) * batch_shards
    sessions = int(os.environ.get("BENCH_PREFIX_SESSIONS", "6"))
    turns = int(os.environ.get("BENCH_PREFIX_TURNS", "5"))
    seed = int(os.environ.get("BENCH_PREFIX_SEED", "0"))
    budget_gib = float(os.environ.get("BENCH_PREFIX_BUDGET_GIB", "0.5"))
    params = clm.params if clm.params is not None else jax.device_get(clm.init_params(0))
    sharded = shard_params(params, mesh)
    record = _prefix_measure(
        clm, mesh, sharded,
        slots=slots, src=src, new_tokens=new_tokens,
        sessions=sessions, turns=turns, seed=seed, budget_gib=budget_gib,
    )
    print(json.dumps({
        "grad_compression": "off",
        "metric": f"{name} prefix-cache warm vs cold serving "
                  f"(chatbot mix: {sessions} sessions x {turns} turns, "
                  f"slots {slots}, src {src} / max_new {new_tokens}, "
                  f"warm budget {budget_gib} GiB) — serving/cache_pool.py "
                  f"content-hash block dedup on mesh {mesh_spec or 'data=-1'}; "
                  "no reference number exists",
        "value": record["prefill_tokens_saved_frac"],
        "unit": "fraction of prefill tokens served from cache",
        "vs_baseline": None,
        **{k: v for k, v in record.items() if k != "prefill_tokens_saved_frac"},
        "chips": n_chips,
        "backend": jax.default_backend(),
    }))


def _spec_measure(
    clm, mesh, cparams, *,
    slots: int, src: int, new_tokens: int,
    sessions: int, turns: int, seed: int,
    spec_tokens: int, draft_model: str,
) -> dict:
    """The speculative-decode A/B (ISSUE 20): the seeded chatbot mix
    through the SAME paged engine config twice — plain greedy (the
    baseline) and draft-then-verify (``--spec-tokens k``, n-gram
    self-drafting by default or ``draft_model`` through the registry).
    Both legs decode the mix's scripted per-turn reply lengths
    (``chatbot_requests(with_budgets=True)``) as per-request budgets, so
    the token counts are identical by construction — apples-to-apples.
    Stamps the acceptance pins: tokens bit-identical to plain,
    accepted_tokens_per_step (per-slot; plain decode ≡ 1.0),
    acceptance_rate, decode tok/s both legs and ``vs_plain`` (relative
    decode-throughput delta, positive = speculation won), p95 TTFT both
    legs (speculation must not touch prefill)."""
    import jax

    from distributed_llms_example_tpu.serving.engine import (
        ServeConfig,
        ServingEngine,
    )
    from distributed_llms_example_tpu.serving.loadgen import chatbot_requests

    requests, _keys, budgets = chatbot_requests(
        sessions=sessions, turns=turns, seed=seed,
        vocab=min(clm.config.vocab_size, 1000),
        system_len=max(src * 3 // 4, 8), user_len=(2, 4),
        # scripted replies span up to the decode cap: speculation needs
        # room (acceptance is clamped to budget - emitted - 1), and a
        # 2..4-token reply would pin every round to partial acceptance
        reply_len=(4, max(new_tokens, 5)),
        max_len=src, with_budgets=True,
    )
    base = dict(
        max_slots=slots, prefill_batch=slots, max_new_tokens=new_tokens,
        max_source_length=src, log_every_steps=0, request_spans=False,
        # same pool shape as the prefix A/B: block size 8 keeps rollback
        # granularity honest, 4x-worst-case headroom keeps admission off
        # the critical path
        paged_kv=True, kv_block_size=8,
        pool_blocks=4 * slots * ((src + new_tokens) // 8),
    )
    n_chips = max(jax.device_count(), 1)

    def run(**kw):
        eng = ServingEngine(
            clm.module, clm.config, mesh, ServeConfig(**base, **kw),
            is_seq2seq=False,
        )
        t0 = time.perf_counter()
        outs = eng.generate(cparams, requests, max_new=budgets)
        return eng, outs, max(time.perf_counter() - t0, 1e-9)

    plain_eng, plain_outs, plain_wall = run()
    ps = plain_eng.last_stats
    spec_eng, spec_outs, spec_wall = run(
        spec_tokens=spec_tokens, spec_draft_model=draft_model,
    )
    ss = spec_eng.last_stats
    _, p95_plain = ps.ttft_percentiles()
    _, p95_spec = ss.ttft_percentiles()
    plain_tps = ps.tokens_per_sec()
    spec_tps = ss.tokens_per_sec()
    return {
        "requests": len(requests),
        "chat_sessions": sessions,
        "chat_turns": turns,
        "decode_budget_tokens": int(sum(budgets)),
        "spec_tokens": spec_tokens,
        "spec_draft_model": draft_model or "ngram",
        # the acceptance pin: speculative tokens == plain greedy tokens
        "bit_identical": list(spec_outs) == list(plain_outs),
        "accepted_tokens_per_step": round(
            ss.spec_emitted / max(ss.spec_slot_rounds, 1), 4
        ),
        "acceptance_rate": round(
            ss.spec_accepted / max(ss.spec_drafted, 1), 4
        ),
        "spec_drafted_tokens": ss.spec_drafted,
        "spec_accepted_tokens": ss.spec_accepted,
        "decode_tokens_per_sec_chip": round(spec_tps / n_chips, 1),
        "decode_tokens_per_sec_chip_plain": round(plain_tps / n_chips, 1),
        "vs_plain": round(spec_tps / max(plain_tps, 1e-9) - 1.0, 4),
        "ttft_p95_ms": round(p95_spec * 1e3, 1),
        "ttft_p95_ms_plain": round(p95_plain * 1e3, 1),
        "wall_s": round(spec_wall, 3),
        "wall_s_plain": round(plain_wall, 3),
    }


def _spec_main() -> None:
    """BENCH_MODE=serve-spec: the standalone speculative-decode record —
    chatbot mix, spec vs plain, on a causal paged engine
    (BENCH_SPEC_MODEL, default the registry's causal test model — random
    init is fine: greedy decode is deterministic, the acceptance rule is
    argmax-exact, and every claim here is weight-independent; the tok/s
    delta is a TPU verdict, CPU pins correctness and the acceptance
    ledger)."""
    import jax

    from distributed_llms_example_tpu.core.config import MeshConfig, parse_mesh_arg
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    name = os.environ.get("BENCH_SPEC_MODEL", "llama-test")
    clm = load_model(name)
    if clm.is_seq2seq:
        raise SystemExit(
            f"BENCH_SPEC_MODEL={name!r} is seq2seq; speculation verifies "
            "through the causal decode path — pick a causal model"
        )
    n_chips = jax.device_count()
    mesh_spec = os.environ.get("BENCH_SERVE_MESH", "")
    mesh = build_mesh(parse_mesh_arg(mesh_spec) if mesh_spec else MeshConfig(data=-1))
    batch_shards = 1
    for a in ("data", "fsdp", "expert"):
        batch_shards *= mesh.shape.get(a, 1)
    src = int(os.environ.get("BENCH_SPEC_SRC", "64"))
    new_tokens = int(os.environ.get("BENCH_SPEC_NEW", "16"))
    slots = int(os.environ.get("BENCH_SPEC_SLOTS_PER_SHARD", "2")) * batch_shards
    sessions = int(os.environ.get("BENCH_SPEC_SESSIONS", "6"))
    turns = int(os.environ.get("BENCH_SPEC_TURNS", "5"))
    seed = int(os.environ.get("BENCH_SPEC_SEED", "0"))
    spec_tokens = int(os.environ.get("BENCH_SPEC_TOKENS", "3"))
    draft = os.environ.get("BENCH_SPEC_DRAFT", "")
    params = clm.params if clm.params is not None else jax.device_get(clm.init_params(0))
    sharded = shard_params(params, mesh)
    record = _spec_measure(
        clm, mesh, sharded,
        slots=slots, src=src, new_tokens=new_tokens,
        sessions=sessions, turns=turns, seed=seed,
        spec_tokens=spec_tokens, draft_model=draft,
    )
    print(json.dumps({
        "grad_compression": "off",
        "metric": f"{name} speculative vs plain greedy decode "
                  f"(chatbot mix: {sessions} sessions x {turns} turns, "
                  f"slots {slots}, src {src} / max_new {new_tokens}, "
                  f"k={spec_tokens}, draft {draft or 'ngram'}) — "
                  f"serving/spec.py draft-then-verify on mesh "
                  f"{mesh_spec or 'data=-1'}; no reference number exists",
        "value": record["accepted_tokens_per_step"],
        "unit": "accepted tokens per verify step per slot (plain = 1.0)",
        "vs_baseline": None,
        **{k: v for k, v in record.items() if k != "accepted_tokens_per_step"},
        "chips": n_chips,
        "backend": jax.default_backend(),
    }))


def main() -> None:
    # Child-side wall-clock budget: the add-on measurements (grad-accum,
    # dropout, rbg-dropout, trainer loop, trainer-rbg) each compile their
    # own program, and on a cold cache the full menu runs ~25 min — past
    # the supervisor's per-attempt timeout, which would lose the already-
    # measured HEADLINE number.  The gate is ADAPTIVE: each add-on states
    # its estimated cost (scaled from the measured cost of the comparable
    # pass — compile time and measure window are both known after the
    # headline), and runs iff estimate fits the time remaining before the
    # deadline (0.9 × the attempt timeout the supervisor actually applied,
    # BENCH_CHILD_TIMEOUT; the 10% margin only has to cover the final
    # print+flush, not a whole add-on — the round-5 flat 0.6 gate skipped
    # the trainer rbg pass with 360 s genuinely left).  Every skip is
    # logged to stderr AND stamped into the result JSON
    # (``skipped_passes``) — a silently missing field reads as "measured,
    # nothing to report", which is exactly wrong.  A DIRECT run
    # (`_DLLM_BENCH_CHILD=1 python bench.py`, no supervisor → no
    # BENCH_CHILD_TIMEOUT) has nothing racing to kill it, so it measures
    # the full menu unless BENCH_CHILD_BUDGET caps it explicitly.
    _t0 = time.monotonic()
    _budget_env = os.environ.get("BENCH_CHILD_BUDGET")
    _timeout_env = os.environ.get("BENCH_CHILD_TIMEOUT")
    if _budget_env:
        _child_budget = float(_budget_env)
    elif _timeout_env:
        _child_budget = 0.9 * float(_timeout_env)
    else:
        _child_budget = float("inf")
    skipped_passes: list[str] = []

    def over_budget(what: str, est: float = 0.0) -> bool:
        elapsed = time.monotonic() - _t0
        if elapsed + est > _child_budget:
            msg = (
                f"{what} skipped (elapsed {elapsed:.0f}s + estimated "
                f"{est:.0f}s > child budget {_child_budget:.0f}s)"
            )
            print(f"bench: {msg}", file=sys.stderr)
            skipped_passes.append(msg)
            return True
        return False

    import jax
    import numpy as np

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.ops.fused_optim import (
        resolve_impl as resolve_optim_impl,
    )
    from distributed_llms_example_tpu.parallel.sharding import shard_params
    from distributed_llms_example_tpu.train.optim import make_optimizer_bundle
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    name, lm, remat = _flagship()
    n_chips = jax.device_count()
    mesh = build_mesh(MeshConfig(data=-1))

    src_len, tgt_len = 1024, 128
    batch = int(os.environ.get("BENCH_BATCH", "16")) * n_chips
    steps = max(1, int(os.environ.get("BENCH_STEPS", "5")))
    # the production-default optimizer path for every synthetic pass
    # (--optim-impl auto = fused Pallas clip+AdamW on TPU, optax
    # elsewhere); the optim A/B add-on below re-measures the other impl
    optim_impl = os.environ.get("BENCH_OPTIM_IMPL", "auto")
    resolved_optim = resolve_optim_impl(optim_impl)
    # gradient-collective compression for the headline step (default off —
    # the A/B add-on below measures int8 against it in-session; a TPU
    # round can flip the headline itself with BENCH_GRAD_COMPRESSION=int8)
    grad_compression = os.environ.get("BENCH_GRAD_COMPRESSION", "off")
    if grad_compression == "int8":
        # same guard the trainer applies: without partitionable threefry
        # the stochastic-rounding bits lower through u32 collectives as
        # large as the gradient traffic the compression removes, skewing
        # every number this session stamps
        jax.config.update("jax_threefry_partitionable", True)

    rng = np.random.RandomState(0)
    vocab = lm.config.vocab_size
    b = {
        "input_ids": rng.randint(2, min(vocab, 30000), (batch, src_len)).astype(np.int32),
        "attention_mask": np.ones((batch, src_len), np.int32),
        "labels": rng.randint(2, min(vocab, 30000), (batch, tgt_len)).astype(np.int32),
    }
    b["labels"][:, -8:] = LABEL_PAD

    tx, schedule, optim_spec = make_optimizer_bundle(
        learning_rate=5e-5, warmup_steps=0, total_steps=1000
    )
    from distributed_llms_example_tpu.ops.quant_collectives import (
        attach_error_feedback,
        worker_count,
    )

    grad_workers = worker_count(dict(mesh.shape))

    def _fresh_state(mode: str):
        """A FRESH state from re-sharded initial params (the A/B arms
        need identical re-inits; the evolving headline state's buffers
        are donated).  Under int8 the EF tree is allocated
        sharded-at-birth (attach_error_feedback) — a default-device
        zeros tree would sit W x params x 4B whole on chip 0."""
        p0 = lm.params if lm.params is not None else jax.device_get(lm.init_params(0))
        st = create_train_state(shard_params(p0, mesh), tx)
        shm = state_shardings(st, mesh)
        if mode == "int8":
            st, shm = attach_error_feedback(st, shm, mesh, grad_workers)
        return jax.tree.map(lambda x, s: jax.device_put(x, s), st, shm), shm

    # the headline state ALIASES the one sharded param tree (`params` is
    # only read for sizes below) — a second resident copy here would
    # double param memory for the whole bench
    params = lm.params if lm.params is not None else jax.device_get(lm.init_params(0))
    params = shard_params(params, mesh)
    state = create_train_state(params, tx)
    sh = state_shardings(state, mesh)
    if grad_compression == "int8":
        state, sh = attach_error_feedback(state, sh, mesh, grad_workers)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    build = make_train_step(
        lm.module, lm.config, tx, schedule, mesh,
        optim_spec=optim_spec, optim_impl=optim_impl,
        grad_compression=grad_compression,
    )
    step_fn, _ = build(state)
    gb = put_batch(b, mesh)

    # Sync via host readbacks: on tunneled/experimental PJRT backends
    # block_until_ready can return before execution finishes, which would
    # report absurd throughput.  A scalar device_get of the loss plus one
    # updated parameter element forces the full step chain.
    def sync(state, metrics) -> float:
        leaf = jax.tree.leaves(state.params)[0]
        _ = jax.device_get(leaf.ravel()[0])
        return float(jax.device_get(metrics["loss"]))

    tokens_per_step = int(np.sum(b["attention_mask"])) + int(np.sum(b["labels"] != LABEL_PAD))
    n_params = int(sum(x.size for x in jax.tree.leaves(params)))

    # Per-step FLOPs: compiler cost analysis of the actual program when the
    # backend reports it, else the standard 6*N*tokens training estimate
    # (fwd 2N + bwd 4N matmul FLOPs per token; attention excluded, so MFU
    # is slightly conservative relative to true utilization).
    from distributed_llms_example_tpu.parallel.activation import activation_mesh

    flops_per_step = 0.0
    lowered = None
    try:
        # HLO-level analysis on the Lowered stage: no second backend compile.
        # Must lower under the mesh context — jit caches the traced jaxpr,
        # and a trace made without the ambient mesh would bake constraint
        # no-ops into the very program the benchmark then measures.
        with activation_mesh(step_fn.mesh):
            lowered = step_fn.jitted.lower(state, gb)
        ca = lowered.cost_analysis()
        if isinstance(ca, list):  # some backends return one dict per device
            ca = ca[0] if ca else {}
        flops_per_step = float((ca or {}).get("flops", 0.0))
    except Exception as e:
        print(f"bench: cost_analysis unavailable ({e}); using 6*N*tokens", file=sys.stderr)
    if flops_per_step <= 0.0:
        flops_per_step = 6.0 * n_params * tokens_per_step

    # Per-step collective-traffic account (obs/gauges.py) from the compiled
    # step's HLO — gradient vs activation bytes per collective op.  The AOT
    # compile shares the persistent compilation cache with the first jit
    # call, so this costs one disk hit, not a second real compile.
    comm_bytes = None
    if lowered is not None and os.environ.get("BENCH_COMM_BYTES", "1") != "0":
        try:
            from distributed_llms_example_tpu.obs.gauges import collective_traffic

            comm_bytes = collective_traffic(
                lowered.compile().as_text(),
                [int(x.size) for x in jax.tree.leaves(params)],
                n_chips,
            )
        except Exception as e:
            print(f"bench: collective-traffic account unavailable ({e})", file=sys.stderr)

    # warmup/compile — timed: the compile cost is the dominant unknown in
    # every add-on's budget estimate below (cache hits make it small,
    # cold compiles make it the whole story)
    t0 = time.perf_counter()
    for _ in range(2):
        state, metrics = step_fn(state, gb)
    sync(state, metrics)
    compile_s = time.perf_counter() - t0

    # throughput: one sync at the end so async dispatch can overlap steps —
    # the same pipelining the trainer gets (a per-step readback here would
    # deflate tokens/sec by the host round-trip)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, gb)
    loss = sync(state, metrics)
    dt = time.perf_counter() - t0
    assert loss == loss, "non-finite loss"

    # one compile + warm + timed window, the shape of every synthetic
    # add-on pass below — the adaptive budget gate scales from it
    est_step_pass = compile_s + 2.5 * dt

    # step-time distribution: a separate pass with a readback per step
    # (sync-inclusive — upper bounds on single-step latency, not 1/throughput)
    times = []
    for _ in range(steps):
        t1 = time.perf_counter()
        state, metrics = step_fn(state, gb)
        sync(state, metrics)
        times.append(time.perf_counter() - t1)

    peak_flops = float(os.environ.get("BENCH_PEAK_TFLOPS", "197")) * 1e12  # v5e bf16
    from distributed_llms_example_tpu.obs.spans import percentiles

    order = sorted(times)
    p50, p95 = percentiles(times, (0.50, 0.95))
    tps = tokens_per_step * steps / dt
    tps_chip = tps / n_chips
    mfu = flops_per_step * steps / dt / (n_chips * peak_flops)

    result = {
        "metric": f"{name} seq2seq fine-tune train-step throughput "
                  f"(src1024/tgt128, bf16{'+remat' if remat else ''}, batch {batch})",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps_chip / BASELINE_TOKENS_PER_SEC_PER_CHIP, 3),
        "mfu": round(mfu, 4),
        "model_flops_per_token": round(flops_per_step / tokens_per_step),
        "params": n_params,
        "chips": n_chips,
        "backend": jax.default_backend(),
        "step_time_ms_sync_inclusive": {
            "p50": round(p50 * 1e3, 1),
            "p90": round(order[min(len(order) - 1, int(0.9 * len(order)))] * 1e3, 1),
            "p95": round(p95 * 1e3, 1),
            "min": round(order[0] * 1e3, 1),
            "max": round(order[-1] * 1e3, 1),
        },
    }
    if comm_bytes is not None:
        result["comm_bytes_per_step"] = comm_bytes
    # the synthetic passes below drive their own keys: headline has no
    # dropout; the with-dropout pass feeds threefry keys, the rbg add-on
    # hardware-RNG keys, and the fused add-on flips --dropout-impl —
    # stamp both knobs so BENCH_*.json rows stay comparable across rounds
    result["dropout_impl"] = "xla"
    result["prng_impl"] = "threefry"
    result["optim_impl"] = resolved_optim  # headline optimizer path (auto-resolved)
    result["grad_accum_steps"] = 1  # the headline step; the A/B below adds accum>1
    result["grad_compression"] = grad_compression  # headline wire mode

    # Emit the record NOW and again after each add-on lands: if an add-on
    # overruns the supervisor's kill (budget gates check only at add-on
    # START), the supervisor salvages the newest line from the dead
    # child's stdout — so every field measured before the kill survives.
    # Consumers take the LAST result line (module docstring contract).
    # Every emit carries the skip log (the no-silent-caps rule: a missing
    # field must say WHY it is missing).
    def emit_result() -> None:
        if skipped_passes:
            result["skipped_passes"] = list(skipped_passes)
        print(json.dumps(result), flush=True)

    emit_result()

    # grad-accumulation A/B: the SAME effective batch cut into 4 in-step
    # microbatches (lax.scan, fp32 accumulators sharded like the params,
    # one optimizer apply per step).  tokens/sec at the same effective
    # batch compares directly; the ratio is the accumulation overhead vs
    # ideal linear scaling (acceptance bar: >= 0.95 at accum=4).
    accum_n = int(os.environ.get("BENCH_ACCUM", "4"))
    if accum_n > 1 and batch % accum_n:
        # a config skip is still a skip (no-silent-caps): a missing
        # grad_accum field must not read as "measured, nothing to report"
        msg = (
            f"grad-accum step skipped (batch {batch} not divisible by "
            f"BENCH_ACCUM={accum_n})"
        )
        print(f"bench: {msg}", file=sys.stderr)
        skipped_passes.append(msg)
    elif accum_n > 1 and not over_budget("grad-accum step", est_step_pass):
        try:
            build_a = make_train_step(
                lm.module, lm.config, tx, schedule, mesh, grad_accum_steps=accum_n,
                optim_spec=optim_spec, optim_impl=optim_impl,
            )
            step_a, _ = build_a(state)
            for _ in range(2):
                state, metrics = step_a(state, gb)
            sync(state, metrics)
            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = step_a(state, gb)
            sync(state, metrics)
            dta = time.perf_counter() - t0
            tps_chip_accum = round(tokens_per_step * steps / dta / n_chips, 1)
            result["grad_accum"] = {
                "steps": accum_n,
                "tokens_per_sec_chip": tps_chip_accum,
                # tokens/sec ratio at equal effective batch == ideal-linear-
                # scaling fraction; 1 - ratio is the per-step scan overhead
                "vs_accum1": round(tps_chip_accum / tps_chip, 3),
                "overhead_frac": round(1.0 - tps_chip_accum / tps_chip, 4),
                "overhead_ok": bool(tps_chip_accum / tps_chip >= 0.95),
            }
            emit_result()
        except Exception as e:
            print(f"bench: grad-accum bench failed ({e})", file=sys.stderr)
            # a failed accum step may have consumed (donated) the state
            # buffers mid-execution — rebuild so the health/dropout/rbg
            # add-ons below don't all die on 'Array has been deleted'.
            # Drop the dead tree FIRST: if the failure was an OOM before
            # donation, old + replacement living at once would OOM the
            # rebuild itself and lose every already-measured field
            state = None
            state, _ = _fresh_state(grad_compression)

    # health-telemetry overhead: the SAME step compiled with the in-graph
    # numerics (param norm, per-bucket update ratios, non-finite counts —
    # train/step.py health_metrics).  The contract is <2% vs the plain
    # step: a handful of elementwise reductions must stay invisible next
    # to the matmuls, or --health on costs real throughput at scale.
    max_overhead = float(os.environ.get("BENCH_HEALTH_MAX_OVERHEAD", "0.02"))
    if os.environ.get("BENCH_HEALTH", "1") != "0" and not over_budget("health step", est_step_pass):
        try:
            build_h = make_train_step(
                lm.module, lm.config, tx, schedule, mesh, health=True,
                optim_spec=optim_spec, optim_impl=optim_impl,
            )
            step_h, _ = build_h(state)
            for _ in range(2):
                state, metrics = step_h(state, gb)
            sync(state, metrics)
            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = step_h(state, gb)
            sync(state, metrics)
            dth = time.perf_counter() - t0
            tps_chip_health = tokens_per_step * steps / dth / n_chips
            overhead = 1.0 - tps_chip_health / tps_chip
            result["health_tokens_per_sec_chip"] = round(tps_chip_health, 1)
            result["health_overhead_frac"] = round(overhead, 4)
            result["health_overhead_ok"] = bool(overhead <= max_overhead)
            if overhead > max_overhead:
                print(
                    f"bench: HEALTH OVERHEAD {overhead:.1%} exceeds the "
                    f"{max_overhead:.0%} budget — the in-graph numerics are "
                    "on the critical path",
                    file=sys.stderr,
                )
            emit_result()
        except Exception as e:
            print(f"bench: health-step bench failed ({e})", file=sys.stderr)

    # fused-optim A/B: the SAME step rebuilt on the optax chain
    # (--optim-impl xla) when the headline resolved to the fused Pallas
    # apply — same session, same shapes, so the tokens/sec delta IS the
    # optimizer-apply component the budget account's optimizer_apply_ms
    # gauge tracks per-window in the trainer loop below.
    if resolved_optim == "fused" and os.environ.get("BENCH_OPTIM_AB", "1") != "0":
        if not over_budget("optim xla A/B step", est_step_pass):
            try:
                build_o = make_train_step(
                    lm.module, lm.config, tx, schedule, mesh,
                    optim_spec=optim_spec, optim_impl="xla",
                )
                step_o, _ = build_o(state)
                for _ in range(2):
                    state, metrics = step_o(state, gb)
                sync(state, metrics)
                t0 = time.perf_counter()
                for _ in range(steps):
                    state, metrics = step_o(state, gb)
                sync(state, metrics)
                dto = time.perf_counter() - t0
                tps_chip_xla_optim = round(tokens_per_step * steps / dto / n_chips, 1)
                result["optim_ab"] = {
                    "xla_tokens_per_sec_chip": tps_chip_xla_optim,
                    # headline(fused) over xla: >1.0 = the fused apply won
                    "fused_vs_xla_optim": round(tps_chip / tps_chip_xla_optim, 3),
                }
                emit_result()
            except Exception as e:
                print(f"bench: optim A/B bench failed ({e})", file=sys.stderr)
    elif resolved_optim != "fused":
        # a config skip is still a skip (no-silent-caps)
        msg = f"optim A/B skipped (headline already {resolved_optim}; fused needs TPU or --optim-impl fused)"
        print(f"bench: {msg}", file=sys.stderr)
        skipped_passes.append(msg)

    # grad-compression A/B: the step rebuilt with --grad-compression int8
    # (ops/quant_collectives.py: per-worker partial grads, s8 wire, error
    # feedback) vs off, SAME session/shapes/seed.  Both arms restart from
    # an identical fresh init so the loss trajectories are comparable;
    # the byte delta comes from the compiled programs' collective
    # accounts (the same classifier the obs gauges use).  Measured
    # per-collective ms + achieved bytes/sec ride the trainer-loop
    # bench's profiled device account (BENCH_DEVICE_PROFILE) — on CPU
    # rounds that capture is auto-skipped, so the A/B stamps the static
    # byte verdict and the TPU round upgrades it to measured bandwidth.
    ab_steps = max(2, int(os.environ.get("BENCH_GRAD_COMPRESSION_STEPS", "4")))
    comp_modes = ("off", "int8")
    if os.environ.get("BENCH_GRAD_COMPRESSION_AB", "1") == "0":
        msg = "grad-compression A/B skipped (BENCH_GRAD_COMPRESSION_AB=0)"
        print(f"bench: {msg}", file=sys.stderr)
        skipped_passes.append(msg)
    elif batch % max(1, grad_workers):
        msg = (
            f"grad-compression A/B skipped (batch {batch} not divisible "
            f"by {grad_workers} worker groups)"
        )
        print(f"bench: {msg}", file=sys.stderr)
        skipped_passes.append(msg)
    elif not over_budget("grad-compression A/B", 3 * est_step_pass):
        try:
            from distributed_llms_example_tpu.analysis.ir_lint import (
                quantized_gradient_census,
            )
            from distributed_llms_example_tpu.obs.gauges import (
                collective_traffic as _ctraffic,
            )

            # counts need SHAPES only — never materialize params for them
            a_params = jax.eval_shape(lambda: lm.init_params(0))
            leaf_counts = [
                int(np.prod(x.shape)) for x in jax.tree.leaves(a_params)
            ]
            # the int8 arm needs partitionable threefry (see the headline
            # guard above); restore the process default afterwards so the
            # dropout add-ons below keep their established bit streams
            _tf_prev = jax.config.jax_threefry_partitionable

            def _comp_arm(mode: str) -> dict:
                st, _shm = _fresh_state(mode)
                build_c = make_train_step(
                    lm.module, lm.config, tx, schedule, mesh,
                    optim_spec=optim_spec, optim_impl=optim_impl,
                    grad_compression=mode,
                )
                step_c, _ = build_c(st)
                losses = []
                for _ in range(ab_steps):
                    st, m = step_c(st, gb)
                    losses.append(sync(st, m))
                t0 = time.perf_counter()
                for _ in range(steps):
                    st, m = step_c(st, gb)
                sync(st, m)
                dtc = time.perf_counter() - t0
                from distributed_llms_example_tpu.parallel.activation import (
                    activation_mesh as _amesh,
                )

                with _amesh(step_c.mesh):
                    text = step_c.jitted.lower(st, gb).compile().as_text()
                from distributed_llms_example_tpu.analysis.ir_lint import (
                    parse_hlo_instructions as _parse,
                )

                instrs = _parse(text)
                comm_c = _ctraffic(instrs, leaf_counts, n_chips)
                census = quantized_gradient_census(
                    instrs, leaf_counts, dict(mesh.shape)
                )
                del st
                return {
                    "losses": losses,
                    "tokens_per_sec_chip": round(tokens_per_step * steps / dtc / n_chips, 1),
                    "gradient_bytes_per_step": int(comm_c["gradient_bytes"]),
                    "gradient_wire_bytes": int(census["gradient_wire_bytes"]),
                    "s8_gradient_collectives": len(census["s8_gradient_collectives"]),
                }

            try:
                jax.config.update("jax_threefry_partitionable", True)
                arms = {m: _comp_arm(m) for m in comp_modes}
            finally:
                jax.config.update("jax_threefry_partitionable", _tf_prev)
            delta = max(
                abs(a - b)
                for a, b in zip(arms["off"]["losses"], arms["int8"]["losses"])
            )
            off_b = max(1, arms["off"]["gradient_bytes_per_step"])
            int8_b = max(1, arms["int8"]["gradient_bytes_per_step"])
            off_w = max(1, arms["off"]["gradient_wire_bytes"])
            int8_w = max(1, arms["int8"]["gradient_wire_bytes"])
            result["grad_compression_ab"] = {
                "steps": ab_steps,
                "workers": grad_workers,
                "off_tokens_per_sec_chip": arms["off"]["tokens_per_sec_chip"],
                "int8_tokens_per_sec_chip": arms["int8"]["tokens_per_sec_chip"],
                # >1.0 = compression won wall-clock (expect <1 on CPU: the
                # wire it saves is free there and the quantize math is not)
                "int8_vs_off": round(
                    arms["int8"]["tokens_per_sec_chip"]
                    / max(arms["off"]["tokens_per_sec_chip"], 1e-9), 3,
                ),
                "loss_max_abs_delta": round(delta, 6),
                "loss_final_off": round(arms["off"]["losses"][-1], 6),
                "loss_final_int8": round(arms["int8"]["losses"][-1], 6),
                "gradient_bytes_per_step": {"off": off_b, "int8": int8_b},
                "gradient_bytes_ratio": round(off_b / int8_b, 2),
                "gradient_wire_bytes": {"off": off_w, "int8": int8_w},
                "gradient_wire_ratio": round(off_w / int8_w, 2),
                "s8_gradient_collectives": arms["int8"]["s8_gradient_collectives"],
                # on profiled rounds the measured per-collective ms +
                # achieved bytes/sec live in trainer_loop.device_account
                # (PR 11); CPU rounds auto-skip that capture, so this A/B
                # carries the static byte verdict only
                "measured_bandwidth": "see trainer_loop.device_account "
                                      "(profiled rounds)",
            }
            emit_result()
        except Exception as e:
            print(f"bench: grad-compression A/B failed ({e})", file=sys.stderr)
            skipped_passes.append(f"grad-compression A/B failed ({str(e)[:200]})")

    # The Trainer trains with the model's real dropout (bart-large-cnn:
    # 0.1, the reference's recipe) while the headline synthetic step runs
    # dropout-free — measured on v5e, dropout alone costs ~20%.  Measure a
    # with-dropout synthetic pass so the trainer-loop comparison below is
    # apples-to-apples (trainer ≈ this number ⇒ the input pipeline is off
    # the critical path; trainer ≈ headline would be impossible).
    tps_chip_dropout = None
    if os.environ.get("BENCH_DROPOUT", "1") != "0" and not over_budget("dropout step", est_step_pass):
        try:
            # pin the BASELINE to the xla impl: on TPU the process default
            # ("auto") resolves to fused, and the fused-vs-xla A/B below
            # would silently compare fused against fused (the rbg add-on
            # retraces this step for the typed key, so the pin must hold
            # through it — restored by the fused A/B block / the reset
            # before the trainer loop)
            from distributed_llms_example_tpu.ops.fused_dropout import (
                set_default_impl as _set_dropout_impl,
            )

            _set_dropout_impl("xla")
            build_d = make_train_step(
                lm.module, lm.config, tx, schedule, mesh, with_dropout=True,
                optim_spec=optim_spec, optim_impl=optim_impl,
            )
            step_d, _ = build_d(state)
            key = jax.random.PRNGKey(0)
            for _ in range(2):
                key, sub = jax.random.split(key)
                state, metrics = step_d(state, gb, sub)
            sync(state, metrics)
            t0 = time.perf_counter()
            for _ in range(steps):
                key, sub = jax.random.split(key)
                state, metrics = step_d(state, gb, sub)
            sync(state, metrics)
            dtd = time.perf_counter() - t0
            tps_chip_dropout = round(tokens_per_step * steps / dtd / n_chips, 1)
            result["with_dropout_tokens_per_sec_chip"] = tps_chip_dropout
            emit_result()
        except Exception as e:
            print(f"bench: dropout-step bench failed ({e})", file=sys.stderr)

    # same with-dropout step fed an RBG (TPU hardware RNG) key — the
    # --prng-impl rbg trainer path.  Threefry mask generation is counter
    # math on the VPU and costs ~20% of the step; this measures what the
    # hardware stream buys back (the jit recompiles for the typed-key
    # argument, a cache hit on every later run).
    tps_chip_dropout_rbg = None
    if (
        tps_chip_dropout is not None
        and os.environ.get("BENCH_DROPOUT_RBG", "1") != "0"
        and not over_budget("rbg dropout step", est_step_pass)
    ):
        try:
            key = jax.random.key(0, impl="rbg")
            for _ in range(2):
                key, sub = jax.random.split(key)
                state, metrics = step_d(state, gb, sub)
            sync(state, metrics)
            t0 = time.perf_counter()
            for _ in range(steps):
                key, sub = jax.random.split(key)
                state, metrics = step_d(state, gb, sub)
            sync(state, metrics)
            dtr = time.perf_counter() - t0
            tps_chip_dropout_rbg = round(tokens_per_step * steps / dtr / n_chips, 1)
            result["with_dropout_rbg_tokens_per_sec_chip"] = tps_chip_dropout_rbg
            emit_result()
        except Exception as e:
            print(f"bench: rbg dropout-step bench failed ({e})", file=sys.stderr)

    # fused-dropout A/B: the SAME with-dropout step rebuilt with
    # --dropout-impl fused (ops/fused_dropout.py — in-kernel RNG, no mask
    # in HBM, seed-recompute backward), same session, same shapes, same
    # threefry key stream (the fused path folds the key to ONE scalar, so
    # host-PRNG choice no longer matters — that is the point).  The
    # acceptance bar is fused ≥ 1.10× the xla with-dropout number.
    if (
        tps_chip_dropout is not None
        and os.environ.get("BENCH_DROPOUT_FUSED", "1") != "0"
        and not over_budget("fused dropout step", est_step_pass)
    ):
        from distributed_llms_example_tpu.ops.fused_dropout import (
            set_default_impl,
        )

        try:
            set_default_impl("fused")
            build_f = make_train_step(
                lm.module, lm.config, tx, schedule, mesh, with_dropout=True,
                optim_spec=optim_spec, optim_impl=optim_impl,
            )
            step_f, _ = build_f(state)
            key = jax.random.PRNGKey(0)
            for _ in range(2):
                key, sub = jax.random.split(key)
                state, metrics = step_f(state, gb, sub)
            sync(state, metrics)
            t0 = time.perf_counter()
            for _ in range(steps):
                key, sub = jax.random.split(key)
                state, metrics = step_f(state, gb, sub)
            sync(state, metrics)
            dtf = time.perf_counter() - t0
            tps_chip_dropout_fused = round(tokens_per_step * steps / dtf / n_chips, 1)
            result["with_dropout_fused_tokens_per_sec_chip"] = tps_chip_dropout_fused
            result["fused_vs_xla_dropout"] = round(tps_chip_dropout_fused / tps_chip_dropout, 3)
            # mask-absence assertion: scan the compiled fused step for any
            # operand shaped like a (B_local·H·S·S) attention-probs mask —
            # the fused path must never materialize one (the headline
            # families run attn_dropout_rate 0, so any hit is a bug)
            try:
                from distributed_llms_example_tpu.analysis.ir_lint import (
                    parse_hlo_instructions,
                )

                with activation_mesh(step_f.mesh):
                    txt = step_f.jitted.lower(state, gb, sub).compile().as_text()
                heads = int(getattr(
                    lm.config, "encoder_attention_heads",
                    getattr(lm.config, "num_heads",
                            getattr(lm.config, "num_attention_heads", 0)),
                ) or 0)
                b_local = max(1, batch // n_chips)
                probs_elems = {
                    b_local * heads * ql * kl
                    for ql in (src_len, tgt_len) for kl in (src_len, tgt_len)
                } if heads else set()
                hits = [
                    i.name for i in parse_hlo_instructions(txt).values()
                    if i.elems in probs_elems
                ]
                result["attn_probs_mask_operands"] = len(hits)
                if hits:
                    print(
                        f"bench: {len(hits)} (B·H·S·S)-sized operand(s) in the "
                        f"fused step (e.g. %{hits[0]}) — probs-mask smell",
                        file=sys.stderr,
                    )
            except Exception as e:
                print(f"bench: fused-step HLO scan unavailable ({e})", file=sys.stderr)
            emit_result()
        except Exception as e:
            print(f"bench: fused dropout-step bench failed ({e})", file=sys.stderr)

    # restore the process default ("auto") after the pinned A/B passes —
    # the trainer-loop bench pins its own cfg, but a leaked pin would
    # still surprise anything imported after us
    try:
        from distributed_llms_example_tpu.ops.fused_dropout import set_default_impl

        set_default_impl("auto")
    except Exception:
        pass

    # serving block: continuous-batching decode tokens/sec/chip + TTFT +
    # the continuous-vs-static and ROUGE-eval-path A/Bs (serving/engine.py)
    # on the same sharded params the train step just used.  Cost is a
    # prefill+decode sweep per path, plus the capacity A/B's int8 engine
    # rebuild — budget it like four step passes.
    if os.environ.get("BENCH_SERVE", "1") != "0" and not over_budget(
        "serve block", 4 * est_step_pass
    ):
        try:
            batch_shards = 1
            for a in ("data", "fsdp", "expert"):
                batch_shards *= mesh.shape.get(a, 1)
            serve_slots = int(os.environ.get("BENCH_SERVE_SLOTS_PER_SHARD", "2")) * batch_shards
            result["serve"] = _serve_measure(
                lm, mesh, state.params,
                slots=serve_slots,
                src=int(os.environ.get("BENCH_SERVE_SRC", str(src_len))),
                new_tokens=int(os.environ.get("BENCH_SERVE_NEW", "32")),
                n_req=int(os.environ.get("BENCH_SERVE_REQUESTS", str(2 * serve_slots))),
                eval_beams=int(os.environ.get("BENCH_SERVE_EVAL_BEAMS", "2")),
            )
            emit_result()
        except Exception as e:
            print(f"bench: serve block failed ({e})", file=sys.stderr)
            skipped_passes.append(f"serve block failed ({str(e)[:200]})")

    # speculative-decode block: spec vs plain greedy on the chatbot mix
    # (serving/spec.py), riding the flagship's params when the flagship
    # is causal.  A seq2seq flagship is a CONFIG skip, stamped like a
    # budget skip — speculation verifies through the causal decode path,
    # and a silently missing spec field would read as "measured, no win".
    if os.environ.get("BENCH_SPEC", "1") != "0":
        if lm.is_seq2seq:
            msg = (
                "serve-spec A/B skipped (flagship model is seq2seq; "
                "speculation verifies through the causal decode path — "
                "run BENCH_MODE=serve-spec on a causal model instead)"
            )
            print(f"bench: {msg}", file=sys.stderr)
            skipped_passes.append(msg)
        elif not over_budget("serve-spec A/B", 4 * est_step_pass):
            try:
                batch_shards = 1
                for a in ("data", "fsdp", "expert"):
                    batch_shards *= mesh.shape.get(a, 1)
                spec_slots = int(os.environ.get("BENCH_SPEC_SLOTS_PER_SHARD", "2")) * batch_shards
                result["serve_spec"] = _spec_measure(
                    lm, mesh, state.params,
                    slots=spec_slots,
                    src=int(os.environ.get("BENCH_SPEC_SRC", "64")),
                    new_tokens=int(os.environ.get("BENCH_SPEC_NEW", "16")),
                    sessions=int(os.environ.get("BENCH_SPEC_SESSIONS", "6")),
                    turns=int(os.environ.get("BENCH_SPEC_TURNS", "5")),
                    seed=int(os.environ.get("BENCH_SPEC_SEED", "0")),
                    spec_tokens=int(os.environ.get("BENCH_SPEC_TOKENS", "3")),
                    draft_model=os.environ.get("BENCH_SPEC_DRAFT", ""),
                )
                emit_result()
            except Exception as e:
                print(f"bench: serve-spec A/B failed ({e})", file=sys.stderr)
                skipped_passes.append(f"serve-spec A/B failed ({str(e)[:200]})")

    # memory stamp: the static bucketed HBM account (obs/memprof.py) at
    # the measured shape plus the allocator watermark this process set —
    # the "where did the bytes go" record for the headline pass.  The
    # account is an abstract AOT compile (no device buffers), so it is
    # safe to run while the synthetic state is still resident.
    if os.environ.get("BENCH_MEMORY", "1") != "0":
        from distributed_llms_example_tpu.obs import memprof

        try:
            acct = memprof.static_memory_account(
                name, mesh,
                global_batch=batch, src_len=src_len, tgt_len=tgt_len,
                remat=remat,
                hbm_budget_gib=float(
                    os.environ.get("BENCH_HBM_BUDGET_GIB", "16")
                ),
            )
            result["memory_account"] = {
                k: acct[k]
                for k in (
                    "buckets_bytes", "peak_bytes", "peak_gib",
                    "additivity_gap_bytes", "hbm_budget_gib",
                    "hbm_headroom_gib", "peak_frac_of_budget", "fits_budget",
                )
            }
        except Exception as e:
            print(f"bench: static memory account failed ({e})", file=sys.stderr)
        wm = memprof.Watermark().read()
        if wm is not None:
            result["memory_watermark"] = wm
        emit_result()

    # the full Trainer loop (bucketed batching + prefetch + logging on the
    # critical path): validating within ~5% of the with-dropout synthetic
    # number proves the input pipeline stays off the device's back
    trainer_loop = None
    if os.environ.get("BENCH_TRAINER", "1") != "0" and not over_budget(
        "trainer loop", 2 * est_step_pass + 2 * dt
    ):
        # free the synthetic run's device state first: params + AdamW
        # moments are ~5 GB for the 406M flagship, and the Trainer builds
        # its own copy — both living at once exhausts a 16 GB chip
        del state, metrics, gb, params
        try:
            trainer_loop = _trainer_loop_bench(
                name, n_chips, remat=remat,
                attention=os.environ.get("BENCH_ATTENTION", "") or None,
                rbg_ok=lambda est: not over_budget("trainer rbg pass", est),
            )
            tl = trainer_loop.get("tokens_per_sec_chip_prefetch2")
            if tl:
                trainer_loop["vs_synthetic_step"] = round(tl / tps_chip, 3)
                if tps_chip_dropout:
                    trainer_loop["vs_synthetic_step_with_dropout"] = round(
                        tl / tps_chip_dropout, 3
                    )
        except Exception as e:  # never lose the headline number to an add-on
            print(f"bench: trainer-loop bench failed ({e})", file=sys.stderr)
            trainer_loop = {"error": str(e)[:300]}

    if trainer_loop is not None:
        result["trainer_loop"] = trainer_loop
    emit_result()


if __name__ == "__main__":
    if os.environ.get(_BENCH_CHILD) == "1":
        if os.environ.get("BENCH_MODE", "") == "llama-depth":
            _llama_depth_main()
        elif os.environ.get("BENCH_MODE", "") == "generate":
            _generate_main()
        elif os.environ.get("BENCH_MODE", "") == "serve":
            _serve_main()
        elif os.environ.get("BENCH_MODE", "") == "serve-router":
            _router_main()
        elif os.environ.get("BENCH_MODE", "") == "serve-loadgen":
            _loadgen_main()
        elif os.environ.get("BENCH_MODE", "") == "serve-prefix":
            _prefix_main()
        elif os.environ.get("BENCH_MODE", "") == "serve-spec":
            _spec_main()
        elif os.environ.get("BENCH_MODE", "") == "host-input":
            _host_input_main()
        else:
            main()
    else:
        raise SystemExit(_supervise())
