"""Early pytest bootstrap (loaded via ``-p dllm_test_bootstrap`` in addopts).

Tests need JAX on an 8-device virtual CPU mesh, which requires
``JAX_PLATFORMS=cpu`` and ``--xla_force_host_platform_device_count=8`` to be
set before the interpreter initializes JAX.  Environments that register a
TPU PJRT plugin from sitecustomize initialize JAX at interpreter startup, so
the only reliable fix is to re-exec pytest once with a corrected
environment.  This module is imported during pytest's pre-parse phase,
before output capture starts, so the re-exec'ed process keeps the original
stdout/stderr.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _dllm_env import cpu_mesh_env  # noqa: E402

if os.environ.get("_DLLM_TPU_TEST_REEXEC") != "1":
    env = cpu_mesh_env(os.environ, n_devices=8)
    env["_DLLM_TPU_TEST_REEXEC"] = "1"
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], env)
