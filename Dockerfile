# TPU runtime image for distributed_llms_example_tpu.
#
# TPU-native counterpart of the reference's CUDA image (reference
# Dockerfile:1-27: nvidia/cuda:12.2.0 base + python3.9 + unpinned pip
# installs).  Differences on purpose: no GPU userspace at all — jax[tpu]
# ships libtpu and talks to the accelerator directly — versions are
# pinned, and g++ is included so the native JSONL loader
# (distributed_llms_example_tpu/native/) compiles on first use.
#
# Build:  docker build -t dllm-tpu:latest .
# The Valohai steps in valohai.yaml run this image on TPU VM hosts.

FROM python:3.12-slim-bookworm

# g++ for the native data loader; git for VCS-pinned installs if needed
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ git curl \
    && rm -rf /var/lib/apt/lists/*

# JAX with the TPU runtime (libtpu wheel comes from the jax release index),
# then the model/data/checkpoint stack.  Versions pinned to a known-good
# set; bump deliberately, together.
RUN pip install --no-cache-dir \
    "jax[tpu]==0.9.0" \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir \
    "flax==0.12.0" \
    "optax==0.2.6" \
    "orbax-checkpoint==0.11.28" \
    "chex==0.1.91" \
    "einops==0.8.1" \
    "numpy>=2.0" \
    "transformers==4.57.1" \
    "safetensors==0.6.2" \
    "sentencepiece==0.2.1" \
    "valohai-utils==0.7.0"

WORKDIR /workspace
COPY distributed_llms_example_tpu/ distributed_llms_example_tpu/
COPY valohai.yaml bench.py __graft_entry__.py _dllm_env.py dllm_test_bootstrap.py pyproject.toml ./

# pre-build the native JSONL loader so first use doesn't pay the compile
RUN python -c "from distributed_llms_example_tpu import native; assert native.available(), native.build_error()"

ENV PYTHONUNBUFFERED=1
CMD ["python", "-m", "distributed_llms_example_tpu.launch.cli", "--help"]
