"""Activation sharding constraints for the SPMD train/eval graphs.

The path-regex rules in ``sharding.py`` pin down *parameter* layouts, but
GSPMD still has to propagate shardings through activations — and with a
vocab/d_model-sharded embedding feeding a batch-sharded residual stream it
can end up with conflicting choices it reconciles by "involuntary full
rematerialization" (replicate, then re-partition: the round-1 dryrun
emitted exactly that warning on the tensor-parallel path).  Explicit
``with_sharding_constraint`` calls at the model's seams give the
partitioner one consistent answer:

- residual stream / hidden states: batch over ``(data, fsdp)``, d_model
  replicated (megatron-style: tensor parallelism lives *inside* the
  attention/MLP blocks, the residual stream is replicated over ``tensor``);
- logits: batch over ``(data, fsdp)``, vocab over ``tensor`` (matches the
  vocab-sharded embedding/lm_head so the loss's logsumexp reduces over a
  sharded axis with a psum instead of materializing replicated logits).

Model code calls the ``constrain_*`` helpers unconditionally; they are
no-ops unless a mesh has been installed with ``activation_mesh`` — the
train step and evaluator install it around tracing, so pure single-device
uses (unit tests, conversion scripts) see unchanged graphs.
"""

from __future__ import annotations

import contextlib
import threading

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_state = threading.local()

BATCH_AXES = ("data", "fsdp", "expert")


def current_mesh() -> Mesh | None:
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def activation_mesh(mesh: Mesh | None):
    """Install ``mesh`` as the ambient mesh for ``constrain_*`` during
    tracing.  Constraints bake into the jitted program, so this only needs
    to wrap the *first* (tracing) call — wrapping every call is harmless."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield
    finally:
        _state.mesh = prev


# Does this jax carry the varying-manual-axes (vma) type system?  Newer
# jax (jax.shard_map, check_vma=) tracks per-axis variance and requires
# explicit pcasts; 0.4-era jax (jax.experimental.shard_map, check_rep=)
# has neither — there pvary_to is a no-op and shard_map calls go through
# ``compat_shard_map`` below with replication checking off.
_HAS_VMA = hasattr(jax, "typeof") and hasattr(jax.lax, "pcast")


def pvary_to(tree, axes):
    """Mark every array in ``tree`` varying over ``axes`` (a name or tuple
    of names) for shard_map's vma checking (check_vma=True), skipping axes
    an array is ALREADY varying over — so values that enter a manual region
    sharded (hence varying) over some axis can be upcast to the full set
    without double-marking.  The single home for this logic: the pipeline
    body and the ring-attention carry init both need it.  On pre-vma jax
    this is the identity: there is no variance type to cast."""
    if not _HAS_VMA:
        return tree
    if isinstance(axes, str):
        axes = (axes,)

    def mark(x):
        have = getattr(jax.typeof(x), "vma", frozenset())
        missing = tuple(a for a in axes if a not in have)
        return jax.lax.pcast(x, missing, to="varying") if missing else x

    return jax.tree.map(mark, tree)


def compat_shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                     check_vma=True):
    """``jax.shard_map`` across jax generations — the ONE place the two
    APIs meet, so every manual region (pipelines, flash/ring attention)
    stays version-portable:

    - new jax: ``jax.shard_map(..., axis_names=..., check_vma=...)``
      (partial-auto via axis_names; vma-typed).
    - 0.4-era jax: ``jax.experimental.shard_map.shard_map(..., auto=...)``
      with ``auto`` = the mesh axes NOT named manual, and
      ``check_rep=False`` — the old replication checker predates the vma
      system and rejects these programs; correctness does not depend on
      it (the bodies do their cross-shard reductions with explicit
      psums).

    ``axis_names=None`` means fully manual (every mesh axis)."""
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": set(axis_names)}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kw,
        )
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    auto = (
        frozenset()
        if axis_names is None
        else frozenset(mesh.axis_names) - frozenset(axis_names)
    )
    # Partial-auto with a REAL (size>1) auto axis is broken on 0.4-era
    # jax: the partitioner rejects the body's axis_index lowering
    # ("PartitionId instruction is not supported for SPMD partitioning").
    # Failing here — before minutes of tracing — names the constraint;
    # the stage>1 pipelines are blocked on a jax upgrade (ROADMAP).
    if any(mesh.shape.get(a, 1) > 1 for a in auto):
        raise NotImplementedError(
            "this jax version does not support partial-auto shard_map "
            f"(manual={sorted(axis_names)} with live auto axes "
            f"{sorted(a for a in auto if mesh.shape.get(a, 1) > 1)}); "
            "the stage>1 pipeline schedules need a newer jax"
        )
    return _legacy_shard_map(
        f, mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, auto=auto,
    )


def current_kv_cache_dtype() -> str:
    """The serving KV-cache storage dtype for programs traced under
    ``kv_cache_context`` — ``"f32"`` (store K/V at compute dtype, the
    default) or ``"int8"`` (quantize on cache write with per-head
    per-position symmetric scales; ``ops/flash_attention.py`` owns the
    quantize/dequantize math).  A trace-time knob exactly like the
    ambient mesh: the attention modules' ``_cache_kv`` reads it when
    creating/writing cache variables, so the flag never threads through
    every model signature."""
    return getattr(_state, "kv_cache_dtype", "f32")


@contextlib.contextmanager
def kv_cache_context(dtype: str):
    """Install the KV-cache storage dtype for tracing (see
    ``current_kv_cache_dtype``).  Must wrap BOTH the cache-allocating
    program (prefill / init) and every program that reads or writes the
    cache — the serving engine and the static runners wrap all their
    jitted calls, so one engine is internally consistent by construction."""
    if dtype not in ("f32", "int8"):
        raise ValueError(
            f"kv_cache_dtype={dtype!r}: must be 'f32' or 'int8'"
        )
    prev = current_kv_cache_dtype()
    _state.kv_cache_dtype = dtype
    try:
        yield
    finally:
        _state.kv_cache_dtype = prev


def current_manual_seq() -> tuple[str, int] | None:
    """(axis_name, axis_size) when tracing inside a manual region that owns
    the sequence axis (the stage×sequence pipeline), else None."""
    return getattr(_state, "manual_seq", None)


@contextlib.contextmanager
def manual_sequence(axis_name: str, axis_size: int):
    """Declare that the enclosing ``shard_map`` is manual over the sequence
    axis: activations carry LOCAL sequence shards and collectives over
    ``axis_name`` are legal.  Attention modules switch to the in-region
    ring-attention body (``ops.ring_attention.ring_attention``) instead of
    opening their own ``shard_map`` — nesting manual regions is not
    supported, which is why the pipeline installs this context rather than
    relying on the modules' normal global-shape dispatch."""
    prev = current_manual_seq()
    _state.manual_seq = (axis_name, axis_size)
    try:
        yield
    finally:
        _state.manual_seq = prev


def constrain(x: jax.Array, spec: P) -> jax.Array:
    """Constrain ``x`` to ``spec`` on the ambient mesh (no-op without one).

    The spec is truncated to ``x.ndim`` so one call site can serve ranks
    that differ by a leading/trailing axis."""
    mesh = current_mesh()
    if mesh is None:
        return x
    if len(spec) > x.ndim:
        spec = P(*spec[: x.ndim])
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _seq_axis(x: jax.Array, dim: int = 1) -> str | None:
    """``"sequence"`` when the ambient mesh runs sequence parallelism and
    the seq dim splits evenly (decode-time length-1 slices stay unsharded),
    else None — so non-SP meshes compile to exactly the old graphs."""
    mesh = current_mesh()
    if mesh is None:
        return None
    n = mesh.shape.get("sequence", 1)
    size = x.shape[dim]
    return "sequence" if n > 1 and size and size % n == 0 else None


def constrain_hidden(x: jax.Array) -> jax.Array:
    """(batch, seq, d_model) residual-stream activations; seq over
    ``sequence`` under context parallelism."""
    return constrain(x, P(BATCH_AXES, _seq_axis(x), None))


def constrain_logits(x: jax.Array) -> jax.Array:
    """(batch, seq, vocab) logits — vocab sharded over ``tensor``, seq over
    ``sequence`` under context parallelism."""
    return constrain(x, P(BATCH_AXES, _seq_axis(x), "tensor"))


def constrain_kv(x: jax.Array) -> jax.Array:
    """(batch, heads, len, head_dim) cached K/V or precomputed cross-K/V:
    batch rows over the batch axes, heads over ``tensor`` — the serving
    twin of ``constrain_hidden``.  The layout (and its divisibility
    fallbacks) is ``parallel/sharding.py kv_leaf_spec`` — the ONE
    definition CACHE_RULES, this constraint, and the engine's host-side
    placement all share."""
    mesh = current_mesh()
    if mesh is None or x.ndim != 4:
        return x
    from distributed_llms_example_tpu.parallel.sharding import kv_leaf_spec

    return constrain(x, kv_leaf_spec(x.shape, dict(mesh.shape)))


def constrain_kv_scale(x: jax.Array) -> jax.Array:
    """(batch, heads, len) int8-KV-cache scale leaf: same layout as the K/V
    buffer it scales, minus the head_dim axis (``kv_scale_spec`` — the one
    definition, like ``kv_leaf_spec`` for the buffers)."""
    mesh = current_mesh()
    if mesh is None or x.ndim != 3:
        return x
    from distributed_llms_example_tpu.parallel.sharding import kv_scale_spec

    return constrain(x, kv_scale_spec(x.shape, dict(mesh.shape)))


def constrain_cache(tree):
    """Pin a whole flax "cache" collection (or cross-KV tuple tree) to the
    serving layout: every 4-D leaf via ``constrain_kv``, 3-D ``*_scale``
    leaves (the int8 KV cache's per-head per-position scales) via
    ``constrain_kv_scale``, scalars (the ``cache_index`` counters)
    replicated by GSPMD default.  No-op without an ambient mesh — the
    decode/prefill programs call it unconditionally, exactly like the
    models call ``constrain_hidden``."""
    import jax.tree_util as jtu

    def leaf_key(path) -> str:
        return str(path[-1].key) if path and hasattr(path[-1], "key") else ""

    def pin(path, x):
        nd = getattr(x, "ndim", 0)
        if nd == 4:
            return constrain_kv(x)
        if nd == 3 and leaf_key(path).endswith("_scale"):
            return constrain_kv_scale(x)
        return x

    return jtu.tree_map_with_path(pin, tree)
