"""Activation sharding constraints for the SPMD train/eval graphs.

The path-regex rules in ``sharding.py`` pin down *parameter* layouts, but
GSPMD still has to propagate shardings through activations — and with a
vocab/d_model-sharded embedding feeding a batch-sharded residual stream it
can end up with conflicting choices it reconciles by "involuntary full
rematerialization" (replicate, then re-partition: the round-1 dryrun
emitted exactly that warning on the tensor-parallel path).  Explicit
``with_sharding_constraint`` calls at the model's seams give the
partitioner one consistent answer:

- residual stream / hidden states: batch over ``(data, fsdp)``, d_model
  replicated (megatron-style: tensor parallelism lives *inside* the
  attention/MLP blocks, the residual stream is replicated over ``tensor``);
- logits: batch over ``(data, fsdp)``, vocab over ``tensor`` (matches the
  vocab-sharded embedding/lm_head so the loss's logsumexp reduces over a
  sharded axis with a psum instead of materializing replicated logits).

Model code calls the ``constrain_*`` helpers unconditionally; they are
no-ops unless a mesh has been installed with ``activation_mesh`` — the
train step and evaluator install it around tracing, so pure single-device
uses (unit tests, conversion scripts) see unchanged graphs.
"""

from __future__ import annotations

import contextlib
import threading

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_state = threading.local()

BATCH_AXES = ("data", "fsdp", "expert")


def current_mesh() -> Mesh | None:
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def activation_mesh(mesh: Mesh | None):
    """Install ``mesh`` as the ambient mesh for ``constrain_*`` during
    tracing.  Constraints bake into the jitted program, so this only needs
    to wrap the *first* (tracing) call — wrapping every call is harmless."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield
    finally:
        _state.mesh = prev


def constrain(x: jax.Array, spec: P) -> jax.Array:
    """Constrain ``x`` to ``spec`` on the ambient mesh (no-op without one).

    The spec is truncated to ``x.ndim`` so one call site can serve ranks
    that differ by a leading/trailing axis."""
    mesh = current_mesh()
    if mesh is None:
        return x
    if len(spec) > x.ndim:
        spec = P(*spec[: x.ndim])
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _seq_axis(x: jax.Array, dim: int = 1) -> str | None:
    """``"sequence"`` when the ambient mesh runs sequence parallelism and
    the seq dim splits evenly (decode-time length-1 slices stay unsharded),
    else None — so non-SP meshes compile to exactly the old graphs."""
    mesh = current_mesh()
    if mesh is None:
        return None
    n = mesh.shape.get("sequence", 1)
    size = x.shape[dim]
    return "sequence" if n > 1 and size and size % n == 0 else None


def constrain_hidden(x: jax.Array) -> jax.Array:
    """(batch, seq, d_model) residual-stream activations; seq over
    ``sequence`` under context parallelism."""
    return constrain(x, P(BATCH_AXES, _seq_axis(x), None))


def constrain_logits(x: jax.Array) -> jax.Array:
    """(batch, seq, vocab) logits — vocab sharded over ``tensor``, seq over
    ``sequence`` under context parallelism."""
    return constrain(x, P(BATCH_AXES, _seq_axis(x), "tensor"))
