"""GPipe-style pipeline parallelism over the ``stage`` mesh axis.

The reference has no model parallelism of any kind (SURVEY.md §2: tensor/
pipeline parallel "No"); this module goes past parity so decoder stacks
too deep for one chip's HBM can be split *by layer* across chips — the
complement of FSDP (which shards within each tensor) and the standard way
to scale across slices, since stage hops are point-to-point and tolerate
DCN latency (the ``stage`` axis is outermost in the mesh for exactly that
reason, core/mesh.py).

Design — a spatial pipeline expressed as one SPMD program, TPU-first:

- Layer parameters are *stacked*: every transformer block's param tree
  gets a leading layer dim (L, ...) sharded over ``stage``, so each device
  group holds L/S contiguous layers and total param memory scales 1/S.
- ``shard_map`` over the mesh runs the scheduling loop per-shard: a
  ``lax.scan`` over M + S - 1 ticks.  Each tick, stage 0 feeds the next
  microbatch in, every stage applies its layers (an inner ``lax.scan``
  over the local layer stack, optionally ``jax.checkpoint``-ed), and
  activations hop to the next stage with a single ``lax.ppermute`` —
  neighbor-to-neighbor traffic XLA can overlap with the next tick's
  compute.  The last stage collects finished microbatches.
- The backward pass is pure autodiff: ``scan`` reverses the schedule and
  the ``ppermute`` transpose carries activation-gradients backwards
  through the ring — the 1F1B-shaped reverse traffic for free.
- Bubble: (S-1)/(M+S-1) of ticks compute garbage that is discarded (and
  contributes zero gradient).  Raise ``num_microbatches`` to amortize.

Composition (v2): the ``shard_map`` is manual over ``stage`` ONLY
(``axis_names={"stage"}``) — every other mesh axis stays *automatic*, so
GSPMD keeps partitioning the per-stage compute over ``data``/``fsdp``
(batch) and ``tensor`` (megatron splits on the stacked kernels, the
standard stage×tensor 7B+ topology) inside the pipeline body, inserting
the collectives itself.  MoE composes too (stage × expert): sown aux
losses can't cross the shard_map, so ``with_aux`` layer_fns return the
load-balance loss as an explicit output the schedule accumulates (bubble
ticks masked) and psums.  ``sequence`` composes on both schedules via
``seq_axis``: the region goes manual over {stage, sequence} — ONE combined
manual region instead of (unsupported) nested ones — hidden shards its
sequence dim, and attention runs the in-region ring body under a
``manual_sequence`` context (see ``pipeline_apply``); long-context models
can then ALSO split their layer stack across stages.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_llms_example_tpu.analysis.composition import reason_for
from distributed_llms_example_tpu.parallel.activation import (
    compat_shard_map,
    manual_sequence,
    pvary_to,
)


def stack_blocks(params: dict, prefix: str = "block_", out_key: str = "stacked_blocks") -> dict:
    """Standard per-layer tree ({block_0: t, block_1: t, ...}) → pipelined
    tree ({stacked_blocks: tree-of-(L, ...) arrays, ...rest}).  The inverse
    of ``unstack_blocks``; checkpoints and HF conversion stay in the
    per-layer layout, this transform is applied at training-setup time."""
    names = sorted(
        (k for k in params if k.startswith(prefix) and k[len(prefix):].isdigit()),
        key=lambda k: int(k[len(prefix):]),
    )
    if not names:
        raise ValueError(f"no {prefix}* subtrees in params")
    if names != [f"{prefix}{i}" for i in range(len(names))]:
        raise ValueError(f"layer indices not contiguous from 0: {names}")
    rest = {k: v for k, v in params.items() if k not in names}
    # host-side stack: jnp.stack would commit the whole stacked tree to the
    # default device before the P('stage') sharding is ever applied — OOM
    # for exactly the too-big-for-one-chip models this module exists for
    import numpy as np

    stacked = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *(params[n] for n in names)
    )
    return {**rest, out_key: stacked}


def unstack_blocks(params: dict, prefix: str = "block_", key: str = "stacked_blocks",
                   layer_transform=None, row_order=None) -> dict:
    """Pipelined tree → standard per-layer tree (for checkpoints/eval).
    ``layer_transform`` (if given) is applied to each layer tree AS it is
    unstacked — the hook the memory-aware reshard path uses so only one
    untransformed (replicated) layer is ever live.  ``row_order`` (if
    given) maps TRUE layer index → storage row — the interleaved pipeline
    schedule's permuted layout resolves here one row at a time, instead of
    materializing a whole un-permuted copy of the stack first."""
    stacked = params[key]
    rest = {k: v for k, v in params.items() if k != key}
    n = jax.tree.leaves(stacked)[0].shape[0]
    out = dict(rest)
    for i in range(n):
        row = i if row_order is None else int(row_order[i])
        layer = jax.tree.map(lambda x: x[row], stacked)
        out[f"{prefix}{i}"] = layer if layer_transform is None else layer_transform(layer)
    return out


def _unstack_dispatch(family: str, params: dict, unstack_one) -> dict:
    """Shared family layout dispatch: LLaMA's single stack, BART's twin
    top-level stacks, T5's nested encoder/decoder stacks."""
    if family == "llama":
        return unstack_one(params, "block_", "stacked_blocks")
    if family == "bart":
        params = unstack_one(params, "encoder_block_", "stacked_encoder_blocks")
        return unstack_one(params, "decoder_block_", "stacked_decoder_blocks")
    if family == "t5":
        return {
            **params,
            "encoder": unstack_one(params["encoder"], "block_", "stacked_blocks"),
            "decoder": unstack_one(params["decoder"], "block_", "stacked_blocks"),
        }
    raise ValueError(f"no pipeline unstacking for family {family!r}")


def stack_for_family(family: str, params: dict) -> dict:
    """Family-aware stacking: LLaMA stacks its single decoder stack; BART
    stacks encoder+decoder at the top level; T5 stacks inside its nested
    encoder/decoder subtrees."""
    if family == "llama":
        return stack_blocks(params)
    if family == "bart":
        params = stack_blocks(params, "encoder_block_", "stacked_encoder_blocks")
        return stack_blocks(params, "decoder_block_", "stacked_decoder_blocks")
    if family == "t5":
        return {
            **params,
            "encoder": stack_blocks(params["encoder"]),
            "decoder": stack_blocks(params["decoder"]),
        }
    raise ValueError(f"no pipeline stacking for family {family!r}")


def unstack_for_family(family: str, params: dict) -> dict:
    return _unstack_dispatch(family, params, unstack_blocks)


def unstack_for_family_resharded(family: str, params: dict, mesh, rules=None,
                                 row_order=None) -> dict:
    """``unstack_for_family`` that device_puts each layer onto its
    (default FSDP/TP) rule sharding AS it is unstacked.  Indexing a
    stage-sharded stack yields a replicated layer; doing all layers before
    resharding would transiently hold a full replicated copy of the model
    on every device — exactly the cliff pipelined eval/export exists to
    avoid.  Here at most ONE replicated layer is live at a time; the
    resulting tree holds params/(fsdp·tensor) per device."""
    from distributed_llms_example_tpu.parallel.sharding import resolve_shardings

    def unstack_one(tree, prefix="block_", key="stacked_blocks"):
        holder = {}  # all layers of one stack share a structure: resolve once

        def transform(layer):
            if not holder:
                holder["sh"] = resolve_shardings(layer, mesh, rules)
            return jax.tree.map(jax.device_put, layer, holder["sh"])

        return unstack_blocks(
            tree, prefix, key, layer_transform=transform, row_order=row_order
        )

    out = _unstack_dispatch(family, params, unstack_one)
    # non-stacked leaves (embeddings/norms/head) get their rule shardings
    # too; the per-layer trees above are already placed, so this final
    # tree-wide device_put no-ops on them
    return jax.tree.map(jax.device_put, out, resolve_shardings(out, mesh, rules))


def gather_tree_to_host(tree, *, writer_only: bool = False):
    """Copy a (possibly multi-host-sharded) pytree to host numpy, one leaf
    at a time.  Non-fully-addressable leaves are allgathered — every
    process enters every collective in the same (tree) order, so this is
    collectively safe.  With ``writer_only``, non-writing processes free
    each gathered leaf immediately and get a tree of None leaves back:
    peak extra host memory on them is ONE leaf, while process 0 (where the
    checkpoint/safetensors writer runs) accumulates the full tree it needs
    anyway.  Shared by the pipelined (per-layer) and non-pipelined export
    paths so the gather semantics cannot drift between them."""
    import numpy as np

    drop = writer_only and jax.process_count() > 1 and jax.process_index() != 0

    def to_host(x):
        if (  # pod-agreed: process_count() is pod-uniform; the per-leaf allgather below runs on every rank
            jax.process_count() > 1 and hasattr(x, "is_fully_addressable") and not x.is_fully_addressable
        ):
            from jax.experimental import multihost_utils

            g = np.asarray(multihost_utils.process_allgather(x, tiled=True))
            return None if drop else g
        return None if drop else np.asarray(jax.device_get(x))

    return jax.tree.map(to_host, tree)


def unstack_for_family_to_host(family: str, params: dict, *, writer_only: bool = False,
                               row_order=None) -> dict:
    """Unstack a pipelined tree layer-by-layer STRAIGHT TO HOST numpy —
    the export path.  Device-side resharded unstacking still replicates
    everything on a pure-pipeline mesh (stage>1 with fsdp=tensor=1, the
    canonical too-big-for-one-chip config), so the HF export gathers each
    layer to host RAM as it is unstacked: HBM peak is the training
    footprint plus ONE gathered layer; the full fp32 tree only ever exists
    host-side, where the checkpoint writer needs it anyway.  Multi-host:
    see ``gather_tree_to_host`` (with ``writer_only`` the full host copy
    exists only on process 0)."""

    def unstack_one(tree, prefix="block_", key="stacked_blocks"):
        return unstack_blocks(
            tree, prefix, key,
            layer_transform=lambda layer: gather_tree_to_host(layer, writer_only=writer_only),
            row_order=row_order,
        )

    out = _unstack_dispatch(family, params, unstack_one)
    return gather_tree_to_host(out, writer_only=writer_only)


def _full_spec(leading, ndim: int) -> P:
    return P(leading, *([None] * (ndim - 1)))


def _seq_specs(seq_axis: str, hidden_ndim: int, *dim_trees) -> tuple:
    """Shard_map specs for the sequence-parallel boundary, shared by the
    gpipe and 1f1b paths so the convention cannot drift: hidden shards dim
    1 over ``seq_axis``; each ``(tree, dims)`` pair in ``dim_trees`` maps
    per-leaf dims (int, <0 or None = replicated) to PartitionSpecs,
    defaulting every leaf to replicated when ``dims`` is None."""
    hidden_spec = P(None, seq_axis, *([None] * (hidden_ndim - 2)))

    def dim_spec(m, d):
        return P() if d is None or d < 0 else P(*([None] * d), seq_axis)

    out = [hidden_spec]
    for tree, dims in dim_trees:
        out.append(jax.tree.map(
            dim_spec, tree,
            jax.tree.map(lambda _: -1, tree) if dims is None else dims,
        ))
    return tuple(out)


def dropout(x: jnp.ndarray, key: jnp.ndarray, rate: float) -> jnp.ndarray:
    """Inverted dropout for the pipeline adapters' out-of-loop layers
    (embeddings, final norms) — in-loop dropout goes through each block's
    own ``ops.fused_dropout.Dropout`` with a per-layer folded key.  Routed
    through the shared helper so the fused Pallas path applies here too
    (these calls run OUTSIDE the pipeline's manual region, under plain
    GSPMD, where the helper's shard_map dispatch is legal)."""
    from distributed_llms_example_tpu.ops.fused_dropout import (
        dropout as shared_dropout,
    )

    return shared_dropout(x, key, rate).astype(x.dtype)


def _vary(tree, axes):
    """Mark every array varying over ``axes``: the body branches on
    axis_index, and shard_map's vma checking (check_vma=True) requires the
    provenance to be explicit rather than inferred.  See ``pvary_to``."""
    return pvary_to(tree, axes)


def _make_run_stage(layer_fn: Callable, checkpoint: bool,
                    with_aux: bool = False) -> Callable:
    """One stage's work: an inner ``lax.scan`` over its local layer stack,
    each layer optionally ``jax.checkpoint``-ed.  With a key, ``layer_fn``
    takes a fourth argument folded to be unique per local layer (callers
    fold stage and microbatch in first).  ``with_aux``: ``layer_fn``
    returns ``(h, aux_scalar)`` (e.g. an MoE load-balance loss) and
    ``run_stage`` returns ``(y, aux_sum_over_local_layers)``."""
    one_layer = jax.checkpoint(layer_fn) if checkpoint else layer_fn

    def call(p, x, ex, k):
        out = one_layer(p, x, ex) if k is None else one_layer(p, x, ex, k)
        return out if with_aux else (out, jnp.zeros((), jnp.float32))

    def run_stage(local_params: Any, x: jnp.ndarray, ex: Any,
                  key: jnp.ndarray | None = None):
        local_l = jax.tree.leaves(local_params)[0].shape[0]
        # derive the zero from x so its vma type (stage-varying inside the
        # pipeline body, plain outside) matches the aux the scan carries
        aux0 = (x.ravel()[0] * 0).astype(jnp.float32)
        if key is None:
            def step(carry, p):
                y, aux = call(p, carry[0], ex, None)
                return (y, carry[1] + aux), None

            (y, aux), _ = jax.lax.scan(step, (x, aux0), local_params)
        else:
            def step(carry, xs):
                p, i = xs
                y, aux = call(p, carry[0], ex, jax.random.fold_in(key, i))
                return (y, carry[1] + aux), None

            (y, aux), _ = jax.lax.scan(
                step, (x, aux0), (local_params, jnp.arange(local_l))
            )
        return (y, aux) if with_aux else y

    return run_stage


def pipeline_apply(
    layer_fn: Callable[[Any, jnp.ndarray, Any], jnp.ndarray],
    stacked_params: Any,
    hidden: jnp.ndarray,
    extras: Any = None,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "stage",
    batch_axes: tuple[str, ...] = ("data", "fsdp", "expert"),
    checkpoint: bool = True,
    rng: jnp.ndarray | None = None,
    with_aux: bool = False,
    seq_axis: str | None = None,
    extras_seq_dims: Any = None,
) -> jnp.ndarray:
    """Run ``hidden`` through the stacked layers as a pipelined schedule.

    ``layer_fn(layer_params, h, extras_microbatch) -> h`` applies ONE
    layer.  ``with_aux``: ``layer_fn`` instead returns ``(h, aux_scalar)``
    (an MoE load-balance loss term); the call then returns
    ``(out, aux_mean)`` where ``aux_mean`` averages the per-(layer,
    microbatch) scalars over all L layers and M microbatches, bubble
    ticks excluded.  The mean is UNWEIGHTED over microbatches: it equals
    the grad-accumulation objective (which token-weights each
    microbatch's aux) exactly when microbatch token counts are uniform,
    and is otherwise an equal-weight estimator of the same batch-level
    statistic.  ``hidden``: (B, ...) global batch; ``extras``: optional pytree
    of per-example arrays (leading dim B, e.g. an attention padding bias)
    or per-call constants (leading dim != B, replicated to every stage).
    Requires L % stages == 0 and (local batch) % num_microbatches == 0.
    Output is bit-identical to applying the layers sequentially (the
    schedule only reorders microbatches, never the math within one).

    ``rng``: optional PRNG key enabling stochastic layers (dropout).  When
    given, ``layer_fn`` must take a fourth argument — a key folded to be
    unique per (microbatch, stage, local layer), so every layer of every
    microbatch draws an independent mask while the whole schedule stays a
    deterministic function of ``rng``.

    ``seq_axis``: compose with sequence/context parallelism by making the
    shard_map manual over {stage, seq_axis} — ONE combined manual region
    instead of (unsupported) nested ones.  ``hidden`` dim 1 is then sharded
    over ``seq_axis``; inside the body every activation holds a local
    sequence shard and ``layer_fn`` is traced under a ``manual_sequence``
    context, which switches attention modules onto the in-region ring body
    (ops/ring_attention.py) with collectives over the manual axis.
    ``extras_seq_dims``: pytree matching ``extras`` giving, per leaf, the
    dim sharded over ``seq_axis`` (None = replicated along sequence) — e.g.
    a K-aligned padding bias (B, 1, 1, K) shards dim 3 and then rotates
    around the ring with K/V.
    """
    S = mesh.shape.get(axis_name, 1)
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    M = num_microbatches
    if L % S:
        raise ValueError(f"{L} layers not divisible into {S} pipeline stages")
    B = hidden.shape[0]
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)
    batch_shards = 1
    for a in batch_axes:
        batch_shards *= mesh.shape[a]
    if B % (batch_shards * M):
        raise ValueError(
            f"global batch {B} not divisible by {batch_shards} batch shards "
            f"× {M} microbatches"
        )

    run_stage = _make_run_stage(layer_fn, checkpoint, with_aux)

    if S == 1:
        # no pipeline: plain scan over the full stack under GSPMD (a
        # sequence axis, if any, is handled by the modules' own global-shape
        # ring dispatch — no manual region to compose with)
        if with_aux:
            y, aux = run_stage(stacked_params, hidden, extras, rng)
            return y, aux / L
        return run_stage(stacked_params, hidden, extras, rng)

    # seq-axis resolution, divisibility, and the bf16→fp32 boundary
    # conversion are shared with the fused executors (_pvg_common) so the
    # partitioner-workaround conventions cannot drift between the paths.
    # The pipeline PLUMBING (microbatch selects, hop buffers, the output
    # accumulator) runs in fp32 when the compute dtype is bf16: the XLA
    # SPMD partitioner miscompiles bf16 select/copy chains under
    # partial-manual shard_map ("Invalid binary instruction opcode copy",
    # observed on jax 0.9/XLA CPU), and the converts fuse into the layer
    # matmuls anyway.  Layer compute still happens in the caller's dtype.
    (seq_axis, n_seq, axes_all, is_batched, ex_dtypes, compute_dtype,
     plumb_dtype, hidden, extras) = _pvg_common(
        hidden, extras, mesh=mesh, axis_name=axis_name, seq_axis=seq_axis,
    )
    if seq_axis is not None and with_aux:
        # deep twin of the adapter-construction check: the message comes
        # from the composition table so it cannot drift
        raise ValueError(reason_for("pipeline-sequence-moe"))

    def body(local_params: Any, h: jnp.ndarray, ex: Any, key: Any) -> jnp.ndarray:
        # Manual over ``stage`` only: shapes here are GLOBAL in every other
        # dim and every array must be made stage-varying (each stage
        # branches on s_idx), hence the pcasts.  GSPMD still auto-shards
        # the per-stage compute over data/fsdp/tensor.
        s_idx = jax.lax.axis_index(axis_name)
        if seq_axis is not None:
            # Params enter stage-varying but sequence-UNvarying; the first
            # op mixing them with sequence-varying activations would insert
            # an implicit pvary whose TRANSPOSE is a psum of the (bf16)
            # parameter cotangent over the sequence axis — and a bf16 psum
            # over a manual axis is exactly the partitioner copy-chain
            # crash.  Pre-vary every bf16 param through an fp32 bridge so
            # the transpose psum runs in fp32 (the converts fuse).
            def seq_vary_param(p):
                if p.dtype == jnp.bfloat16:
                    return _vary(p.astype(jnp.float32), axes_all).astype(p.dtype)
                return _vary(p, axes_all)

            local_params = jax.tree.map(seq_vary_param, local_params)
        ex = jax.tree.map(
            lambda m: m.astype(plumb_dtype) if m.dtype == jnp.bfloat16 else m, ex
        )
        h, ex = _vary(h.astype(plumb_dtype), axes_all), _vary(ex, axes_all)
        if key is not None:
            # unique stream per stage (and per sequence shard, so local
            # dropout masks are independent); tick folds in the microbatch
            key = jax.random.fold_in(_vary(key, axes_all), s_idx)
            if seq_axis is not None:
                key = jax.random.fold_in(key, jax.lax.axis_index(seq_axis))
        mb = h.shape[0] // M
        micro = h.reshape(M, mb, *h.shape[1:])
        micro_ex = jax.tree.map(
            lambda m, batched: m.reshape(M, m.shape[0] // M, *m.shape[1:]) if batched else m,
            ex,
            is_batched,
        )
        buf = _vary(jnp.zeros((mb, *h.shape[1:]), h.dtype), axes_all)
        outputs = _vary(jnp.zeros((M, mb, *h.shape[1:]), h.dtype), axes_all)
        aux_acc = _vary(jnp.zeros((), jnp.float32), axes_all)
        perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            buf, outputs, aux_acc = carry
            # stage s processes microbatch (t - s); clamp covers bubble ticks
            m_idx = jnp.clip(t - s_idx, 0, M - 1)
            x0 = jax.lax.dynamic_index_in_dim(micro, m_idx, 0, keepdims=False)
            ex_t = jax.tree.map(
                lambda m, batched, dt: (
                    jax.lax.dynamic_index_in_dim(m, m_idx, 0, keepdims=False)
                    if batched else m
                ).astype(dt),
                micro_ex,
                is_batched,
                ex_dtypes,
            )
            inp = jnp.where(s_idx == 0, x0, buf)
            key_m = None if key is None else jax.random.fold_in(key, m_idx)
            y = run_stage(local_params, inp.astype(compute_dtype), ex_t, key_m)
            if with_aux:
                y, aux_t = y
                # bubble ticks run the layers on clamped garbage; only
                # ticks where this stage holds a real microbatch count
                active = (t >= s_idx) & (t - s_idx < M)
                aux_acc = aux_acc + jnp.where(active, aux_t, 0.0)
            y = y.astype(plumb_dtype)
            nxt = jax.lax.ppermute(y, axis_name, perm)
            write = (s_idx == S - 1) & (t >= S - 1)
            upd = jax.lax.dynamic_update_index_in_dim(outputs, y, m_idx, 0)
            outputs = jnp.where(write, upd, outputs)
            return (nxt, outputs, aux_acc), None

        (_, outputs, aux_acc), _ = jax.lax.scan(
            tick, (buf, outputs, aux_acc), jnp.arange(M + S - 1)
        )
        # only the last stage holds real results; replicate them to every
        # stage so downstream (final norm / head / loss) is stage-uniform
        outputs = jax.lax.psum(
            jnp.where(s_idx == S - 1, outputs, jnp.zeros_like(outputs)), axis_name
        )
        # on the sequence-sharded path the output boundary stays fp32 too
        # (cast back outside the region, same bug as the input boundary)
        out = outputs.reshape(h.shape)
        if seq_axis is None:
            out = out.astype(compute_dtype)
        if with_aux:
            # every (layer, microbatch) contributed once across all stages
            return out, jax.lax.psum(aux_acc, axis_name) / (L * M)
        return out

    # in/out specs name ONLY the manual axes; shardings over the automatic
    # axes (fsdp/tensor splits on the stacked kernels, data/fsdp on the
    # batch) ride through untouched
    param_specs = jax.tree.map(lambda x: _full_spec(axis_name, x.ndim), stacked_params)
    if seq_axis is None:
        hidden_spec = P()
        extras_specs = jax.tree.map(lambda m: P(), extras)
    else:
        hidden_spec, extras_specs = _seq_specs(
            seq_axis, hidden.ndim, (extras, extras_seq_dims)
        )
    # rng enters as a pytree ({} when absent) so in_specs structure-matches
    rng_tree = {} if rng is None else {"key": rng}
    rng_specs = jax.tree.map(lambda _: P(), rng_tree)

    def outer(sp, h, ex, rt):
        if seq_axis is None:
            return body(sp, h, ex, rt.get("key"))
        with manual_sequence(seq_axis, n_seq):
            return body(sp, h, ex, rt.get("key"))

    out_specs = (hidden_spec, P()) if with_aux else hidden_spec

    result = compat_shard_map(
        outer,
        mesh=mesh,
        axis_names=set(axes_all),
        in_specs=(param_specs, hidden_spec, extras_specs, rng_specs),
        out_specs=out_specs,
        check_vma=True,
    )(stacked_params, hidden, extras, rng_tree)
    if seq_axis is None:
        return result
    # with_aux cannot reach here (seq_axis + with_aux raises above)
    return result.astype(compute_dtype)


def _pvg_single_stage(run_stage, post_loss_fn, stacked_params, post_params,
                      hidden, extras, loss_batch, rng):
    """S == 1 fallback shared by the fused-schedule executors: one vjp over
    (blocks ∘ tail) under plain GSPMD — no pipeline."""

    def whole(sp, pp, h):
        return post_loss_fn(pp, run_stage(sp, h, extras, rng), loss_batch)

    (lsum, tokens), vjp = jax.vjp(whole, stacked_params, post_params, hidden)
    d_sp, d_pp, d_h = vjp((jnp.ones((), lsum.dtype), jnp.zeros((), tokens.dtype)))
    return lsum, tokens, d_sp, d_pp, d_h


def _pvg_single_stage_aux(run_stage, post_loss_fn, stacked_params, post_params,
                          hidden, extras, loss_batch, rng, aux_cotangent, M):
    """S == 1 fallback for the fused executors when ``with_aux``: one vjp
    under plain GSPMD, with the aux output's cotangent folded in.

    Contract note: aux_sum spans L layers × M microbatches; the single-
    stage path runs ONE full-batch pass (aux over L only), so aux scales
    by M — the caller's /(L·M) normalization and the /(L·M) cotangent
    then stay exact, and the value equals the gpipe S==1 aux/L mean."""

    def whole(sp, pp, h):
        y, aux = run_stage(sp, h, extras, rng)
        ls, tk = post_loss_fn(pp, y, loss_batch)
        return ls, tk, aux * M

    (lsum, tokens, aux_sum), vjp = jax.vjp(
        whole, stacked_params, post_params, hidden
    )
    # the aux output's cotangent IS the constant d(objective)/d(aux) —
    # one vjp covers CE and load-balance gradients together
    d_sp, d_pp, d_h = vjp((
        jnp.ones((), lsum.dtype),
        jnp.zeros((), tokens.dtype),
        jnp.asarray(aux_cotangent, aux_sum.dtype),
    ))
    return lsum, tokens, d_sp, d_pp, d_h, aux_sum


def _pvg_check_batch(B: int, mesh: Mesh, M: int, batch_axes) -> None:
    """Fail fast on a batch that doesn't divide into (batch shards ×
    microbatches) — run BEFORE the S==1 early return too, so a stage=1
    misconfiguration surfaces immediately instead of when scaled up."""
    batch_shards = 1
    for a in batch_axes:
        if a in mesh.shape:
            batch_shards *= mesh.shape[a]
    if B % (batch_shards * M):
        raise ValueError(
            f"global batch {B} not divisible by {batch_shards} batch shards "
            f"× {M} microbatches"
        )


def _pvg_common(hidden, extras, *, mesh, axis_name, seq_axis):
    """Shared setup for the fused-schedule executors (plain 1F1B and
    interleaved): sequence axis resolution and the bf16→fp32 boundary
    conversion (sharded-boundary bf16 crossings feed the partitioner
    copy-chain bug — convert OUTSIDE the manual region, see
    ``pipeline_apply``).  Returns ``(seq_axis, n_seq, axes_all,
    is_batched, ex_dtypes, compute_dtype, plumb_dtype, hidden, extras)``.
    Batch divisibility is validated by the executors themselves
    (``_pvg_check_batch``, BEFORE their S==1 early return) — not here."""
    B = hidden.shape[0]
    n_seq = mesh.shape.get(seq_axis, 1) if seq_axis else 1
    if n_seq <= 1:
        seq_axis = None
    if seq_axis is not None and hidden.ndim >= 2 and hidden.shape[1] % n_seq:
        raise ValueError(
            f"sequence length {hidden.shape[1]} not divisible by "
            f"{seq_axis}={n_seq}"
        )
    axes_all = (axis_name,) if seq_axis is None else (axis_name, seq_axis)
    is_batched = jax.tree.map(lambda m: m.ndim > 0 and m.shape[0] == B, extras)
    ex_dtypes = jax.tree.map(lambda m: m.dtype, extras)
    compute_dtype = hidden.dtype
    plumb_dtype = jnp.float32 if compute_dtype == jnp.bfloat16 else compute_dtype
    if seq_axis is not None:
        hidden = hidden.astype(plumb_dtype)
        extras = jax.tree.map(
            lambda m: m.astype(plumb_dtype) if m.dtype == jnp.bfloat16 else m, extras
        )
    return (seq_axis, n_seq, axes_all, is_batched, ex_dtypes,
            compute_dtype, plumb_dtype, hidden, extras)


def _pvg_body_prologue(sp_local, pp, h, ex, lb, rt, *, S, M, axis_name,
                       axes_all, seq_axis, plumb_dtype, is_batched, ex_dtypes):
    """Shared in-body setup for the fused-schedule executors.  Everything
    entering a ``jax.vjp`` is pre-varied: differentiating w.r.t. an
    unvarying input under a varying cotangent transposes the implicit
    broadcast into a hidden psum over the manual axes — the per-stage
    grads would then already contain every OTHER stage's (garbage)
    contribution, leaking through the schedule masks (and over ``seq``
    that implicit psum would be bf16, the partitioner crash).  Explicit
    fp32 psums in the epilogue do the real cross-shard reductions.

    Returns ``(s_idx, is_last, sp_local, pp, key, mb, micro, micro_ex,
    micro_lb, ex_at)`` with the batch already split into M microbatches."""
    s_idx = jax.lax.axis_index(axis_name)
    is_last = s_idx == S - 1
    ex = jax.tree.map(
        lambda m: m.astype(plumb_dtype) if m.dtype == jnp.bfloat16 else m, ex
    )
    h, ex, lb = _vary(h.astype(plumb_dtype), axes_all), _vary(ex, axes_all), _vary(lb, axes_all)
    pp = _vary(pp, axes_all)
    sp_local = _vary(sp_local, axes_all)
    key = rt.get("key")
    if key is not None:
        key = jax.random.fold_in(_vary(key, axes_all), s_idx)
        if seq_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(seq_axis))
    mb = h.shape[0] // M
    micro = h.reshape(M, mb, *h.shape[1:])
    micro_ex = jax.tree.map(
        lambda m, batched: m.reshape(M, m.shape[0] // M, *m.shape[1:]) if batched else m,
        ex, is_batched,
    )
    micro_lb = jax.tree.map(lambda m: m.reshape(M, m.shape[0] // M, *m.shape[1:]), lb)

    def ex_at(m_idx):
        return jax.tree.map(
            lambda m, batched, dt: (
                jax.lax.dynamic_index_in_dim(m, m_idx, 0, keepdims=False)
                if batched else m
            ).astype(dt),
            micro_ex, is_batched, ex_dtypes,
        )

    return s_idx, is_last, sp_local, pp, key, mb, micro, micro_ex, micro_lb, ex_at


def _pvg_loss_vjp(loss_f, pp, y, do_loss):
    """Loss-head forward+vjp, gated on ``do_loss`` — a tick-level predicate
    that is UNVARYING across devices (derived from the tick index / a
    schedule table, never from ``axis_index``), so ``lax.cond`` runs ONE
    branch and all devices agree (collectives inside ``loss_f``, e.g. the
    seq-sharded label-shift ppermute, stay consistent).  Without the gate
    every tick of every device would pay a full loss-head fwd+bwd
    (final-norm + lm_head over a microbatch + CE) that only the last
    stage's real loss ticks need — for large-vocab models that fixed cost
    rivals a layer chunk's.  Returns ``(ls_m, tk_m, d_pp_m, dy_loss)``;
    the skip branch returns zeros of the same shapes/dtypes (vma types
    derived from the varying operands, so ``check_vma`` stays happy).
    ``y`` may be any pytree (a single activation array here; the twin
    seq2seq executor carries an {enc, dec} pair through the same gate)."""

    def with_loss(ops):
        pp_, y_ = ops
        (ls_m, tk_m), loss_vjp = jax.vjp(loss_f, pp_, y_)
        # cotangents must carry exactly the outputs' vma type (varying or
        # not, depending on what loss_f computes) — derive from the outputs
        d_pp_m, dy_loss = loss_vjp((ls_m * 0 + 1, tk_m * 0))
        return ls_m, tk_m, d_pp_m, dy_loss

    def skip_loss(ops):
        pp_, y_ = ops
        out_sh = jax.eval_shape(loss_f, pp_, y_)
        zscal = jax.tree.leaves(y_)[0].ravel()[0] * 0
        ls_m = zscal.astype(out_sh[0].dtype)
        tk_m = zscal.astype(out_sh[1].dtype)
        d_pp_m = jax.tree.map(lambda p: p * 0, pp_)
        dy_loss = jax.tree.map(lambda a: a * 0, y_)
        return ls_m, tk_m, d_pp_m, dy_loss

    return jax.lax.cond(do_loss, with_loss, skip_loss, (pp, y))


def _pvg_body_epilogue(lsum, toks, d_sp, d_pp, d_h, h_shape, *, axis_name,
                       axes_all, seq_axis):
    """Shared reduction epilogue: loss/tail grads live on the last stage,
    d_hidden on stage 0 (updates already masked to those stages); psum
    replicates.  Under sequence parallelism the scalars and param/tail
    grads additionally reduce over the seq shards (all fp32 — bf16 psums
    over manual axes crash the partitioner); d_h stays seq-sharded (it IS
    the local positions' gradient)."""
    lsum = jax.lax.psum(lsum, axes_all)
    toks = jax.lax.psum(toks, axes_all)
    d_pp = jax.tree.map(lambda g: jax.lax.psum(g, axes_all), d_pp)
    d_h = jax.lax.psum(d_h, axis_name)
    if seq_axis is not None:
        d_sp = jax.tree.map(lambda g: jax.lax.psum(g, seq_axis), d_sp)
    return lsum, toks, d_sp, d_pp, d_h.reshape(h_shape)


def _pvg_shard_map(body, *, mesh, axis_name, axes_all, seq_axis, n_seq,
                   stacked_params, post_params, hidden, extras, loss_batch,
                   rng, extras_seq_dims, loss_seq_dims, with_aux=False):
    """Shared spec construction + ``shard_map`` epilogue for the fused-
    schedule executors.  ``body(sp, pp, h, ex, lb, rt)`` returns
    ``(lsum, tokens, d_sp, d_pp, d_h)`` (plus an aux-sum scalar when
    ``with_aux``); it is wrapped in the ``manual_sequence`` context when a
    sequence axis is live."""
    param_specs = jax.tree.map(lambda x: _full_spec(axis_name, x.ndim), stacked_params)
    rng_tree = {} if rng is None else {"key": rng}
    if seq_axis is None:
        hidden_spec = P()
        extras_specs = jax.tree.map(lambda m: P(), extras)
        loss_specs = jax.tree.map(lambda m: P(), loss_batch)
    else:
        hidden_spec, extras_specs, loss_specs = _seq_specs(
            seq_axis, hidden.ndim, (extras, extras_seq_dims), (loss_batch, loss_seq_dims)
        )

    def outer(sp, pp, h, ex, lb, rt):
        if seq_axis is None:
            return body(sp, pp, h, ex, lb, rt)
        with manual_sequence(seq_axis, n_seq):
            return body(sp, pp, h, ex, lb, rt)

    return compat_shard_map(
        outer,
        mesh=mesh,
        axis_names=set(axes_all),
        in_specs=(
            param_specs,
            jax.tree.map(lambda _: P(), post_params),
            hidden_spec,
            extras_specs,
            loss_specs,
            jax.tree.map(lambda _: P(), rng_tree),
        ),
        out_specs=(
            P(), P(), param_specs,
            jax.tree.map(lambda _: P(), post_params),
            hidden_spec,
            *((P(),) if with_aux else ()),
        ),
        check_vma=True,
    )(stacked_params, post_params, hidden, extras, loss_batch, rng_tree)


def pipeline_value_and_grad(
    layer_fn: Callable,
    post_loss_fn: Callable,
    stacked_params: Any,
    post_params: Any,
    hidden: jnp.ndarray,
    extras: Any,
    loss_batch: Any,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "stage",
    batch_axes: tuple[str, ...] = ("data", "fsdp", "expert"),
    checkpoint: bool = True,
    rng: jnp.ndarray | None = None,
    seq_axis: str | None = None,
    extras_seq_dims: Any = None,
    loss_seq_dims: Any = None,
    with_aux: bool = False,
    aux_cotangent: jnp.ndarray | float = 0.0,
):
    """1F1B pipeline schedule: loss AND parameter gradients in ONE fused
    scan, backward microbatches interleaved with forward.

    The GPipe path (``pipeline_apply`` + autodiff) must keep every
    microbatch's stage activations alive between the forward scan and the
    reversed backward scan — O(M) activations per stage.  Differentiating
    through the scan cannot reorder that; interleaving requires owning the
    backward, so this function computes gradients itself:

    - tick ``t``, stage ``s`` FORWARDS microbatch ``t - s`` (saving only
      the CHUNK INPUT in a ring buffer of ``2S - 1`` slots) and BACKWARDS
      microbatch ``t - (2(S-1) - s)`` via ``jax.vjp`` of the stage chunk —
      which recomputes the chunk forward, so per-stage activation memory is
      O(S) ring slots + one chunk's transient, independent of M;
    - the last stage folds the loss in-tick: its just-finished forward
      microbatch immediately drives ``post_loss_fn``'s vjp, and the
      resulting activation-gradient starts hopping backwards on the SAME
      tick (the 1F1B signature — microbatch 0's backward begins while
      microbatch S's forward is still entering the pipe);
    - activation-gradients ride a second ``ppermute`` ring in the opposite
      direction; total ticks = M + 2(S-1).

    The trade, honestly: under SPMD every stage executes both the F and B
    slots of every tick (masked when inactive), so wall-clock is
    ~(M + 2(S-1)) fused ticks vs GPipe's (M+S-1) forward + (M+S-1)
    backward ticks — about (S-1) extra tick-equivalents of compute — in
    exchange for activation memory dropping from O(M) to O(S) microbatches
    per stage.  That is the trade that makes LARGE microbatch counts (the
    bubble amortizer) affordable at stage>2.

    ``layer_fn(p, h, ex[, key]) -> h`` as in ``pipeline_apply``.
    ``post_loss_fn(post_params, h, loss_microbatch) -> (loss_sum, tokens)``
    runs the model tail + loss for ONE microbatch (token-SUM semantics so
    microbatch results add exactly).  ``loss_batch``: pytree of per-example
    arrays (leading dim B) consumed by the loss.  Returns
    ``(loss_sum, tokens, d_stacked, d_post, d_hidden)`` — unnormalized
    sums, gradients of loss_sum w.r.t. the three differentiable inputs.

    Schedule-only reordering: the math per microbatch is identical to the
    sequential computation, so results match GPipe and the single-device
    step exactly (tests/test_pipeline.py::test_1f1b_*).

    ``seq_axis``/``extras_seq_dims``: sequence-parallel composition, same
    contract as ``pipeline_apply`` — ONE manual region over {stage,
    seq_axis}, ``layer_fn``/``post_loss_fn`` traced under a
    ``manual_sequence`` context with LOCAL sequence shards.
    ``loss_seq_dims``: like ``extras_seq_dims`` but for ``loss_batch``
    (e.g. next-token labels shard dim 1; the loss fn must handle the
    cross-shard target shift itself — see models/llama.py).  All manual-
    axis gradient reductions run in fp32 (bf16 psums over manual axes
    crash the partitioner, see ``pipeline_apply``).

    ``with_aux``: ``layer_fn`` returns ``(h, aux_scalar)`` (the MoE
    load-balance loss).  The call then additionally returns ``aux_sum``
    (the raw sum over all L layers × M microbatches — the caller
    normalizes), and every chunk vjp receives ``aux_cotangent`` as the
    aux output's cotangent so its gradient lands in d_stacked/d_hidden
    with everything else.  ``aux_cotangent`` must be the CONSTANT
    d(objective)/d(aux_sum) — for the ``moe_weight·aux_mean·tokens``
    objective that is ``moe_weight·tokens/(L·M)``, computable from the
    labels alone BEFORE the schedule runs (token counts don't depend on
    params).  Does not compose with ``seq_axis`` (per-shard router
    statistics would need their own reduction — same restriction as
    ``pipeline_apply``).
    """
    S = mesh.shape.get(axis_name, 1)
    M = num_microbatches
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % max(S, 1):
        raise ValueError(f"{L} layers not divisible into {S} pipeline stages")
    if with_aux and seq_axis is not None and mesh.shape.get(seq_axis, 1) > 1:
        raise ValueError(reason_for("pipeline-sequence-moe"))
    run_stage = _make_run_stage(layer_fn, checkpoint, with_aux)
    _pvg_check_batch(hidden.shape[0], mesh, M, batch_axes)
    if S == 1:
        if with_aux:
            return _pvg_single_stage_aux(
                run_stage, post_loss_fn, stacked_params, post_params,
                hidden, extras, loss_batch, rng, aux_cotangent, M,
            )
        return _pvg_single_stage(
            run_stage, post_loss_fn, stacked_params, post_params,
            hidden, extras, loss_batch, rng,
        )
    (seq_axis, n_seq, axes_all, is_batched, ex_dtypes, compute_dtype,
     plumb_dtype, hidden, extras) = _pvg_common(
        hidden, extras, mesh=mesh, axis_name=axis_name, seq_axis=seq_axis,
    )
    K = 2 * S - 1  # ring depth ≥ max activation lifetime in ticks (stage 0)
    T = M + 2 * (S - 1)

    def body(sp_local, pp, h, ex, lb, rt):
        h_shape = h.shape
        (s_idx, is_last, sp_local, pp, key, mb, micro, micro_ex, micro_lb,
         ex_at) = _pvg_body_prologue(
            sp_local, pp, h, ex, lb, rt, S=S, M=M, axis_name=axis_name,
            axes_all=axes_all, seq_axis=seq_axis, plumb_dtype=plumb_dtype,
            is_batched=is_batched, ex_dtypes=ex_dtypes,
        )

        zeros_like_f32 = lambda t: jax.tree.map(  # noqa: E731
            lambda x: _vary(jnp.zeros(x.shape, jnp.float32), axes_all), t
        )
        fwd_buf = _vary(jnp.zeros((mb, *h.shape[1:]), plumb_dtype), axes_all)
        bwd_buf = _vary(jnp.zeros((mb, *h.shape[1:]), plumb_dtype), axes_all)
        act = _vary(jnp.zeros((K, mb, *h.shape[1:]), plumb_dtype), axes_all)
        d_sp = zeros_like_f32(sp_local)
        d_pp = zeros_like_f32(pp)
        d_h = _vary(jnp.zeros((M, mb, *h.shape[1:]), jnp.float32), axes_all)
        scal0 = _vary(jnp.zeros((), jnp.float32), axes_all)
        aux_ct = _vary(jnp.asarray(aux_cotangent, jnp.float32), axes_all)
        perm_fwd = [(i, i + 1) for i in range(S - 1)]
        perm_bwd = [(i + 1, i) for i in range(S - 1)]

        def tick(carry, t):
            fwd_buf, bwd_buf, act, d_sp, d_pp, d_h, lsum, toks, aux_acc = carry
            mf = t - s_idx
            mb_i = t - (2 * (S - 1) - s_idx)
            act_f = (mf >= 0) & (mf < M)
            act_b = (mb_i >= 0) & (mb_i < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            mb_c = jnp.clip(mb_i, 0, M - 1)

            # ---- forward: one microbatch through this stage's chunk
            x0 = jax.lax.dynamic_index_in_dim(micro, mf_c, 0, keepdims=False)
            x_in = jnp.where(s_idx == 0, x0, fwd_buf)
            ex_f = ex_at(mf_c)
            key_f = None if key is None else jax.random.fold_in(key, mf_c)

            def chunk_f(p_, x_):
                out = run_stage(p_, x_.astype(compute_dtype), ex_f, key_f)
                if with_aux:
                    return out[0].astype(plumb_dtype), out[1]
                return out.astype(plumb_dtype)

            y = chunk_f(sp_local, x_in)
            if with_aux:
                y, aux_f = y
                aux_acc = aux_acc + jnp.where(act_f, aux_f.astype(jnp.float32), 0.0)
            act = jax.lax.dynamic_update_index_in_dim(act, x_in, mf_c % K, 0)

            # ---- last stage: loss fwd+vjp for the microbatch it just
            # finished (1F then immediately 1B of the same microbatch).
            # The gate is TICK-level (the last stage's F is active exactly
            # on ticks S-1 .. S-1+M-1) and unvarying across devices, so
            # the loss head runs on M ticks instead of all T.
            lb_f = jax.tree.map(
                lambda m: jax.lax.dynamic_index_in_dim(m, mf_c, 0, keepdims=False),
                micro_lb,
            )

            def loss_f(pp_, y_):
                return post_loss_fn(pp_, y_.astype(compute_dtype), lb_f)

            do_loss = (t >= S - 1) & (t < S - 1 + M)
            ls_m, tk_m, d_pp_m, dy_loss = _pvg_loss_vjp(loss_f, pp, y, do_loss)
            take_loss = is_last & act_f
            lsum = lsum + jnp.where(take_loss, ls_m.astype(jnp.float32), 0.0)
            toks = toks + jnp.where(take_loss, tk_m.astype(jnp.float32), 0.0)
            d_pp = jax.tree.map(
                lambda a, g: a + jnp.where(take_loss, g.astype(jnp.float32), 0.0),
                d_pp, d_pp_m,
            )

            # ---- backward: vjp of this stage's chunk for an EARLIER
            # microbatch (recomputes the chunk forward — remat)
            x_b = jax.lax.dynamic_index_in_dim(act, mb_c % K, 0, keepdims=False)
            ex_b = ex_at(mb_c)
            key_b = None if key is None else jax.random.fold_in(key, mb_c)

            def chunk_b(p_, x_):
                out = run_stage(p_, x_.astype(compute_dtype), ex_b, key_b)
                if with_aux:
                    return out[0].astype(plumb_dtype), out[1]
                return out.astype(plumb_dtype)

            _, chunk_vjp = jax.vjp(chunk_b, sp_local, x_b)
            dy_in = jnp.where(is_last, dy_loss.astype(plumb_dtype), bwd_buf)
            if with_aux:
                # the aux output's cotangent: the constant objective
                # coefficient, masked to active backward ticks (bubble
                # ticks' dx is never consumed, but bounding it costs one
                # where and keeps the invariant obvious)
                aux_dy = jnp.where(act_b, aux_ct, 0.0)
                d_sp_m, dx = chunk_vjp((dy_in, aux_dy))
            else:
                d_sp_m, dx = chunk_vjp(dy_in)
            d_sp = jax.tree.map(
                lambda a, g: a + jnp.where(act_b, g.astype(jnp.float32), 0.0),
                d_sp, d_sp_m,
            )
            d_h_upd = jax.lax.dynamic_update_index_in_dim(
                d_h, dx.astype(jnp.float32), mb_c, 0
            )
            d_h = jnp.where(act_b & (s_idx == 0), d_h_upd, d_h)

            # ---- hops: activations forward, activation-grads backward
            fwd_buf = jax.lax.ppermute(y, axis_name, perm_fwd)
            bwd_buf = jax.lax.ppermute(dx.astype(plumb_dtype), axis_name, perm_bwd)
            return (fwd_buf, bwd_buf, act, d_sp, d_pp, d_h, lsum, toks, aux_acc), None

        carry = (fwd_buf, bwd_buf, act, d_sp, d_pp, d_h, scal0, scal0, scal0)
        (fwd_buf, bwd_buf, act, d_sp, d_pp, d_h, lsum, toks, aux_acc), _ = jax.lax.scan(
            tick, carry, jnp.arange(T)
        )
        out = _pvg_body_epilogue(
            lsum, toks, d_sp, d_pp, d_h, h_shape,
            axis_name=axis_name, axes_all=axes_all, seq_axis=seq_axis,
        )
        if with_aux:
            # every (stage-chunk, microbatch) contributed its layer-sum once
            return (*out, jax.lax.psum(aux_acc, axis_name))
        return out

    return _pvg_shard_map(
        body, mesh=mesh, axis_name=axis_name, axes_all=axes_all,
        seq_axis=seq_axis, n_seq=n_seq, stacked_params=stacked_params,
        post_params=post_params, hidden=hidden, extras=extras,
        loss_batch=loss_batch, rng=rng, extras_seq_dims=extras_seq_dims,
        loss_seq_dims=loss_seq_dims, with_aux=with_aux,
    )


def pipeline_value_and_grad_interleaved(
    layer_fn: Callable,
    post_loss_fn: Callable,
    stacked_params: Any,
    post_params: Any,
    hidden: jnp.ndarray,
    extras: Any,
    loss_batch: Any,
    *,
    mesh: Mesh,
    num_microbatches: int,
    virtual_stages: int,
    axis_name: str = "stage",
    batch_axes: tuple[str, ...] = ("data", "fsdp", "expert"),
    checkpoint: bool = True,
    rng: jnp.ndarray | None = None,
    seq_axis: str | None = None,
    extras_seq_dims: Any = None,
    loss_seq_dims: Any = None,
    with_aux: bool = False,
    aux_cotangent: jnp.ndarray | float = 0.0,
):
    """Interleaved (virtual-stage) 1F1B: each device runs ``virtual_stages``
    NON-CONTIGUOUS layer chunks, table-driven by a precomputed schedule
    (``parallel/interleave.py`` — see its docstring for the model and the
    honest cost accounting: in this fused-tick SPMD executor the win over
    plain 1F1B is the shorter tick count T(v)/v < T(1), ~7-10% of pipeline
    wall at stage >= 4, growing with depth; the price is ~v× more buffered
    chunk inputs.  The loss-head vjp is gated to its M real ticks on BOTH
    schedules — ``_pvg_loss_vjp`` — so it does not scale with T(v)).
    ``stacked_params`` rows must already be in INTERLEAVED
    storage order (``interleave.interleave_tree``): device ``s``'s shard
    holds its v chunks contiguously, chunk ``c`` covering true layers
    ``(c*S + s) * Lc .. + Lc``.  Same contract as
    ``pipeline_value_and_grad`` otherwise; ``virtual_stages=1`` is plain
    1F1B through the table machinery (the equivalence tests pin both
    against the single-device step).  ``with_aux``/``aux_cotangent``:
    same MoE contract as ``pipeline_value_and_grad`` — chunks emit their
    aux sums and every chunk vjp takes the constant objective
    coefficient as the aux output's cotangent.
    """
    from distributed_llms_example_tpu.parallel.interleave import (
        make_interleaved_schedule,
    )

    S = mesh.shape.get(axis_name, 1)
    M = num_microbatches
    v = int(virtual_stages)
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if with_aux and seq_axis is not None and mesh.shape.get(seq_axis, 1) > 1:
        raise ValueError(reason_for("pipeline-sequence-moe"))
    run_stage = _make_run_stage(layer_fn, checkpoint, with_aux)
    _pvg_check_batch(hidden.shape[0], mesh, M, batch_axes)
    if S == 1:
        if with_aux:
            return _pvg_single_stage_aux(
                run_stage, post_loss_fn, stacked_params, post_params,
                hidden, extras, loss_batch, rng, aux_cotangent, M,
            )
        return _pvg_single_stage(
            run_stage, post_loss_fn, stacked_params, post_params,
            hidden, extras, loss_batch, rng,
        )
    if L % (S * v):
        raise ValueError(
            f"{L} layers not divisible into {S} stages x {v} virtual chunks"
        )
    sc = make_interleaved_schedule(S, v, M)
    (seq_axis, n_seq, axes_all, is_batched, ex_dtypes, compute_dtype,
     plumb_dtype, hidden, extras) = _pvg_common(
        hidden, extras, mesh=mesh, axis_name=axis_name, seq_axis=seq_axis,
    )

    # schedule tables as device constants; each tick reads its own row
    tbl = {
        name: jnp.asarray(getattr(sc, name))
        for name in (
            "f_active", "f_micro", "f_chunk", "f_src_q", "f_save", "arr_f",
            "b_active", "b_micro", "b_chunk", "b_act", "b_src_q", "arr_b",
            "b_emit_dh",
        )
    }
    # tick-level (device-independent) gate for the loss-head vjp: the
    # ticks where device S-1 forwards the loss chunk — exactly M of them
    _t_loss_np = (sc.f_active[:, S - 1] == 1) & (sc.f_chunk[:, S - 1] == v - 1)
    if int(_t_loss_np.sum()) != M:  # not assert: must survive python -O
        raise ValueError(
            f"interleaved schedule runs the loss chunk {int(_t_loss_np.sum())} "
            f"times, expected {M}"
        )
    t_loss = jnp.asarray(_t_loss_np)

    def body(sp_local, pp, h, ex, lb, rt):
        h_shape = h.shape
        (s_idx, is_last, sp_local, pp, key, mb, micro, micro_ex, micro_lb,
         ex_at) = _pvg_body_prologue(
            sp_local, pp, h, ex, lb, rt, S=S, M=M, axis_name=axis_name,
            axes_all=axes_all, seq_axis=seq_axis, plumb_dtype=plumb_dtype,
            is_batched=is_batched, ex_dtypes=ex_dtypes,
        )
        # local rows -> (v, Lc, ...): chunk c of device s = global chunk
        # c*S + s (the interleaved storage order)
        sp_v = jax.tree.map(
            lambda a: a.reshape(v, a.shape[0] // v, *a.shape[1:]), sp_local
        )

        def chunk_key(c_idx, m_idx):
            if key is None:
                return None
            return jax.random.fold_in(jax.random.fold_in(key, c_idx), m_idx)

        def chunk_run(p_all, c_idx, x, ex_c, k):
            p_c = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, c_idx, 0, keepdims=False),
                p_all,
            )
            out = run_stage(p_c, x.astype(compute_dtype), ex_c, k)
            if with_aux:
                return out[0].astype(plumb_dtype), out[1]
            return out.astype(plumb_dtype)

        zeros_like_f32 = lambda t: jax.tree.map(  # noqa: E731
            lambda x: _vary(jnp.zeros(x.shape, jnp.float32), axes_all), t
        )
        zbuf = lambda n: _vary(jnp.zeros((n, mb, *h.shape[1:]), plumb_dtype), axes_all)  # noqa: E731
        fwd_in = zbuf(1)[0]
        bwd_in = zbuf(1)[0]
        fqbuf = zbuf(sc.fq_depth)
        bqbuf = zbuf(sc.bq_depth)
        act = zbuf(sc.act_depth)
        d_sp = zeros_like_f32(sp_v)
        d_pp = zeros_like_f32(pp)
        d_h = _vary(jnp.zeros((M, mb, *h.shape[1:]), jnp.float32), axes_all)
        scal0 = _vary(jnp.zeros((), jnp.float32), axes_all)
        aux_ct = _vary(jnp.asarray(aux_cotangent, jnp.float32), axes_all)
        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [(i, (i - 1) % S) for i in range(S)]

        def at(name, t):
            return tbl[name][t, s_idx]

        def tick(carry, t):
            (fwd_in, bwd_in, fqbuf, bqbuf, act, d_sp, d_pp, d_h, lsum, toks,
             aux_acc) = carry

            # ---- queue arrivals (values sent on the rings last tick)
            af = at("arr_f", t)
            fq_upd = jax.lax.dynamic_update_index_in_dim(
                fqbuf, fwd_in, jnp.clip(af, 0, sc.fq_depth - 1), 0
            )
            fqbuf = jnp.where(af >= 0, fq_upd, fqbuf)
            ab = at("arr_b", t)
            bq_upd = jax.lax.dynamic_update_index_in_dim(
                bqbuf, bwd_in, jnp.clip(ab, 0, sc.bq_depth - 1), 0
            )
            bqbuf = jnp.where(ab >= 0, bq_upd, bqbuf)

            # ---- forward slot
            f_on = at("f_active", t) == 1
            fm = at("f_micro", t)
            fc = at("f_chunk", t)
            fsrc = at("f_src_q", t)
            x0 = jax.lax.dynamic_index_in_dim(micro, fm, 0, keepdims=False)
            xq = jax.lax.dynamic_index_in_dim(
                fqbuf, jnp.clip(fsrc, 0, sc.fq_depth - 1), 0, keepdims=False
            )
            x_in = jnp.where(fsrc < 0, x0, xq)
            ex_f = ex_at(fm)
            y = chunk_run(sp_v, fc, x_in, ex_f, chunk_key(fc, fm))
            if with_aux:
                y, aux_f = y
                aux_acc = aux_acc + jnp.where(f_on, aux_f.astype(jnp.float32), 0.0)
            a_save = jnp.clip(at("f_save", t), 0, sc.act_depth - 1)
            act_upd = jax.lax.dynamic_update_index_in_dim(act, x_in, a_save, 0)
            act = jnp.where(f_on, act_upd, act)

            # ---- loss vjp on the in-tick forward output; tick-gated by
            # the schedule table (unvarying across devices → lax.cond),
            # folded only where this slot IS the loss chunk
            lb_f = jax.tree.map(
                lambda m: jax.lax.dynamic_index_in_dim(m, fm, 0, keepdims=False),
                micro_lb,
            )

            def loss_f(pp_, y_):
                return post_loss_fn(pp_, y_.astype(compute_dtype), lb_f)

            ls_m, tk_m, d_pp_m, dy_loss = _pvg_loss_vjp(loss_f, pp, y, t_loss[t])
            take_loss = f_on & is_last & (fc == v - 1)
            lsum = lsum + jnp.where(take_loss, ls_m.astype(jnp.float32), 0.0)
            toks = toks + jnp.where(take_loss, tk_m.astype(jnp.float32), 0.0)
            d_pp = jax.tree.map(
                lambda a_, g: a_ + jnp.where(take_loss, g.astype(jnp.float32), 0.0),
                d_pp, d_pp_m,
            )

            # ---- backward slot (recomputes its chunk forward under vjp)
            b_on = at("b_active", t) == 1
            bm = at("b_micro", t)
            bc = at("b_chunk", t)
            bsrc = at("b_src_q", t)
            x_b = jax.lax.dynamic_index_in_dim(
                act, jnp.clip(at("b_act", t), 0, sc.act_depth - 1), 0, keepdims=False
            )
            ex_b = ex_at(bm)
            k_b = chunk_key(bc, bm)

            def chunk_b(p_, x_):
                return chunk_run(p_, bc, x_, ex_b, k_b)

            _, chunk_vjp = jax.vjp(chunk_b, sp_v, x_b)
            dy_q = jax.lax.dynamic_index_in_dim(
                bqbuf, jnp.clip(bsrc, 0, sc.bq_depth - 1), 0, keepdims=False
            )
            dy_in = jnp.where(bsrc < 0, dy_loss.astype(plumb_dtype), dy_q)
            if with_aux:
                # constant objective coefficient on active backward ticks
                # (see pipeline_value_and_grad)
                d_sp_m, dx = chunk_vjp((dy_in, jnp.where(b_on, aux_ct, 0.0)))
            else:
                d_sp_m, dx = chunk_vjp(dy_in)
            d_sp = jax.tree.map(
                lambda a_, g: a_ + jnp.where(b_on, g.astype(jnp.float32), 0.0),
                d_sp, d_sp_m,
            )
            emit = (at("b_emit_dh", t) == 1) & b_on
            d_h_upd = jax.lax.dynamic_update_index_in_dim(
                d_h, dx.astype(jnp.float32), bm, 0
            )
            d_h = jnp.where(emit, d_h_upd, d_h)

            # ---- ring hops
            fwd_in = jax.lax.ppermute(y, axis_name, perm_fwd)
            bwd_in = jax.lax.ppermute(dx.astype(plumb_dtype), axis_name, perm_bwd)
            return (fwd_in, bwd_in, fqbuf, bqbuf, act, d_sp, d_pp, d_h, lsum, toks,
                    aux_acc), None

        carry = (fwd_in, bwd_in, fqbuf, bqbuf, act, d_sp, d_pp, d_h, scal0, scal0,
                 scal0)
        carry, _ = jax.lax.scan(tick, carry, jnp.arange(sc.T))
        (d_sp, d_pp, d_h, lsum, toks, aux_acc) = (
            carry[5], carry[6], carry[7], carry[8], carry[9], carry[10]
        )
        # (v, Lc, ...) grads back to the sharded row layout first
        d_sp = jax.tree.map(
            lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]), d_sp
        )
        out = _pvg_body_epilogue(
            lsum, toks, d_sp, d_pp, d_h, h_shape,
            axis_name=axis_name, axes_all=axes_all, seq_axis=seq_axis,
        )
        if with_aux:
            return (*out, jax.lax.psum(aux_acc, axis_name))
        return out

    return _pvg_shard_map(
        body, mesh=mesh, axis_name=axis_name, axes_all=axes_all,
        seq_axis=seq_axis, n_seq=n_seq, stacked_params=stacked_params,
        post_params=post_params, hidden=hidden, extras=extras,
        loss_batch=loss_batch, rng=rng, extras_seq_dims=extras_seq_dims,
        loss_seq_dims=loss_seq_dims, with_aux=with_aux,
    )
