"""Parameter and batch sharding rules.

Where the reference's only distribution strategy is full replication with
explicit gradient all-reduce (per-parameter ``dist.all_reduce(SUM)`` then
divide, reference train-task.py:65-69), here parallelism is declarative:
every parameter gets a ``PartitionSpec`` chosen by path-regex rules, the
batch is sharded over the ``("data","fsdp")`` axes, and the XLA SPMD
partitioner inserts the (bucketed, overlapped) collectives — the gradient
``pmean`` that replaces ``average_gradients`` costs zero lines of user code.

Rules are ordered (first match wins) and tested against the '/'-joined
parameter path.  A spec entry names a mesh axis, a tuple of axes, or None.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path: tuple) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


@dataclasses.dataclass
class ShardingRules:
    """Ordered (regex, PartitionSpec) rules mapped over a param pytree.

    The default rule set implements FSDP+TP for the transformer layouts in
    ``models/``:

    - embeddings:            ((tensor, fsdp), None) — vocab sharded over
                             both axes, d_model replicated (a d_model/fsdp
                             split here would push a d-sharded layout into
                             the batch-sharded residual stream — see the
                             rule comment below)
    - attention q/k/v/(o):   column/row split over ``tensor``, remainder
                             over ``fsdp`` (ZeRO-3 style)
    - MLP in/out:            column/row split over ``tensor``
    - norms / biases / scalars: replicated
    """

    rules: Sequence[tuple[str, P]]
    default: P = dataclasses.field(default_factory=P)

    def spec_for(self, path: str, ndim: int) -> P:
        i = self.match_path(path)
        if i is not None:
            return _clip_spec(self.match_rules()[i][1], ndim)
        return _clip_spec(self.default, ndim)

    def match_rules(self) -> Sequence[tuple[str, P]]:
        """The (pattern, spec) sequence ``match_path`` indexes into — the
        surface the dead-rule check and the spec lint walk."""
        return self.rules

    def match_path(self, path: str) -> int | None:
        """Index of the first rule matching ``path`` (first match wins), or
        None for the default fallthrough."""
        for i, (pattern, _) in enumerate(self.match_rules()):
            if re.search(pattern, path):
                return i
        return None

    def tree_specs(self, params: Any) -> Any:
        # tree_util spelling: jax.tree.map_with_path only exists on newer
        # jax than this image ships; tree_util has carried it for years
        return jax.tree_util.tree_map_with_path(
            lambda path, x: self.spec_for(_path_str(path), getattr(x, "ndim", 0)), params
        )


def _clip_spec(spec: P, ndim: int) -> P:
    """Truncate a spec to the array rank (so one rule covers kernel+bias)."""
    if len(spec) <= ndim:
        return spec
    return P(*spec[:ndim])


# Matches the parameter naming used by models/ (flax.linen module paths).
DEFAULT_RULES: list[tuple[str, P]] = [
    # token / position embeddings: (vocab, d_model) — vocab over BOTH tensor
    # and fsdp, d_model replicated.  Sharding d_model over fsdp here pushes a
    # d-sharded layout into the batch-sharded residual stream through the
    # gather, which GSPMD reconciles by involuntary full rematerialization
    # (replicate + repartition) on every lookup/scatter; vocab-only sharding
    # keeps the same per-device memory without that cliff.
    # learned position tables are tiny (BART: (max_positions+2, d_model) —
    # 1026 rows for bart-large, not divisible by tensor×fsdp) → replicate
    (r"embed_positions/embedding", P()),
    (r"(shared|embed_tokens|lm_head)/embedding", P(("tensor", "fsdp"), None)),
    (r"lm_head/kernel", P("fsdp", "tensor")),
    # attention projections: q/k/v are column-parallel (d_model, heads*head_dim),
    # o is row-parallel (heads*head_dim, d_model)
    (r"(self_attn|cross_attn|attention)/(q|k|v)_proj/kernel", P("fsdp", "tensor")),
    (r"(self_attn|cross_attn|attention)/o_proj/kernel", P("tensor", "fsdp")),
    # MoE: stacked expert weights — experts over the dedicated ``expert``
    # axis (GSPMD lowers the dispatch/combine einsums to the expert
    # all-to-all), megatron column/row splits over ``tensor`` WITHIN each
    # expert (EP × TP compose instead of competing for one axis, the round-2
    # weld VERDICT weak #5 called out), remainder over ``fsdp``; fp32
    # router replicated (falls through to default)
    (r"mlp/(gate_proj|up_proj)$", P("expert", "fsdp", "tensor")),
    (r"mlp/down_proj$", P("expert", "tensor", "fsdp")),
    # MLP: in column-parallel, out row-parallel
    (r"mlp/(wi|wi_0|wi_1|gate_proj|up_proj|fc1)/kernel", P("fsdp", "tensor")),
    (r"mlp/(wo|down_proj|fc2)/kernel", P("tensor", "fsdp")),
    # relative position bias tables: (buckets, heads) — heads over tensor
    (r"relative_attention_bias/embedding", P(None, "tensor")),
    # anything unmatched (norm scales, biases, scalars) falls through to
    # ShardingRules.default = replicated
]


def default_rules() -> ShardingRules:
    return ShardingRules(rules=DEFAULT_RULES)


# Serving state: the per-layer KV cache is the SECOND long-lived sharded
# tree (params being the first) — slot rows over the batch axes
# (data×fsdp×expert, like the batch they decode), heads over ``tensor``
# (like the attention projections that produce them), sequence position
# and head_dim replicated.  The per-module ``cache_index`` counters are
# scalars and stay replicated.  ``analysis/spec_lint.py
# lint_cache_sharding`` validates this rule set against an abstract cache
# tree exactly like the param rules; ``parallel/activation.py
# constrain_cache`` applies it inside the compiled prefill/decode
# programs.
CACHE_RULES: list[tuple[str, P]] = [
    (r"(cached_key|cached_value)$", P(("data", "fsdp", "expert"), "tensor", None, None)),
    # int8 KV cache (--kv-cache-dtype int8): per-head per-position f32
    # scales, (batch, heads, len) — the K/V layout minus head_dim, so the
    # scales always live next to the buffers they dequantize
    (r"(key_scale|value_scale)$", P(("data", "fsdp", "expert"), "tensor", None)),
    (r"cache_index$", P()),
]


def cache_rules() -> ShardingRules:
    return ShardingRules(rules=CACHE_RULES)


# Paged serving state (--paged-kv): the shared block pool replaces the
# per-slot K/V buffers as the resident serving tree.  Blocks belong to
# individual slots, so the block dim cannot shard over the batch axes the
# way slot rows do (a slot's blocks would scatter across devices and every
# gather would cross the mesh); heads still split over ``tensor`` like the
# projections that produce them.  ``analysis/spec_lint.py
# lint_cache_sharding`` validates this rule set over the abstract pool
# exactly like CACHE_RULES over the slot cache.
POOL_RULES: list[tuple[str, P]] = [
    (r"(cached_key|cached_value)$", P(None, "tensor", None, None)),
    (r"(key_scale|value_scale)$", P(None, "tensor", None)),
    (r"cache_index$", P()),
]


def pool_rules() -> ShardingRules:
    return ShardingRules(rules=POOL_RULES)


def kv_leaf_spec(shape: tuple, mesh_axes: Any) -> P:
    """The CACHE_RULES layout for one (batch, heads, len, head_dim) K/V
    leaf, divisibility-guarded per-dim (ragged batch or head counts
    replicate that dim, mirroring ``divisible_spec``).  THE single
    definition of the serving K/V layout — ``activation.constrain_kv``
    (in-graph constraints) and the engine's host-side placement both
    derive from it, so they cannot drift."""
    batch_shards = 1
    for a in ("data", "fsdp", "expert"):
        batch_shards *= mesh_axes.get(a, 1)
    batch = (
        ("data", "fsdp", "expert")
        if shape[0] % max(batch_shards, 1) == 0
        else None
    )
    heads = (
        "tensor" if shape[1] % max(mesh_axes.get("tensor", 1), 1) == 0 else None
    )
    return P(batch, heads, None, None)


def kv_scale_spec(shape: tuple, mesh_axes: Any) -> P:
    """The CACHE_RULES layout for one (batch, heads, len) int8-KV scale
    leaf — ``kv_leaf_spec`` minus the head_dim axis, divisibility-guarded
    the same way.  THE single definition of the scale layout:
    ``activation.constrain_kv_scale`` and the engine's host placement
    both derive from it."""
    full = kv_leaf_spec((*shape, 1), mesh_axes)
    return P(full[0], full[1], None)


# Pipelined (stage>1) param layout: stacked block trees shard their leading
# layer dim over ``stage`` AND keep the default megatron/FSDP splits on the
# per-layer dims behind it (stage × tensor × fsdp compose — the pipeline
# shard_map is manual over ``stage`` only, so GSPMD still partitions the
# inner compute); non-stacked params (embed/norms/head) use the default
# rules directly.
@dataclasses.dataclass
class PipelineShardingRules(ShardingRules):
    """Wraps the default rules: a ``stacked_blocks/`` path gets
    P("stage", *inner-spec-of-the-per-layer-path); other paths pass
    through unchanged."""

    inner: ShardingRules = dataclasses.field(default_factory=lambda: ShardingRules(DEFAULT_RULES))

    def spec_for(self, path: str, ndim: int) -> P:
        # matches stacked_blocks/ (llama, t5 nested) and
        # stacked_{encoder,decoder}_blocks/ (bart)
        m = re.search(r"stacked_[a-z]*_?blocks/", path)
        if m:
            rest = path[m.end():]
            inner = self.inner.spec_for(rest, max(ndim - 1, 0))
            return _clip_spec(P("stage", *inner), ndim)
        return self.inner.spec_for(path, ndim)

    def match_rules(self) -> Sequence[tuple[str, P]]:
        return self.inner.match_rules()

    def match_path(self, path: str) -> int | None:
        m = re.search(r"stacked_[a-z]*_?blocks/", path)
        return self.inner.match_path(path[m.end():] if m else path)


def pipeline_rules() -> ShardingRules:
    return PipelineShardingRules(rules=())


def tree_paths(tree: Any) -> list[str]:
    """'/'-joined path of every leaf — the strings the rule regexes see."""
    paths: list[str] = []
    jax.tree_util.tree_map_with_path(
        lambda path, _: paths.append(_path_str(path)), tree
    )
    return paths


def rule_match_counts(rules: ShardingRules, tree: Any) -> list[int]:
    """How many leaf paths each rule wins (first match wins — a rule
    shadowed by an earlier one counts as unmatched), aligned with
    ``rules.match_rules()``."""
    counts = [0] * len(rules.match_rules())
    for path in tree_paths(tree):
        i = rules.match_path(path)
        if i is not None:
            counts[i] += 1
    return counts


def find_dead_rules(rules: ShardingRules, tree: Any) -> list[str]:
    """Patterns that matched zero parameter paths.  A dead rule is how a
    typo'd regex silently replicates the parameters it meant to shard —
    the tree it intended to match falls through to ``rules.default``."""
    return [
        pattern
        for (pattern, _), n in zip(rules.match_rules(), rule_match_counts(rules, tree))
        if n == 0
    ]


def divisible_spec(spec: P, shape: tuple, mesh: Mesh) -> P:
    """Drop spec entries whose mesh-axes product doesn't divide the dim.

    Ragged dims are real: bart-large-cnn's vocab is 50265 (odd), so a
    ``(tensor, fsdp)`` split can't apply on even meshes — ``device_put``
    would refuse outright.  Replicating just that dim (the JAX sharding
    model has no padded shards) keeps the rule set model-agnostic; the
    big divisible tables (t5 32128, llama 32000) still shard fully.
    """
    out = []
    for i, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            n *= mesh.shape.get(a, 1)
        out.append(entry if i < len(shape) and shape[i] % n == 0 else None)
    return P(*out)


_RAGGED_LOGGED: set = set()


def resolve_shardings(tree: Any, mesh: Mesh, rules: ShardingRules | None = None) -> Any:
    """Pytree of NamedSharding for any pytree (params, TrainState, ...):
    path-regex rules → specs, clipped to rank and to mesh divisibility.
    Dropped (ragged) entries are logged once per (spec, shape): replicating
    e.g. a 50265-row vocab table instead of sharding it is a real
    per-device memory change an operator must be able to see in the run log.
    """
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    rules = rules or default_rules()
    specs = rules.tree_specs(tree)

    def resolve(s: P, x: Any) -> NamedSharding:
        shape = tuple(getattr(x, "shape", ()))
        got = divisible_spec(s, shape, mesh)
        if got != _clip_spec(s, len(shape)):
            key = (str(s), shape)
            if key not in _RAGGED_LOGGED:
                _RAGGED_LOGGED.add(key)
                log_json({
                    "event": "sharding_fallback",
                    "reason": f"shape {shape} not divisible by spec {s} on mesh "
                              f"{dict(mesh.shape)}; ragged dims replicated",
                    "spec": str(got),
                })
        return NamedSharding(mesh, got)

    return jax.tree.map(resolve, specs, tree, is_leaf=lambda x: isinstance(x, P))


def infer_param_shardings(params: Any, mesh: Mesh, rules: ShardingRules | None = None) -> Any:
    """Pytree of NamedSharding matching ``params``."""
    return resolve_shardings(params, mesh, rules)


def batch_sharding(mesh: Mesh, *, sequence_sharded: bool = False) -> NamedSharding:
    """Batch arrays are (batch, length): batch over data+fsdp+expert (each
    expert group works distinct tokens; the MoE all-to-all routes them),
    length optionally over sequence (context parallelism)."""
    if sequence_sharded:
        return NamedSharding(mesh, P(("data", "fsdp", "expert"), "sequence"))
    return NamedSharding(mesh, P(("data", "fsdp", "expert"), None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_params(params: Any, mesh: Mesh, rules: ShardingRules | None = None) -> Any:
    """Device-put a host param tree onto the mesh with the rule shardings.

    Dead rules (regexes that matched zero parameter paths) are logged
    after mapping the tree: the normal-path surface of the analysis/ spec
    lint's core check — a typo'd pattern means the params it meant to
    shard fell through to the replicated default.  Severity "warning"
    for a caller-supplied rule set; "info" for the stock DEFAULT_RULES,
    whose multi-family union is dead-by-design on any single model."""
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    rules = rules or default_rules()
    dead = find_dead_rules(rules, params)
    if dead:
        log_json({
            "event": "dead_sharding_rules",
            "severity": (
                "info" if rules.match_rules() is DEFAULT_RULES else "warning"
            ),
            "reason": "sharding rules matched zero parameter paths; the "
                      "params they targeted (if any) fell through to the "
                      "replicated default",
            "patterns": dead,
        })
    shardings = infer_param_shardings(params, mesh, rules)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), params, shardings)
