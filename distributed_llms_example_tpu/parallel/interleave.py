"""Interleaved (virtual-stage) 1F1B pipeline schedule generation.

Plain 1F1B (``pipeline_value_and_grad``) gives each of the S ``stage``
devices ONE contiguous block of L/S layers, so the pipeline fill/drain
bubble is (S-1) chunk-times long.  Interleaving (the Megatron-LM
"virtual pipeline" refinement — reimplemented here from the published
schedule shape, not from any code) gives each device ``v`` NON-contiguous
chunks of L/(S*v) layers: global chunk ``g = c*S + s`` lives on device
``s``, so a microbatch hops device 0..S-1 v times.  Each schedule slot
then moves 1/v of the work, cutting the fill/drain bubble toward
(S-1)/v chunk-times — the standard way to make deep pipelines affordable
at small microbatch counts.  The price, stated honestly: up to ~v times
more in-flight chunk inputs buffered per device (each one microbatch
hidden; the per-slot remat transient shrinks by v), and v times more
ppermute hops per microbatch.

The reference has no pipeline parallelism at all (SURVEY.md §2); this
module is part of going past parity, like ops/ring_attention.py.

Design: schedules are PRECOMPUTED here in pure Python as numpy tables
(one row per tick, one column per device) and executed by a table-driven
``lax.scan`` in ``parallel/pipeline.py``.  All correctness constraints —
dependency order, one F and one B slot per device per tick, hop latency,
buffer slot lifetimes — are enforced by construction and independently
re-checked by ``validate_schedule`` from the tables alone, so the
on-device executor contains no scheduling logic, only masked dynamic
indexing.  A greedy backward-first list scheduler reproduces 1F1B
behavior (backwards drain as soon as dependencies allow) without
hand-deriving Megatron's closed-form warmup counts.

Execution model the tables assume (mirrors the 1F1B executor):

- Each tick every device runs one FORWARD slot then one BACKWARD slot
  (masked when inactive, so SPMD compute is uniform).
- The F slot's output hops +1 on the stage ring between ticks; the B
  slot's activation-gradient hops -1.  Arrivals are written into fixed
  queue slots at the START of the next tick.
- The F slot saves its INPUT into an act-buffer slot; the B slot
  recomputes the chunk forward from that slot under ``jax.vjp``.
- The LAST global chunk's backward runs in the SAME tick as its forward
  (the executor computes F before B within a tick): the loss vjp consumes
  the in-tick forward output, exactly like the non-interleaved 1F1B.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "InterleavedSchedule",
    "interleave_order",
    "interleave_tree",
    "make_interleaved_schedule",
    "uninterleave_order",
    "uninterleave_tree",
    "validate_schedule",
]


def _check(cond, msg: str) -> None:
    """Schedule-validation check that survives ``python -O`` (a stripped
    ``assert`` here would silently drop the independent safety net the
    table executor relies on)."""
    if not cond:
        raise ValueError(f"invalid interleaved schedule: {msg}")


def interleave_order(L: int, S: int, v: int) -> np.ndarray:
    """Row permutation for interleaved storage: ``order[new_row]`` is the
    TRUE layer index.  Device ``s``'s shard (rows ``s*L/S .. (s+1)*L/S``)
    then holds its v chunks contiguously — chunk ``c`` at local offset
    ``c*Lc`` covering true layers ``(c*S + s)*Lc .. + Lc`` — which is what
    ``pipeline_value_and_grad_interleaved``'s ``(v, Lc)`` reshape assumes."""
    if S < 1 or v < 1:
        raise ValueError(f"stages and virtual chunks must be >= 1, got S={S} v={v}")
    if L % (S * v):
        raise ValueError(f"{L} layers not divisible into {S} stages x {v} chunks")
    Lc = L // (S * v)
    order = np.empty(L, np.int64)
    for s in range(S):
        for c in range(v):
            for j in range(Lc):
                order[s * (L // S) + c * Lc + j] = (c * S + s) * Lc + j
    return order


def interleave_tree(stacked, S: int, v: int):
    """Reorder every leaf's leading (layer) dim into interleaved storage
    order.  Works on numpy or jax arrays (``take`` along axis 0)."""
    import jax

    L = jax.tree.leaves(stacked)[0].shape[0]
    order = interleave_order(L, S, v)
    return jax.tree.map(lambda a: a.take(order, axis=0), stacked)


def uninterleave_order(L: int, S: int, v: int) -> np.ndarray:
    """Inverse of ``interleave_order``: ``inv[true_layer]`` is the storage
    row holding that layer — the single shared definition every
    storage→true-order consumer (eval unstack, export, tree un-permute)
    must use."""
    return np.argsort(interleave_order(L, S, v))


def uninterleave_tree(stacked, S: int, v: int):
    """Inverse of ``interleave_tree`` — back to true layer order (for
    eval/export unstacking)."""
    import jax

    L = jax.tree.leaves(stacked)[0].shape[0]
    inv = uninterleave_order(L, S, v)
    return jax.tree.map(lambda a: a.take(inv, axis=0), stacked)


@dataclass(frozen=True)
class InterleavedSchedule:
    """Table-driven schedule: all arrays are (T, S) int32.

    Forward slot of device s at tick t:
      f_active[t, s]  — 1 when the slot runs a real unit
      f_micro[t, s]   — microbatch index m
      f_chunk[t, s]   — LOCAL chunk index c (global chunk g = c*S + s)
      f_src_q[t, s]   — fwd-queue slot holding the chunk input (-1: read
                        the microbatch store; global chunk 0 only)
      f_save[t, s]    — act-buffer slot the chunk INPUT is saved to
      arr_f[t, s]     — fwd-queue slot the value arriving on the forward
                        ring this tick is written to (-1: nothing arrives)
    Backward slot mirrors forward:
      b_active, b_micro, b_chunk,
      b_act[t, s]     — act-buffer slot holding the saved chunk input
      b_src_q[t, s]   — bwd-queue slot holding the incoming activation
                        gradient (-1: in-tick loss vjp; last chunk only)
      arr_b[t, s]     — bwd-queue arrival slot this tick (-1: none)
      b_emit_dh[t, s] — 1 when this backward's dx is d_hidden (chunk 0)
    Sizes: T ticks; fq_depth/bq_depth/act_depth buffer slot counts.
    """

    S: int
    v: int
    M: int
    T: int
    fq_depth: int
    bq_depth: int
    act_depth: int
    f_active: np.ndarray
    f_micro: np.ndarray
    f_chunk: np.ndarray
    f_src_q: np.ndarray
    f_save: np.ndarray
    arr_f: np.ndarray
    b_active: np.ndarray
    b_micro: np.ndarray
    b_chunk: np.ndarray
    b_act: np.ndarray
    b_src_q: np.ndarray
    arr_b: np.ndarray
    b_emit_dh: np.ndarray
    meta: dict = field(default_factory=dict)


class _SlotPool:
    """Free-list of buffer slots; grows on demand, records peak size."""

    def __init__(self):
        self.free: list[int] = []
        self.next = 0

    def take(self) -> int:
        if self.free:
            return self.free.pop()
        s = self.next
        self.next += 1
        return s

    def give(self, s: int) -> None:
        self.free.append(s)

    @property
    def peak(self) -> int:
        return self.next


def make_interleaved_schedule(S: int, v: int, M: int) -> InterleavedSchedule:
    """Greedy backward-first list schedule for S devices, v chunks each,
    M microbatches.  ``validate_schedule`` runs on the result before it is
    returned."""
    if S < 2:
        raise ValueError(f"interleaving needs stage >= 2, got {S}")
    if v < 1:
        raise ValueError(f"virtual stages must be >= 1, got {v}")
    if M < 1:
        raise ValueError(f"need at least one microbatch, got {M}")
    G = v * S
    LAST = G - 1  # the loss chunk, on device S-1, local chunk v-1

    f_done = [[None] * M for _ in range(G)]
    b_done = [[None] * M for _ in range(G)]
    fq = [dict() for _ in range(S)]      # (g, m) -> queue slot
    bq = [dict() for _ in range(S)]
    fq_pool = [_SlotPool() for _ in range(S)]
    bq_pool = [_SlotPool() for _ in range(S)]
    act_pool = [_SlotPool() for _ in range(S)]
    act_slot = [dict() for _ in range(S)]

    def dev(g: int) -> int:
        return g % S

    def fwd_order(g: int, m: int) -> tuple:
        # Megatron-style grouping: microbatches advance in rounds of S per
        # chunk — (round, chunk, member) aligns chunk order across devices
        # so chunk-boundary queue waits stay bounded.
        return (m // S, g // S, m % S)

    rows: list[dict] = []
    hop_f: list[tuple] = []
    hop_b: list[tuple] = []
    t = 0
    total = G * M
    done_b = 0
    max_ticks = 6 * (v * M + 2 * S) + 32
    while done_b < total:
        if t > max_ticks:
            raise RuntimeError(
                f"schedule did not converge: S={S} v={v} M={M} tick={t}"
            )
        row = {"arr_f": [-1] * S, "arr_b": [-1] * S, "f": [None] * S, "b": [None] * S}

        # deliver last tick's hops into queues (visible this tick)
        for (g, m) in hop_f:
            if g + 1 < G:
                d = dev(g + 1)
                slot = fq_pool[d].take()
                fq[d][(g + 1, m)] = slot
                row["arr_f"][d] = slot
        for (g, m) in hop_b:
            if g - 1 >= 0:
                d = dev(g - 1)
                slot = bq_pool[d].take()
                bq[d][(g - 1, m)] = slot
                row["arr_b"][d] = slot
        hop_f, hop_b = [], []

        for s in range(S):
            # ---- backward slot first (1F1B drain): earliest microbatch,
            # deepest chunk; the loss chunk is handled by F/B pairing below
            cand_b = []
            for g in range(s, G, S):
                if g == LAST:
                    continue
                for m in range(M):
                    if b_done[g][m] is None and (g, m) in bq[s]:
                        cand_b.append((m, -g, g))
            b_pick = min(cand_b) if cand_b else None

            # ---- forward slot: Megatron grouping order.  The loss
            # chunk's F is eligible only when the B slot can pair with it
            # in the same tick.
            cand_f = []
            for g in range(s, G, S):
                for m in range(M):
                    if f_done[g][m] is not None:
                        continue
                    if g == 0 or (g, m) in fq[s]:
                        if g == LAST and b_pick is not None:
                            continue  # B slot taken; pair next tick
                        cand_f.append((fwd_order(g, m), g, m))
            f_pick = min(cand_f) if cand_f else None

            if f_pick is not None:
                _, g, m = f_pick
                a = act_pool[s].take()
                act_slot[s][(g, m)] = a
                if g == 0:
                    src = -1
                else:
                    src = fq[s].pop((g, m))
                    fq_pool[s].give(src)
                row["f"][s] = (g, m, src, a)
                f_done[g][m] = t
                hop_f.append((g, m))
                if g == LAST:
                    # paired in-tick backward (loss vjp on the fresh y)
                    assert b_pick is None
                    b_done[g][m] = t
                    done_b += 1
                    hop_b.append((g, m))
                    a2 = act_slot[s].pop((g, m))
                    act_pool[s].give(a2)
                    row["b"][s] = (g, m, -1, a)
                    b_pick = "paired"

            if b_pick is not None and b_pick != "paired":
                m, _, g = b_pick
                src = bq[s].pop((g, m))
                bq_pool[s].give(src)
                a = act_slot[s].pop((g, m))
                act_pool[s].give(a)
                row["b"][s] = (g, m, src, a)
                b_done[g][m] = t
                done_b += 1
                hop_b.append((g, m))

        rows.append(row)
        t += 1

    T = len(rows)

    def tab(fill=0):
        return np.full((T, S), fill, np.int32)

    f_active, f_micro, f_chunk = tab(), tab(), tab()
    f_src_q, f_save, arr_f, arr_b = tab(-1), tab(-1), tab(-1), tab(-1)
    b_active, b_micro, b_chunk = tab(), tab(), tab()
    b_act, b_src_q, b_emit_dh = tab(-1), tab(-1), tab()

    for t, row in enumerate(rows):
        for s in range(S):
            arr_f[t, s] = row["arr_f"][s]
            arr_b[t, s] = row["arr_b"][s]
            if row["f"][s] is not None:
                g, m, src, a = row["f"][s]
                f_active[t, s] = 1
                f_micro[t, s] = m
                f_chunk[t, s] = g // S
                f_src_q[t, s] = src
                f_save[t, s] = a
            if row["b"][s] is not None:
                g, m, src, a = row["b"][s]
                b_active[t, s] = 1
                b_micro[t, s] = m
                b_chunk[t, s] = g // S
                b_src_q[t, s] = src
                b_act[t, s] = a
                b_emit_dh[t, s] = 1 if g == 0 else 0

    sched = InterleavedSchedule(
        S=S, v=v, M=M, T=T,
        fq_depth=max(max(p.peak for p in fq_pool), 1),
        bq_depth=max(max(p.peak for p in bq_pool), 1),
        act_depth=max(max(p.peak for p in act_pool), 1),
        f_active=f_active, f_micro=f_micro, f_chunk=f_chunk,
        f_src_q=f_src_q, f_save=f_save, arr_f=arr_f,
        b_active=b_active, b_micro=b_micro, b_chunk=b_chunk,
        b_act=b_act, b_src_q=b_src_q, arr_b=arr_b, b_emit_dh=b_emit_dh,
        meta={"ticks": T, "ideal_ticks": v * M, "bubble_ticks": T - v * M},
    )
    validate_schedule(sched)
    return sched


def validate_schedule(sc: InterleavedSchedule) -> None:
    """Re-check every execution constraint from the tables alone (the
    generator's internal state is not trusted): every unit runs exactly
    once; forward dependency order with hop latency >= 1; backward after
    (same tick for the loss chunk as) its forward and before the previous
    chunk's backward; queue/act slots written before read, never clobbered
    while live, and freed exactly once; every send has a matching arrival."""
    S, v, M, G, T = sc.S, sc.v, sc.M, sc.v * sc.S, sc.T
    f_tick, b_tick = {}, {}
    for t in range(T):
        for s in range(S):
            if sc.f_active[t, s]:
                key = (sc.f_chunk[t, s] * S + s, int(sc.f_micro[t, s]))
                _check(key not in f_tick, f"F{key} scheduled twice")
                f_tick[key] = t
            if sc.b_active[t, s]:
                key = (sc.b_chunk[t, s] * S + s, int(sc.b_micro[t, s]))
                _check(key not in b_tick, f"B{key} scheduled twice")
                b_tick[key] = t
    _check(len(f_tick) == G * M, f"{len(f_tick)} forward units != {G * M}")
    _check(len(b_tick) == G * M, f"{len(b_tick)} backward units != {G * M}")
    for g in range(G):
        for m in range(M):
            if g > 0:
                _check(f_tick[(g, m)] > f_tick[(g - 1, m)], f"F({g},{m}) not after F({g - 1},{m})")
            if g < G - 1:
                _check(b_tick[(g, m)] > b_tick[(g + 1, m)], f"B({g},{m}) not after B({g + 1},{m})")
            if g == G - 1:
                _check(b_tick[(g, m)] == f_tick[(g, m)], "loss-chunk backward must pair with its forward in-tick")
            else:
                _check(
                    b_tick[(g, m)] > f_tick[(g, m)],
                    f"B({g},{m}) must run after F({g},{m})",
                )

    # buffer lifetime simulation straight from the tables; within a tick
    # the executor order is: queue arrivals, then F (reads fq, writes
    # act), then B (reads act + bq).  Queue entries track the UNIT whose
    # value they hold (like the act check), so a generator bug that swaps
    # two in-flight units' slot assignments — write-before-read and
    # no-clobber both still holding — cannot slip a wrong microbatch's
    # activation into a chunk vjp.
    for s in range(S):
        live_f, live_b, live_a = {}, {}, {}
        for t in range(T):
            if sc.arr_f[t, s] >= 0:
                _check(t > 0, f"fq arrival at tick 0 has no sender (s={s})")
                _check(sc.arr_f[t, s] not in live_f, f"fq clobber t={t} s={s}")
                src = (s - 1) % S
                g_sent = int(sc.f_chunk[t - 1, src]) * S + src
                live_f[int(sc.arr_f[t, s])] = (g_sent + 1, int(sc.f_micro[t - 1, src]))
            if sc.arr_b[t, s] >= 0:
                _check(t > 0, f"bq arrival at tick 0 has no sender (s={s})")
                _check(sc.arr_b[t, s] not in live_b, f"bq clobber t={t} s={s}")
                srcb = (s + 1) % S
                g_b = int(sc.b_chunk[t - 1, srcb]) * S + srcb
                live_b[int(sc.arr_b[t, s])] = (g_b - 1, int(sc.b_micro[t - 1, srcb]))
            if sc.f_active[t, s]:
                g = int(sc.f_chunk[t, s]) * S + s
                m = int(sc.f_micro[t, s])
                q = int(sc.f_src_q[t, s])
                if q >= 0:
                    _check(q in live_f, f"fq slot {q} read before write t={t} s={s}")
                    _check(live_f[q] == (g, m), f"fq slot {q} holds unit {live_f[q]}, forward wants ({g}, {m})")
                    del live_f[q]
                else:
                    _check(g == 0, "src -1 is chunk-0 only")
                a = int(sc.f_save[t, s])
                _check(a >= 0 and a not in live_a, f"act clobber t={t} s={s}")
                live_a[a] = (int(sc.f_chunk[t, s]), int(sc.f_micro[t, s]))
            if sc.b_active[t, s]:
                g = int(sc.b_chunk[t, s]) * S + s
                m = int(sc.b_micro[t, s])
                q = int(sc.b_src_q[t, s])
                if q >= 0:
                    _check(q in live_b, f"bq slot {q} read before write t={t} s={s}")
                    _check(live_b[q] == (g, m), f"bq slot {q} holds unit {live_b[q]}, backward wants ({g}, {m})")
                    del live_b[q]
                else:
                    _check(g == G - 1, "src -1 is loss chunk only")
                a = int(sc.b_act[t, s])
                _check(a in live_a, f"act slot {a} not live t={t} s={s}")
                _check(live_a[a] == (int(sc.b_chunk[t, s]), int(sc.b_micro[t, s])), f"act slot {a} holds {live_a[a]} but backward wants " f"({int(sc.b_chunk[t, s])}, {int(sc.b_micro[t, s])})")
                del live_a[a]
        _check(not live_a, f"act slots leaked on device {s}: {live_a}")
        _check(not live_f, f"fwd-queue slots leaked on device {s}: {live_f}")
        _check(not live_b, f"bwd-queue slots leaked on device {s}: {live_b}")

    # every ring send must land in a queue slot on the right neighbor one
    # tick later (or be the final chunk, which sends nothing useful)
    for t in range(T):
        for s in range(S):
            if sc.f_active[t, s]:
                g = sc.f_chunk[t, s] * S + s
                if g + 1 < G:
                    d = (s + 1) % S
                    _check(t + 1 < T and sc.arr_f[t + 1, d] >= 0, f"F output of t={t} s={s} (g={g}) never delivered")
            if sc.b_active[t, s]:
                g = sc.b_chunk[t, s] * S + s
                if g - 1 >= 0:
                    d = (s - 1) % S
                    _check(t + 1 < T and sc.arr_b[t + 1, d] >= 0, f"B output of t={t} s={s} (g={g}) never delivered")
    # conversely: an arrival implies its sender was active last tick
    for t in range(1, T):
        for s in range(S):
            if sc.arr_f[t, s] >= 0:
                src = (s - 1) % S
                _check(sc.f_active[t - 1, src], f"fq arrival t={t} s={s} unsent")
            if sc.arr_b[t, s] >= 0:
                src = (s + 1) % S
                _check(sc.b_active[t - 1, src], f"bq arrival t={t} s={s} unsent")
