from distributed_llms_example_tpu.parallel.sharding import (
    ShardingRules,
    batch_sharding,
    infer_param_shardings,
    replicated,
)

__all__ = ["ShardingRules", "batch_sharding", "infer_param_shardings", "replicated"]
