from distributed_llms_example_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_blocks,
    unstack_blocks,
)
from distributed_llms_example_tpu.parallel.sharding import (
    ShardingRules,
    batch_sharding,
    infer_param_shardings,
    pipeline_rules,
    replicated,
    resolve_shardings,
)

__all__ = [
    "ShardingRules",
    "batch_sharding",
    "infer_param_shardings",
    "pipeline_apply",
    "pipeline_rules",
    "replicated",
    "resolve_shardings",
    "stack_blocks",
    "unstack_blocks",
]
