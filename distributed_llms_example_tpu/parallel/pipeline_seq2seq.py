"""Fused 1F1B for TWIN encoder→decoder pipelines (BART/T5, stage>1).

The gpipe seq2seq adapters (``PipelinedBart``/``PipelinedT5``) run two
``pipeline_apply`` calls back to back — encoder drains fully, then its
output feeds every decoder stage's cross-attention — and differentiate the
whole thing with autodiff, which must keep O(M) microbatch activations
alive per stage between the forward and reversed-backward scans.  This
module gives the reference's flagship model family (bart-large-cnn,
reference valohai.yaml:10) and the flan-t5-xl BASELINE config the same
O(S)-memory fused schedule ``pipeline_value_and_grad`` gives LLaMA.

Design — ONE pipeline of 2S chunks over S devices, table-driven:

- Device ``s`` holds encoder part ``s`` (global chunk ``s``) and decoder
  part ``s`` (global chunk ``S + s``): exactly the interleaved-schedule
  chunk placement ``g = c*S + s`` with v=2 virtual chunks, so the
  precomputed tables from ``parallel/interleave.py`` orchestrate the twin
  pipeline unchanged — a microbatch rides the stage ring through all S
  encoder chunks, wraps 0→S-1→0, and rides it again through the S decoder
  chunks; forwards and backwards interleave 1F1B-style with the loss vjp
  folded into the last decoder chunk's tick.
- The carried value is an ``{"enc", "dec"}`` PAIR (source and target
  lengths differ, so one buffer cannot hold both).  Encoder chunks map
  ``enc`` and pass ``dec`` through; decoder chunks pass ``enc`` through —
  every later decoder chunk still needs it for cross-attention — and map
  ``dec``.  The pass-throughs are differentiated with everything else, so
  the backward ring's ``enc`` component accumulates each decoder chunk's
  cross-attention gradient for free.
- Each tick a device runs EITHER its encoder chunk or its decoder chunk.
  On pure stage(×data) meshes that is a ``lax.cond`` on the table's chunk
  id — a device-varying predicate; one branch executes, so a tick costs
  one chunk.  On meshes whose AUTO axes shard the block params (fsdp /
  tensor: GSPMD inserts all-gathers/all-reduces INSIDE the chunk bodies)
  the cond is unsound: stages on different branches would execute
  different collective sequences and the rendezvous deadlocks (observed
  as an XLA collective-permute rendezvous abort on CPU; a hang on TPU).
  There the executor computes BOTH chunks and selects — collectives run
  uniformly on every device, at the honest price of one extra
  decoder-chunk-equivalent per tick (small next to the encoder chunk at
  summarization shapes: tgt 128 vs src 1024).  fsdp>1 is guarded off
  entirely: the partitioner crashes compiling the chunk-pair program
  with dim-0-sharded params under either dispatch mode (gpipe remains
  the fsdp×stage path for seq2seq).
- The enc→dec SEAM (device 0's decoder chunk): the decoder embedding
  enters from the microbatch store (like global chunk 0's input), an
  optional differentiable ``seam_fn`` (T5's encoder final-norm + dropout)
  transforms the arriving encoder output once per microbatch, and on the
  backward the pair's ``dec`` gradient is emitted as d(decoder embedding)
  and cut from the ring before it would leak into the encoder phase.
- ``diff_extras``: replicated per-call inputs that DO need gradients
  (T5's relative-position bias tensors) — chunk vjps accumulate their
  cotangents across every (chunk, microbatch), psum'd in the epilogue.

Same contracts as ``pipeline_value_and_grad`` otherwise: microbatch math
is identical to the sequential computation (schedule-only reordering,
pinned by tests/test_pipeline_seq2seq.py against the plain modules), all
manual-axis reductions run in fp32, and the loss head is tick-gated to
its M real ticks (``_pvg_loss_vjp``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_llms_example_tpu.parallel.activation import compat_shard_map
from distributed_llms_example_tpu.parallel.pipeline import (
    _full_spec,
    _make_run_stage,
    _pvg_check_batch,
    _pvg_loss_vjp,
    _vary,
)


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _tree_astype(tree, dt):
    return jax.tree.map(lambda x: x.astype(dt), tree)


def _tree_index(tree, i, depth):
    return jax.tree.map(
        lambda b: jax.lax.dynamic_index_in_dim(
            b, jnp.clip(i, 0, depth - 1), 0, keepdims=False
        ),
        tree,
    )


def _tree_update(tree, val, i, depth):
    return jax.tree.map(
        lambda b, v: jax.lax.dynamic_update_index_in_dim(
            b, v, jnp.clip(i, 0, depth - 1), 0
        ),
        tree,
        val,
    )


def pipeline_value_and_grad_seq2seq(
    enc_layer_fn: Callable,
    dec_layer_fn: Callable,
    post_loss_fn: Callable,
    stacked_enc: Any,
    stacked_dec: Any,
    post_params: Any,
    enc_hidden: jnp.ndarray,
    dec_hidden: jnp.ndarray,
    extras: Any,
    loss_batch: Any,
    *,
    mesh: Mesh,
    num_microbatches: int,
    seam_fn: Callable | None = None,
    seam_params: Any = None,
    diff_extras: Any = None,
    axis_name: str = "stage",
    batch_axes: tuple[str, ...] = ("data", "fsdp", "expert"),
    checkpoint: bool = True,
    rng: jnp.ndarray | None = None,
):
    """Twin-pipeline 1F1B: loss and ALL parameter gradients in one fused
    scan over the interleaved v=2 schedule tables.

    ``enc_layer_fn(p, h, ex[, key]) -> h`` applies one encoder layer;
    ``dec_layer_fn`` one decoder layer, reading the (seamed) encoder
    output from ``ex["enc"]``.  Both also see ``diff_extras`` merged into
    their ``ex``.  ``post_loss_fn(post_params, pair, loss_microbatch,
    key) -> (loss_sum, tokens)`` runs the model tail + loss on
    ``pair["dec"]`` for ONE microbatch (token-SUM semantics).
    ``seam_fn(seam_params, enc_out, key) -> enc_out`` (optional) is
    applied exactly once per microbatch where the encoder output enters
    the decoder pipeline — T5's encoder final-norm + dropout; BART has no
    seam (pass None).  ``key`` args are None when ``rng`` is None.

    Returns ``(loss_sum, tokens, d_enc_stacked, d_dec_stacked, d_post,
    d_seam, d_diff_extras, d_enc_hidden, d_dec_hidden)`` — unnormalized
    sums and gradients of loss_sum w.r.t. every differentiable input.
    """
    from distributed_llms_example_tpu.parallel.interleave import (
        make_interleaved_schedule,
    )

    S = mesh.shape.get(axis_name, 1)
    M = num_microbatches
    if S > 1 and mesh.shape.get("fsdp", 1) > 1:
        # The crash class lives as a row in the composition matrix
        # (analysis/composition.py, id "seq2seq-1f1b-fsdp"); the adapters
        # reject it at construction, and this deep guard covers direct
        # executor calls with the same table-driven message.  Technical
        # detail: the partitioner SIGABRTs under BOTH dispatch modes and
        # with the param gather hoisted out of the branches — reproduced
        # on XLA CPU; the llama 1f1b executor (single chunk body, no pair)
        # compiles fine on the same mesh, so this is specific to the twin
        # shape.  Until the compiler moves: seq2seq fsdp×stage uses gpipe.
        from distributed_llms_example_tpu.analysis.composition import reason_for

        raise ValueError(reason_for("seq2seq-1f1b-fsdp"))
    seam_params = {} if seam_params is None else seam_params
    diff_extras = {} if diff_extras is None else diff_extras
    for stacked, what in ((stacked_enc, "encoder"), (stacked_dec, "decoder")):
        L = jax.tree.leaves(stacked)[0].shape[0]
        if L % max(S, 1):
            raise ValueError(f"{L} {what} layers not divisible into {S} stages")
    run_enc = _make_run_stage(enc_layer_fn, checkpoint)
    run_dec = _make_run_stage(dec_layer_fn, checkpoint)
    B = enc_hidden.shape[0]
    if dec_hidden.shape[0] != B:
        raise ValueError(
            f"encoder batch {B} != decoder batch {dec_hidden.shape[0]}"
        )
    _pvg_check_batch(B, mesh, M, batch_axes)

    compute_dtype = enc_hidden.dtype

    def keys_for(key, m):
        # distinct streams per (role, microbatch); role 0=enc 1=dec 2=seam
        if key is None:
            return None, None, None
        return tuple(
            jax.random.fold_in(jax.random.fold_in(key, role), m) for role in range(3)
        )

    if S == 1:
        # no pipeline: one vjp over (embeds already outside) enc → seam →
        # dec → tail under plain GSPMD
        k_enc, k_dec, k_seam = keys_for(rng, 0)

        def whole(se, sd, pp, sp, dex, eh, dh):
            ex = {**extras, **dex}
            enc = run_enc(se, eh, ex, k_enc)
            if seam_fn is not None:
                enc = seam_fn(sp, enc, k_seam)
            y = run_dec(sd, dh, {**ex, "enc": enc}, k_dec)
            return post_loss_fn(pp, {"enc": enc, "dec": y}, loss_batch, k_dec)

        (lsum, tokens), vjp = jax.vjp(
            whole, stacked_enc, stacked_dec, post_params, seam_params,
            diff_extras, enc_hidden, dec_hidden,
        )
        d_se, d_sd, d_pp, d_sp, d_dex, d_eh, d_dh = vjp(
            (jnp.ones((), lsum.dtype), jnp.zeros((), tokens.dtype))
        )
        return lsum, tokens, d_se, d_sd, d_pp, d_sp, d_dex, d_eh, d_dh

    sc = make_interleaved_schedule(S, 2, M)
    # chunk dispatch mode: see the module docstring.  ``data`` only shards
    # the batch (no collectives in a chunk body); fsdp/tensor/expert shard
    # the block params themselves, putting partitioner collectives inside
    # the would-be cond branches.
    branch_free = any(
        mesh.shape.get(a, 1) > 1 for a in ("fsdp", "tensor", "expert")
    )
    plumb_dtype = jnp.float32 if compute_dtype == jnp.bfloat16 else compute_dtype
    axes_all = (axis_name,)
    is_batched = jax.tree.map(lambda m: m.ndim > 0 and m.shape[0] == B, extras)
    ex_dtypes = jax.tree.map(lambda m: m.dtype, extras)

    # schedule tables as device constants; each tick reads its own row
    tbl = {
        name: jnp.asarray(getattr(sc, name))
        for name in (
            "f_active", "f_micro", "f_chunk", "f_src_q", "f_save", "arr_f",
            "b_active", "b_micro", "b_chunk", "b_act", "b_src_q", "arr_b",
            "b_emit_dh",
        )
    }
    # tick-level (device-unvarying) loss gate: device S-1 forwards the
    # last decoder chunk on exactly M ticks
    _t_loss_np = (sc.f_active[:, S - 1] == 1) & (sc.f_chunk[:, S - 1] == 1)
    if int(_t_loss_np.sum()) != M:  # not assert: must survive python -O
        raise ValueError(
            f"twin schedule runs the loss chunk {int(_t_loss_np.sum())} "
            f"times, expected {M}"
        )
    t_loss = jnp.asarray(_t_loss_np)

    def body(se_local, sd_local, pp, sp, dex, eh, dh, ex, lb, rt):
        eh_shape, dh_shape = eh.shape, dh.shape
        s_idx = jax.lax.axis_index(axis_name)
        is_last = s_idx == S - 1
        ex = jax.tree.map(
            lambda m: m.astype(plumb_dtype) if m.dtype == jnp.bfloat16 else m, ex
        )
        se_local, sd_local = _vary(se_local, axes_all), _vary(sd_local, axes_all)
        pp, sp, dex = _vary(pp, axes_all), _vary(sp, axes_all), _vary(dex, axes_all)
        eh = _vary(eh.astype(plumb_dtype), axes_all)
        dh = _vary(dh.astype(plumb_dtype), axes_all)
        ex, lb = _vary(ex, axes_all), _vary(lb, axes_all)
        key = rt.get("key")
        if key is not None:
            key = jax.random.fold_in(_vary(key, axes_all), s_idx)
        mb = eh.shape[0] // M
        micro = {
            "enc": eh.reshape(M, mb, *eh.shape[1:]),
            "dec": dh.reshape(M, mb, *dh.shape[1:]),
        }
        micro_ex = jax.tree.map(
            lambda m, batched: m.reshape(M, m.shape[0] // M, *m.shape[1:]) if batched else m,
            ex, is_batched,
        )
        micro_lb = jax.tree.map(lambda m: m.reshape(M, m.shape[0] // M, *m.shape[1:]), lb)

        def ex_at(m_idx):
            return jax.tree.map(
                lambda m, batched, dt: (
                    jax.lax.dynamic_index_in_dim(m, m_idx, 0, keepdims=False)
                    if batched else m
                ).astype(dt),
                micro_ex, is_batched, ex_dtypes,
            )

        def zpair(*lead):
            return {
                k: _vary(jnp.zeros((*lead, mb, *shape[1:]), plumb_dtype), axes_all)
                for k, shape in (("enc", eh_shape), ("dec", dh_shape))
            }

        zeros_like_f32 = lambda t: jax.tree.map(  # noqa: E731
            lambda x: _vary(jnp.zeros(x.shape, jnp.float32), axes_all), t
        )
        fwd_in = zpair()
        bwd_in = zpair()
        fqbuf = zpair(sc.fq_depth)
        bqbuf = zpair(sc.bq_depth)
        act = zpair(sc.act_depth)
        d_se = zeros_like_f32(se_local)
        d_sd = zeros_like_f32(sd_local)
        d_sp = zeros_like_f32(sp)
        d_dex = zeros_like_f32(dex)
        d_pp = zeros_like_f32(pp)
        d_he = _vary(jnp.zeros((M, mb, *eh.shape[1:]), jnp.float32), axes_all)
        d_hd = _vary(jnp.zeros((M, mb, *dh.shape[1:]), jnp.float32), axes_all)
        scal0 = _vary(jnp.zeros((), jnp.float32), axes_all)
        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [(i, (i - 1) % S) for i in range(S)]

        def at(name, t):
            return tbl[name][t, s_idx]

        def chunk_apply(se_, sd_, sp_, dex_, c_idx, x, ex_m, keys):
            """One chunk on the {enc, dec} pair.  c_idx 0 = this device's
            encoder chunk, 1 = its decoder chunk (device-varying: each
            device branches on its own table entry)."""
            k_enc, k_dec, k_seam = keys

            def enc_branch(ops):
                se_o, sd_o, sp_o, dex_o, x_o = ops
                y = run_enc(
                    se_o, x_o["enc"].astype(compute_dtype),
                    {**ex_m, **dex_o}, k_enc,
                )
                return {"enc": y.astype(plumb_dtype), "dec": x_o["dec"]}

            def dec_branch(ops):
                se_o, sd_o, sp_o, dex_o, x_o = ops
                enc_in = x_o["enc"].astype(compute_dtype)
                if seam_fn is not None:
                    # the seam transform applies only where the encoder
                    # output ENTERS the decoder pipeline (device 0's
                    # decoder chunk); later devices receive the already-
                    # seamed value through the ring pass-through
                    seamed = seam_fn(sp_o, enc_in, k_seam)
                    enc_in = jnp.where(s_idx == 0, seamed, enc_in)
                y = run_dec(
                    sd_o, x_o["dec"].astype(compute_dtype),
                    {**ex_m, **dex_o, "enc": enc_in}, k_dec,
                )
                return {"enc": enc_in.astype(plumb_dtype), "dec": y.astype(plumb_dtype)}

            ops = (se_, sd_, sp_, dex_, x)
            if branch_free:
                # both chunks, select: collective sequence is device-uniform
                # (the unselected side's vjp cotangent is zero, so gradients
                # stay exact)
                return _tree_where(c_idx == 0, enc_branch(ops), dec_branch(ops))
            return jax.lax.cond(c_idx == 0, enc_branch, dec_branch, ops)

        def tick(carry, t):
            (fwd_in, bwd_in, fqbuf, bqbuf, act, d_se, d_sd, d_sp, d_dex,
             d_pp, d_he, d_hd, lsum, toks) = carry

            # ---- queue arrivals (values sent on the rings last tick)
            af = at("arr_f", t)
            fqbuf = _tree_where(af >= 0, _tree_update(fqbuf, fwd_in, af, sc.fq_depth), fqbuf)
            ab = at("arr_b", t)
            bqbuf = _tree_where(ab >= 0, _tree_update(bqbuf, bwd_in, ab, sc.bq_depth), bqbuf)

            # ---- forward slot
            f_on = at("f_active", t) == 1
            fm = at("f_micro", t)
            fc = at("f_chunk", t)
            fsrc = at("f_src_q", t)
            x0 = {
                "enc": jax.lax.dynamic_index_in_dim(micro["enc"], fm, 0, keepdims=False),
                "dec": jax.tree.map(jnp.zeros_like, fwd_in["dec"]),
            }
            xq = _tree_index(fqbuf, fsrc, sc.fq_depth)
            x_in = _tree_where(fsrc < 0, x0, xq)
            # enc→dec seam: the decoder embedding enters HERE, from the
            # microbatch store (device 0's decoder chunk — global chunk S)
            is_seam_f = (s_idx == 0) & (fc == 1)
            x_in["dec"] = jnp.where(
                is_seam_f,
                jax.lax.dynamic_index_in_dim(micro["dec"], fm, 0, keepdims=False),
                x_in["dec"],
            )
            ex_f = ex_at(fm)
            keys_f = keys_for(key, fm)
            y = chunk_apply(se_local, sd_local, sp, dex, fc, x_in, ex_f, keys_f)
            a_save = at("f_save", t)
            act = _tree_where(f_on, _tree_update(act, x_in, a_save, sc.act_depth), act)

            # ---- loss vjp on the in-tick forward output (tick-gated)
            lb_f = jax.tree.map(
                lambda m: jax.lax.dynamic_index_in_dim(m, fm, 0, keepdims=False),
                micro_lb,
            )
            k_loss = None if keys_f is None else keys_f[1]

            def loss_f(pp_, y_):
                return post_loss_fn(pp_, _tree_astype(y_, compute_dtype), lb_f, k_loss)

            ls_m, tk_m, d_pp_m, dy_loss = _pvg_loss_vjp(loss_f, pp, y, t_loss[t])
            take_loss = f_on & is_last & (fc == 1)
            lsum = lsum + jnp.where(take_loss, ls_m.astype(jnp.float32), 0.0)
            toks = toks + jnp.where(take_loss, tk_m.astype(jnp.float32), 0.0)
            d_pp = jax.tree.map(
                lambda a_, g: a_ + jnp.where(take_loss, g.astype(jnp.float32), 0.0),
                d_pp, d_pp_m,
            )

            # ---- backward slot (recomputes its chunk forward under vjp)
            b_on = at("b_active", t) == 1
            bm = at("b_micro", t)
            bc = at("b_chunk", t)
            bsrc = at("b_src_q", t)
            x_b = _tree_index(act, at("b_act", t), sc.act_depth)
            ex_b = ex_at(bm)
            keys_b = keys_for(key, bm)

            def chunk_b(se_, sd_, sp_, dex_, x_):
                return chunk_apply(se_, sd_, sp_, dex_, bc, x_, ex_b, keys_b)

            _, chunk_vjp = jax.vjp(chunk_b, se_local, sd_local, sp, dex, x_b)
            dy_q = _tree_index(bqbuf, bsrc, sc.bq_depth)
            dy_in = _tree_where(bsrc < 0, _tree_astype(dy_loss, plumb_dtype), dy_q)
            d_se_m, d_sd_m, d_sp_m, d_dex_m, dx = chunk_vjp(dy_in)
            acc = lambda a_, g: a_ + jnp.where(b_on, g.astype(jnp.float32), 0.0)  # noqa: E731
            d_se = jax.tree.map(acc, d_se, d_se_m)
            d_sd = jax.tree.map(acc, d_sd, d_sd_m)
            d_sp = jax.tree.map(acc, d_sp, d_sp_m)
            d_dex = jax.tree.map(acc, d_dex, d_dex_m)

            # seam backward: the pair's dec gradient IS d(decoder
            # embedding) — emit it and cut it from the ring so it cannot
            # leak into the encoder phase's pass-throughs
            is_seam_b = b_on & (s_idx == 0) & (bc == 1)
            d_hd = jnp.where(
                is_seam_b,
                jax.lax.dynamic_update_index_in_dim(
                    d_hd, dx["dec"].astype(jnp.float32), bm, 0
                ),
                d_hd,
            )
            dx["dec"] = jnp.where(is_seam_b, jnp.zeros_like(dx["dec"]), dx["dec"])
            # global chunk 0 backward: d(encoder embedding)
            emit = (at("b_emit_dh", t) == 1) & b_on
            d_he = jnp.where(
                emit,
                jax.lax.dynamic_update_index_in_dim(
                    d_he, dx["enc"].astype(jnp.float32), bm, 0
                ),
                d_he,
            )

            # ---- ring hops
            fwd_in = jax.tree.map(
                lambda v: jax.lax.ppermute(v, axis_name, perm_fwd), y
            )
            bwd_in = jax.tree.map(
                lambda v: jax.lax.ppermute(v.astype(plumb_dtype), axis_name, perm_bwd), dx
            )
            return (fwd_in, bwd_in, fqbuf, bqbuf, act, d_se, d_sd, d_sp, d_dex,
                    d_pp, d_he, d_hd, lsum, toks), None

        carry = (fwd_in, bwd_in, fqbuf, bqbuf, act, d_se, d_sd, d_sp, d_dex,
                 d_pp, d_he, d_hd, scal0, scal0)
        carry, _ = jax.lax.scan(tick, carry, jnp.arange(sc.T))
        (_, _, _, _, _, d_se, d_sd, d_sp, d_dex, d_pp, d_he, d_hd,
         lsum, toks) = carry

        # reductions: loss/tail grads live on the last stage, seam grads on
        # device 0, diff-extra grads on every device, d_hidden on device 0
        # — psum replicates (and, for d_dex, sums the real contributions)
        lsum = jax.lax.psum(lsum, axes_all)
        toks = jax.lax.psum(toks, axes_all)
        d_pp = jax.tree.map(lambda g: jax.lax.psum(g, axes_all), d_pp)
        d_sp = jax.tree.map(lambda g: jax.lax.psum(g, axes_all), d_sp)
        d_dex = jax.tree.map(lambda g: jax.lax.psum(g, axes_all), d_dex)
        d_he = jax.lax.psum(d_he, axis_name).reshape(eh_shape)
        d_hd = jax.lax.psum(d_hd, axis_name).reshape(dh_shape)
        return lsum, toks, d_se, d_sd, d_pp, d_sp, d_dex, d_he, d_hd

    enc_specs = jax.tree.map(lambda x: _full_spec(axis_name, x.ndim), stacked_enc)
    dec_specs = jax.tree.map(lambda x: _full_spec(axis_name, x.ndim), stacked_dec)
    rng_tree = {} if rng is None else {"key": rng}
    repl = lambda t: jax.tree.map(lambda _: P(), t)  # noqa: E731

    return compat_shard_map(
        body,
        mesh=mesh,
        axis_names={axis_name},
        in_specs=(
            enc_specs, dec_specs, repl(post_params), repl(seam_params),
            repl(diff_extras), P(), P(), repl(extras), repl(loss_batch),
            repl(rng_tree),
        ),
        out_specs=(
            P(), P(), enc_specs, dec_specs, repl(post_params),
            repl(seam_params), repl(diff_extras), P(), P(),
        ),
        check_vma=True,
    )(stacked_enc, stacked_dec, post_params, seam_params, diff_extras,
      enc_hidden, dec_hidden, extras, loss_batch, rng_tree)
