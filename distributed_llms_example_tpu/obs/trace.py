"""Unified cross-host trace export: one Perfetto timeline per run.

Two halves:

- **TraceCollector** (runtime, owned by ``TrainerObs`` when ``--obs
  jsonl`` and the budget layer are on): receives every outermost span
  instance from the span recorder's listener hook — ``(name, t0, dur)``
  host-clock triples plus per-step boundary marks — and flushes them at
  the log cadence as one ``trace_spans`` event per window into the
  per-process JSONL file.  The buffer is bounded; overflow is COUNTED
  (``dropped_spans``) rather than silently truncated.  ``trace_spans``
  records are ``bulk``: they land in the file channel only, never on the
  Valohai stdout contract.

- **the exporter** (offline, jax-free like the rest of obs/report.py):
  ``python -m distributed_llms_example_tpu.obs.report <dir> --trace
  out.json`` (or this module's own CLI) merges every rank's spans,
  step-budget gauges, heartbeats, anomalies, chaos injections, recovery
  actions, serving request lifecycles AND the device lanes of any
  profiled window (``device_account`` events — per-bucket device slices
  drawn beside the host spans, end-aligned on the window's closing step)
  into ONE Chrome-trace JSON — load it at https://ui.perfetto.dev (or
  chrome://tracing).

Cross-host alignment: each rank's span clocks are host-monotonic with an
arbitrary epoch, but synchronous SPMD gives a shared ordinal axis — every
rank executes global step S between the same two collectives.  The
exporter aligns rank r onto rank 0's clock by the median, over shared
steps, of the step-boundary timestamp difference; ranks that share no
step marks fall back to their recorded wall-clock epochs (NTP-bounded,
same trade the heartbeat makes).  After the shift, both ranks' step-S
spans interleave on one timeline — the acceptance criterion's
"events from both ranks interleave on the shared step timeline".

Chrome-trace dicts are built HERE and only here — repo-lint rule 7 bans
``"ph"``/``"ts"`` event dicts anywhere else, the same ownership pattern
the sink layer has for metric emission.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Any

from distributed_llms_example_tpu.obs import sink as sink_mod

# cap on buffered span instances between cadence flushes: at 4 spans/step
# this covers a 2k-step logging window; beyond it we count drops
MAX_SPANS_PER_WINDOW = 8192

# Perfetto track (tid) layout per rank-process
TID_SPANS = 0      # the train-loop spans (data_wait / dispatch / ...)
TID_STEPS = 1      # step-boundary slices + instant events
TID_COUNTERS = 2   # dispatch_efficiency counter track
TID_DEVICE = 3     # device lanes: per-bucket slices from device_account
TID_REQUESTS = 10  # serving: request lifecycles, one track per slot offset


class TraceCollector:
    """Buffers span instances + step marks; flushed per logging window."""

    def __init__(self, clock=time.perf_counter, max_spans: int = MAX_SPANS_PER_WINDOW):
        self.clock = clock
        self.clock0 = clock()
        self.wall0 = time.time()
        self.max_spans = int(max_spans)
        self._spans: list[list] = []   # [name, t0_rel_s, dur_s]
        self._steps: list[list] = []   # [step, t_end_rel_s]
        self.dropped = 0

    # SpanRecorder listener protocol ------------------------------------
    def on_span(self, name: str, t0: float, dur: float) -> None:
        if len(self._spans) >= self.max_spans:
            self.dropped += 1
            return
        self._spans.append([name, round(t0 - self.clock0, 6), round(dur, 6)])

    def note_step(self, step: int) -> None:
        """Record the step's completion time on this rank's clock — the
        synchronization anchor the exporter aligns ranks on."""
        self._steps.append([int(step), round(self.clock() - self.clock0, 6)])

    def flush(self, step: int) -> None:
        """Emit the window's buffered spans as ONE ``trace_spans`` event
        (bulk: file channel only) and reset the buffer."""
        if not self._spans and not self._steps:
            return
        rec: dict[str, Any] = {
            "event": "trace_spans",
            "step": int(step),
            "wall0": round(self.wall0, 6),
            "spans": self._spans,
            "steps": self._steps,
        }
        if self.dropped:
            rec["dropped_spans"] = self.dropped
        sink_mod.emit(rec, local=True, bulk=True)
        self._spans, self._steps, self.dropped = [], [], 0


# ---------------------------------------------------------------------------
# offline exporter
# ---------------------------------------------------------------------------


def rank_offsets(
    step_marks: dict[int, dict[int, float]],
    wall0: dict[int, float],
) -> dict[int, float]:
    """Per-rank clock shift onto the base (lowest) rank's axis.

    ``step_marks[rank]`` maps global step → that rank's relative
    completion time.  Shared steps give the alignment (median of the
    per-step differences — robust to one straggler window); ranks with
    no shared step fall back to the wall-clock epoch difference."""
    if not step_marks:
        return {}
    base = min(step_marks)
    base_marks = step_marks[base]
    offsets = {base: 0.0}
    for rank, marks in step_marks.items():
        if rank == base:
            continue
        shared = sorted(set(base_marks) & set(marks))
        if shared:
            offsets[rank] = statistics.median(
                base_marks[s] - marks[s] for s in shared
            )
        elif wall0.get(rank) and wall0.get(base):
            # no shared step marks: NTP-bounded wall-clock fallback.
            # wall0[r] + t_rel is the absolute time, so on the base axis
            # t_base = t_rel + (wall0[rank] - wall0[base])
            offsets[rank] = wall0[rank] - wall0[base]
        else:
            offsets[rank] = 0.0
    return offsets


def _us(seconds: float) -> float:
    return round(seconds * 1e6, 1)


def build_trace(output_dir: str) -> dict[str, Any]:
    """Read ``<output_dir>/obs`` (via obs/report.py's loader) and build
    the merged Chrome-trace object."""
    from distributed_llms_example_tpu.obs.report import load_run

    run = load_run(output_dir)
    processes: dict[int, list[dict]] = run["processes"]
    events: list[dict] = []
    # collect per-rank span streams + step marks
    step_marks: dict[int, dict[int, float]] = {}
    wall0: dict[int, float] = {}
    spans_by_rank: dict[int, list[list]] = {}
    for rank, records in sorted(processes.items()):
        spans: list[list] = []
        marks: dict[int, float] = {}
        for r in records:
            if r.get("event") != "trace_spans":
                continue
            wall0.setdefault(rank, float(r.get("wall0", 0.0) or 0.0))
            spans.extend(r.get("spans", []))
            for step, t_end in r.get("steps", []):
                marks[int(step)] = float(t_end)
        if spans or marks:
            spans_by_rank[rank] = spans
            step_marks[rank] = marks
    offsets = rank_offsets(step_marks, wall0)
    for rank in sorted(set(processes) | set(spans_by_rank)):
        events.append({
            "ph": "M", "name": "process_name", "pid": rank,
            "args": {"name": f"rank {rank}"},
        })
        for tid, label in (
            (TID_SPANS, "loop spans"), (TID_STEPS, "steps"),
            (TID_COUNTERS, "gauges"), (TID_DEVICE, "device (profiled)"),
        ):
            events.append({
                "ph": "M", "name": "thread_name", "pid": rank, "tid": tid,
                "args": {"name": label},
            })
    for rank, spans in sorted(spans_by_rank.items()):
        off = offsets.get(rank, 0.0)
        for name, t0, dur in spans:
            events.append({
                "name": str(name), "ph": "X", "pid": rank, "tid": TID_SPANS,
                "ts": _us(float(t0) + off), "dur": _us(float(dur)),
            })
        # step-boundary slices: consecutive marks bound each step
        marks = sorted(step_marks.get(rank, {}).items(), key=lambda kv: kv[1])
        for (s_prev, t_prev), (s, t_end) in zip(marks, marks[1:]):
            events.append({
                "name": f"step {s}", "ph": "X", "pid": rank, "tid": TID_STEPS,
                "ts": _us(t_prev + off), "dur": _us(t_end - t_prev),
            })
        if marks:
            s0, t0_end = marks[0]
            events.append({
                "name": f"step {s0}", "ph": "i", "s": "t",
                "pid": rank, "tid": TID_STEPS, "ts": _us(t0_end + off),
            })
    # step-anchored records from every rank: budget counters + instants
    for rank, records in sorted(processes.items()):
        off = offsets.get(rank, 0.0)
        marks = step_marks.get(rank, {})

        def at_step(rec: dict) -> float | None:
            s = rec.get("step")
            if isinstance(s, (int, float)) and int(s) in marks:
                return marks[int(s)] + off
            return None

        for r in records:
            ev = r.get("event")
            if ev == "step_budget":
                t = at_step(r)
                if t is not None and "dispatch_efficiency" in r:
                    events.append({
                        "name": "dispatch_efficiency", "ph": "C",
                        "pid": rank, "tid": TID_COUNTERS, "ts": _us(t),
                        "args": {"dispatch_efficiency": r["dispatch_efficiency"]},
                    })
            elif ev in (
                "heartbeat", "obs_anomaly", "chaos_injection", "recovery",
                "ckpt_verify_failed", "topology_change", "reshard_restore",
            ):
                t = at_step(r)
                if t is None:
                    continue
                detail = r.get("code") or r.get("kind") or r.get("action") or ""
                events.append({
                    "name": f"{ev}{':' + str(detail) if detail else ''}",
                    "ph": "i", "s": "p", "pid": rank, "tid": TID_STEPS,
                    "ts": _us(t),
                })
            elif ev == "memory_window":
                # the per-rank memory counter track: live bytes + the
                # process peak as stacked counters on the gauges lane,
                # anchored like every other step-cadence record
                t = at_step(r)
                if t is not None and "bytes_in_use" in r:
                    events.append({
                        "name": "hbm_bytes", "ph": "C",
                        "pid": rank, "tid": TID_COUNTERS, "ts": _us(t),
                        "args": {
                            "bytes_in_use": r.get("bytes_in_use", 0),
                            "peak_bytes_in_use": r.get(
                                "peak_bytes_in_use", 0
                            ),
                        },
                    })
            elif ev == "device_account":
                events.extend(_device_lane_events(rank, r, marks, off))
            elif ev == "serve_request":
                events.extend(_request_events(rank, r))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "distributed_llms_example_tpu.obs.trace",
            "output_dir": output_dir,
            "ranks": sorted(spans_by_rank) or sorted(processes),
        },
    }


def _device_lane_events(
    rank: int, r: dict, marks: dict[int, float], off: float
) -> list[dict]:
    """One ``device_account``'s bounded per-bucket lane slices →
    device-track slices BESIDE the host spans, aligned on the shared step
    ordinals: the capture's device span ends when its window's closing
    step completes on the host clock, so the device lane sits under
    exactly the host steps it profiled."""
    window = r.get("window") or []
    lanes = r.get("lanes") or []
    if len(window) != 2 or not lanes:
        return []
    stop = int(window[1])
    # anchor: prefer the window's closing step mark; fall back to any
    # recorded mark at/after it (a truncated capture may stop early)
    t_end = marks.get(stop)
    if t_end is None:
        later = [t for s, t in marks.items() if s >= stop]
        if not later:
            return []
        t_end = min(later)
    span_s = float(r.get("span_ms", 0.0) or 0.0) / 1e3
    t0 = t_end - span_s + off
    out: list[dict] = []
    for bucket, rel_ms, dur_ms in lanes:
        out.append({
            "name": f"dev:{bucket}", "ph": "X", "pid": rank,
            "tid": TID_DEVICE,
            "ts": _us(t0 + float(rel_ms) / 1e3),
            "dur": _us(float(dur_ms) / 1e3),
        })
    return out


def _request_events(rank: int, r: dict) -> list[dict]:
    """One serving request's lifecycle → queue/prefill/decode slices on a
    per-slot track (times are relative to the engine's submit instant —
    serving runs own their timeline)."""
    out: list[dict] = []
    slot = int(r.get("slot", 0) or 0)
    tid = TID_REQUESTS + slot
    req = r.get("request")
    t_admit = float(r.get("t_admit_s", 0.0) or 0.0)
    t_done = float(r.get("t_done_s", t_admit) or t_admit)
    queue_s = float(r.get("queue_wait_ms", 0.0) or 0.0) / 1e3
    prefill_s = float(r.get("prefill_ms", 0.0) or 0.0) / 1e3
    label = f"req {req}"
    out.append({
        "ph": "M", "name": "thread_name", "pid": rank, "tid": tid,
        "args": {"name": f"slot {slot}"},
    })
    if queue_s > 0:
        out.append({
            "name": f"{label} queue", "ph": "X", "pid": rank, "tid": tid,
            "ts": _us(t_admit - queue_s), "dur": _us(queue_s),
        })
    out.append({
        "name": f"{label} prefill", "ph": "X", "pid": rank, "tid": tid,
        "ts": _us(t_admit), "dur": _us(prefill_s),
    })
    decode_start = t_admit + prefill_s
    if t_done > decode_start:
        out.append({
            "name": f"{label} decode ({r.get('tokens', '?')} tok)",
            "ph": "X", "pid": rank, "tid": tid,
            "ts": _us(decode_start), "dur": _us(t_done - decode_start),
        })
    return out


def export_chrome_trace(output_dir: str, out_path: str) -> dict[str, Any]:
    """Build the merged trace and write it to ``out_path``.  Returns a
    small summary (event count, ranks) for the caller to surface."""
    trace = build_trace(output_dir)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    summary = {
        "event": "trace_export",
        "path": out_path,
        "events": len(trace["traceEvents"]),
        "ranks": trace["otherData"]["ranks"],
    }
    return summary


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llms_example_tpu.obs.trace",
        description=__doc__,
    )
    p.add_argument("output_dir", help="a run's --output-dir (containing obs/)")
    p.add_argument(
        "-o", "--out", default="trace.json",
        help="Chrome-trace JSON to write (open at ui.perfetto.dev)",
    )
    args = p.parse_args(argv)
    if not os.path.isdir(os.path.join(args.output_dir, "obs")):
        print(f"no obs/ directory under {args.output_dir}", file=sys.stderr)
        return 2
    summary = export_chrome_trace(args.output_dir, args.out)
    print(
        f"wrote {summary['events']} events from ranks "
        f"{summary['ranks']} to {summary['path']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
