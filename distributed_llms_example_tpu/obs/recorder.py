"""The flight recorder: a bounded ring of the last N steps' evidence.

A crashed or diverged run is only debuggable if the steps LEADING UP to
the failure are reconstructable: which batch (shapes, content hash, where
the data iterator stood), what the numerics looked like, when.  The
recorder keeps exactly that — a ring of per-step entries holding the
step's metrics (device scalars until the cadence fetch resolves them;
never a per-step sync) and a host-side batch fingerprint — and dumps it
as a schema-stamped JSON bundle when an anomaly fires, a SIGTERM lands,
or the train loop raises.

The bundle write is ATOMIC (tmp file + fsync + rename in the same
directory): a kill -9 mid-dump leaves either the previous bundle or the
complete new one, never a torn JSON.  Per-process file names
(``flight-recorder-p{process}.json``) keep a shared output dir
collision-free, exactly like the JSONL metric files.
"""

from __future__ import annotations

import collections
import json
import os
import zlib
from typing import Any, Mapping, Sequence

from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.sink import SCHEMA_VERSION


def batch_fingerprint(
    batch: Mapping[str, Any], *, epoch: int, epoch_step: int
) -> dict[str, Any]:
    """Host-side identity of one host-local batch: array shapes, a crc32
    of the token ids and labels (cheap — zlib's C loop over the raw
    bytes), and the data-iterator position.  Enough to answer "was it the
    data?" post-mortem: replaying the deterministic batch plan at
    (seed, epoch, epoch_step) must reproduce these hashes."""
    import numpy as np

    fp: dict[str, Any] = {
        "epoch": int(epoch),
        "epoch_step": int(epoch_step),
        "shapes": {k: list(np.asarray(v).shape) for k, v in batch.items()},
    }
    for key in ("input_ids", "labels"):
        v = batch.get(key)
        if v is not None:
            fp[f"{key}_crc32"] = zlib.crc32(np.ascontiguousarray(v).tobytes()) & 0xFFFFFFFF
    return fp


class FlightRecorder:
    """Bounded ring of per-step records, dumped on demand.

    ``record`` is on the step cadence: it stores REFERENCES to the step's
    device-scalar metrics (no conversion, no sync).  The health cadence
    resolves them to host floats via ``annotate``; anything still
    unresolved at ``dump`` time is converted then (dump only happens on
    anomaly / shutdown, where a sync is free).
    """

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._by_step: dict[int, dict] = {}

    def __len__(self) -> int:
        return len(self._ring)

    def record(
        self,
        step: int,
        epoch: int,
        metrics: Mapping[str, Any],
        fingerprint: Mapping[str, Any] | None = None,
    ) -> None:
        if len(self._ring) == self.capacity:
            evicted = self._ring[0]
            self._by_step.pop(evicted["step"], None)
        entry: dict[str, Any] = {
            "step": int(step),
            "epoch": int(epoch),
            "metrics": dict(metrics),
            "resolved": False,
        }
        if fingerprint is not None:
            entry["fingerprint"] = dict(fingerprint)
        self._ring.append(entry)
        self._by_step[int(step)] = entry

    def fingerprint_for(self, step: int) -> dict | None:
        """The batch fingerprint recorded for one step (None once evicted
        or never recorded) — what the rewind recovery path quarantines
        by."""
        entry = self._by_step.get(int(step))
        if entry is None:
            return None
        return entry.get("fingerprint")

    def annotate(self, step: int, host_metrics: Mapping[str, float]) -> None:
        """Replace a step's device-scalar metrics with the host floats the
        health cadence already fetched — dump then needs no sync for any
        step the watchdog has seen."""
        entry = self._by_step.get(int(step))
        if entry is not None:
            entry["metrics"] = dict(host_metrics)
            entry["resolved"] = True

    # -- dumping ---------------------------------------------------------

    @staticmethod
    def _to_jsonable(v: Any) -> Any:
        # broad except: unresolved entries hold DEVICE scalars, and dump
        # runs on the crash path — if the runtime died with the step,
        # float(v) raises a backend error, and losing one value must not
        # lose the bundle ("telemetry never takes down the run")
        try:
            f = float(v)
        except Exception:
            return str(v)[:80]
        if f != f or f in (float("inf"), float("-inf")):
            return repr(f)  # "nan"/"inf": NaN literals are not valid JSON
        return round(f, 6)

    def bundle_path(self, output_dir: str) -> str:
        import jax

        return os.path.join(
            output_dir, "obs", f"flight-recorder-p{jax.process_index():03d}.json"
        )

    def dump(
        self,
        output_dir: str,
        *,
        reason: str,
        step: int,
        anomalies: Sequence[Any] = (),
    ) -> str | None:
        """Write the ring as a schema-stamped bundle (atomic: tmp + fsync
        + rename) and announce it on the sink.  Telemetry must never take
        down the run: IO errors are reported, not raised."""
        import jax

        path = self.bundle_path(output_dir)
        entries = []
        for e in self._ring:
            out = {
                "step": e["step"],
                "epoch": e["epoch"],
                "metrics": {k: self._to_jsonable(v) for k, v in e["metrics"].items()},
            }
            if "fingerprint" in e:
                out["fingerprint"] = e["fingerprint"]
            entries.append(out)
        bundle = {
            "schema_version": SCHEMA_VERSION,
            "event": "flight_recorder",
            "reason": reason,
            "step": int(step),
            "process_index": int(jax.process_index()),
            "capacity": self.capacity,
            "entries": entries,
            "anomalies": [
                {
                    "step": int(a.step),
                    "code": a.code,
                    "value": self._to_jsonable(a.value),
                    "detail": a.detail,
                }
                for a in anomalies
            ],
        }
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            sink_mod.emit(
                {"event": "recorder_dump_failed", "reason": str(e)[:200]},
                local=True,
            )
            return None
        sink_mod.emit(
            {
                "event": "recorder_dump",
                "path": path,
                "reason": reason,
                "step": int(step),
                "steps_recorded": len(entries),
            },
            local=True,
        )
        return path
