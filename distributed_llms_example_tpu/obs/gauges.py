"""Derived device gauges: MFU, live HBM, and the collective-traffic account.

Three signals, all computed without ever materializing a weight:

- **MFU numerator** — per-step FLOPs from XLA's cost analysis of the
  AOT-compiled train step (the shared compile recipe in
  utils/memory_audit.py, the SAME program the memory audit and IR lint
  reason about), with the standard ``6·N·tokens`` training estimate as a
  backend-independent fallback.  The Trainer divides by measured window
  step time × chips × peak FLOPs at the logging cadence.
- **Live HBM** — ``device.memory_stats()`` per local device (bytes in
  use / peak / limit).  CPU's PJRT client reports None; the gauge then
  reports nothing rather than zeros an operator might believe.
- **Collective-traffic account** — a static per-step byte account of the
  compiled program's collectives (all-reduce / all-gather /
  reduce-scatter / all-to-all / collective-permute), split into
  gradient/parameter traffic vs activation traffic.  Classification: a
  collective whose tensor element count matches a model-tree leaf (full,
  or an even mesh shard of one — ``analysis/ir_lint.py``'s candidate
  set, so the lint census and this account can never disagree) moves the
  parameter/gradient tree; everything else moves activations.  Byte
  totals count each instruction once per program pass (a grad-accum scan
  body is counted once, not per microbatch).

This is the runtime face of the IR lint's open reduce-scatter item: a
correctly sharded FSDP step reduce-scatters its gradients; an account
showing the same bytes all-REDUCED instead is the 2× gradient-traffic
smell (arxiv 2004.13336) showing up in production telemetry.
"""

from __future__ import annotations

import math
from typing import Any, Iterable

from distributed_llms_example_tpu.analysis.ir_lint import (
    model_tree_element_candidates,
    op_bucket_index,
    parse_hlo_instructions,
)

# async -start forms account like their sync ops; -done carries no bytes
_TRAFFIC_OPS = {
    "all-gather": "all-gather",
    "all-gather-start": "all-gather",
    "all-reduce": "all-reduce",
    "all-reduce-start": "all-reduce",
    "reduce-scatter": "reduce-scatter",
    "all-to-all": "all-to-all",
    "collective-permute": "collective-permute",
    "collective-permute-start": "collective-permute",
}


def training_flops_estimate(n_params: int, tokens_per_step: int) -> float:
    """The standard 6·N FLOPs/token training estimate (fwd 2N + bwd 4N
    matmul FLOPs; attention excluded, so MFU built on it runs slightly
    conservative)."""
    return 6.0 * float(n_params) * float(tokens_per_step)


def mfu(
    flops_per_step: float,
    step_time_s: float,
    n_chips: int,
    peak_flops_per_chip: float,
) -> float:
    """Model FLOPs utilization: achieved FLOP rate over aggregate peak."""
    denom = step_time_s * n_chips * peak_flops_per_chip
    if denom <= 0:
        return 0.0
    return flops_per_step / denom


def hbm_stats() -> list[dict] | None:
    """Per-local-device live memory: bytes in use / peak / limit.  None
    when the backend does not report (CPU PJRT) — absent beats zero.
    Since the memprof PR, ``obs/memprof.py`` owns the raw
    ``memory_stats`` read (repo-lint rule 15); this re-export keeps the
    historical import site working."""
    from distributed_llms_example_tpu.obs import memprof

    return memprof.hbm_stats()


def collective_traffic(
    hlo_text,
    param_element_counts: Iterable[int],
    mesh_size: int,
) -> dict:
    """Static per-step collective-traffic account from compiled HLO text
    (or an already-parsed instruction dict — see ``op_bucket_index``).

    Returns ``{op: {count, gradient_bytes, activation_bytes}, ...}`` plus
    ``total_bytes``/``gradient_bytes``/``activation_bytes`` rollups.
    Sizes are the per-device tensor bytes the instruction defines (max
    tuple element for async starts) — the same sizing the IR lint census
    reports, via the same parser.
    """
    instrs = (
        parse_hlo_instructions(hlo_text)
        if isinstance(hlo_text, str)
        else hlo_text
    )
    candidates = model_tree_element_candidates(param_element_counts, mesh_size)
    account: dict[str, dict[str, int]] = {}
    total = grad_total = 0
    for instr in instrs.values():
        op = _TRAFFIC_OPS.get(instr.op)
        if op is None:
            continue
        touched = {instr.elems} | {
            instrs[o].elems for o in instr.operands if o in instrs
        }
        is_grad = bool(touched & candidates)
        slot = account.setdefault(
            op, {"count": 0, "gradient_bytes": 0, "activation_bytes": 0}
        )
        slot["count"] += 1
        slot["gradient_bytes" if is_grad else "activation_bytes"] += instr.bytes
        total += instr.bytes
        grad_total += instr.bytes if is_grad else 0
    return {
        **account,
        "total_bytes": total,
        "gradient_bytes": grad_total,
        "activation_bytes": total - grad_total,
    }


def train_step_static_gauges(
    model_name: str,
    mesh: Any,
    *,
    global_batch: int = 8,
    src_len: int = 1024,
    tgt_len: int = 128,
    dtype: str = "bfloat16",
    remat: bool = False,
    remat_policy: str = "full",
    grad_accum_steps: int = 1,
    grad_compression: str = "",
    hbm_budget_gib: float = 16.0,
) -> dict:
    """AOT-compile the train step (the shared recipe the memory audit and
    IR lint use — utils/memory_audit.py) and derive the static gauges:
    per-step FLOPs for the MFU numerator, the collective-traffic account,
    and the bucketed HBM account (obs/memprof.py) — all from the ONE
    compiled program.  No weights materialize; the compile is the only
    cost."""
    import jax

    from distributed_llms_example_tpu.obs import memprof
    from distributed_llms_example_tpu.utils.memory_audit import (
        aot_compile_train_step,
    )

    compiled, lm, a_params, a_state, state_sh = aot_compile_train_step(
        model_name,
        mesh,
        global_batch=global_batch,
        src_len=src_len,
        tgt_len=tgt_len,
        dtype=dtype,
        remat=remat,
        remat_policy=remat_policy,
        grad_accum_steps=grad_accum_steps,
        grad_compression=grad_compression,
    )
    leaves = jax.tree.leaves(a_params)
    n_params = int(sum(int(math.prod(x.shape)) for x in leaves))
    tokens_per_step = global_batch * (
        src_len + tgt_len if lm.is_seq2seq else src_len
    )
    mesh_size = 1
    for v in dict(mesh.shape).values():
        mesh_size *= int(v)
    flops_source = "hlo_cost_analysis"
    flops = 0.0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):  # some backends return one dict per device
            ca = ca[0] if ca else {}
        # the compiled (post-SPMD) module is the PER-DEVICE program —
        # measured: an 8-way sharded matmul reports 1/8 of the lowered
        # module's flops — so scale to the global per-step count the MFU
        # formula divides by aggregate peak.  Under grad accumulation the
        # cost analysis counts the scan's while BODY exactly once
        # (measured on jax 0.4.37: flops(accum=4) ≈ flops(accum=1)/4 +
        # loop bookkeeping at the same effective batch — pinned in
        # tests/test_obs.py), so scale by N to cover all N microbatches.
        # This overcounts the once-per-step optimizer tail by (N-1)× —
        # visible only at toy widths (~10% on t5-test), vanishing at real
        # model widths where the tail is <0.1% of model flops.
        flops = float((ca or {}).get("flops", 0.0)) * mesh_size * int(grad_accum_steps)
    except Exception:
        pass
    if flops <= 0.0:
        flops = training_flops_estimate(n_params, tokens_per_step)
        flops_source = "6N_tokens_estimate"
    # ONE parse of the (potentially tens-of-MB) compiled text feeds both
    # the traffic account and the device-attribution index
    instrs = parse_hlo_instructions(compiled.as_text())
    comm = collective_traffic(
        instrs,
        [int(math.prod(x.shape)) for x in leaves],
        mesh_size,
    )
    return {
        "model": model_name,
        "mesh": dict(mesh.shape),
        "global_batch": global_batch,
        "grad_accum_steps": int(grad_accum_steps),
        # stamped so the byte account reads in context: an s8-dominated
        # gradient account is correct under int8 and a bug under off
        "grad_compression": grad_compression or "off",
        "params": n_params,
        "tokens_per_step": tokens_per_step,
        "flops_per_step": flops,
        "flops_source": flops_source,
        "comm": comm,
        # the bucketed HBM account of the SAME compiled program — the
        # trainer pops this into its own memory_account event and hands
        # it to the memory monitor for OOM postmortems
        "memory_account": memprof.account_from_compiled(
            compiled, a_state, state_sh,
            hbm_budget_gib=hbm_budget_gib,
            model=model_name, mesh=dict(mesh.shape),
        ),
        # instruction→bucket index for the device-time attribution
        # (obs/devprof.py): CPU-backend traces name device events by HLO
        # instruction, and this program is the same lowering the runtime
        # executes.  Popped off before the obs_gauges record is emitted —
        # thousands of entries have no place on a metric line.
        "op_bucket_index": op_bucket_index(instrs),
    }
