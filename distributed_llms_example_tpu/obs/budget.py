"""Step-time budget accounting: where every step's milliseconds go.

BENCH_r05 measured the trainer loop at 0.751× synthetic-step throughput
with dropout off and BENCH_7B_r05 pinned 99.3 ms/step of non-layer
overhead — both host-side, neither explainable from the existing span
*aggregates* (total data_wait per window says nothing about whether the
missing quarter of wall time is input stall, dispatch serialization, or
untracked host bookkeeping).  This module closes each logging window into
an **additive account** of the window's step wall time:

    wall = data_wait + dispatch + device_busy + sync_block
         + host_overhead + unattributed

- ``data_wait``       blocked on the input pipeline (tokenize/pad/prefetch)
- ``dispatch``        host time issuing the compiled step (put_batch +
                      the jitted call's enqueue) — milliseconds when async
                      dispatch is healthy, a whole device step when a
                      hidden host sync serializes it
- ``device_busy``     the cadenced queue-drain probe: at the log cadence
                      (and ONLY there) the budget times a
                      ``block_until_ready`` on the step output *before*
                      the metric logger's fetch — the un-overlapped device
                      tail the host genuinely waits on
- ``sync_block``      the ``device_sync`` spans (the logger's cadenced
                      device→host conversion + emit)
- ``host_overhead``   every other recorded span landing inside a step's
                      duration: batch fingerprinting, flight-recorder/
                      metrics bookkeeping.  Cadenced checkpoint/eval time
                      BETWEEN steps is excluded from the partition (the
                      trainer re-anchors the step clock after it — see
                      ``SpanRecorder.mark_step_start``); read those costs
                      from the ``obs_window`` span aggregates instead
- ``unattributed``    the remainder — loop bookkeeping in no span.  The
                      additivity contract (test-pinned, and the e2e
                      acceptance bar) is that this stays under
                      ``tolerance`` of wall: the named components explain
                      ≥ 95% of where the time went.

Two derived signals ride each ``step_budget`` event:

- ``dispatch_efficiency`` = 1 − (data_wait + host_overhead +
  unattributed) / wall: the fraction of wall during which the device was
  being fed or drained rather than idling behind a host-side stall.  The
  ROADMAP's ``vs_synthetic_step ≥ 0.95`` attack is exactly "drive this
  toward 1.0"; bench stamps it per trainer-loop pass so the A/B is
  same-session.
- the **off-cadence host-transfer tripwire**: a host-blocking transfer
  inside the step body (a stray ``float()``/``device_get`` — the pattern
  repo-lint rule 4 bans *statically*) shows up at runtime as a dispatch
  span that consumes a device-step's worth of wall on a NON-cadence step.
  Any non-cadence step whose dispatch exceeds half the window's mean step
  wall (and an absolute floor) is counted in ``offcadence_sync_steps``
  and flags ``offcadence_sync_suspect`` — the runtime complement of the
  static rule, catching the transfers that hide behind attribute lookups
  or third-party code the AST lint cannot see.  The first window stands
  down (``"warmup": true``): it holds the JIT compile, a legitimate
  dispatch block wall time alone cannot tell from a transfer.

Everything here is host-clock arithmetic over the span recorder's
per-step records; the ONLY device interaction is the cadenced probe.  The
zero-new-syncs-off-cadence property is pinned by a counting-leaf test the
same way PR 3 pinned the health telemetry.
"""

from __future__ import annotations

from typing import Any

from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.spans import SpanRecorder

# the additive components, in emission order; "<name>_ms" fields on every
# step_budget event.  obs/report.py and bench.py iterate this list — one
# definition, three consumers.
COMPONENTS: tuple[str, ...] = (
    "data_wait",
    "dispatch",
    "device_busy",
    "sync_block",
    "host_overhead",
    "unattributed",
)

# span name → component.  Spans not named here (checkpoint, eval,
# host_overhead itself, obs_gauge_compile, future additions) fold into
# host_overhead: they are host work riding a step's wall time.
_SPAN_COMPONENT = {
    "data_wait": "data_wait",
    "step_dispatch": "dispatch",
    "device_busy": "device_busy",
    "device_sync": "sync_block",
}

# a dispatch must eat at least this much wall before the tripwire will
# consider it a blocked transfer — keeps clock jitter on sub-ms steps out
MIN_BLOCK_S = 0.005


def _ms(seconds: float) -> float:
    return round(seconds * 1e3, 3)


class BudgetAccountant:
    """Closes the span recorder's window into one ``step_budget`` event.

    ``probe(sync_leaf)`` is the cadenced device timing (call it at the
    log cadence, BEFORE the metric logger's own fetch, so the measured
    block is the genuine queue drain and the logger's fetch lands on an
    already-idle device); ``close_window(step)`` computes the account
    from the per-step span records and emits it.  ``history`` keeps the
    last ``history_size`` accounts for in-process consumers (bench)."""

    def __init__(
        self,
        spans: SpanRecorder,
        *,
        tolerance: float = 0.05,
        suspect_frac: float = 0.5,
        min_block_s: float = MIN_BLOCK_S,
        warmup_windows: int = 1,
        async_dispatch: bool = True,
        history_size: int = 64,
    ):
        self.spans = spans
        self.tolerance = float(tolerance)
        self.suspect_frac = float(suspect_frac)
        self.min_block_s = float(min_block_s)
        # multi-device CPU executes the program inline in the dispatching
        # thread — EVERY dispatch legitimately spans the device step, so
        # a blocked dispatch carries no signal there.  The count is still
        # reported (it is a measurement); only the SUSPECT verdict stands
        # down, stamped "sync_dispatch_backend" so the report can say why.
        self.async_dispatch = bool(async_dispatch)
        # the first window contains the JIT compile — a legitimate
        # dispatch block indistinguishable from a host-blocking transfer
        # by wall time alone, so the tripwire stands down for it (the
        # account itself still closes; the event carries "warmup": true)
        self.warmup_windows = int(warmup_windows)
        self.history_size = int(history_size)
        self.history: list[dict] = []
        # the newest device-side decomposition of device_busy (a parsed
        # profile capture — obs/devprof.py via attach_device_account);
        # bench reads it after a profiled trainer-loop pass
        self.last_device_account: dict | None = None
        self._closed = 0
        # cadenced gauges riding the account (not partition components):
        # currently the optimizer-apply wall sample (probe_optimizer)
        self._gauges: dict[str, float] = {}

    # -- the one device interaction (log cadence only) -------------------

    def probe(self, sync_leaf: Any) -> None:
        """Time the device-queue drain as a ``device_busy`` span: blocks
        until ``sync_leaf`` (the step's loss scalar) is ready.  The
        caller gates this to the log cadence — at that boundary the host
        would block for the same drain one line later inside the metric
        logger anyway, so the probe adds measurement, not a sync."""
        import jax

        with self.spans.span("device_busy"):
            jax.block_until_ready(sync_leaf)

    def probe_optimizer(self, fn: Any) -> None:
        """Time one stand-alone optimizer apply (``fn`` runs the jitted
        apply and returns its output to block on) — the satellite gauge
        that lets the fused-vs-xla A/B read optimizer milliseconds
        DIRECTLY from the ``step_budget`` account instead of inferring
        them from step-time deltas.  Cadence-gated by the caller
        (``TrainerObs.optimizer_probe``), and run AFTER the window
        closes, alongside checkpoint/eval, so its wall is EXCLUDED from
        the additive step-time partition (it is measurement, not step
        work); the sample lands on the NEXT window's account as
        ``optimizer_apply_ms``.  The FIRST invocation runs one untimed
        warm call: the lazily-built probe program jit-compiles inside
        ``fn`` and a compile is not an apply (the warm flag is set only
        AFTER that call succeeds, so a transient failure cannot leave a
        later compile mislabeled as the timed sample).

        The probe is a GAUGE, never load-bearing: any failure (an OOM
        compiling the stand-alone apply on a memory-tight config, a
        transient backend error inside the blocking call) disables
        further probes for this run with one logged event instead of
        propagating into the training loop."""
        import jax

        if getattr(self, "_opt_probe_dead", False):
            return
        try:
            if not getattr(self, "_opt_probe_warm", False):
                jax.block_until_ready(fn())
                self._opt_probe_warm = True
            t0 = self.spans.clock()
            jax.block_until_ready(fn())
            self._gauges["optimizer_apply_ms"] = _ms(self.spans.clock() - t0)
        except Exception as e:  # noqa: BLE001 — telemetry must not kill the run
            self._opt_probe_dead = True
            self._gauges.pop("optimizer_apply_ms", None)
            sink_mod.emit({
                "event": "optimizer_probe_disabled",
                "reason": str(e)[:300],
            }, local=True)

    # -- the device-side decomposition (profile windows only) ------------

    def attach_device_account(self, account: dict) -> dict:
        """Emit one parsed profile capture (obs/devprof.py) as a
        ``device_account`` event — the device-side decomposition of the
        host account's ``device_busy`` blob: per-module-bucket device
        time, per-collective time (+ achieved bandwidth when the byte
        account joined), and the overlap/exposed-idle metrics.  Same
        sink rules as ``trace_spans``: bulk (file channel only — the
        lanes payload has no place on the Valohai stdout contract) and
        local (every capturing rank's file carries its own account).
        Retained as ``last_device_account`` for in-process consumers
        (bench)."""
        record = {"event": "device_account", **{
            k: v for k, v in account.items() if k != "event"
        }}
        self.last_device_account = record
        sink_mod.emit(record, local=True, bulk=True)
        return record

    # -- window close (log cadence only) ---------------------------------

    def close_window(
        self, step: int, epoch: int | None = None, *, emit: bool = True
    ) -> dict | None:
        """Fold the window's per-step records into the additive account.
        Call BEFORE ``spans.summary()`` (which resets the window).  Emits
        a ``step_budget`` event (``local``: every rank's file carries its
        own account) and returns it; None when no step completed."""
        recs = self.spans.window_step_records()
        if not recs:
            return None
        wall = sum(r["dur"] for r in recs)
        if wall <= 0:
            return None
        comp = {c: 0.0 for c in COMPONENTS[:-1]}
        for r in recs:
            for name, s in r["spans"].items():
                comp[_SPAN_COMPONENT.get(name, "host_overhead")] += s
        # the remainder: host time in no span (loop bookkeeping).  Clock
        # rounding can push the sum a hair past wall — clamp at zero so
        # the account never reports negative time.
        unattributed = max(0.0, wall - sum(comp.values()))
        # the off-cadence tripwire: the window's LAST record is the
        # cadence step (probe + logger fetch legitimately block there);
        # any earlier step whose dispatch ate half a mean step-wall was
        # host-blocked inside the step body
        mean_step = wall / len(recs)
        threshold = max(self.suspect_frac * mean_step, self.min_block_s)
        self._closed += 1
        warmup = self._closed <= self.warmup_windows
        offcadence = 0 if warmup else sum(
            1
            for r in recs[:-1]
            if r["spans"].get("step_dispatch", 0.0) > threshold
        )
        stalled = comp["data_wait"] + comp["host_overhead"] + unattributed
        acct: dict[str, Any] = {
            "event": "step_budget",
            "step": int(step),
            "window_steps": len(recs),
            "wall_ms": _ms(wall),
        }
        if epoch is not None:
            acct["epoch"] = int(epoch)
        for c in COMPONENTS[:-1]:
            acct[f"{c}_ms"] = _ms(comp[c])
        acct["unattributed_ms"] = _ms(unattributed)
        acct["accounted_frac"] = round((wall - unattributed) / wall, 4)
        acct["additivity_ok"] = bool(unattributed <= self.tolerance * wall)
        acct["dispatch_efficiency"] = round(max(0.0, 1.0 - stalled / wall), 4)
        acct["offcadence_sync_steps"] = int(offcadence)
        acct["offcadence_sync_suspect"] = bool(
            offcadence > 0 and self.async_dispatch
        )
        opt_ms = self._gauges.get("optimizer_apply_ms")
        if opt_ms is not None:
            # the newest cadenced optimizer-apply sample (probe_optimizer)
            # + its share of the window's mean step wall — the direct
            # "how much of each step is the optimizer" read the fused
            # optimizer A/B consumes
            acct["optimizer_apply_ms"] = opt_ms
            acct["optimizer_share_of_step"] = round(
                opt_ms / max(_ms(mean_step), 1e-9), 4
            )
        if not self.async_dispatch:
            acct["sync_dispatch_backend"] = True
        if warmup:
            acct["warmup"] = True
        self.history.append(acct)
        if len(self.history) > self.history_size:
            del self.history[: len(self.history) - self.history_size]
        if emit:
            sink_mod.emit(acct, local=True)
        return acct


def aggregate_accounts(accounts: list[dict]) -> dict | None:
    """Fold ``step_budget`` accounts (one run / one bench pass) into
    per-component totals plus the wall-weighted dispatch efficiency —
    shared by bench.py's trainer-loop stamping and obs/report.py's
    per-rank rollup, so the two cannot disagree on the arithmetic."""
    accounts = [a for a in accounts if a.get("wall_ms")]
    if not accounts:
        return None
    wall = sum(float(a["wall_ms"]) for a in accounts)
    out: dict[str, Any] = {
        "windows": len(accounts),
        "steps": sum(int(a.get("window_steps", 0)) for a in accounts),
        "wall_ms": round(wall, 3),
    }
    for c in COMPONENTS:
        out[f"{c}_ms"] = round(
            sum(float(a.get(f"{c}_ms", 0.0) or 0.0) for a in accounts), 3
        )
    out["dispatch_efficiency"] = round(
        sum(
            float(a.get("dispatch_efficiency", 0.0) or 0.0) * float(a["wall_ms"])
            for a in accounts
        )
        / wall,
        4,
    )
    out["accounted_frac"] = round(
        (wall - out["unattributed_ms"]) / wall, 4
    ) if wall else None
    out["offcadence_sync_steps"] = sum(
        int(a.get("offcadence_sync_steps", 0) or 0) for a in accounts
    )
    opt_samples = [
        float(a["optimizer_apply_ms"])
        for a in accounts
        if a.get("optimizer_apply_ms") is not None
    ]
    if opt_samples:
        out["optimizer_apply_ms"] = round(
            sum(opt_samples) / len(opt_samples), 3
        )
        share_samples = [
            float(a["optimizer_share_of_step"])
            for a in accounts
            if a.get("optimizer_share_of_step") is not None
        ]
        if share_samples:
            out["optimizer_share_of_step"] = round(
                sum(share_samples) / len(share_samples), 4
            )
    return out


def budget_enabled(cfg: Any) -> bool:
    """``--obs-budget`` tristate: "on" forces, "off" disables, "auto"
    follows the obs instrumentation gate (any mode but "off")."""
    mode = getattr(cfg, "obs_budget", "auto")
    if mode == "on":
        return True
    if mode == "off":
        return False
    return getattr(cfg, "obs", "stdout") != "off"


__all__ = [
    "COMPONENTS",
    "BudgetAccountant",
    "aggregate_accounts",
    "budget_enabled",
]
# NOTE: the device-side decomposition of device_busy is emitted through
# BudgetAccountant.attach_device_account (device_account events) — parsed
# by obs/devprof.py from profile captures, rendered by obs/report.py.
