"""The pluggable metric sink — every JSON-line producer funnels here.

Channels:

- ``StdoutSink``: the Valohai metadata contract.  Byte-for-byte the line
  the pre-obs ``log_json`` printed (``json.dumps`` with default
  separators, one line, flushed) so the platform parser and every
  stdout-scraping consumer (bench supervisor, tests) see an unchanged
  stream.  Process-0 gated like every producer before it.
- ``JsonlFileSink``: the same records appended to a per-process JSONL
  file under the output dir, each stamped with ``schema_version`` so
  offline consumers can evolve.  Best-effort: a full disk or a vanished
  output dir must never kill a training step.
- ``TeeSink``: fan-out.

The module-level sink is what ``utils.jsonlog.log_json`` routes through;
``install_sink`` swaps it (the Trainer installs per --obs mode at
startup).  The process gate lives in ``wants`` and is checked BEFORE the
caller converts device scalars to host floats — on non-zero processes a
record nobody will emit must not cost a device sync.

Schema note (still ``schema_version`` 1 — event kinds are additive):
the open-loop load generator (serving/loadgen.py) emits one
``loadgen_point`` per offered-QPS grid point (offered/achieved QPS,
goodput, SLO attainment judged over every OFFERED request, TTFT
percentiles from ARRIVAL — ``None`` when nothing finished, so a
missing measurement can never gate as a pass — queue-delay percentiles
and the ``queue_growing`` verdict) and a closing ``loadgen_summary``
carrying the whole curve plus the detected ``knee_qps``; ``serve_request``
records gained ``t_arrival_s``/``queue_delay_ms`` (arrival→submit) and
``serve_window`` the ``arrival_rate_per_sec``/``service_rate_per_sec``/
``queue_growth`` gauges.  ``obs.report``'s "Open-loop load sweep"
section and the ``--min-slo-attainment``/``--max-p99-ttft-ms`` strict
gates consume these from the JSONL stream alone.

HBM attribution (obs/memprof.py — still additive, same version):
``memory_account`` is the static bucketed peak composition of the
compiled train step (``buckets_bytes`` over params / optimizer_state /
grad_accum / activations / kv_cache / other, the compiled byte view,
the largest-N buffers, and the ``fits_budget`` verdict against
``hbm_budget_gib``), emitted once at startup; ``memory_window`` is the
log-cadence runtime reading (bytes in use / process-lifetime peak /
``watermark_delta_bytes`` since the previous window, per-process
``local`` files since every rank's devices differ) with a once-only
``memory_window_skipped`` named skip where the backend reports no
``memory_stats`` (CPU PJRT — absent beats zero); ``memory_postmortem``
announces an OOM forensics bundle landed atomically at
``obs/memory-postmortem-p*.json`` (the bundle itself carries the same
``schema_version``).  ``obs.report``'s "Where did the bytes go" section
and the ``--max-peak-hbm-frac``/``--min-hbm-headroom-gib`` strict gates
consume these from the JSONL/bundle files alone.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Mapping

SCHEMA_VERSION = 1


def _process_index() -> int:
    import jax

    return jax.process_index()


class StdoutSink:
    """The Valohai stdout channel (process 0 only, byte-parity lines).

    ``local`` records (per-process telemetry: span windows, recorder
    events) do NOT widen the stdout gate — the platform channel stays
    process-0-only; only file channels fan out per process.  ``bulk``
    records (trace span dumps — hundreds of entries per line) never hit
    stdout at all: the platform parser and every stdout-scraping consumer
    see only the compact metric stream."""

    def wants(
        self, *, all_processes: bool = False, local: bool = False,
        bulk: bool = False,
    ) -> bool:
        return not bulk and (all_processes or _process_index() == 0)

    def emit(
        self,
        record: Mapping[str, Any],
        *,
        all_processes: bool = False,
        local: bool = False,
        bulk: bool = False,
    ) -> None:
        if not self.wants(all_processes=all_processes, local=local, bulk=bulk):
            return
        print(json.dumps(record), file=sys.stdout, flush=True)

    def flush(self, *, fsync: bool = False) -> None:
        pass  # print() above already flushes per line

    def close(self) -> None:
        pass


class JsonlFileSink:
    """Append records to a JSONL file, one ``schema_version``-stamped
    object per line.  Opened lazily so constructing a sink for a not-yet-
    created output dir is free; IO errors are swallowed (telemetry must
    never take down the run)."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._dead = False

    def wants(
        self, *, all_processes: bool = False, local: bool = False,
        bulk: bool = False,
    ) -> bool:
        # ``local``: per-process telemetry (span windows, recorder events)
        # lands in every process's OWN file — cross-host timelines need
        # every host's view, and the file is already per-process by path.
        # ``bulk`` records are file-channel material by definition.
        return not self._dead and (
            all_processes or local or bulk or _process_index() == 0
        )

    def emit(
        self,
        record: Mapping[str, Any],
        *,
        all_processes: bool = False,
        local: bool = False,
        bulk: bool = False,
    ) -> None:
        if not self.wants(all_processes=all_processes, local=local, bulk=bulk):
            return
        try:
            if self._f is None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._f = open(self.path, "a", buffering=1)
            # ONE write() per record: the line (payload + newline) reaches
            # the OS atomically w.r.t. this process's own later writes, so
            # a kill can truncate only the final line, never interleave
            self._f.write(json.dumps({"schema_version": SCHEMA_VERSION, **record}) + "\n")
        except OSError:
            self._dead = True

    def flush(self, *, fsync: bool = False) -> None:
        """Push buffered lines to the OS — and with ``fsync`` to DISK, so
        the last window survives a kill -9 (the anomaly/final-flush
        durability contract; per-line fsync would put a disk round-trip
        on every cadence)."""
        if self._f is None:
            return
        try:
            self._f.flush()
            if fsync:
                os.fsync(self._f.fileno())
        except OSError:
            self._dead = True

    def close(self) -> None:
        if self._f is not None:
            try:
                self.flush(fsync=True)
                self._f.close()
            except OSError:
                pass
            self._f = None


class ProductJsonlWriter:
    """Crash-safe JSONL writer for PRODUCT output — the serve CLI's
    request records, NOT a metric channel (no ``schema_version`` stamp,
    no process gate; the caller owns what goes in the file).

    Stronger than ``JsonlFileSink``'s line-buffered discipline: each
    record is encoded once and pushed through ``os.write`` on the raw
    fd, so even a line larger than the TextIOWrapper chunk (~8 KiB)
    reaches the OS in one syscall — a ``kill -9`` mid-run can drop only
    records never written, never interleave or tear a line (the only
    residual window is a kernel short write on a regular file, which the
    loop below completes and which does not occur outside signals/ENOSPC)
    — plus an fsync on ``close()`` so a completed run's output survives
    a machine-level interruption too.  Errors raise (this is the served
    product: losing it silently is not "best effort", it is data loss
    the caller must see)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fd: int | None = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644
        )
        self.records = 0

    def write(self, record: Mapping[str, Any]) -> None:
        data = (json.dumps(record) + "\n").encode("utf-8")
        while data:
            n = os.write(self._fd, data)
            data = data[n:]
        self.records += 1

    def close(self) -> None:
        if self._fd is None:
            return
        os.fsync(self._fd)
        os.close(self._fd)
        self._fd = None


class TeeSink:
    def __init__(self, sinks: list):
        self.sinks = list(sinks)

    def wants(
        self, *, all_processes: bool = False, local: bool = False,
        bulk: bool = False,
    ) -> bool:
        return any(
            s.wants(all_processes=all_processes, local=local, bulk=bulk)
            for s in self.sinks
        )

    def emit(
        self,
        record: Mapping[str, Any],
        *,
        all_processes: bool = False,
        local: bool = False,
        bulk: bool = False,
    ) -> None:
        for s in self.sinks:
            s.emit(record, all_processes=all_processes, local=local, bulk=bulk)

    def flush(self, *, fsync: bool = False) -> None:
        for s in self.sinks:
            s.flush(fsync=fsync)

    def close(self) -> None:
        for s in self.sinks:
            s.close()


_DEFAULT = StdoutSink()
_SINK = _DEFAULT


def current_sink():
    return _SINK


def install_sink(sink) -> None:
    """Swap the process-wide sink (closing the old one unless it is the
    default stdout sink, which is shared and stateless)."""
    global _SINK
    if _SINK is not _DEFAULT and _SINK is not sink:
        _SINK.close()
    _SINK = sink


def build_sink(mode: str, output_dir: str):
    """``--obs`` mode → sink.  "off"/"stdout" keep the stdout contract
    alone ("off" disables the obs *instrumentation*, never the Valohai
    channel); "jsonl" tees it into ``<output_dir>/obs/metrics-p{i}.jsonl``
    (process index in the name: multi-host runs share one output dir)."""
    if mode != "jsonl":
        return _DEFAULT
    path = os.path.join(
        output_dir, "obs", f"metrics-p{_process_index():03d}.jsonl"
    )
    return TeeSink([_DEFAULT, JsonlFileSink(path)])


def wants(
    *, all_processes: bool = False, local: bool = False, bulk: bool = False
) -> bool:
    return _SINK.wants(all_processes=all_processes, local=local, bulk=bulk)


def emit(
    record: Mapping[str, Any],
    *,
    all_processes: bool = False,
    local: bool = False,
    bulk: bool = False,
) -> None:
    _SINK.emit(record, all_processes=all_processes, local=local, bulk=bulk)


def flush(*, fsync: bool = False) -> None:
    """Flush the active sink's file channels (``fsync=True`` → to disk).
    Called on anomaly and at final close so the freshest telemetry
    survives even a kill -9 right after."""
    _SINK.flush(fsync=fsync)
