"""The training-health anomaly watchdog.

The in-graph numerics (train/step.py ``health_metrics``) make every step
self-describing: loss, global grad norm, per-bucket update ratios, and a
non-finite element count ride the metrics dict as device scalars.  This
module is their consumer — host-side, cadence-gated:

- every step the Trainer's obs bundle APPENDS the device scalars to a
  pending list (two pointer writes, no device sync);
- at the logging cadence the whole window converts to host floats in one
  ``jax.device_get`` (the same fetch the MetricLogger already pays) and
  the detectors run over the per-step values — so an anomaly is
  attributed to the exact step it happened, not the cadence step that
  noticed it;
- detectors: a NaN/Inf **tripwire** (non-finite loss or any non-finite
  grad element — fires immediately, no warmup), an EWMA **loss-spike**
  detector (loss above the running mean by ``spike_factor`` mean
  absolute deviations), and a **grad-norm explosion** threshold
  (``grad_factor`` × the EWMA grad norm, plus an optional absolute cap);
- multi-host **agreement** rides the heartbeat allgather channel
  (obs/heartbeat.py ``gather_probe``): every process contributes its
  local verdict at the same cadence step, so one bad host trips a
  rank-attributed ``obs_anomaly`` event on process 0 and EVERY process
  computes the same policy action (``warn`` / ``halt`` / ``checkpoint``)
  — a host-local decision would desynchronize the pod exactly like an
  un-agreed preemption.

Host clocks and floats only; the one ``jax.device_get`` lives in
``to_host`` and runs only at the cadence (pinned by the repo lint's
step-cadence sync rule and tests/test_health.py).

Every "step" here is an OPTIMIZER step: under in-step gradient
accumulation (``--grad-accum-steps N``) the compiled step scans N
microbatches internally and returns ONE metrics dict from the single
clip/AdamW/health tail, so the watchdog's EWMAs, warmup counter, and
anomaly attribution all advance once per optimizer step regardless of N
— microbatches are invisible to this layer by construction (pinned by
tests/test_health.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import numpy as np

from distributed_llms_example_tpu.obs import sink as sink_mod

ANOMALY_POLICIES = ("warn", "halt", "checkpoint", "rewind")

# stable wire codes for the agreement allgather (int32 payload)
CODE_IDS = {"nonfinite": 1, "loss_spike": 2, "grad_explosion": 3}
ID_CODES = {v: k for k, v in CODE_IDS.items()}


def health_enabled(cfg: Any) -> bool:
    """Resolve the ``--health`` tri-state: "on"/"off" are literal, "auto"
    follows ``--obs jsonl`` (the same convention as ``--obs-gauges``)."""
    mode = getattr(cfg, "health", "auto")
    if mode == "on":
        return True
    if mode == "off":
        return False
    return getattr(cfg, "obs", "stdout") == "jsonl"


@dataclasses.dataclass(frozen=True)
class Anomaly:
    step: int
    code: str  # "nonfinite" | "loss_spike" | "grad_explosion"
    value: float
    detail: str


def to_host(pending: Sequence[tuple[int, Mapping[str, Any]]]) -> list[tuple[int, dict]]:
    """Convert a window of per-step device-scalar metric dicts to host
    floats in ONE transfer.  This is the log-cadence fetch — the only
    place the health path touches a device."""
    import jax

    host = jax.device_get([dict(m) for _, m in pending])
    out = []
    for (step, _), vals in zip(pending, host):
        out.append((step, {k: float(v) for k, v in vals.items()}))
    return out


class HealthWatchdog:
    """EWMA-based per-step anomaly detection over host-float metrics.

    State persists across windows (the EWMAs are the run's memory); the
    detectors run per step inside each window so the reported anomaly
    step is the step the signal broke, not the cadence boundary.
    """

    def __init__(
        self,
        *,
        loss_spike_factor: float = 4.0,
        grad_norm_factor: float = 10.0,
        grad_norm_max: float = 0.0,  # 0 = no absolute cap
        warmup_steps: int = 20,
        ewma_alpha: float = 0.05,
    ):
        self.loss_spike_factor = float(loss_spike_factor)
        self.grad_norm_factor = float(grad_norm_factor)
        self.grad_norm_max = float(grad_norm_max)
        self.warmup_steps = int(warmup_steps)
        self.alpha = float(ewma_alpha)
        self.n = 0  # finite samples absorbed
        self.loss_ewma = 0.0
        self.loss_dev_ewma = 0.0  # EWMA of |loss - mean|
        self.grad_ewma = 0.0

    # -- detection -------------------------------------------------------

    def _check_one(self, step: int, m: Mapping[str, float]) -> Anomaly | None:
        loss = float(m.get("loss", 0.0))
        grad = float(m.get("grad_norm", 0.0))
        nonfinite = float(m.get("nonfinite_count", 0.0))
        if not np.isfinite(loss) or not np.isfinite(grad) or nonfinite > 0:
            return Anomaly(
                step=step,
                code="nonfinite",
                value=nonfinite if nonfinite > 0 else loss,
                detail=(
                    f"loss={loss!r}, grad_norm={grad!r}, "
                    f"{nonfinite:.0f} non-finite grad elements"
                ),
            )
        if self.grad_norm_max > 0 and grad > self.grad_norm_max:
            return Anomaly(
                step=step,
                code="grad_explosion",
                value=grad,
                detail=f"grad_norm {grad:.4g} > absolute cap {self.grad_norm_max:.4g}",
            )
        if self.n >= self.warmup_steps:
            if grad > self.grad_norm_factor * max(self.grad_ewma, 1e-12):
                return Anomaly(
                    step=step,
                    code="grad_explosion",
                    value=grad,
                    detail=(
                        f"grad_norm {grad:.4g} > {self.grad_norm_factor:g}× "
                        f"EWMA {self.grad_ewma:.4g}"
                    ),
                )
            # deviation floor: a perfectly flat loss stream must not turn
            # epsilon wiggles into spikes
            floor = max(self.loss_dev_ewma, 1e-3 * max(abs(self.loss_ewma), 1.0))
            if loss - self.loss_ewma > self.loss_spike_factor * floor:
                return Anomaly(
                    step=step,
                    code="loss_spike",
                    value=loss,
                    detail=(
                        f"loss {loss:.4g} > EWMA {self.loss_ewma:.4g} + "
                        f"{self.loss_spike_factor:g}× deviation {floor:.4g}"
                    ),
                )
        return None

    def _absorb(self, m: Mapping[str, float]) -> None:
        loss = float(m.get("loss", 0.0))
        grad = float(m.get("grad_norm", 0.0))
        if not (np.isfinite(loss) and np.isfinite(grad)):
            return  # never learn from garbage
        if self.n == 0:
            self.loss_ewma, self.grad_ewma = loss, grad
        else:
            a = self.alpha
            self.loss_dev_ewma = (1 - a) * self.loss_dev_ewma + a * abs(loss - self.loss_ewma)
            self.loss_ewma = (1 - a) * self.loss_ewma + a * loss
            self.grad_ewma = (1 - a) * self.grad_ewma + a * grad
        self.n += 1

    def check(self, entries: Sequence[tuple[int, Mapping[str, float]]]) -> list[Anomaly]:
        """Run the detectors over one window of (step, host metrics).
        Returns the anomalies in step order; a non-finite step ends the
        scan (every later value is arithmetic on garbage).

        Flagged FINITE samples are still absorbed after detection: a
        legitimate permanent level shift (curriculum change, new data
        mix) must re-baseline the EWMAs within ~1/alpha steps instead of
        firing — and re-dumping the flight recorder — on every window
        for the rest of the run.  A genuine divergence keeps firing
        while it outruns the re-baselining; a one-off spike fires once.
        """
        out: list[Anomaly] = []
        for step, m in entries:
            a = self._check_one(step, m)
            if a is not None:
                out.append(a)
                if a.code == "nonfinite":
                    break
            self._absorb(m)  # finite values only (_absorb guards)
        return out


class LaggardStreaks:
    """Persistent heartbeat-laggard classification — the first slice of
    ORGANIC host-loss detection (ISSUE 15 satellite; the ROADMAP's PR 14
    caveat).  A rank named laggard in one heartbeat is a wobble; a rank
    named laggard in ``suspect_beats`` CONSECUTIVE heartbeats is a
    ``host_loss_suspect`` — the operator's "go look at host N before the
    next collective hangs" signal.

    Pod-agreed by construction: every rank feeds this the SAME gathered
    probe (the heartbeat allgather is a barrier returning identical data
    everywhere), so every rank computes the same streaks and the same
    suspects — no second collective.  Detection + report row ONLY: the
    ``--on-host-loss`` policy still fires on the agreed signal path
    (chaos, scheduler restart), never on this classifier.
    """

    def __init__(self, *, suspect_beats: int = 3):
        self.suspect_beats = max(1, int(suspect_beats))
        self.streaks: dict[int, int] = {}
        self._suspected: set[int] = set()

    def update(self, laggards: Sequence[int], step: int) -> list[dict]:
        """Fold one heartbeat's laggard set; returns the NEW suspects
        crossing the streak threshold this beat (each as an event-ready
        record).  A rank that recovers (one clean beat) resets its
        streak and re-arms — a later persistent lag re-fires."""
        lag = {int(r) for r in laggards}
        out: list[dict] = []
        for r in list(self.streaks):
            if r not in lag:
                self.streaks.pop(r)
                self._suspected.discard(r)
        for r in sorted(lag):
            self.streaks[r] = self.streaks.get(r, 0) + 1
            if self.streaks[r] >= self.suspect_beats and r not in self._suspected:
                self._suspected.add(r)
                out.append({
                    "event": "host_loss_suspect",
                    "rank": r,
                    "step": int(step),
                    "consecutive_beats": self.streaks[r],
                })
        return out


def agree_and_emit(
    anomalies: Sequence[Anomaly],
    *,
    step: int,
    policy: str,
    extra: Mapping[str, Any] | None = None,
) -> dict | None:
    """Multi-host anomaly agreement + the ``obs_anomaly`` event.

    Every process calls this at the same cadence step (the Trainer's
    deterministic log cadence) with its LOCAL verdict; the verdicts ride
    the heartbeat allgather channel, so all processes return the same
    agreed record (→ the same policy action) and process 0 emits the
    rank-attributed event.  Returns None when no rank flagged anything.
    Single-process: no collective.
    """
    import jax

    from distributed_llms_example_tpu.obs.heartbeat import gather_probe

    first = anomalies[0] if anomalies else None
    local = np.asarray(
        [
            1 if first is not None else 0,
            first.step if first is not None else 0,
            CODE_IDS.get(first.code, 0) if first is not None else 0,
        ],
        np.int32,
    )
    gathered = gather_probe(local)  # single-process: just the local row
    ranks = [i for i in range(gathered.shape[0]) if int(gathered[i, 0])]
    if not ranks:
        return None
    # attribute to the EARLIEST flagged step across ranks (with in-graph
    # numerics the verdicts usually agree; host-local detectors may not)
    steps = [int(gathered[r, 1]) for r in ranks]
    r0 = ranks[int(np.argmin(steps))]
    record: dict[str, Any] = {
        "event": "obs_anomaly",
        "code": ID_CODES.get(int(gathered[r0, 2]), "unknown"),
        "step": int(gathered[r0, 1]),
        "detected_at_step": int(step),
        "ranks": ranks,
        "policy": policy,
        "process_count": int(gathered.shape[0]),
    }
    if first is not None:
        # each rank stamps ITS OWN numeric view; the agreed fields above
        # are identical everywhere.  Non-finite values go as strings:
        # "NaN" is not valid JSON.
        v = float(first.value)
        record["value"] = round(v, 6) if np.isfinite(v) else repr(v)
        record["detail"] = first.detail
        record["detail_rank"] = int(jax.process_index())
    # local: every rank's metrics-p*.jsonl carries its verdict (the
    # flagging rank's file is where the numbers live when process 0
    # itself saw nothing); stdout stays process-0-only as always
    sink_mod.emit(record, local=True)
    return record
