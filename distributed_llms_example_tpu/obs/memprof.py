"""Per-device HBM attribution: the bucketed byte account + OOM forensics.

HBM is the binding constraint for every 7B-class config on a 16 GB v5e
chip, and until now it had no account: the memory audit was a one-shot
CLI, runtime ``memory_stats`` reads were scattered ad hoc, peaks were
process-lifetime, and an OOM died with a raw RESOURCE_EXHAUSTED and no
record of where the bytes went.  This module is the one owner of both
faces of the question "where did the bytes go":

- **the static account** (``account_from_compiled`` /
  ``static_memory_account``): walk the AOT-compiled train step's
  ``memory_analysis()`` plus the abstract state tree's per-shard byte
  counts (both via ``utils/memory_audit.py``'s shared accounting
  functions — single owner, no forked arithmetic) into ONE bucketed
  peak composition over the shared taxonomy ``BUCKETS`` (params /
  optimizer_state / grad_accum — the EF carry — / activations+temps /
  kv_cache / other), with donation/aliasing credited (outputs minus
  aliased), the largest-N buffers named, and a fit verdict against an
  ``--hbm-budget-gib`` ceiling.  The decomposition is ADDITIVE: the
  bucket bytes sum to the compiled peak up to a stamped
  ``additivity_gap_bytes`` (test-pinned within 5% on the real compiled
  fsdp=8 program), and the params/optimizer buckets equal the memory
  audit's analytic shard-byte counts EXACTLY because they ARE the same
  numbers from the same function.

- **the runtime side** (``Watermark`` / ``MemoryMonitor``): sample the
  backend's ``memory_stats`` at log cadence into ``memory_window``
  events.  PJRT peaks are PROCESS-LIFETIME — a per-phase "did this pass
  allocate a new high-water mark?" needs reset-or-delta semantics, and
  there is no public reset, so ``Watermark`` owns the delta form:
  ``mark()`` snapshots per-device peaks, readings report
  ``watermark_delta_bytes`` since the mark.  Everyone who used to
  hand-roll this (bench's per-pass ``peak_hbm_new_high_water``, the
  serving engine's peak reads) now goes through here — repo-lint rule
  15 forbids raw ``memory_stats()``/``live_buffers()`` outside the
  owners.  On backends that report nothing (CPU PJRT) the account
  degrades to STATIC-ONLY with one named ``memory_window_skipped``
  event — absent beats zero, never a silent 0.

- **OOM forensics** (``is_resource_exhausted`` / ``dump_postmortem``):
  when a RESOURCE_EXHAUSTED escapes the trainer or the serving engine,
  a schema-stamped ``memory-postmortem-p*.json`` bundle lands via the
  recorder's atomic-write discipline (tmp + fsync + rename — a kill -9
  mid-dump leaves either nothing or a complete bundle) carrying the
  last static account, the watermark history, and a live-buffer top-N
  where the backend supports it; then the error re-raises.  The report
  CLI (obs/report.py "Where did the bytes go") renders account, windows
  and postmortems from the JSONL/bundle files alone.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Iterable, Mapping

from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.sink import SCHEMA_VERSION

# The ONE bucket taxonomy both faces (and the serving account) share.
# grad_accum covers the in-step fp32 accumulation carry AND the
# error-feedback tree (TrainState.ef); kv_cache is the serving cache
# (flat or paged pool); activations is the compiled program's temp
# arena (saved residuals + recompute working set + logits).
BUCKETS = (
    "params", "optimizer_state", "grad_accum", "activations", "kv_cache",
    "other",
)

GIB = 1024**3


# ---------------------------------------------------------------------------
# runtime readings: memory_stats ownership + watermark semantics
# ---------------------------------------------------------------------------


def hbm_stats() -> list[dict] | None:
    """Per-local-device live memory: bytes in use / peak / limit.  None
    when the backend does not report (CPU PJRT) — absent beats zero.
    The ONE raw ``memory_stats()`` read of the runtime side (repo-lint
    rule 15); ``obs/gauges.py`` re-exports this for its callers."""
    import jax

    out = []
    for d in jax.local_devices():
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None
        if not stats:
            return None
        out.append({
            "device": d.id,
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return out


class Watermark:
    """Reset-or-delta semantics over the process-lifetime PJRT peak.

    ``peak_bytes_in_use`` never goes down, so "what did THIS pass / THIS
    window newly touch?" cannot be read off the raw stat.  There is no
    public peak-reset API either; the delta form is the honest one:
    ``mark()`` snapshots each device's current peak, and every reading
    reports ``watermark_delta_bytes`` = max over devices of (peak now −
    peak at the mark) — 0 when the phase stayed under the old high-water
    mark, the newly claimed bytes when it did not."""

    def __init__(self):
        self._marked: dict[int, int] = {}

    def mark(self) -> None:
        """Snapshot per-device peaks as the new baseline.  A no-op (the
        baseline stays empty ⇒ deltas read as absolute peaks) on
        backends without memory_stats."""
        stats = hbm_stats()
        if stats:
            self._marked = {
                s["device"]: s["peak_bytes_in_use"] for s in stats
            }

    def read(self) -> dict | None:
        """One reading, maxed over local devices: ``bytes_in_use``,
        ``peak_bytes_in_use``, ``watermark_delta_bytes`` (since the last
        ``mark()``), ``bytes_limit``.  None when the backend reports
        nothing — the caller emits a named skip, never zeros."""
        stats = hbm_stats()
        if not stats:
            return None
        return {
            "bytes_in_use": max(s["bytes_in_use"] for s in stats),
            "peak_bytes_in_use": max(s["peak_bytes_in_use"] for s in stats),
            "watermark_delta_bytes": max(
                s["peak_bytes_in_use"] - self._marked.get(s["device"], 0)
                for s in stats
            ),
            "bytes_limit": max(s["bytes_limit"] for s in stats),
            "devices": len(stats),
        }

    def peak_bytes(self) -> int:
        """Current process-lifetime peak (max over local devices), 0 when
        the backend reports nothing — the legacy ``device_peak_bytes``
        shape the serving summary stamps."""
        stats = hbm_stats()
        if not stats:
            return 0
        return max(s["peak_bytes_in_use"] for s in stats)

    def delta_bytes(self) -> int | None:
        """Peak bytes newly claimed since ``mark()`` (None when the
        backend reports nothing) — bench's per-pass high-water delta."""
        reading = self.read()
        return None if reading is None else reading["watermark_delta_bytes"]


def is_resource_exhausted(e: BaseException) -> bool:
    """Does this exception look like an HBM/host OOM?  XLA surfaces
    RESOURCE_EXHAUSTED through ``XlaRuntimeError`` (message-matched —
    the type is not constructible for tests), chaos injects a plain
    RuntimeError with the same marker, and MemoryError covers the host
    side."""
    if isinstance(e, MemoryError):
        return True
    text = f"{type(e).__name__}: {e}".lower()
    return (
        "resource_exhausted" in text
        or "resource exhausted" in text
        or "out of memory" in text
        or "allocation failure" in text
    )


# ---------------------------------------------------------------------------
# the static account
# ---------------------------------------------------------------------------


def account_from_compiled(
    compiled: Any,
    a_state: Any,
    sh: Any,
    *,
    hbm_budget_gib: float = 16.0,
    top_n: int = 8,
    model: str = "",
    mesh: Mapping[str, int] | None = None,
) -> dict:
    """The bucketed peak composition of one AOT-compiled train step.

    Every byte comes from the memory audit's shared accounting functions
    (``compiled_byte_view`` over XLA's ``memory_analysis()``,
    ``state_bucket_bytes`` over the abstract state's shard shapes) so
    this account and the audit's ``analytic_*``/``compiled_*`` views can
    never fork.  Decomposition, per device:

    - params / optimizer_state / grad_accum (EF carry) / other(step
      counter): the donated state argument, split by TrainState field —
      these ARE the audit's analytic shard-byte counts;
    - activations: the compiled temp arena (saved residuals, recompute
      working set, fp32 logits — plus the in-step grad-accum scan carry,
      which XLA allocates as a temp);
    - other also absorbs non-state arguments (the batch) and the
      non-aliased output slack (donation credited: outputs − aliased).

    The buckets sum to the compiled peak up to ``additivity_gap_bytes``
    (0 by construction unless XLA reports arguments smaller than the
    state that rides them)."""
    import jax

    from distributed_llms_example_tpu.utils.memory_audit import (
        compiled_byte_view,
        state_bucket_bytes,
    )

    view = compiled_byte_view(compiled.memory_analysis())
    state_buckets = state_bucket_bytes(a_state, sh)
    state_total = sum(state_buckets.values())
    buckets = {b: 0 for b in BUCKETS}
    for k, v in state_buckets.items():
        buckets[k] += int(v)
    buckets["activations"] = int(view["temp_bytes"])
    buckets["other"] += max(0, view["arguments_bytes"] - state_total)
    buckets["other"] += max(0, view["output_bytes"] - view["aliased_bytes"])
    total = sum(buckets.values())
    peak = int(view["peak_bytes"])
    budget_bytes = int(float(hbm_budget_gib) * GIB)
    account: dict[str, Any] = {
        "model": model,
        "mesh": dict(mesh) if mesh is not None else None,
        "backend": jax.default_backend(),
        "buckets_bytes": buckets,
        "bucket_total_bytes": total,
        "peak_bytes": peak,
        "peak_gib": round(peak / GIB, 3),
        "additivity_gap_bytes": peak - total,
        "compiled": view,
        "largest_buffers": largest_state_buffers(a_state, sh, n=top_n),
        "hbm_budget_gib": float(hbm_budget_gib),
        "hbm_budget_bytes": budget_bytes,
        "peak_frac_of_budget": (
            round(peak / budget_bytes, 4) if budget_bytes else None
        ),
        "hbm_headroom_gib": round((budget_bytes - peak) / GIB, 3),
        "fits_budget": peak < budget_bytes,
    }
    return account


def largest_state_buffers(a_state: Any, sh: Any, *, n: int = 8) -> list[dict]:
    """The N largest per-device state buffers, named by pytree path and
    tagged with the coarse model-module bucket
    (``analysis/ir_lint.py``'s MODULE_BUCKET_PATTERNS) where the path
    names one."""
    import jax
    import numpy as np

    from distributed_llms_example_tpu.analysis.ir_lint import module_bucket_of

    rows: list[dict] = []
    leaves, _ = jax.tree_util.tree_flatten_with_path(a_state)
    sh_leaves = jax.tree.leaves(sh)
    for (path, leaf), shard in zip(leaves, sh_leaves):
        name = jax.tree_util.keystr(path)
        shard_shape = shard.shard_shape(leaf.shape)
        nbytes = int(np.prod(shard_shape)) * leaf.dtype.itemsize
        row = {
            "name": name,
            "shape": list(leaf.shape),
            "shard_shape": list(shard_shape),
            "dtype": str(leaf.dtype),
            "bytes": nbytes,
        }
        module = module_bucket_of(name)
        if module is not None:
            row["module"] = module
        rows.append(row)
    rows.sort(key=lambda r: (-r["bytes"], r["name"]))
    return rows[: max(0, int(n))]


def static_memory_account(
    model_name: str,
    mesh: Any,
    *,
    global_batch: int = 8,
    src_len: int = 1024,
    tgt_len: int = 128,
    dtype: str = "bfloat16",
    remat: bool = True,
    remat_policy: str = "full",
    grad_accum_steps: int = 1,
    grad_compression: str = "",
    hbm_budget_gib: float = 16.0,
    top_n: int = 8,
) -> dict:
    """Compile the train step via the shared AOT recipe and account it —
    the stand-alone entry ``analysis/lint.py --memory`` and tests use
    when no caller already holds a compiled program."""
    from distributed_llms_example_tpu.utils.memory_audit import (
        aot_compile_train_step,
    )

    compiled, _, _, a_state, sh = aot_compile_train_step(
        model_name, mesh,
        global_batch=global_batch, src_len=src_len, tgt_len=tgt_len,
        dtype=dtype, remat=remat, remat_policy=remat_policy,
        grad_accum_steps=grad_accum_steps, grad_compression=grad_compression,
    )
    return account_from_compiled(
        compiled, a_state, sh,
        hbm_budget_gib=hbm_budget_gib, top_n=top_n,
        model=model_name, mesh=dict(mesh.shape),
    )


def serving_account(
    *,
    params_bytes: int,
    kv_cache_bytes: int,
    hbm_budget_gib: float = 16.0,
) -> dict:
    """The serving tier's bucketed account over the SAME taxonomy: the
    capacity gauges' cache-bytes arithmetic (serving/engine.py) lands in
    ``kv_cache``, the loaded weights in ``params``.  Shares the fit
    fields with the training account so the report renders both with one
    table shape."""
    buckets = {b: 0 for b in BUCKETS}
    buckets["params"] = int(params_bytes)
    buckets["kv_cache"] = int(kv_cache_bytes)
    total = sum(buckets.values())
    budget_bytes = int(float(hbm_budget_gib) * GIB)
    return {
        "buckets_bytes": buckets,
        "bucket_total_bytes": total,
        "peak_bytes": total,
        "peak_gib": round(total / GIB, 3),
        "hbm_budget_gib": float(hbm_budget_gib),
        "hbm_budget_bytes": budget_bytes,
        "peak_frac_of_budget": (
            round(total / budget_bytes, 4) if budget_bytes else None
        ),
        "hbm_headroom_gib": round((budget_bytes - total) / GIB, 3),
        "fits_budget": total < budget_bytes,
    }


# ---------------------------------------------------------------------------
# the runtime monitor
# ---------------------------------------------------------------------------


class MemoryMonitor:
    """Log-cadence memory telemetry + the OOM postmortem's state.

    Owns one ``Watermark`` (marked after every window, so each
    ``memory_window`` event carries the delta SINCE THE LAST WINDOW) and
    a bounded history of recent readings — exactly what the postmortem
    bundle replays.  ``sample()`` off a reporting backend emits ONE
    named ``memory_window_skipped`` event and then stays silent: the
    account degrades to static-only, never to a stream of zeros."""

    def __init__(self, *, history: int = 64):
        self.account: dict | None = None
        self.watermark = Watermark()
        self.history: deque = deque(maxlen=max(1, int(history)))
        self._skip_emitted = False

    def attach_account(self, account: dict | None) -> None:
        """The last static account — stamped into postmortem bundles."""
        self.account = account

    def sample(self, step: int, *, emit: bool = True) -> dict | None:
        """One log-cadence reading → a ``memory_window`` event (local:
        every rank's file carries its own devices' numbers).  Returns the
        record, or None when the backend reports nothing."""
        reading = self.watermark.read()
        if reading is None:
            if emit and not self._skip_emitted:
                self._skip_emitted = True
                sink_mod.emit({
                    "event": "memory_window_skipped",
                    "step": int(step),
                    "reason": (
                        "backend reports no memory_stats (CPU PJRT) — "
                        "memory account degrades to static-only"
                    ),
                }, local=True)
            return None
        record = {"event": "memory_window", "step": int(step), **reading}
        self.history.append({
            "step": int(step),
            "bytes_in_use": reading["bytes_in_use"],
            "peak_bytes_in_use": reading["peak_bytes_in_use"],
            "watermark_delta_bytes": reading["watermark_delta_bytes"],
        })
        self.watermark.mark()
        if emit:
            sink_mod.emit(record, local=True)
        return record

    def maybe_dump_postmortem(
        self, output_dir: str, *, step: int, error: BaseException
    ) -> str | None:
        """The tripwire: when ``error`` is a RESOURCE_EXHAUSTED, dump the
        postmortem bundle (atomic) and return its path; otherwise do
        nothing.  The caller re-raises either way — forensics never
        swallow the failure."""
        if not is_resource_exhausted(error):
            return None
        return dump_postmortem(
            output_dir,
            reason=f"{type(error).__name__}: {str(error)[:300]}",
            step=step,
            account=self.account,
            watermark_history=list(self.history),
        )


# ---------------------------------------------------------------------------
# OOM postmortem bundles
# ---------------------------------------------------------------------------


def postmortem_path(output_dir: str) -> str:
    import jax

    return os.path.join(
        output_dir, "obs", f"memory-postmortem-p{jax.process_index():03d}.json"
    )


def _live_buffer_top(n: int = 10) -> list[dict] | None:
    """Largest live device buffers at dump time, where the backend can
    enumerate them.  Broad except: this runs on the crash path against a
    runtime that may have just OOMed — losing the top-N must not lose
    the bundle."""
    import jax

    try:
        arrays = jax.live_arrays()
        rows = sorted(
            (
                {
                    "shape": list(a.shape),
                    "dtype": str(a.dtype),
                    "bytes": int(a.size) * a.dtype.itemsize,
                }
                for a in arrays
            ),
            key=lambda r: -r["bytes"],
        )[: max(0, int(n))]
        return rows or None
    except Exception:
        return None


def dump_postmortem(
    output_dir: str,
    *,
    reason: str,
    step: int,
    account: dict | None = None,
    watermark_history: Iterable[Mapping] = (),
    top_n: int = 10,
) -> str | None:
    """Write the schema-stamped ``memory-postmortem-p*.json`` bundle via
    the recorder's atomic-write discipline (tmp + fsync + rename: a kill
    mid-dump leaves the previous bundle or the complete new one, never a
    torn JSON) and announce it on the sink.  Telemetry never takes down
    the run — IO errors are reported as ``memory_postmortem_failed``,
    not raised."""
    import jax

    path = postmortem_path(output_dir)
    final_reading = Watermark().read()
    bundle: dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "event": "memory_postmortem",
        "reason": str(reason)[:400],
        "step": int(step),
        "process_index": int(jax.process_index()),
        "account": account,
        "watermark_history": [dict(w) for w in watermark_history],
        "final_reading": final_reading,
    }
    top = _live_buffer_top(top_n)
    if top is not None:
        bundle["live_buffers_top"] = top
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:
        sink_mod.emit(
            {"event": "memory_postmortem_failed", "reason": str(e)[:200]},
            local=True,
        )
        return None
    sink_mod.emit(
        {
            "event": "memory_postmortem",
            "path": path,
            "reason": str(reason)[:200],
            "step": int(step),
        },
        local=True,
    )
    return path
