"""Device-time attribution: profiler traces → a per-module device account.

The budget layer (obs/budget.py) closes every logging window into an
additive HOST account, but its largest component — ``device_busy`` — is
one opaque measured blob.  This module opens it: a **jax-free** parser
for the trace-viewer JSON ``jax.profiler`` leaves under a capture dir
(obs/profile.py), reducing the raw device events into a **device
account**:

- **per-bucket device time** — every device op event is attributed to a
  module bucket via the HLO ``op_name`` scope metadata, through the SAME
  matching table the health telemetry's param buckets use
  (analysis/ir_lint.py ``MODULE_BUCKET_PATTERNS``: embed / attn / mlp /
  head) plus the device-only classes ``optimizer`` (the clip/AdamW tail),
  ``collective`` (comm), ``infeed`` (host transfers) and ``other``
  (loss arithmetic, layout ops, scan plumbing);
- **per-collective-op time** — counts and total device time per base
  collective opcode, joined against obs/gauges.py's static byte account
  (``join_collective_bandwidth``) to yield **achieved bytes/sec** per
  collective — the measured half of every queued comms PR's verdict;
- **overlap / exposed idle** — interval arithmetic over the merged
  collective vs compute timelines: how much comm hid under compute
  (``overlap_frac``), how much was exposed, and how much of the window's
  span no device op covered at all (``exposed_idle``).

Backend notes: TPU/GPU traces carry per-device processes (``/device:…``
pids) whose event names are op_name scopes; the CPU thunk runtime names
device events by HLO *instruction* (``args.hlo_op = "fusion.3"``) on the
host process's executor threads.  Both shapes parse here — instruction
names are joined to buckets through an ``op_bucket_index`` built from
the SAME compiled HLO text the startup gauges already hold (the AOT
compile in utils/memory_audit.py), with opcode-class fallbacks for
events the index misses.  Bucket sums are per-op durations, so on a
multi-device (or multi-thread) timeline they can legitimately exceed
the busy UNION — they are device·time, the union is wall coverage.

Offline: ``python -m distributed_llms_example_tpu.obs.devprof
<trace_dir>`` prints the account; at runtime TrainerObs parses each
landed capture and emits it as a ``device_account`` event through
obs/budget.py (bulk/local, like ``trace_spans``), so obs/report.py
renders the tables from the JSONL alone — no trace files needed at
report time.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from typing import Any, Iterable, Mapping

from distributed_llms_example_tpu.analysis.ir_lint import (
    base_collective_op,
    classify_op_scope,
    op_bucket_index,  # noqa: F401  (re-exported: the runtime's index builder)
)

# the device-account buckets, in emission order: the four module buckets
# (shared with train/step.py HEALTH_BUCKETS via MODULE_BUCKET_PATTERNS)
# plus the device-only classes
DEVICE_BUCKETS: tuple[str, ...] = (
    "embed", "attn", "mlp", "head", "optimizer", "collective", "infeed",
    "other",
)

_INFEED_NAMES = (
    "infeed", "outfeed", "send", "recv", "send-done", "recv-done",
)

# cap on the per-bucket lane slices a device_account event carries for
# the Perfetto export — bounded like the trace collector's span buffer;
# overflow is counted (lane_slices_dropped), never silent
MAX_LANE_SLICES = 512


# ---------------------------------------------------------------------------
# trace loading
# ---------------------------------------------------------------------------


def find_trace_files(trace_dir: str) -> list[str]:
    """Every ``*.trace.json(.gz)`` under ``trace_dir`` (jax writes them at
    ``plugins/profile/<date>/<host>.trace.json.gz``), newest session
    first."""
    hits = [
        p
        for pattern in ("*.trace.json.gz", "*.trace.json")
        for p in glob.glob(
            os.path.join(trace_dir, "**", pattern), recursive=True
        )
    ]
    return sorted(hits, key=os.path.getmtime, reverse=True)


def load_trace_events(path: str) -> list[dict]:
    """One trace-viewer JSON file → its ``traceEvents`` list."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else []
    return [e for e in events if isinstance(e, dict)]


def device_op_events(events: Iterable[dict]) -> list[dict]:
    """Normalize the raw event stream to the DEVICE OP events only:
    ``{"name", "hlo_op", "ts", "dur", "pid", "tid"}`` (times in µs).

    Two backend shapes: accelerator traces put ops on ``/device:…``
    processes (every complete event there counts); the CPU thunk runtime
    has no device pids — there the op events are exactly the ones stamped
    with ``args.hlo_op``."""
    meta_pid_names: dict[Any, str] = {}
    thread_names: dict[tuple, str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            meta_pid_names[e.get("pid")] = str(
                (e.get("args") or {}).get("name", "")
            )
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = str(
                (e.get("args") or {}).get("name", "")
            ).lower()
    device_pids = {
        pid for pid, name in meta_pid_names.items()
        if name.startswith("/device:")
    }
    out: list[dict] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        hlo_op = args.get("hlo_op")
        if e.get("pid") in device_pids and not hlo_op:
            # accelerator traces stack several lanes under each device
            # pid; only the per-op lanes are device ops.  Aggregate lanes
            # ("XLA Modules", "Steps" — one whole-step slice enclosing
            # every op) would double-count the entire span into "other"
            # and pin overlap_frac at 1.0, so they are excluded.
            lane = thread_names.get((e.get("pid"), e.get("tid")), "")
            if "module" in lane or "step" in lane:
                continue
        if e.get("pid") in device_pids or hlo_op:
            dur = float(e.get("dur", 0.0) or 0.0)
            if dur <= 0:
                continue
            out.append({
                "name": str(e.get("name", "")),
                "hlo_op": str(hlo_op) if hlo_op else "",
                "ts": float(e.get("ts", 0.0) or 0.0),
                "dur": dur,
                "pid": e.get("pid"),
                "tid": e.get("tid"),
            })
    return out


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------


def classify_event(
    name: str, hlo_op: str, op_buckets: Mapping[str, str] | None
) -> str:
    """One device op event → its account bucket.

    Order: collective/infeed by opcode shape (works with or without an
    index); the instruction-name join through ``op_buckets`` (CPU traces
    name events by HLO instruction); a scope classification of the event
    name itself (TPU device lanes name events by op_name scope); then
    ``other``."""
    instr = hlo_op or name
    if base_collective_op(instr) is not None:
        return "collective"
    base = instr.split(".", 1)[0]
    if base in _INFEED_NAMES:
        return "infeed"
    if op_buckets:
        bucket = op_buckets.get(instr)
        if bucket:
            return bucket
    if "/" in name:  # an op_name scope path, classifiable directly
        return classify_op_scope(name) or "other"
    return "other"


def _merged_intervals(spans: Iterable[tuple[float, float]]) -> list[list[float]]:
    """Sorted (start, end) µs intervals → merged disjoint cover."""
    merged: list[list[float]] = []
    for t0, t1 in sorted(spans):
        if merged and t0 <= merged[-1][1]:
            if t1 > merged[-1][1]:
                merged[-1][1] = t1
        else:
            merged.append([t0, t1])
    return merged


def _union_us(merged: list[list[float]]) -> float:
    return sum(t1 - t0 for t0, t1 in merged)


def _intersect_us(a: list[list[float]], b: list[list[float]]) -> float:
    """Total overlap between two merged interval lists."""
    out = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _ms(us: float) -> float:
    return round(us / 1e3, 3)


def build_account(
    events: list[dict],
    *,
    op_buckets: Mapping[str, str] | None = None,
    max_lane_slices: int = MAX_LANE_SLICES,
) -> dict[str, Any] | None:
    """Reduce normalized device op events into the device account.

    Returns None when the trace holds no device op events (a capture
    that caught no step).  All times in ms (3 decimals — trace input is
    µs, so the rounding is exact representation, not loss)."""
    if not events:
        return None
    span_lo = min(e["ts"] for e in events)
    span_hi = max(e["ts"] + e["dur"] for e in events)
    buckets = {b: 0.0 for b in DEVICE_BUCKETS}
    collectives: dict[str, dict[str, Any]] = {}
    op_spans: dict[str, list[tuple[float, float]]] = {}
    all_spans: list[tuple[float, float]] = []
    comm_spans: list[tuple[float, float]] = []
    compute_spans: list[tuple[float, float]] = []
    # per-bucket lane slices for the Perfetto export, relative to span_lo
    lane_raw: dict[str, list[tuple[float, float]]] = {}
    for e in events:
        bucket = classify_event(e["name"], e["hlo_op"], op_buckets)
        buckets[bucket] += e["dur"]
        t0, t1 = e["ts"], e["ts"] + e["dur"]
        all_spans.append((t0, t1))
        if bucket == "collective":
            comm_spans.append((t0, t1))
            op = base_collective_op(e["hlo_op"] or e["name"]) or "collective"
            slot = collectives.setdefault(op, {"count": 0, "time_us": 0.0})
            slot["count"] += 1
            slot["time_us"] += e["dur"]
            op_spans.setdefault(op, []).append((t0, t1))
        else:
            compute_spans.append((t0, t1))
        lane_raw.setdefault(bucket, []).append((t0 - span_lo, t1 - span_lo))
    busy = _merged_intervals(all_spans)
    comm = _merged_intervals(comm_spans)
    compute = _merged_intervals(compute_spans)
    busy_us = _union_us(busy)
    comm_us = _union_us(comm)
    compute_us = _union_us(compute)
    overlapped_us = _intersect_us(comm, compute)
    span_us = span_hi - span_lo
    total_op_us = sum(buckets.values())
    acct: dict[str, Any] = {
        "event": "device_account",
        "events": len(events),
        "span_ms": _ms(span_us),
        "busy_ms": _ms(busy_us),
        "exposed_idle_ms": _ms(max(0.0, span_us - busy_us)),
        "buckets_ms": {b: _ms(buckets[b]) for b in DEVICE_BUCKETS},
        "bucket_frac": {
            b: round(buckets[b] / total_op_us, 4) if total_op_us else 0.0
            for b in DEVICE_BUCKETS
        },
        # per op: time_ms is summed device·time across every lane that
        # ran the op (N participants ≈ N× one device's time); wall_ms is
        # the interval UNION — the wall during which the op ran on ANY
        # lane, the lane-count-independent denominator the bandwidth
        # join divides by
        "collectives": {
            op: {
                "count": s["count"],
                "time_ms": _ms(s["time_us"]),
                "wall_ms": _ms(_union_us(_merged_intervals(op_spans[op]))),
            }
            for op, s in sorted(collectives.items())
        },
        "overlap": {
            "collective_ms": _ms(comm_us),
            "compute_ms": _ms(compute_us),
            "overlapped_ms": _ms(overlapped_us),
            "exposed_collective_ms": _ms(comm_us - overlapped_us),
            **(
                {"overlap_frac": round(overlapped_us / comm_us, 4)}
                if comm_us > 0
                else {}
            ),
        },
    }
    # bounded per-bucket lanes (merged, largest-first) for the trace
    # exporter's device tracks — enough to DRAW the account, not a full
    # op dump (that is what the raw capture is for)
    lanes: list[list[Any]] = []
    dropped = 0
    for b in DEVICE_BUCKETS:
        if b not in lane_raw:
            continue
        merged = _merged_intervals(lane_raw[b])
        merged.sort(key=lambda iv: iv[0] - iv[1])  # longest first
        budget_n = max_lane_slices - len(lanes)
        dropped += max(0, len(merged) - budget_n)
        lanes.extend(
            [b, _ms(t0), _ms(t1 - t0)] for t0, t1 in merged[:budget_n]
        )
    lanes.sort(key=lambda s: s[1])
    acct["lanes"] = lanes
    if dropped:
        acct["lane_slices_dropped"] = dropped
    return acct


def device_account_from_dir(
    trace_dir: str,
    *,
    op_buckets: Mapping[str, str] | None = None,
) -> dict[str, Any] | None:
    """Parse the newest trace session under ``trace_dir`` into a device
    account.  None when no trace file or no device op events exist."""
    files = find_trace_files(trace_dir)
    if not files:
        return None
    # one capture session can write several host files; take every file
    # sharing the newest session directory
    session_dir = os.path.dirname(files[0])
    events: list[dict] = []
    for path in files:
        if os.path.dirname(path) == session_dir:
            events.extend(device_op_events(load_trace_events(path)))
    acct = build_account(events, op_buckets=op_buckets)
    if acct is not None:
        acct["trace_dir"] = trace_dir
    return acct


# ---------------------------------------------------------------------------
# the byte-account join
# ---------------------------------------------------------------------------


def join_collective_bandwidth(
    account: dict[str, Any],
    comm: Mapping[str, Any] | None,
    window_steps: int,
) -> dict[str, Any]:
    """Stamp achieved bytes/sec onto the account's per-collective rows.

    ``comm`` is obs/gauges.py's static per-step byte account
    (``collective_traffic``: per-op dicts with gradient/activation
    bytes).  bytes moved = per-step bytes × window steps; achieved
    bandwidth = bytes moved / the op's WALL time (``wall_ms``, the
    cross-lane interval union) — dividing by the lane-summed ``time_ms``
    would understate bandwidth by the local-device count on any
    multi-device host.  The byte account is already per-device tensor
    bytes, so the quotient is the per-device achieved rate.  Mutates and
    returns ``account`` — shared by the runtime emission (TrainerObs)
    and the offline report, so the two cannot disagree on the
    arithmetic."""
    if not comm or window_steps <= 0:
        return account
    for op, slot in account.get("collectives", {}).items():
        per_step = comm.get(op)
        if not isinstance(per_step, Mapping):
            continue
        step_bytes = int(per_step.get("gradient_bytes", 0)) + int(
            per_step.get("activation_bytes", 0)
        )
        slot["bytes_per_step"] = step_bytes
        wall_s = float(slot.get("wall_ms", slot.get("time_ms", 0.0)) or 0.0) / 1e3
        if step_bytes > 0 and wall_s > 0:
            slot["achieved_bytes_per_sec"] = round(
                step_bytes * window_steps / wall_s, 1
            )
    return account


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llms_example_tpu.obs.devprof",
        description=__doc__,
    )
    p.add_argument("trace_dir", help="a profile capture dir (obs/profile.py)")
    p.add_argument(
        "--hlo-text", default="",
        help="compiled HLO text file: builds the instruction→bucket index "
             "so CPU-trace events attribute to module buckets",
    )
    args = p.parse_args(argv)
    op_buckets = None
    if args.hlo_text:
        with open(args.hlo_text) as f:
            op_buckets = op_bucket_index(f.read())
    acct = device_account_from_dir(args.trace_dir, op_buckets=op_buckets)
    if acct is None:
        print(f"no device op events under {args.trace_dir}", file=sys.stderr)
        return 2
    print(json.dumps(acct))
    return 0


if __name__ == "__main__":
    sys.exit(main())
