"""On-demand ``jax.profiler`` capture for the train loop.

Two triggers, one controller:

- **Config window** — ``--profile-steps`` accepts the legacy count form
  (``3``: trace 3 steps starting 2 after the run's first step, skipping
  the compile step) or an absolute inclusive window (``100:105``: trace
  exactly those global steps, e.g. the steps right before a known OOM).
  Requires ``--profile-dir`` in count form; window form defaults the
  trace dir under the output dir.
- **Trigger file** — an operator touches
  ``<output_dir>/obs/profile.trigger`` on any host and the NEXT step
  starts a trace there (file contents = step count, default 3).  Polled
  once per step: one ``os.path.exists`` on the host, nothing on the
  device.  The file is consumed (removed) when the capture starts so a
  shared filesystem does not re-trigger every host forever.

Traces land under
``<trace_dir>/proc{process_index:03d}-s{start:06d}-{stop:06d}-{wallclock}``
— every process captures its own host's view (jax.profiler traces are
process-local), the index keeps a shared output dir collision-free, and
the step window + wall clock in the name let obs/report.py and
obs/devprof.py locate a specific capture without globbing timestamps out
of jax's internal session layout.  Each landed capture additionally
announces itself with a ``profile_captured`` event (path + step window),
and an ``on_capture`` hook hands the capture to the device-time
attribution (obs/devprof.py via TrainerObs) so a ``device_account``
rides the same window.

The stop path syncs on the step's loss before ``stop_trace`` so the
traced window contains completed steps — the one deliberate device sync,
and it only ever happens on the window's closing step.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable

from distributed_llms_example_tpu.obs import sink as sink_mod

DEFAULT_TRIGGER_STEPS = 3


def parse_profile_steps(spec: Any) -> tuple[int, int] | int | None:
    """``"a:b"`` → absolute inclusive window (a, b); ``"n"``/``n`` → the
    legacy relative count; 0/""/None → off."""
    if spec is None or spec == "":
        return None
    if isinstance(spec, int):
        return spec if spec > 0 else None
    s = str(spec).strip()
    if ":" in s:
        a, _, b = s.partition(":")
        start, stop = int(a), int(b)
        if stop < start or start < 1:
            raise ValueError(
                f"--profile-steps window {spec!r} must be start:stop with "
                "1 <= start <= stop"
            )
        return (start, stop)
    n = int(s)
    return n if n > 0 else None


class ProfileController:
    """Owns profiler state for one training run."""

    def __init__(
        self,
        *,
        profile_dir: str = "",
        steps_spec: Any = 0,
        trigger_path: str = "",
        start_step: int = 0,
        output_dir: str = "",
    ):
        spec = parse_profile_steps(steps_spec)
        self.trigger_path = trigger_path
        self.window: tuple[int, int] | None = None
        self.profile_dir = profile_dir
        if isinstance(spec, tuple):
            self.window = spec
        elif isinstance(spec, int) and profile_dir:
            # legacy: skip the first (compiled) step so the trace holds
            # steady-state steps; window is inclusive
            first = start_step + 2
            self.window = (first, first + spec - 1)
        if not self.profile_dir and output_dir:
            # window/trigger captures without an explicit --profile-dir
            # land under the output dir
            self.profile_dir = os.path.join(output_dir, "obs", "profile")
        self.active = False
        self._stop_step = 0
        self._start_step = 0
        self._trace_dir = ""
        # called as on_capture(trace_dir, (start, stop), truncated) after
        # each landed capture — TrainerObs hangs the device-account parse
        # here.  On truncated stops the window is clamped to the last
        # completed step.
        self.on_capture: Callable[[str, tuple[int, int], bool], None] | None = None

    # -- loop hooks ------------------------------------------------------

    def before_step(self, next_step: int) -> None:
        """Called before dispatching ``next_step``: open the trace when
        the configured window begins here, or when the trigger file
        appeared since the last step."""
        if self.active:
            return
        # range, not equality: a run that resumes INSIDE the window (the
        # preempt-at-102-of-100:105 case) still captures the remainder
        if self.window and self.window[0] <= next_step <= self.window[1]:
            self._start(next_step, self.window[1])
            return
        if self.trigger_path and os.path.exists(self.trigger_path):
            steps = DEFAULT_TRIGGER_STEPS
            try:
                with open(self.trigger_path) as f:
                    text = f.read().strip()
                if text:
                    steps = max(1, int(text))
            except (OSError, ValueError):
                pass
            try:  # consume so a shared FS doesn't re-trigger forever
                os.remove(self.trigger_path)
            except OSError:
                pass
            self._start(next_step, next_step + steps - 1)

    def after_step(self, step: int, sync_leaf: Any = None) -> None:
        if self.active and step >= self._stop_step:
            self._stop(sync_leaf, truncated=False)

    def finalize(self, sync_leaf: Any = None, last_step: int | None = None) -> None:
        """Training ended inside an open window: flush the (short) trace
        rather than losing it.  ``last_step`` (the run's final completed
        step) clamps the reported window so downstream per-step
        arithmetic — the bandwidth join multiplies bytes/step by window
        steps — is not inflated by steps that never ran."""
        if self.active:
            self._stop(sync_leaf, truncated=True, last_step=last_step)

    # -- internals -------------------------------------------------------

    def _start(self, start_step: int, stop_step: int) -> None:
        import jax

        # step window + wall clock in the dir name: a run that captures
        # twice (trigger, then --profile-on-anomaly) writes two
        # self-describing dirs, and the profile_captured event's path is
        # enough to find THIS capture's files without globbing
        self._trace_dir = os.path.join(
            self.profile_dir or ".",
            f"proc{jax.process_index():03d}"
            f"-s{start_step:06d}-{stop_step:06d}"
            f"-{time.strftime('%Y%m%d-%H%M%S')}",
        )
        os.makedirs(self._trace_dir, exist_ok=True)
        jax.profiler.start_trace(self._trace_dir)
        self.active = True
        self._start_step = start_step
        self._stop_step = stop_step

    def _stop(
        self, sync_leaf: Any, *, truncated: bool, last_step: int | None = None
    ) -> None:
        import jax

        if sync_leaf is not None:
            jax.block_until_ready(sync_leaf)
        jax.profiler.stop_trace()
        self.active = False
        record = {"event": "profile_trace", "dir": self.profile_dir or self._trace_dir}
        if truncated:
            record["truncated"] = True
        elif self.window and self._stop_step == self.window[1]:
            record["steps"] = self.window[1] - self.window[0] + 1
        else:
            record["trace_dir"] = self._trace_dir
        # every capturing process announces its own trace (all_processes:
        # a trigger may fire on one non-zero host only)
        sink_mod.emit(record, all_processes=True)
        # a truncated capture's REAL window ends at the last completed
        # step, not the scheduled stop — report the honest step count or
        # every per-step consumer (achieved bytes/sec = bytes/step ×
        # steps / time) overstates
        stop = self._stop_step
        if truncated and last_step is not None:
            stop = max(self._start_step, min(stop, int(last_step)))
        window = (self._start_step, stop)
        captured: dict[str, Any] = {
            "event": "profile_captured",
            "path": self._trace_dir,
            "window": [int(window[0]), int(window[1])],
            "steps": int(window[1] - window[0] + 1),
        }
        if truncated:
            captured["truncated"] = True
        sink_mod.emit(captured, all_processes=True)
        if self.on_capture is not None:
            self.on_capture(self._trace_dir, window, truncated)
