"""Multi-host liveness / step-skew heartbeat.

Synchronous SPMD fails silently-by-hanging: when one host stalls, every
other host blocks inside the next collective with no diagnostic.  The
heartbeat gets ahead of that: at a coarse step cadence every process
contributes ``(step, wall-clock arrival)`` to a tiny device all-gather
(the psum-family probe ``multihost_utils.process_allgather`` lowers to —
a few dozen bytes over the same interconnect training uses, so a
heartbeat that completes IS a liveness proof for the collective fabric),
and process 0 publishes the spread:

- ``skew_steps``   max−min step counter across processes.  Nonzero means
                   a host is running a different loop (crash-restarted,
                   wrong resume step) — the config-drift failure mode.
- ``arrival_spread_s``  latest−earliest wall-clock arrival at the probe.
                   The gather is a barrier, so the spread is exactly how
                   long fast hosts waited for the straggler since the
                   last synchronization point.
- ``laggards``     process indices that arrived ``laggard_threshold_s``
                   after the earliest — the hosts to go look at before
                   the next collective hangs for real.

The probe must be called at the SAME global step by every process (the
trainer calls it on its deterministic step cadence, the same guarantee
the preemption agreement uses) — a conditional heartbeat on one host
would itself deadlock the pod.

Wall clocks ride as int32 (seconds, microseconds) because x64 is off by
default and ~1.7e9 epoch-seconds in f32 quantizes to ~100 s; cross-host
comparability is then bounded by NTP skew, which is plenty for "which
host is seconds behind".
"""

from __future__ import annotations

import time

import numpy as np

from distributed_llms_example_tpu.obs import sink as sink_mod

DEFAULT_LAGGARD_THRESHOLD_S = 5.0


def gather_probe(local: "np.ndarray") -> "np.ndarray":
    """THE heartbeat allgather channel: every process contributes one
    small int32 vector, every process receives the (P, n) stack.  MUST be
    called by all processes at the same global step (same contract as
    ``Heartbeat.beat``).  The health watchdog's multi-host anomaly
    agreement rides this same channel at the logging cadence.
    Single-process: no collective, just the local row."""
    import jax

    local = np.asarray(local, dtype=np.int32)
    if jax.process_count() == 1:
        return local[None, :]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(local))


def detect_laggards(
    steps: "np.ndarray",
    arrivals_s: "np.ndarray",
    *,
    laggard_threshold_s: float = DEFAULT_LAGGARD_THRESHOLD_S,
) -> dict:
    """Pure skew analysis over per-process ``(step, arrival time)``
    vectors — unit-testable without a multi-process rendezvous."""
    steps = np.asarray(steps)
    arrivals_s = np.asarray(arrivals_s, dtype=np.float64)
    earliest = float(arrivals_s.min())
    spread = float(arrivals_s.max() - earliest)
    laggards = [
        int(i)
        for i in range(len(arrivals_s))
        if float(arrivals_s[i] - earliest) > laggard_threshold_s
    ]
    return {
        "min_step": int(steps.min()),
        "max_step": int(steps.max()),
        "skew_steps": int(steps.max() - steps.min()),
        "arrival_spread_s": round(spread, 3),
        "laggards": laggards,
    }


class Heartbeat:
    def __init__(
        self,
        every_steps: int,
        *,
        laggard_threshold_s: float = DEFAULT_LAGGARD_THRESHOLD_S,
        suspect_beats: int = 3,
    ):
        from distributed_llms_example_tpu.obs.health import LaggardStreaks

        self.every = max(1, int(every_steps))
        self.laggard_threshold_s = float(laggard_threshold_s)
        # persistent-laggard classification (obs/health.py): a rank
        # named laggard ``suspect_beats`` heartbeats in a row becomes a
        # pod-agreed host_loss_suspect event — organic host-loss
        # DETECTION, report row only (--on-host-loss policy unchanged).
        # 0 = off, the same convention as the heartbeat cadence itself.
        self.streaks = (
            LaggardStreaks(suspect_beats=suspect_beats)
            if int(suspect_beats) > 0
            else None
        )

    def beat(self, step: int) -> dict | None:
        """Contribute this process's probe and, on process 0, emit the
        heartbeat record.  MUST be called by every process at the same
        global step.  Returns the record on process 0 (None elsewhere).

        Every rank folds the SAME gathered probe into the laggard-streak
        classifier (the gather is a barrier returning identical data
        everywhere — agreement without a second collective), so a
        persistent laggard becomes a pod-agreed ``host_loss_suspect``
        event in every rank's local stream."""
        import jax

        t = time.time()
        local = np.asarray(
            [int(step), int(t), int((t % 1.0) * 1e6)], dtype=np.int32
        )
        gathered = gather_probe(local)
        steps = gathered[:, 0]
        arrivals = gathered[:, 1].astype(np.float64) + gathered[:, 2] / 1e6
        analysis = detect_laggards(
            steps, arrivals, laggard_threshold_s=self.laggard_threshold_s
        )
        if self.streaks is not None:
            for suspect in self.streaks.update(analysis["laggards"], step):
                # local: each rank's file carries the agreed verdict (the
                # suspect's own file may be the last thing it ever
                # writes); stdout stays process-0-only via the sink gate
                sink_mod.emit(suspect, local=True)
        if jax.process_index() != 0:
            return None
        record = {
            "event": "heartbeat",
            "step": int(step),
            "process_count": int(gathered.shape[0]),
            **analysis,
        }
        sink_mod.emit(record)
        return record
