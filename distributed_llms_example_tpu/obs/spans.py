"""Host-side span tracing for the train loop.

Monotonic-clock spans (``data_wait``, ``step_dispatch``, ``device_sync``,
``eval``, ``checkpoint``, nested freely) plus a per-step ring buffer from
which each logging window reports step-time percentiles (p50/p95/max) and
a straggler flag.  Everything is ``time.perf_counter`` arithmetic on the
host — recording a span costs two clock reads and a dict update, and
NOTHING here touches a device, so instrumented non-logging steps keep the
zero-sync async-dispatch property MetricLogger already guarantees.

The clock is injectable so tests drive the recorder deterministically.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Sequence

# step-time max > STRAGGLER_FACTOR × p50 within a window flags the window:
# on a healthy synchronous-SPMD step the distribution is tight, and a fat
# max means some host stalled (GC, page cache, a slow storage read) — the
# local precursor of the cross-host skew the heartbeat watches for.
STRAGGLER_FACTOR = 2.0


def percentiles(values: Sequence[float], qs: Sequence[float]) -> list[float]:
    """Nearest-rank percentiles of ``values`` (no numpy: callers live on
    the trainer hot path's cadence and in bench post-processing)."""
    if not values:
        return [0.0 for _ in qs]
    s = sorted(values)
    out = []
    for q in qs:
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        out.append(s[idx])
    return out


class SpanRecorder:
    """Ring-buffered span/step-time recorder with window summaries.

    ``span(name)`` times a (possibly nested) region; ``step_complete()``
    closes one loop iteration and records its wall duration in the ring.
    ``summary()`` reports the window since the previous summary —
    per-step percentiles plus per-span aggregates — and resets the window
    (the ring keeps ``ring_size`` steps for end-of-run retrospectives).
    """

    def __init__(
        self,
        ring_size: int = 512,
        clock: Callable[[], float] = time.perf_counter,
        straggler_factor: float = STRAGGLER_FACTOR,
    ):
        self.ring_size = int(ring_size)
        self.clock = clock
        self.straggler_factor = float(straggler_factor)
        self._ring: list[float] = []  # per-step wall seconds, newest last
        self._depth = 0
        self._window_spans: dict[str, list[float]] = {}  # name → [total_s, count, max_s]
        self._window_steps = 0
        self._window_t0 = clock()
        self._step_t0: float | None = None
        # per-step breakdown for the budget layer (obs/budget.py): the
        # OUTERMOST spans closed since the step's anchor, keyed by name —
        # a partition of the step's ring duration (nested spans would
        # double-count, so only depth-0 exits land here)
        self._step_spans: dict[str, float] = {}
        self._step_records: list[dict] = []  # rings with _ring
        # optional span-instance listener (obs/trace.py TraceCollector):
        # called with (name, t0, dur) on every OUTERMOST span exit — a
        # None check per span, nothing else, so the zero-cost-when-off
        # property of the recorder is untouched
        self.listener = None

    # -- recording -------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str):
        self._depth += 1
        t0 = self.clock()
        try:
            yield
        finally:
            dt = self.clock() - t0
            self._depth -= 1
            agg = self._window_spans.get(name)
            if agg is None:
                self._window_spans[name] = [dt, 1, dt]
            else:
                agg[0] += dt
                agg[1] += 1
                if dt > agg[2]:
                    agg[2] = dt
            if self._depth == 0:
                self._step_spans[name] = self._step_spans.get(name, 0.0) + dt
                if self.listener is not None:
                    self.listener.on_span(name, t0, dt)

    def step_complete(self) -> None:
        """One train-loop iteration finished: record its wall duration
        (time since the previous ``step_complete`` / window start)."""
        now = self.clock()
        t0 = self._step_t0 if self._step_t0 is not None else self._window_t0
        dur = now - t0
        self._ring.append(dur)
        self._step_records.append({"dur": dur, "spans": self._step_spans})
        self._step_spans = {}
        if len(self._ring) > self.ring_size:
            del self._ring[: len(self._ring) - self.ring_size]
            del self._step_records[: len(self._step_records) - self.ring_size]
        self._step_t0 = now
        self._window_steps += 1

    def mark_step_start(self) -> None:
        """Re-anchor the per-step clock.  The trainer calls this after
        cadenced non-step work (checkpoint save, eval) so that wall time
        — already tracked under its own span — is not also charged to
        the NEXT step's ring-buffer duration (which would fire the
        straggler flag on every healthy eval cadence).  The per-step span
        breakdown is re-anchored with it: a span recorded between the
        boundary and here (checkpoint/eval) is excluded from the next
        step's duration, so charging it to that step's budget would break
        the partition the budget account sums over."""
        self._step_t0 = self.clock()
        self._step_spans = {}

    # -- reporting -------------------------------------------------------

    def window_step_times(self) -> list[float]:
        if self._window_steps == 0:
            return []
        return self._ring[-min(self._window_steps, len(self._ring)):]

    def window_step_records(self) -> list[dict]:
        """The current window's per-step ``{"dur": s, "spans": {name: s}}``
        records (the budget account's raw material).  Read BEFORE
        ``summary()`` — which resets the window counter this slices by."""
        if self._window_steps == 0:
            return []
        n = min(self._window_steps, len(self._step_records))
        return self._step_records[-n:]

    def summary(self) -> dict | None:
        """Close the window: step-time percentiles + span aggregates.
        None when no step completed since the last summary (telemetry
        cadence fired before any work — nothing to report)."""
        times = self.window_step_times()
        if not times:
            return None
        now = self.clock()
        p50, p95 = percentiles(times, (0.50, 0.95))
        mx = max(times)
        out = {
            "window_steps": self._window_steps,
            "window_seconds": round(now - self._window_t0, 6),
            "step_ms_p50": round(p50 * 1e3, 3),
            "step_ms_p95": round(p95 * 1e3, 3),
            "step_ms_max": round(mx * 1e3, 3),
            "straggler": bool(p50 > 0 and mx > self.straggler_factor * p50),
            "spans": {
                name: {
                    "total_ms": round(total * 1e3, 3),
                    "count": count,
                    "max_ms": round(peak * 1e3, 3),
                }
                for name, (total, count, peak) in sorted(self._window_spans.items())
            },
        }
        self._window_spans = {}
        self._window_steps = 0
        self._window_t0 = now
        return out
