"""Layered telemetry for the training system.

The reference's observability contract is ONE channel: a JSON line per
metric window printed to stdout, parsed by Valohai as execution metadata
(utils/jsonlog.py).  That is enough to watch a loss curve and nothing
else — pjit-at-scale training reports (PAPERS.md: arxiv 2204.06514) treat
MFU and per-step comm/compute breakdowns as the primary tuning signal,
and weight-update-sharding work (arxiv 2004.13336) shows gradient-traffic
accounting is what separates a correctly sharded step from a 2× overweight
one.  This package supplies those signals in four layers:

- ``spans``     host-side monotonic-clock span tracing (data_wait /
                step_dispatch / device_sync / eval / checkpoint) with a
                ring buffer and per-window step-time percentiles; zero
                device syncs off the logging cadence
- ``gauges``    derived device gauges: MFU from the AOT-compiled train
                step's HLO cost analysis (the shared compile recipe in
                utils/memory_audit.py), live HBM via ``memory_stats()``,
                and a static per-step collective-traffic account scanned
                from the same HLO the IR lint parses
- ``profile``   on-demand ``jax.profiler`` capture for a step window
                (``--profile-steps 100:105``), a trigger file polled at
                step cadence, or an agreed anomaly
                (``--profile-on-anomaly``); captures land in
                step-window-stamped dirs and announce themselves with
                ``profile_captured`` events
- ``devprof``   device-time attribution: the jax-free trace parser that
                reduces a landed capture into the ``device_account`` —
                per-module-bucket device time (op_name scopes through
                the same table as the health param buckets), achieved
                bytes/sec per collective, compute↔comm overlap
- ``heartbeat`` multi-host liveness/step-skew probe so process 0 reports
                laggards before a collective hangs silently
- ``health``    the training-signal watchdog: consumes the in-graph
                numerics (train/step.py ``health_metrics``) at the log
                cadence — NaN/Inf tripwire, EWMA loss-spike, grad-norm
                explosion — with multi-host agreement over the heartbeat
                allgather channel and a ``warn``/``halt``/``checkpoint``
                policy
- ``recorder``  the flight recorder: a bounded ring of the last N steps'
                metrics + batch fingerprints, dumped as a schema-stamped
                bundle on anomaly / SIGTERM / crash
- ``budget``    step-time budget accounting: each window's wall time
                decomposed into data_wait / dispatch / device_busy /
                sync_block / host_overhead (additive, test-pinned), a
                ``dispatch_efficiency`` gauge, and the runtime tripwire
                for host-blocking transfers off the log cadence
- ``trace``     span-instance capture + the Chrome-trace/Perfetto
                exporter merging every rank's spans, budget gauges and
                serving request lifecycles onto one timeline
- ``report``    the offline consumer: merges the per-process JSONL into
                a cross-host step timeline (``python -m
                distributed_llms_example_tpu.obs.report <output_dir>``;
                ``--trace out.json`` exports the merged Perfetto trace)

Everything funnels through ``sink`` (stdout Valohai channel + optional
JSONL file, same schema).  ``TrainerObs`` below is the one object the
Trainer holds — it owns the wiring so the train loop stays readable.
"""

from __future__ import annotations

import math
import os
from typing import Any, Iterable, Iterator

from distributed_llms_example_tpu.obs import health as health_mod
from distributed_llms_example_tpu.obs import profile as profile_mod
from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.memprof import MemoryMonitor
from distributed_llms_example_tpu.obs.budget import BudgetAccountant, budget_enabled
from distributed_llms_example_tpu.obs.health import HealthWatchdog, health_enabled
from distributed_llms_example_tpu.obs.heartbeat import Heartbeat
from distributed_llms_example_tpu.obs.profile import ProfileController
from distributed_llms_example_tpu.obs.recorder import FlightRecorder, batch_fingerprint
from distributed_llms_example_tpu.obs.sink import build_sink, install_sink
from distributed_llms_example_tpu.obs.spans import SpanRecorder

__all__ = [
    "TrainerObs",
    "HealthWatchdog",
    "FlightRecorder",
    "batch_fingerprint",
    "health_enabled",
    "budget_enabled",
]


class TrainerObs:
    """The Trainer's telemetry bundle.

    Owns the sink, the span recorder, the (optional) static gauges, the
    heartbeat, and the profiler controller.  Everything here is host-side
    bookkeeping except: the startup gauge compile (one AOT compile of the
    train step, gated by ``obs_gauges``), the heartbeat's cadenced
    cross-process gather, and the profiler's start/stop syncs — none of
    which ever lands on a non-cadence step.
    """

    def __init__(self, cfg: Any, *, start_step: int = 0, manage_sink: bool = True):
        self.cfg = cfg
        self.enabled = getattr(cfg, "obs", "stdout") != "off"
        if manage_sink:
            # standalone use (tests, tools); the Trainer installs its sink
            # itself — before its first device_report line — and passes
            # manage_sink=False so the file channel is opened exactly once
            install_sink(build_sink(getattr(cfg, "obs", "stdout"), cfg.output_dir))
        self.spans = SpanRecorder()
        self.every = max(1, int(cfg.log_every_steps))
        self.flops_per_step: float | None = None
        self.peak_flops_per_chip = float(
            getattr(cfg, "obs_peak_tflops", 197.0)
        ) * 1e12
        hb_every = int(getattr(cfg, "obs_heartbeat_steps", 0) or 0)
        self.heartbeat = Heartbeat(
            every_steps=hb_every,
            # 0 = classification off (the knob's own convention); only a
            # MISSING config field falls back to the default of 3
            suspect_beats=int(
                getattr(cfg, "obs_heartbeat_suspect_beats", 3)
            ),
        ) if (
            self.enabled and hb_every > 0
        ) else None
        # training-health layer: the watchdog consumes the in-graph
        # numerics at the log cadence; the recorder rings every step
        self.health_on = health_enabled(cfg)
        self.on_anomaly = getattr(cfg, "on_anomaly", "warn")
        self.watchdog = (
            HealthWatchdog(
                loss_spike_factor=float(getattr(cfg, "health_loss_spike_factor", 4.0)),
                grad_norm_factor=float(getattr(cfg, "health_grad_norm_factor", 10.0)),
                warmup_steps=int(getattr(cfg, "health_warmup_steps", 20)),
            )
            if self.health_on
            else None
        )
        # gated on obs OR health: --obs off --health on --on-anomaly
        # checkpoint still promises a bundle with the checkpoint
        rec_steps = int(getattr(cfg, "recorder_steps", 0) or 0)
        self.recorder = (
            FlightRecorder(rec_steps)
            if (rec_steps > 0 and (self.enabled or self.health_on))
            else None
        )
        self._pending_health: list[tuple[int, dict]] = []
        self._last_health: dict[str, Any] | None = None
        # the last agreed obs_anomaly record (pod-consistent fields:
        # step/code/policy) — what the rewind recovery path consumes when
        # on_step returns its action
        self.last_anomaly: dict[str, Any] | None = None
        self._trigger = getattr(cfg, "profile_trigger", "") or (
            os.path.join(cfg.output_dir, "obs", "profile.trigger")
            if self.enabled
            else ""
        )
        # device-time attribution (obs/devprof.py) inputs, filled by
        # startup_gauges: the instruction→bucket index of the compiled
        # step and the static per-step collective byte account
        self._op_buckets: dict[str, str] | None = None
        self._comm_account: dict | None = None
        # the HBM account + watermark telemetry (obs/memprof.py): samples
        # memory_window events at the log cadence and holds the last
        # static account for the OOM postmortem bundle
        self.memory = MemoryMonitor() if self.enabled else None
        # --profile-on-anomaly: an agreed anomaly arms the profiler's own
        # trigger file, so the NEXT steps are captured and the post-mortem
        # carries a device timeline next to the flight recorder
        self.profile_on_anomaly = bool(getattr(cfg, "profile_on_anomaly", False))
        self.profiler = self._build_profiler(start_step)
        # step-time budget layer (obs/budget.py): host-clock arithmetic
        # over the span recorder's per-step records, closed at the log
        # cadence into a step_budget event; its ONE device interaction is
        # the cadenced queue-drain probe (budget_probe below)
        self.budget = None
        if budget_enabled(cfg):
            import jax

            self.budget = BudgetAccountant(
                self.spans,
                # multi-device CPU dispatch runs the program inline: a
                # blocked dispatch is that backend's normal mode, not a
                # stray transfer — the tripwire verdict stands down there
                async_dispatch=jax.default_backend() != "cpu",
            )
        # trace capture (obs/trace.py): individual span instances for the
        # Perfetto export.  File-channel material (bulk records), so only
        # worth collecting when a JSONL channel exists to receive them.
        self.trace = None
        if self.budget is not None and getattr(cfg, "obs", "") == "jsonl":
            # imported here (not at module top) so `python -m ...obs.trace`
            # runs the exporter without a double-import warning
            from distributed_llms_example_tpu.obs.trace import TraceCollector

            self.trace = TraceCollector()
            self.spans.listener = self.trace

    def _build_profiler(self, start_step: int) -> ProfileController:
        ctl = ProfileController(
            profile_dir=self.cfg.profile_dir,
            steps_spec=self.cfg.profile_steps,
            trigger_path=self._trigger,
            start_step=start_step,
            output_dir=self.cfg.output_dir,
        )
        ctl.on_capture = self._on_profile_captured
        return ctl

    def set_start_step(self, start_step: int) -> None:
        """Re-anchor the legacy relative profile window once the Trainer
        knows its resume step (checkpoint restore happens after obs
        construction)."""
        self.profiler = self._build_profiler(start_step)

    # -- startup ---------------------------------------------------------

    def startup_gauges(self, mesh: Any, *, tgt_cap: int) -> None:
        """AOT-compile the train step via the shared recipe
        (utils/memory_audit.py) and emit the static gauges: per-step HLO
        FLOPs (the MFU numerator) and the collective-traffic account.
        One extra compile at startup — on TPU with the persistent
        compilation cache it is a disk hit for any program the run will
        compile anyway."""
        cfg = self.cfg
        mode = getattr(cfg, "obs_gauges", "auto")
        want = mode == "on" or (mode == "auto" and getattr(cfg, "obs", "") == "jsonl")
        if not (self.enabled and want):
            return
        from distributed_llms_example_tpu.obs import gauges

        try:
            with self.spans.span("obs_gauge_compile"):
                report = gauges.train_step_static_gauges(
                    cfg.model_ckpt,
                    mesh,
                    global_batch=cfg.batch_size,
                    src_len=cfg.max_source_length,
                    tgt_len=tgt_cap,
                    dtype=cfg.compute_dtype,
                    remat=cfg.remat,
                    remat_policy=cfg.remat_policy,
                    grad_accum_steps=cfg.grad_accum_steps,
                    grad_compression=getattr(cfg, "grad_compression", ""),
                    hbm_budget_gib=float(getattr(cfg, "hbm_budget_gib", 16.0)),
                )
        except Exception as e:  # never fail training for telemetry
            sink_mod.emit({
                "event": "obs_gauges_skipped",
                "reason": str(e)[:300],
            })
            return
        self.flops_per_step = report["flops_per_step"]
        # devprof inputs stay in-process: the instruction→bucket index is
        # thousands of entries (no place on a metric line) and the byte
        # account is re-read from the emitted record at report time
        self._op_buckets = report.pop("op_bucket_index", None)
        self._comm_account = report.get("comm")
        # the bucketed HBM account gets its OWN event (the report's
        # "Where did the bytes go" table reads it from the JSONL alone)
        # and seeds the monitor so an OOM postmortem carries it
        account = report.pop("memory_account", None)
        if account is not None:
            if self.memory is not None:
                self.memory.attach_account(account)
            sink_mod.emit({"event": "memory_account", **account})
        sink_mod.emit({
            "event": "obs_gauges",
            "peak_flops_per_chip": self.peak_flops_per_chip,
            **report,
        })

    # -- the step loop ---------------------------------------------------

    def wrap_batches(self, batches: Iterable[dict]) -> Iterator[dict]:
        """Time host-batch availability as ``data_wait`` spans — the time
        the device loop spends blocked on tokenize/pad/bucket (or on the
        prefetcher when it cannot keep up)."""
        it = iter(batches)
        while True:
            with self.spans.span("data_wait"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
            yield batch

    def step_span(self):
        return self.spans.span("step_dispatch")

    def sync_span(self):
        return self.spans.span("device_sync")

    def host_span(self):
        """Host bookkeeping riding the step's wall (batch fingerprinting,
        metric/recorder prep) — the budget account's ``host_overhead``."""
        return self.spans.span("host_overhead")

    def budget_probe(self, step: int, sync_leaf: Any) -> None:
        """The budget layer's cadenced device timing: at the log cadence
        ONLY, time the queue drain on the step output BEFORE the metric
        logger's own fetch (so the logger's conversion lands on an idle
        device and the measured block is the genuine un-overlapped device
        tail).  Off-cadence steps return after two comparisons — zero
        device syncs, the invariant the counting-leaf test pins."""
        if self.budget is None or sync_leaf is None or step % self.every != 0:
            return
        self.budget.probe(sync_leaf)

    def optimizer_probe(self, step: int, fn_factory: Any) -> None:
        """The budget layer's cadenced optimizer-apply wall sample: at
        the log cadence ONLY (after the window closed — the trainer's
        ``mark_step_start`` excludes the probe's wall from the step-time
        partition like checkpoint/eval), run one stand-alone jitted
        optimizer apply and time it (``optimizer_apply_ms`` on the next
        ``step_budget`` account).  Off-cadence this is two comparisons
        and returns — zero device syncs."""
        if self.budget is None or step % self.every != 0:
            return
        self.budget.probe_optimizer(fn_factory)

    def _on_profile_captured(
        self, trace_dir: str, window: tuple[int, int], truncated: bool = False
    ) -> None:
        """A profile window landed: parse the capture into the device
        account (obs/devprof.py — host-side file IO on the capture's
        closing step only) and emit it through the budget layer.  A GAUGE,
        never load-bearing: any parse failure logs one event and the run
        continues.  Truncated captures carry the clamped (honest) window
        and a ``truncated`` stamp."""
        if self.budget is None:
            return
        try:
            from distributed_llms_example_tpu.obs.devprof import (
                device_account_from_dir,
                join_collective_bandwidth,
            )

            acct = device_account_from_dir(trace_dir, op_buckets=self._op_buckets)
            if acct is None:
                sink_mod.emit({
                    "event": "device_account_skipped",
                    "reason": f"no device op events under {trace_dir}",
                }, local=True)
                return
            steps = int(window[1] - window[0] + 1)
            acct["step"] = int(window[1])
            acct["window"] = [int(window[0]), int(window[1])]
            acct["window_steps"] = steps
            if truncated:
                acct["truncated"] = True
            join_collective_bandwidth(acct, self._comm_account, steps)
            self.budget.attach_device_account(acct)
        except Exception as e:  # noqa: BLE001 — telemetry must not kill the run
            sink_mod.emit({
                "event": "device_account_skipped",
                "reason": str(e)[:300],
            }, local=True)

    def eval_span(self):
        return self.spans.span("eval")

    def checkpoint_span(self):
        return self.spans.span("checkpoint")

    def on_step(
        self,
        step: int,
        epoch: int,
        metrics: dict,
        fingerprint: dict | None = None,
    ) -> str:
        """Per-step bookkeeping: host clocks only (pointer appends for the
        recorder/health pending list), except the profiler's stop sync
        (cadenced), the heartbeat gather (cadenced), and the health
        window's one device_get (cadenced).  Returns the anomaly policy
        action for the train loop: "ok" / "warn" / "halt" / "checkpoint".
        """
        self.profiler.after_step(step, metrics.get("loss"))
        self.spans.step_complete()
        if self.trace is not None:
            # the step-boundary mark the cross-host trace merge aligns on
            self.trace.note_step(step)
        if self.recorder is not None:
            self.recorder.record(step, epoch, metrics, fingerprint)
        if self.watchdog is not None:
            self._pending_health.append((step, dict(metrics)))
        if self.heartbeat is not None and step % self.heartbeat.every == 0:
            self.heartbeat.beat(step)
        action = "ok"
        if step % self.every == 0:
            # budget first: it reads the window's per-step records, which
            # emit_window's summary() resets
            if self.budget is not None:
                self.budget.close_window(step, epoch)
            if self.trace is not None:
                self.trace.flush(step)
            if self.watchdog is not None:
                action = self._health_cadence(step)
            if self.enabled:
                self.emit_window(step, epoch)
            elif self.budget is not None:
                # --obs off --obs-budget on: emit_window won't run, so
                # consume the window here — otherwise every later account
                # re-reads (and re-counts) the same ever-growing records
                self.spans.summary()
        return action

    def _health_cadence(self, step: int) -> str:
        """The log-cadence health check: resolve the window's device
        scalars to host floats (ONE transfer — the same fetch the metric
        logger pays), run the detectors, agree across hosts, apply the
        policy.  Every process runs this at the same step, so the
        returned action is pod-consistent."""
        if not self._pending_health:
            return "ok"
        entries = health_mod.to_host(self._pending_health)
        self._pending_health = []
        if self.recorder is not None:
            for s, vals in entries:
                self.recorder.annotate(s, vals)
        last_step, last_vals = entries[-1]
        # non-finite values become strings: an anomalous window is exactly
        # when these are NaN, and a bare NaN literal is invalid JSON on
        # the stdout/JSONL channels (same convention as the recorder)
        self._last_health = {
            k: (float(f"{v:.6g}") if math.isfinite(v) else repr(v))
            for k, v in last_vals.items()
            if k in ("param_norm", "grad_norm", "nonfinite_count")
            or k.startswith("update_ratio_")
        }
        anomalies = self.watchdog.check(entries)
        event = health_mod.agree_and_emit(
            anomalies, step=step, policy=self.on_anomaly
        )
        if event is None:
            return "ok"
        self.last_anomaly = event
        if (
            self.profile_on_anomaly
            and self._trigger
            and not self.profiler.active
        ):
            # arm the profiler's OWN trigger-file machinery: the next
            # step opens a capture, so the post-mortem carries a device
            # timeline next to the flight-recorder bundle.  Every rank
            # writes the same path (the schedule is pod-agreed); the
            # controller consumes it exactly like an operator touch.
            try:
                os.makedirs(os.path.dirname(self._trigger), exist_ok=True)
                with open(self._trigger, "w") as f:
                    f.write(str(profile_mod.DEFAULT_TRIGGER_STEPS))
                sink_mod.emit({
                    "event": "profile_trigger_armed",
                    "step": step,
                    "reason": f"anomaly:{event['code']}",
                }, local=True)
            except OSError:
                pass  # a failed arm must not change the policy action
        if self.recorder is not None:
            self.recorder.dump(
                self.cfg.output_dir,
                reason=f"anomaly:{event['code']}",
                step=step,
                anomalies=anomalies,
            )
        # the last window must survive whatever the policy does next
        sink_mod.flush(fsync=True)
        return self.on_anomaly

    def emit_window(self, step: int, epoch: int | None = None) -> None:
        summary = self.spans.summary()
        if summary is None:
            return
        record: dict[str, Any] = {"event": "obs_window", "step": step}
        if epoch is not None:
            record["epoch"] = epoch
        record.update(summary)
        mfu = self.window_mfu(summary)
        if mfu is not None:
            # significant digits, not decimal places: a CPU-mesh MFU of
            # 2e-9 must not round to a flat 0.0
            record["mfu"] = float(f"{mfu:.4g}")
        if self._last_health is not None:
            record["health"] = self._last_health
        if self.memory is not None:
            # one cadenced memory_stats read: a memory_window event with
            # watermark-delta-since-last-window (or a single named skip on
            # backends that report nothing), plus the live summary inline
            hbm = self.memory.sample(step)
            if hbm is not None:
                record["hbm"] = {
                    k: hbm[k]
                    for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                }
        # local: every process's window lands in its OWN jsonl file (the
        # cross-host timeline obs/report.py merges); stdout stays p0-only
        sink_mod.emit(record, local=True)

    def window_mfu(self, summary: dict) -> float | None:
        """MFU over the just-closed window: compiled-step FLOPs × steps
        over wall seconds and aggregate peak FLOPs.  None until the
        startup gauge compile has supplied the numerator."""
        if not self.flops_per_step or not summary.get("window_seconds"):
            return None
        import jax

        from distributed_llms_example_tpu.obs.gauges import mfu

        return mfu(
            self.flops_per_step,
            summary["window_seconds"] / max(1, summary["window_steps"]),
            jax.device_count(),
            self.peak_flops_per_chip,
        )

    # -- shutdown --------------------------------------------------------

    def finalize(self, step: int, epoch: int | None = None, sync_leaf: Any = None) -> str:
        """End of run: close the profiler, run the health check over the
        final partial window (a NaN in the last steps must still fire),
        emit the final span window, and push the file channel to disk.
        Returns the final health action (informational — the loop is
        already over)."""
        self.profiler.finalize(sync_leaf, last_step=step)
        action = "ok"
        if self.budget is not None:
            # the final partial window's account (before summary resets it)
            self.budget.close_window(step, epoch)
        if self.trace is not None:
            self.trace.flush(step)
        if self.watchdog is not None and self._pending_health:
            action = self._health_cadence(step)
        if self.enabled:
            self.emit_window(step, epoch)
        elif self.budget is not None:
            self.spans.summary()  # consume the window the budget read
        sink_mod.flush(fsync=True)
        return action
