"""Deterministic fault injection — the chaos harness.

Detection (the anomaly watchdog), recovery (rewind-and-retry), and
integrity (checkpoint checksums) are only trustworthy if they can be
EXERCISED: a fault path nothing can trigger is a fault path nobody has
seen work.  This module injects the pod-scale fault classes on a fixed,
reproducible schedule so every recovery mechanism has a test switch:

    --chaos nan_grad@120,ckpt_corrupt@2,data_error@300,sigterm@240

Grammar: a comma list of ``kind@tick``.  Ticks are **global optimizer
steps** except for ``ckpt_corrupt``, whose tick is the **Nth checkpoint
save of the run** (corruption must hit a checkpoint regardless of how
the save cadence maps to steps).  Kinds:

- ``nan_grad@K``      poison one parameter element with NaN right before
                      step K dispatches (a lazy device-side op — the NaN
                      surfaces in the step's in-graph numerics, exactly
                      like a real numeric fault would)
- ``ckpt_corrupt@N``  flip bytes in the Nth checkpoint AFTER its
                      checksum manifest is finalized — the manifest
                      verification, not luck, must catch it
- ``data_error@K``    raise one transient ``OSError`` from the batch
                      fetch before step K — exercises the loader's
                      retry-with-backoff
- ``sigterm@K``       deliver SIGTERM to this process after step K —
                      exercises the graceful-preemption checkpoint path
- ``host_loss@K``     raise the agreed topology-change signal after step
                      K — exercises the elastic-recovery path (teardown,
                      ``jax.distributed`` re-init on the surviving
                      slice, resharding restore) the way ``sigterm``
                      rides the real preemption handler: the flag is
                      agreed over the same heartbeat-cadence allgather,
                      so every rank takes the topology branch together
- ``oom@K``           raise a RESOURCE_EXHAUSTED-shaped error before
                      step K dispatches — exercises the OOM tripwire
                      (obs/memprof.py): the trainer must land an atomic
                      ``memory-postmortem-p*.json`` bundle and re-raise,
                      never swallow

Serving kinds (the router tier, serving/router.py — ticks are **router
scheduler ticks**, the serving counterpart of optimizer steps; they fire
only under ``serve-router``, a training run never consults them):

- ``replica_crash@K`` kill the busiest engine replica at router tick K
                      (most active decode slots, ties to the lowest
                      replica id — deterministic): its step raises, the
                      router marks it dead and RE-PREFILLS every request
                      it held on a surviving replica
- ``replica_stall@K`` wedge the busiest replica at tick K: it stops
                      making progress without raising, so the router's
                      heartbeat-miss / step-stall detector (live →
                      suspect → dead) must catch it
- ``request_storm@K`` inject a burst of synthetic requests at tick K —
                      exercises admission control (shed/defer under
                      pool pressure) without letting the storm starve
                      real traffic

Every injection is **one-shot** (armed → fired): a rewind replaying the
same steps does not re-inject, so a recovered run stays recovered.  Each
firing is logged as a schema-stamped ``chaos_injection`` obs event, which
is how ``obs.report`` separates *injected* faults from *organic* ones
(``--strict`` fails only on the latter).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable

KINDS = (
    "nan_grad", "ckpt_corrupt", "data_error", "sigterm", "host_loss", "oom",
    "replica_crash", "replica_stall", "request_storm",
)
# the serving subset: ticks are router scheduler ticks, consumed only by
# serving/router.py (a training run leaves them armed and unfired)
SERVING_KINDS = ("replica_crash", "replica_stall", "request_storm")

GRAMMAR_HELP = (
    "expected a comma list of kind@tick with kind in "
    f"{'/'.join(KINDS)} and tick a positive integer "
    "(global step; for ckpt_corrupt the Nth checkpoint save; for the "
    "replica_*/request_storm serving kinds a router scheduler tick), "
    "e.g. 'nan_grad@120,ckpt_corrupt@2,sigterm@240' or "
    "'replica_crash@40,request_storm@10'"
)


@dataclasses.dataclass
class Injection:
    kind: str
    at: int  # global step, or save ordinal for ckpt_corrupt
    fired: bool = False


class ChaosSchedule:
    """The armed injections, consumed one-shot via ``take``."""

    def __init__(self, injections: Iterable[Injection] = ()):
        self.injections = list(injections)

    def __bool__(self) -> bool:
        return bool(self.injections)

    def arm(self, kind: str, at: int) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown chaos kind {kind!r}; {GRAMMAR_HELP}")
        self.injections.append(Injection(kind, int(at)))

    def armed_at(self, kind: str) -> list[int]:
        """Unfired ticks for one kind (the legacy test-hook getter)."""
        return [i.at for i in self.injections if i.kind == kind and not i.fired]

    def disarm(self, kind: str) -> None:
        """Drop every UNFIRED injection of one kind (fired ones stay for
        the record) — the legacy test hook's ``= None`` disarm."""
        self.injections = [
            i for i in self.injections if i.kind != kind or i.fired
        ]

    def take(self, kind: str, tick: int) -> bool:
        """True — exactly once — when an unfired ``kind@tick`` injection
        is armed; marks it fired and logs the ``chaos_injection`` event
        (``local``: every process's JSONL carries its own firing — the
        schedule is deterministic, so all ranks fire together)."""
        for inj in self.injections:
            if inj.kind == kind and inj.at == tick and not inj.fired:
                inj.fired = True
                from distributed_llms_example_tpu.obs import sink as sink_mod

                sink_mod.emit(
                    {"event": "chaos_injection", "kind": kind, "step": int(tick)},
                    local=True,
                )
                return True
        return False


def parse_chaos(spec: str) -> ChaosSchedule:
    """Parse the ``--chaos`` grammar; raises ValueError (with the grammar
    help) on anything malformed — chaos configs must fail at parse time,
    not at injection time 4 hours into the run."""
    schedule = ChaosSchedule()
    spec = (spec or "").strip()
    if not spec:
        return schedule
    for part in spec.split(","):
        part = part.strip()
        kind, sep, tick = part.partition("@")
        if not sep or kind not in KINDS or not tick.isdigit() or int(tick) < 1:
            raise ValueError(f"bad --chaos entry {part!r}: {GRAMMAR_HELP}")
        schedule.arm(kind, int(tick))
    return schedule


def corrupt_checkpoint(step_dir: str, *, nbytes: int = 64) -> str | None:
    """Flip ``nbytes`` in the middle of the largest file under a
    checkpoint step directory (deterministic pick: size desc, then path)
    — the torn/bit-rotted-storage simulation the integrity manifest must
    catch.  Returns the corrupted file's path, or None if the directory
    holds no files."""
    candidates: list[tuple[int, str]] = []
    for dirpath, _, files in os.walk(step_dir):
        for name in sorted(files):
            path = os.path.join(dirpath, name)
            candidates.append((-os.path.getsize(path), path))
    if not candidates:
        return None
    candidates.sort()
    path = candidates[0][1]
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        off = max(0, size // 2 - nbytes // 2)
        f.seek(off)
        chunk = f.read(min(nbytes, max(1, size - off)))
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
        f.flush()
        os.fsync(f.fileno())
    from distributed_llms_example_tpu.obs import sink as sink_mod

    record = {
        "event": "chaos_ckpt_corrupted",
        "path": path,
        "bytes_flipped": len(chunk),
    }
    # orbax step dirs are named by their step number: carrying it lets
    # obs.report match a later ckpt_verify_failed to THIS injection
    # per-step (an unrelated organic corruption must stay organic)
    base = os.path.basename(os.path.normpath(step_dir))
    if base.isdigit():
        record["step"] = int(base)
    sink_mod.emit(record, local=True)
    return path
