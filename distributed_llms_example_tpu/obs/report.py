"""Offline consumer for the per-process ``--obs jsonl`` telemetry.

``python -m distributed_llms_example_tpu.obs.report <output_dir>`` reads
every ``obs/metrics-p*.jsonl`` (and any ``obs/flight-recorder-p*.json``
bundle) a run left behind, validates ``schema_version`` on every line,
and reconstructs the run:

- a **merged per-step timeline** joining, on the global ``step`` field,
  process 0's metric lines (loss / lr / tokens-per-sec), every process's
  ``obs_window`` span summaries, eval events (``val_loss`` — same
  ``step`` field as train events), heartbeat skew, and anomalies;
- a **"Where did the time go" budget section** from the ``step_budget``
  events (obs/budget.py): per-window additive component tables per rank,
  the worst-offender ranking over the host-stall components, the
  wall-weighted ``dispatch_efficiency``, and every off-cadence
  host-blocking-dispatch incident the runtime tripwire flagged.
  ``--min-dispatch-efficiency X`` + ``--strict`` turn a regressed
  efficiency into a nonzero exit (the trainer-loop-gap CI gate);
- a **device account section** from the ``device_account`` events
  (obs/devprof.py — profile captures parsed at runtime): per-module-
  bucket device time, per-collective achieved bandwidth (measured device
  time joined with the gauges' static byte account), and the compute↔comm
  overlap / exposed-idle metrics, all from the JSONL alone (no trace
  files needed at report time).  ``--min-overlap-frac X`` + ``--strict``
  gate on exposed collectives and on captures that produced no account;
- ``--trace out.json`` additionally exports the merged **Perfetto /
  Chrome trace** (obs/trace.py): every rank's span instances aligned on
  shared step boundaries, budget counters, anomaly/chaos instants, and
  serving request lifecycles — load at https://ui.perfetto.dev;
- **window trends**: p50/p95 step time per process across the run (is it
  getting slower? did one host drift?);
- **straggler attribution**: which ranks the heartbeat named laggards
  and how often, next to each rank's own window p95 — the "go look at
  host N" answer;
- the **comm-bytes account** from the startup gauges, with the
  reduce-scatter smell predicate (analysis/ir_lint.py) evaluated over it
  — an fsdp run whose gradient bytes ride all-reduce is flagged right in
  the report;
- an **"Open-loop load sweep" section** from the ``loadgen_point`` /
  ``loadgen_summary`` events (serving/loadgen.py): the offered-vs-
  achieved/goodput and TTFT-percentile curves per offered-QPS grid
  point, per-point SLO attainment, and the detected saturation knee —
  rendered from the JSONL alone.  ``--min-slo-attainment X`` /
  ``--max-p99-ttft-ms Y`` + ``--strict`` gate on the curve (missing
  loadgen measurement = fail);
- the **anomaly log** (``obs_anomaly`` events + flight-recorder
  bundles).

Output: human markdown (default) or ``--json``.  Schema drift is
reported per line; ``--strict`` turns any invalid line into a nonzero
exit.  Pure file reader — jax is imported by nothing on this path, so
the report runs anywhere the output dir is mounted.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any

from distributed_llms_example_tpu.obs.sink import SCHEMA_VERSION

_PROC_RE = re.compile(r"-p(\d+)\.jsonl?$")


def load_jsonl(path: str) -> tuple[list[dict], list[str]]:
    """Parse one JSONL file, checking ``schema_version`` on every line.
    Returns (valid records, per-line error strings).  A trailing torn
    line (kill mid-write) is an error entry, not an exception."""
    records: list[dict] = []
    errors: list[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"{path}:{lineno}: unparseable line ({e})")
                continue
            if not isinstance(rec, dict):
                errors.append(f"{path}:{lineno}: not a JSON object")
                continue
            v = rec.get("schema_version")
            if v != SCHEMA_VERSION:
                errors.append(
                    f"{path}:{lineno}: schema_version {v!r} != {SCHEMA_VERSION}"
                )
                continue
            records.append(rec)
    return records, errors


def load_run(output_dir: str) -> dict[str, Any]:
    """Read every per-process stream + recorder bundle under
    ``<output_dir>/obs/``."""
    obs_dir = os.path.join(output_dir, "obs")
    processes: dict[int, list[dict]] = {}
    errors: list[str] = []
    for path in sorted(glob.glob(os.path.join(obs_dir, "metrics-p*.jsonl"))):
        m = _PROC_RE.search(path)
        if not m:
            continue
        recs, errs = load_jsonl(path)
        processes[int(m.group(1))] = recs
        errors.extend(errs)
    recorders: dict[int, dict] = {}
    for path in sorted(glob.glob(os.path.join(obs_dir, "flight-recorder-p*.json"))):
        m = re.search(r"-p(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                bundle = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            errors.append(f"{path}: unreadable bundle ({e})")
            continue
        if bundle.get("schema_version") != SCHEMA_VERSION:
            errors.append(
                f"{path}: schema_version {bundle.get('schema_version')!r} "
                f"!= {SCHEMA_VERSION}"
            )
            continue
        recorders[int(m.group(1))] = bundle
    postmortems: dict[int, dict] = {}
    for path in sorted(
        glob.glob(os.path.join(obs_dir, "memory-postmortem-p*.json"))
    ):
        m = re.search(r"-p(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                bundle = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            errors.append(f"{path}: unreadable bundle ({e})")
            continue
        if bundle.get("schema_version") != SCHEMA_VERSION:
            errors.append(
                f"{path}: schema_version {bundle.get('schema_version')!r} "
                f"!= {SCHEMA_VERSION}"
            )
            continue
        postmortems[int(m.group(1))] = bundle
    return {
        "processes": processes,
        "recorders": recorders,
        "postmortems": postmortems,
        "errors": errors,
    }


def _by_event(records: list[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for r in records:
        out.setdefault(r.get("event", "metric"), []).append(r)
    return out


def merge_timeline(processes: dict[int, list[dict]]) -> list[dict]:
    """Join every process's records on the global ``step`` field into one
    chronological per-step timeline."""
    steps: dict[int, dict[str, Any]] = {}

    def at(step: Any) -> dict | None:
        if not isinstance(step, (int, float)):
            return None
        return steps.setdefault(int(step), {"step": int(step)})

    for proc, records in sorted(processes.items()):
        ev = _by_event(records)  # bucket once per process
        for r in ev.get("metric", []):
            row = at(r.get("step"))
            if row is None or "loss" not in r:
                continue
            for k in ("loss", "learning_rate", "tokens_per_sec", "steps_per_sec", "epoch"):
                if k in r:
                    row[k] = r[k]
        for r in ev.get("obs_window", []):
            row = at(r.get("step"))
            if row is None:
                continue
            row.setdefault("windows", {})[proc] = {
                "p50": r.get("step_ms_p50"),
                "p95": r.get("step_ms_p95"),
                "max": r.get("step_ms_max"),
                "straggler": r.get("straggler"),
            }
            if "health" in r:
                row.setdefault("health", {})[proc] = r["health"]
        for r in ev.get("eval", []):
            row = at(r.get("step"))
            if row is None:
                continue
            for k, v in r.items():
                if k not in ("event", "step", "schema_version"):
                    row.setdefault("eval", {})[k] = v
        for r in ev.get("heartbeat", []):
            row = at(r.get("step"))
            if row is None:
                continue
            row["heartbeat"] = {
                k: r.get(k)
                for k in ("skew_steps", "arrival_spread_s", "laggards", "process_count")
            }
        for r in ev.get("obs_anomaly", []):
            row = at(r.get("step"))
            if row is None:
                continue
            row.setdefault("anomalies", []).append(
                {
                    k: r.get(k)
                    for k in ("code", "ranks", "policy", "value", "detail", "detected_at_step")
                    if k in r
                }
            )
    return [steps[s] for s in sorted(steps)]


def straggler_attribution(processes: dict[int, list[dict]]) -> dict[str, Any]:
    """Who was slow: heartbeat laggard counts per rank (the gather is a
    barrier, so a laggard there really did keep everyone waiting) next to
    each rank's own mean window p95."""
    laggard_counts: dict[int, int] = {}
    max_skew = 0
    max_spread = 0.0
    per_rank_p95: dict[int, float] = {}
    straggler_windows: dict[int, int] = {}
    for proc, records in sorted(processes.items()):
        ev = _by_event(records)  # bucket once per process
        for r in ev.get("heartbeat", []):
            for lag in r.get("laggards", []) or []:
                laggard_counts[int(lag)] = laggard_counts.get(int(lag), 0) + 1
            max_skew = max(max_skew, int(r.get("skew_steps", 0) or 0))
            max_spread = max(max_spread, float(r.get("arrival_spread_s", 0.0) or 0.0))
        windows = ev.get("obs_window", [])
        p95s = [
            r["step_ms_p95"]
            for r in windows
            if isinstance(r.get("step_ms_p95"), (int, float))
        ]
        if p95s:
            per_rank_p95[proc] = round(sum(p95s) / len(p95s), 3)
        straggler_windows[proc] = sum(1 for r in windows if r.get("straggler"))
    return {
        "heartbeat_laggard_counts": {str(k): v for k, v in sorted(laggard_counts.items())},
        "max_skew_steps": max_skew,
        "max_arrival_spread_s": max_spread,
        "mean_step_ms_p95_by_rank": {str(k): v for k, v in sorted(per_rank_p95.items())},
        "straggler_windows_by_rank": {str(k): v for k, v in sorted(straggler_windows.items())},
    }


def window_trends(processes: dict[int, list[dict]]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for proc, records in sorted(processes.items()):
        out[str(proc)] = [
            {
                "step": r.get("step"),
                "p50": r.get("step_ms_p50"),
                "p95": r.get("step_ms_p95"),
                "mfu": r.get("mfu"),
            }
            for r in _by_event(records).get("obs_window", [])
        ]
    return out


def comm_report(processes: dict[int, list[dict]]) -> dict[str, Any] | None:
    """The startup gauges' collective-traffic account, with the
    reduce-scatter smell predicate evaluated over it."""
    for records in processes.values():
        for r in _by_event(records).get("obs_gauges", []):
            comm = r.get("comm")
            if not isinstance(comm, dict):
                continue
            out: dict[str, Any] = {
                "mesh": r.get("mesh"),
                "flops_per_step": r.get("flops_per_step"),
                "flops_source": r.get("flops_source"),
                "grad_compression": r.get("grad_compression"),
                "comm": comm,
            }
            from distributed_llms_example_tpu.analysis.ir_lint import (
                account_gradient_bytes_by_op,
                reduce_scatter_smell,
            )

            smell = reduce_scatter_smell(
                account_gradient_bytes_by_op(comm), r.get("mesh") or {}
            )
            if smell is not None:
                out["reduce_scatter_smell"] = smell.to_json()
            return out
    return None


def budget_report(processes: dict[int, list[dict]]) -> dict[str, Any] | None:
    """The "Where did the time go" rollup over every rank's
    ``step_budget`` events: per-rank component totals + efficiency (via
    obs/budget.py's shared aggregation, so bench and the report cannot
    disagree), the worst-offender ranking over host-stall components, and
    the off-cadence host-blocking-dispatch incident list."""
    from distributed_llms_example_tpu.obs.budget import (
        COMPONENTS,
        aggregate_accounts,
    )

    ranks: dict[str, Any] = {}
    windows: dict[str, list[dict]] = {}
    incidents: list[dict] = []
    eff_wall: list[tuple[float, float]] = []
    for proc, records in sorted(processes.items()):
        accts = _by_event(records).get("step_budget", [])
        if not accts:
            continue
        agg = aggregate_accounts(accts)
        ranks[str(proc)] = agg
        windows[str(proc)] = [
            {
                "step": a.get("step"),
                "wall_ms": a.get("wall_ms"),
                **{f"{c}_ms": a.get(f"{c}_ms") for c in COMPONENTS},
                "dispatch_efficiency": a.get("dispatch_efficiency"),
                "accounted_frac": a.get("accounted_frac"),
                "offcadence_sync_steps": a.get("offcadence_sync_steps", 0),
            }
            for a in accts
        ]
        for a in accts:
            # SUSPECT windows only: on a synchronous-dispatch backend
            # (multi-device CPU) the raw count is that backend's normal
            # mode, stamped sync_dispatch_backend — not an incident
            if a.get("offcadence_sync_suspect"):
                incidents.append({
                    "rank": proc,
                    "step": a.get("step"),
                    "blocked_steps": int(a.get("offcadence_sync_steps", 0) or 0),
                    "window_steps": a.get("window_steps"),
                    "dispatch_ms": a.get("dispatch_ms"),
                })
        if agg and agg.get("wall_ms"):
            eff_wall.append((agg["dispatch_efficiency"], agg["wall_ms"]))
    if not ranks:
        return None
    total_wall = sum(w for _, w in eff_wall)
    overall_eff = (
        round(sum(e * w for e, w in eff_wall) / total_wall, 4)
        if total_wall
        else None
    )
    # worst offenders: the host-stall components (the time the device was
    # NOT being fed), ranked by share of total wall across ranks
    stall_components = ("data_wait", "host_overhead", "sync_block", "unattributed")
    totals = {
        c: sum(r.get(f"{c}_ms", 0.0) or 0.0 for r in ranks.values())
        for c in stall_components
    }
    all_wall = sum(r.get("wall_ms", 0.0) or 0.0 for r in ranks.values())
    offenders = sorted(
        (
            {"component": c, "total_ms": round(v, 3),
             "share": round(v / all_wall, 4) if all_wall else 0.0}
            for c, v in totals.items()
        ),
        key=lambda o: -o["total_ms"],
    )
    return {
        "ranks": ranks,
        "windows": windows,
        "offenders": offenders,
        "incidents": incidents,
        "dispatch_efficiency": overall_eff,
    }


def device_report(processes: dict[int, list[dict]]) -> dict[str, Any] | None:
    """The device-time attribution rollup: each rank's NEWEST
    ``device_account`` (a parsed profile capture — obs/devprof.py), the
    ``profile_captured`` inventory, and the achieved-bandwidth join
    against the startup gauges' byte account for any account the runtime
    emitted without one (e.g. gauges landed after the capture).  Renders
    from the JSONL alone — no trace files are read here."""
    from distributed_llms_example_tpu.obs.devprof import (
        join_collective_bandwidth,
    )

    comm = None
    for records in processes.values():
        for r in _by_event(records).get("obs_gauges", []):
            if isinstance(r.get("comm"), dict):
                comm = r["comm"]
                break
        if comm:
            break
    ranks: dict[str, dict] = {}
    captures: list[dict] = []
    n_accounts = 0
    for proc, records in sorted(processes.items()):
        ev = _by_event(records)
        for r in ev.get("profile_captured", []):
            captures.append({
                "rank": proc,
                "path": r.get("path"),
                "window": r.get("window"),
                "steps": r.get("steps"),
                **({"truncated": True} if r.get("truncated") else {}),
            })
        accts = ev.get("device_account", [])
        n_accounts += len(accts)
        if not accts:
            continue
        acct = dict(accts[-1])  # newest capture is the rank's account
        acct.pop("lanes", None)  # exporter payload, not report material
        needs_join = any(
            "achieved_bytes_per_sec" not in slot
            for slot in (acct.get("collectives") or {}).values()
        )
        if needs_join and comm:
            join_collective_bandwidth(
                acct, comm, int(acct.get("window_steps", 0) or 0)
            )
        ranks[str(proc)] = acct
    if not ranks and not captures:
        return None
    return {"ranks": ranks, "captures": captures, "accounts": n_accounts}


def recovery_report(processes: dict[int, list[dict]]) -> dict[str, Any]:
    """The fault-tolerance timeline: chaos injections, recovery actions
    (rewinds / skip-batch / halts), quarantines, checkpoint-integrity
    failures, data retries — with the injected/organic split.

    A fault is **injected** when a ``chaos_injection`` event explains it
    (``nan_grad`` at the anomaly's step; any ``ckpt_corrupt`` firing for
    an integrity failure; ``data_error`` at a retry's step); everything
    else is **organic** — the distinction ``--strict`` gates on (a chaos
    run is green only when every fault it saw is one it caused)."""
    injections: list[dict] = []
    corrupted: list[dict] = []
    recoveries: list[dict] = []
    quarantines: list[dict] = []
    verify_failures: list[dict] = []
    data_events: list[dict] = []
    anomalies: list[dict] = []
    topo_changes: list[dict] = []
    reshards: list[dict] = []
    replica_events: list[dict] = []
    serve_retries: list[dict] = []
    serve_sheds: list[dict] = []
    router_summaries: list[dict] = []
    suspects: list[dict] = []
    # injections/recoveries/quarantines are ``local`` events (every
    # rank's file carries its own copy — the schedule and the escalation
    # are deterministic across the pod): dedup to per-run rows
    seen: set = set()

    def dedup(into: list[dict], rec: dict, *keys: str) -> None:
        k = (rec.get("event"),) + tuple(rec.get(x) for x in keys)
        if k not in seen:
            seen.add(k)
            into.append(rec)

    for _, records in sorted(processes.items()):
        ev = _by_event(records)
        for r in ev.get("chaos_injection", []):
            dedup(injections, r, "kind", "step")
        for r in ev.get("chaos_ckpt_corrupted", []):
            dedup(corrupted, r, "step", "path")
        for r in ev.get("recovery", []):
            # rewind_index is in the key: two rewinds with the same
            # (step, restored_step) — a second poison batch on the replay
            # — are distinct recoveries, not per-rank copies
            dedup(
                recoveries, r,
                "action", "step", "detected_at_step", "restored_step",
                "rewind_index",
            )
        for r in ev.get("quarantine", []):
            dedup(quarantines, r, "epoch", "epoch_step")
        for r in ev.get("topology_change", []):
            dedup(topo_changes, r, "step", "policy")
        for r in ev.get("reshard_restore", []):
            # (step, detected_at_step) identifies one reshard across the
            # ranks' local copies; wall clock differs per rank, so it
            # must stay OUT of the key
            dedup(reshards, r, "step", "detected_at_step", "new_processes")
        for r in ev.get("replica_health", []):
            # local events: every rank's file may carry a copy (single-
            # process today, per-host tomorrow) — one row per transition
            dedup(replica_events, r, "replica", "from", "to", "tick")
        for r in ev.get("serve_retry", []):
            dedup(serve_retries, r, "request", "retries", "tick", "reason")
        for r in ev.get("serve_shed", []):
            dedup(serve_sheds, r, "request", "tick")
        for r in ev.get("host_loss_suspect", []):
            dedup(suspects, r, "rank", "step")
        router_summaries.extend(ev.get("router_summary", []))
        for kind in ("ckpt_verify_failed", "ckpt_restore_failed"):
            verify_failures.extend(ev.get(kind, []))
        for kind in ("data_retry", "data_skipped_records"):
            data_events.extend(ev.get(kind, []))
        anomalies.extend(ev.get("obs_anomaly", []))
    injected_at: dict[str, set] = {}
    for i in injections:
        injected_at.setdefault(i.get("kind", "?"), set()).add(i.get("step"))

    def fault_row(kind: str, step: Any, injected: bool, detail: str) -> dict:
        return {"kind": kind, "step": step, "injected": injected, "detail": detail}

    faults: list[dict] = []
    seen_anomaly_steps = set()
    for a in anomalies:
        key = (a.get("step"), a.get("code"))
        if key in seen_anomaly_steps:
            continue  # one fault per (step, code), however many ranks logged it
        seen_anomaly_steps.add(key)
        injected = a.get("step") in injected_at.get("nan_grad", set())
        faults.append(fault_row(
            f"anomaly:{a.get('code')}", a.get("step"), injected,
            str(a.get("detail", ""))[:120],
        ))
    # per-step match: a verify failure is injected only when the chaos
    # harness corrupted THAT step (chaos_ckpt_corrupted carries the step
    # dir's number) — an organic corruption elsewhere in the same chaos
    # run must stay organic
    corrupted_steps = {c.get("step") for c in corrupted if "step" in c}
    seen_ckpt_steps = set()
    for v in verify_failures:
        if v.get("step") in seen_ckpt_steps:
            continue
        seen_ckpt_steps.add(v.get("step"))
        faults.append(fault_row(
            "ckpt_integrity", v.get("step"), v.get("step") in corrupted_steps,
            str(v.get("detail", v.get("error", "")))[:120],
        ))
    seen_data_steps = set()
    for d in data_events:
        if d.get("event") == "data_retry" and d.get("step") not in seen_data_steps:
            seen_data_steps.add(d.get("step"))
            injected = d.get("step") in injected_at.get("data_error", set())
            faults.append(fault_row(
                "data_retry", d.get("step"), injected, str(d.get("error", ""))[:120]
            ))
    # a topology change is a FAULT (a host left) even when the recovery
    # succeeds: injected when a host_loss chaos firing explains its step,
    # organic otherwise — exactly the split --strict gates on
    for t in topo_changes:
        injected = t.get("step") in injected_at.get("host_loss", set())
        faults.append(fault_row(
            "topology_change", t.get("step"), injected,
            f"policy {t.get('policy')}: "
            f"{t.get('old_mesh')} → {t.get('reason', 'reshard')}"[:120],
        ))
    # serving tier (ISSUE 15): a replica DYING is a fault even when every
    # request re-prefilled cleanly — the crash kind matches the injection
    # at its tick exactly; a stall's death tick trails its injection (the
    # heartbeat-miss detector needs dead_after ticks), so the match
    # window is [since_tick, tick] (since_tick = the replica's last
    # progress, stamped on the transition event)
    for r in replica_events:
        if r.get("to") != "dead":
            continue
        cause = r.get("cause", "crash")
        tick = r.get("tick")
        if cause == "stall":
            lo = r.get("since_tick", tick)
            injected = any(
                s is not None and lo is not None and tick is not None
                and lo <= s <= tick
                for s in injected_at.get("replica_stall", set())
            )
        else:
            injected = tick in injected_at.get("replica_crash", set())
        faults.append(fault_row(
            f"replica_{cause}", tick, injected,
            f"replica {r.get('replica')}: {str(r.get('reason', ''))}"[:120],
        ))
    organic = [f for f in faults if not f["injected"]]
    rewinds = [r for r in recoveries if r.get("action") == "rewind"]
    # reshard wall-clock counts toward MTTR: a topology recovery is a
    # recovery, and its restore is the dominant cost
    mttr_vals = [
        r["recovery_wall_s"]
        for r in rewinds
        if isinstance(r.get("recovery_wall_s"), (int, float))
    ] + [
        r["reshard_wall_s"]
        for r in reshards
        if isinstance(r.get("reshard_wall_s"), (int, float))
    ]
    serving = None
    if replica_events or serve_retries or serve_sheds or router_summaries:
        rs = router_summaries[-1] if router_summaries else {}
        serving = {
            "replica_transitions": [
                {
                    k: r.get(k)
                    for k in ("replica", "from", "to", "tick", "reason", "cause")
                    if k in r
                }
                for r in replica_events
            ],
            "replicas_lost": sum(
                1 for r in replica_events if r.get("to") == "dead"
            ),
            # failure retries of REAL traffic only: a drain re-dispatch
            # lost no work (the router doesn't count it either), and a
            # synthetic storm request's retries are injected load — both
            # would overstate failures next to the summary's rate
            "retries": sum(
                1 for r in serve_retries
                if r.get("reason") != "drain" and not r.get("synthetic")
            ),
            "redispatches": len(serve_retries),
            "shed": sum(
                1 for r in serve_sheds if not r.get("synthetic")
            ),
            "shed_total": len(serve_sheds),  # synthetic storm included
            "shed_by_reason": rs.get("shed_by_reason"),
            # the request-level recovery numbers the acceptance pins:
            # finite MTTR for re-prefilled requests + the gate inputs
            "request_mttr_s": rs.get("request_mttr_s"),
            "request_retry_rate": rs.get("request_retry_rate"),
            "goodput_frac": rs.get("goodput_frac"),
            "requests": rs.get("requests"),
            "completed": rs.get("completed"),
        }
    return {
        "injections": [
            {"kind": i.get("kind"), "step": i.get("step")} for i in injections
        ],
        "actions": [
            {
                k: r.get(k)
                for k in (
                    "action", "step", "code", "restored_step", "steps_lost",
                    "rewind_index", "recovery_wall_s", "reason",
                )
                if k in r
            }
            for r in recoveries
        ],
        "quarantines": [
            {k: q.get(k) for k in ("epoch", "epoch_step", "reason") if k in q}
            for q in quarantines
        ],
        "topology": [
            {
                k: t.get(k)
                for k in (
                    "step", "policy", "old_mesh", "old_processes", "reason",
                )
                if k in t
            }
            for t in topo_changes
        ],
        "reshards": [
            {
                k: r.get(k)
                for k in (
                    "step", "detected_at_step", "old_mesh", "new_mesh",
                    "old_processes", "new_processes", "ef_mode",
                    "steps_lost", "reshard_wall_s",
                )
                if k in r
            }
            for r in reshards
        ],
        "rewinds": len(rewinds),
        "steps_lost_total": sum(
            int(r.get("steps_lost", 0) or 0) for r in rewinds
        ) + sum(int(r.get("steps_lost", 0) or 0) for r in reshards),
        "mttr_s": (
            round(sum(mttr_vals) / len(mttr_vals), 4) if mttr_vals else None
        ),
        "serving": serving,
        "host_loss_suspects": [
            {
                k: s.get(k)
                for k in ("rank", "step", "consecutive_beats")
                if k in s
            }
            for s in suspects
        ],
        "faults": faults,
        "organic_faults": organic,
    }


def loadgen_report(processes: dict[int, list[dict]]) -> dict[str, Any] | None:
    """The open-loop load sweep rollup: the curve (one row per offered-
    QPS grid point) + the detected knee, from ``loadgen_point`` /
    ``loadgen_summary`` events alone.  The newest ``loadgen_summary``
    is authoritative for the curve and knee (it embeds its points);
    bare points (a run killed mid-sweep) still render.

    ``best_slo_attainment`` / ``best_ttft_p99_ms`` are the gate inputs:
    the best attainment any measured point reached, and the lowest
    MEASURED p99 TTFT (points where nothing finished measure None and
    are excluded — so a run whose every point collapsed has no p99 at
    all, and a p99 gate on it fails as a missing measurement)."""
    points: list[dict] = []
    summaries: list[dict] = []
    for _, records in sorted(processes.items()):
        ev = _by_event(records)
        points.extend(ev.get("loadgen_point", []))
        summaries.extend(ev.get("loadgen_summary", []))
    if not points and not summaries:
        return None
    summary = summaries[-1] if summaries else None
    curve = list((summary or {}).get("points") or points)
    attains = [
        p["slo_attainment"] for p in curve
        if isinstance(p.get("slo_attainment"), (int, float))
    ]
    p99s = [
        p["ttft_p99_ms"] for p in curve
        if isinstance(p.get("ttft_p99_ms"), (int, float))
    ]
    meta = summary or (points[-1] if points else {})
    return {
        "process": meta.get("process"),
        "seed": meta.get("seed"),
        "ttft_slo_ms": meta.get("ttft_slo_ms"),
        "requests_per_point": (summary or {}).get("requests_per_point"),
        "qps_grid": (summary or {}).get("qps_grid"),
        "knee_qps": (summary or {}).get("knee_qps"),
        "sweeps": len(summaries),
        "points": curve,
        "best_slo_attainment": max(attains) if attains else None,
        "best_ttft_p99_ms": min(p99s) if p99s else None,
    }


def prefix_report(processes: dict[int, list[dict]]) -> dict[str, Any] | None:
    """The prefix-cache rollup: reuse ledger from ``serve_summary``
    events whose engine ran with the cache on (``prefix_cache: true``)
    plus the router's cross-replica aggregate when one exists (a
    ``router_summary`` carrying ``prefix_hit_rate``).  The router
    aggregate is authoritative when present — per-replica summaries
    double-count nothing but see only their own traffic.

    ``hit_rate`` is the gate input: None when no prefix-enabled engine
    ever summarized, and the strict ``--min-prefix-hit-rate`` gate
    treats that as a failure, never a pass."""
    serve: list[dict] = []
    router: list[dict] = []
    windows = 0
    for _, records in sorted(processes.items()):
        ev = _by_event(records)
        serve.extend(
            r for r in ev.get("serve_summary", []) if r.get("prefix_cache")
        )
        router.extend(
            r for r in ev.get("router_summary", []) if "prefix_hit_rate" in r
        )
        windows += sum(
            1 for r in ev.get("serve_window", []) if "prefix_hit_rate" in r
        )
    if not (serve or router):
        return None
    src = router[-1] if router else serve[-1]
    latest = serve[-1] if serve else {}
    return {
        "scope": "router" if router else "engine",
        "hit_rate": src.get("prefix_hit_rate"),
        "lookups": src.get("prefix_lookups"),
        "hits": src.get("prefix_hits"),
        "prefill_tokens_total": src.get("prefill_tokens_total"),
        "prefill_tokens_saved": src.get("prefill_tokens_saved"),
        "prefill_tokens_saved_frac": src.get("prefill_tokens_saved_frac"),
        "budget_gib": latest.get("prefix_cache_budget_gib"),
        "pool_blocks_warm": latest.get("pool_blocks_warm"),
        "warm_bytes": latest.get("warm_bytes"),
        "windows": windows,
        "engines": len(serve),
    }


def spec_report(processes: dict[int, list[dict]]) -> dict[str, Any] | None:
    """The speculative-decode rollup: the acceptance ledger from
    ``serve_summary`` events whose engine ran with speculation on
    (``spec_decode: true``) plus the router's cross-replica aggregate
    when one exists (a ``router_summary`` carrying ``acceptance_rate``).
    The router aggregate is authoritative when present — same precedence
    as the prefix-cache rollup.

    ``acceptance_rate`` is the gate input: None when no spec-enabled
    engine ever summarized, and the strict ``--min-acceptance-rate``
    gate treats that as a failure, never a pass."""
    serve: list[dict] = []
    router: list[dict] = []
    windows = 0
    for _, records in sorted(processes.items()):
        ev = _by_event(records)
        serve.extend(
            r for r in ev.get("serve_summary", []) if r.get("spec_decode")
        )
        router.extend(
            r for r in ev.get("router_summary", []) if "acceptance_rate" in r
        )
        windows += sum(
            1 for r in ev.get("serve_window", []) if "acceptance_rate" in r
        )
    if not (serve or router):
        return None
    src = router[-1] if router else serve[-1]
    latest = serve[-1] if serve else {}
    return {
        "scope": "router" if router else "engine",
        "acceptance_rate": src.get("acceptance_rate"),
        "accepted_tokens_per_step": src.get("accepted_tokens_per_step"),
        "drafted_tokens": src.get("spec_drafted_tokens"),
        "accepted_tokens": src.get("spec_accepted_tokens"),
        "spec_tokens": latest.get("spec_tokens", src.get("spec_tokens")),
        "draft_model": latest.get("spec_draft_model"),
        "spec_steps": latest.get("spec_steps"),
        "windows": windows,
        "engines": len(serve),
    }


def memory_report(
    processes: dict[int, list[dict]],
    postmortems: dict[int, dict] | None = None,
) -> dict[str, Any] | None:
    """"Where did the bytes go" — the HBM rollup from the JSONL (and
    postmortem bundles) alone: the last static ``memory_account`` (the
    bucketed peak composition of the compiled step), the runtime
    ``memory_window`` envelope (max bytes-in-use / peak / per-window
    watermark delta over every rank's samples), the serving tier's
    account off its ``serve_summary``, and any ``memory-postmortem-p*``
    bundles.  ``measured_peak_bytes`` is the gate input: the runtime peak
    when any window was sampled, else the static account's compiled peak
    — a run with NEITHER has no measurement, and the strict gates treat
    that as a failure, never a pass."""
    accounts: list[dict] = []
    windows: list[dict] = []
    skips: list[dict] = []
    serve_accounts: list[dict] = []
    for _, records in sorted(processes.items()):
        ev = _by_event(records)
        accounts.extend(ev.get("memory_account", []))
        windows.extend(ev.get("memory_window", []))
        skips.extend(ev.get("memory_window_skipped", []))
        for r in ev.get("serve_summary", []):
            if isinstance(r.get("memory_account"), dict):
                serve_accounts.append(r["memory_account"])
    postmortems = postmortems or {}
    if not (accounts or windows or skips or serve_accounts or postmortems):
        return None
    account = accounts[-1] if accounts else None
    serve_account = serve_accounts[-1] if serve_accounts else None
    runtime = None
    if windows:
        runtime = {
            "windows": len(windows),
            "max_bytes_in_use": max(int(w.get("bytes_in_use", 0)) for w in windows),
            "peak_bytes_in_use": max(
                int(w.get("peak_bytes_in_use", 0)) for w in windows
            ),
            "max_watermark_delta_bytes": max(
                int(w.get("watermark_delta_bytes", 0)) for w in windows
            ),
            "bytes_limit": max(int(w.get("bytes_limit", 0)) for w in windows),
        }
    measured_peak = None
    peak_source = None
    if runtime is not None:
        measured_peak = runtime["peak_bytes_in_use"]
        peak_source = "memory_window"
    elif account is not None and isinstance(
        account.get("peak_bytes"), (int, float)
    ):
        measured_peak = int(account["peak_bytes"])
        peak_source = "static_account"
    budget_bytes = None
    for src in (account, serve_account):
        if src is not None and isinstance(
            src.get("hbm_budget_bytes"), (int, float)
        ):
            budget_bytes = int(src["hbm_budget_bytes"])
            break
    headrooms = [
        a["hbm_headroom_gib"]
        for a in (account, serve_account)
        if a is not None and isinstance(a.get("hbm_headroom_gib"), (int, float))
    ]
    return {
        "account": account,
        "serve_account": serve_account,
        "runtime": runtime,
        "static_only": bool(not windows and (account or serve_account)),
        "skips": [s.get("reason") for s in skips[:1]],
        "measured_peak_bytes": measured_peak,
        "measured_peak_source": peak_source,
        "hbm_budget_bytes": budget_bytes,
        "peak_frac_of_budget": (
            round(measured_peak / budget_bytes, 4)
            if (measured_peak is not None and budget_bytes)
            else None
        ),
        "min_headroom_gib": min(headrooms) if headrooms else None,
        "postmortems": {
            str(p): {
                "reason": b.get("reason"),
                "step": b.get("step"),
                "has_account": b.get("account") is not None,
                "watermark_samples": len(b.get("watermark_history") or []),
                "live_buffers_top": len(b.get("live_buffers_top") or []),
            }
            for p, b in sorted(postmortems.items())
        },
    }


def build_report(output_dir: str) -> dict[str, Any]:
    run = load_run(output_dir)
    processes = run["processes"]
    anomalies = [
        r
        for records in processes.values()
        for r in _by_event(records).get("obs_anomaly", [])
    ]
    report: dict[str, Any] = {
        "output_dir": output_dir,
        "schema_version": SCHEMA_VERSION,
        "processes": sorted(processes),
        "records": sum(len(r) for r in processes.values()),
        "schema_errors": run["errors"],
        "timeline": merge_timeline(processes),
        "trends": window_trends(processes),
        "stragglers": straggler_attribution(processes),
        "comm": comm_report(processes),
        "budget": budget_report(processes),
        "device": device_report(processes),
        "memory": memory_report(processes, run["postmortems"]),
        "loadgen": loadgen_report(processes),
        "prefix": prefix_report(processes),
        "spec": spec_report(processes),
        "recovery": recovery_report(processes),
        "anomalies": anomalies,
        "recorders": {
            str(p): {
                "reason": b.get("reason"),
                "step": b.get("step"),
                "steps_recorded": len(b.get("entries", [])),
                "anomalies": b.get("anomalies", []),
            }
            for p, b in run["recorders"].items()
        },
    }
    return report


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return "" if v is None else str(v)


def render_markdown(report: dict[str, Any], *, last: int = 20) -> str:
    lines: list[str] = []
    add = lines.append
    add(f"# obs report — {report['output_dir']}")
    add("")
    add(
        f"processes: {report['processes'] or 'none'} · records: "
        f"{report['records']} · schema errors: {len(report['schema_errors'])}"
    )
    for e in report["schema_errors"][:10]:
        add(f"- schema error: {e}")
    timeline = report["timeline"]
    add("")
    add(f"## Step timeline ({len(timeline)} steps with events; last {last} shown)")
    add("")
    add("| step | loss | val_loss | p50/p95 ms by rank | skew | anomalies |")
    add("|---|---|---|---|---|---|")
    for row in timeline[-last:]:
        win = row.get("windows", {})
        winfmt = " ".join(
            f"r{p}:{_fmt(w['p50'])}/{_fmt(w['p95'])}"
            + ("!" if w.get("straggler") else "")
            for p, w in sorted(win.items())
        )
        hb = row.get("heartbeat") or {}
        anom = "; ".join(
            f"{a.get('code')}@ranks{a.get('ranks')}" for a in row.get("anomalies", [])
        )
        add(
            f"| {row['step']} | {_fmt(row.get('loss'))} | "
            f"{_fmt((row.get('eval') or {}).get('val_loss'))} | {winfmt} | "
            f"{_fmt(hb.get('skew_steps'))} | {anom} |"
        )
    add("")
    add("## Trends (window p50/p95 ms)")
    for proc, ws in report["trends"].items():
        if not ws:
            continue
        first, final = ws[0], ws[-1]
        add(
            f"- rank {proc}: p50 {_fmt(first['p50'])} → {_fmt(final['p50'])}, "
            f"p95 {_fmt(first['p95'])} → {_fmt(final['p95'])} over {len(ws)} windows"
            + (f", last mfu {_fmt(final['mfu'])}" if final.get("mfu") is not None else "")
        )
    s = report["stragglers"]
    add("")
    add("## Straggler attribution")
    add(
        f"- max heartbeat skew: {s['max_skew_steps']} steps; max arrival "
        f"spread: {_fmt(s['max_arrival_spread_s'])} s"
    )
    if s["heartbeat_laggard_counts"]:
        for rank, n in s["heartbeat_laggard_counts"].items():
            add(f"- rank {rank}: named laggard in {n} heartbeat(s)")
    else:
        add("- no laggards named by any heartbeat")
    if s["mean_step_ms_p95_by_rank"]:
        add(
            "- mean window p95 by rank: "
            + ", ".join(
                f"r{k}={_fmt(v)}ms" for k, v in s["mean_step_ms_p95_by_rank"].items()
            )
        )
    budget = report.get("budget")
    add("")
    add("## Where did the time go")
    if budget is None:
        add("- no step_budget records (run without --obs-budget?)")
    else:
        from distributed_llms_example_tpu.obs.budget import COMPONENTS

        add(
            f"- dispatch efficiency (wall-weighted, all ranks): "
            f"{_fmt(budget['dispatch_efficiency'])}"
        )
        add("")
        header = " | ".join(c for c in COMPONENTS)
        add(f"| rank | windows | wall ms | {header} | efficiency |")
        add("|---" * (len(COMPONENTS) + 4) + "|")
        for rank, agg in sorted(budget["ranks"].items()):
            comps = " | ".join(_fmt(agg.get(f"{c}_ms")) for c in COMPONENTS)
            add(
                f"| {rank} | {agg['windows']} | {_fmt(agg['wall_ms'])} | "
                f"{comps} | {_fmt(agg['dispatch_efficiency'])} |"
            )
        opt_rows = [
            (rank, agg)
            for rank, agg in sorted(budget["ranks"].items())
            if agg.get("optimizer_apply_ms") is not None
        ]
        if opt_rows:
            # the cadenced stand-alone apply sample (obs/budget.py
            # probe_optimizer) — the direct optimizer-ms read the
            # fused-vs-xla --optim-impl A/B consumes
            add(
                "optimizer apply (cadenced stand-alone sample): "
                + ", ".join(
                    f"r{rank}={_fmt(agg['optimizer_apply_ms'])}ms"
                    + (
                        f" ({_fmt(agg['optimizer_share_of_step'] * 100)}% of step)"
                        if agg.get("optimizer_share_of_step") is not None
                        else ""
                    )
                    for rank, agg in opt_rows
                )
            )
        add("")
        add("worst offenders (host-stall components, share of total wall):")
        for o in budget["offenders"]:
            add(
                f"- {o['component']}: {_fmt(o['total_ms'])} ms "
                f"({_fmt(o['share'] * 100)}% of wall)"
            )
        if budget["incidents"]:
            add("")
            add("**off-cadence host-blocking dispatch incidents** (the "
                "runtime rule-4 tripwire — a transfer blocked the step "
                "body outside the logging window):")
            for inc in budget["incidents"]:
                add(
                    f"- rank {inc['rank']} window@step {inc['step']}: "
                    f"{inc['blocked_steps']}/{inc['window_steps']} step(s) "
                    f"blocked in dispatch ({_fmt(inc['dispatch_ms'])} ms total)"
                )
        else:
            add("- no off-cadence host-blocking dispatch detected")
        # per-window trend, most recent windows per rank
        for rank, ws in sorted(budget["windows"].items()):
            shown = ws[-last:]
            if not shown:
                continue
            first, final = shown[0], shown[-1]
            add(
                f"- rank {rank} windows: efficiency "
                f"{_fmt(first['dispatch_efficiency'])} → "
                f"{_fmt(final['dispatch_efficiency'])}, accounted "
                f"{_fmt(final['accounted_frac'])} of wall over {len(ws)} window(s)"
            )
    device = report.get("device")
    add("")
    add("## Device account (profiled windows)")
    if device is None:
        add("- no device_account records (no profile window landed — "
            "touch the profile trigger or pass --profile-steps)")
    else:
        from distributed_llms_example_tpu.obs.devprof import DEVICE_BUCKETS

        for cap in device["captures"]:
            add(
                f"- capture r{cap['rank']}: steps {cap.get('window')} → "
                f"`{cap.get('path')}`"
                + (" (truncated)" if cap.get("truncated") else "")
            )
        if not device["ranks"]:
            add("- captures exist but no device_account parsed — run with "
                "--obs-budget on, or parse offline: python -m "
                "distributed_llms_example_tpu.obs.devprof <capture_dir>")
        else:
            add("")
            add("| rank | window | span ms | busy ms | idle ms | "
                + " | ".join(DEVICE_BUCKETS) + " |")
            add("|---" * (len(DEVICE_BUCKETS) + 5) + "|")
            for rank, acct in sorted(device["ranks"].items()):
                b = acct.get("buckets_ms", {})
                cells = " | ".join(_fmt(b.get(k)) for k in DEVICE_BUCKETS)
                add(
                    f"| {rank} | {acct.get('window')} | "
                    f"{_fmt(acct.get('span_ms'))} | {_fmt(acct.get('busy_ms'))} | "
                    f"{_fmt(acct.get('exposed_idle_ms'))} | {cells} |"
                )
            add("")
            add("collective bandwidth (measured device time × static "
                "byte account):")
            any_coll = False
            for rank, acct in sorted(device["ranks"].items()):
                for op, slot in sorted((acct.get("collectives") or {}).items()):
                    any_coll = True
                    bw = slot.get("achieved_bytes_per_sec")
                    add(
                        f"- r{rank} {op}: ×{slot.get('count')} — "
                        f"{_fmt(slot.get('time_ms'))} ms"
                        + (
                            f", {slot.get('bytes_per_step', 0):,} B/step → "
                            f"{bw / 1e6:.1f} MB/s achieved"
                            if isinstance(bw, (int, float))
                            else ""
                        )
                    )
            if not any_coll:
                add("- no collective device time in the captured window")
            for rank, acct in sorted(device["ranks"].items()):
                ov = acct.get("overlap") or {}
                if not ov:
                    continue
                frac = ov.get("overlap_frac")
                add(
                    f"- r{rank} overlap: collective {_fmt(ov.get('collective_ms'))} ms, "
                    f"compute {_fmt(ov.get('compute_ms'))} ms, "
                    f"overlapped {_fmt(ov.get('overlapped_ms'))} ms"
                    + (
                        f" (overlap_frac {_fmt(frac)})"
                        if frac is not None
                        else ""
                    )
                    + f", exposed collective {_fmt(ov.get('exposed_collective_ms'))} ms, "
                    f"exposed idle {_fmt(acct.get('exposed_idle_ms'))} ms"
                )
    comm = report["comm"]
    add("")
    add("## Comm account")
    if comm is None:
        add("- no obs_gauges record (run without --obs-gauges?)")
    else:
        acct = comm["comm"]
        add(
            f"- total {acct.get('total_bytes', 0):,} B/step — gradient "
            f"{acct.get('gradient_bytes', 0):,} B, activation "
            f"{acct.get('activation_bytes', 0):,} B (mesh {comm.get('mesh')})"
        )
        for op, slot in sorted(acct.items()):
            if isinstance(slot, dict):
                add(
                    f"  - {op}: ×{slot.get('count')} — grad "
                    f"{slot.get('gradient_bytes', 0):,} B, act "
                    f"{slot.get('activation_bytes', 0):,} B"
                )
        if "reduce_scatter_smell" in comm:
            add(f"- **smell**: {comm['reduce_scatter_smell'].get('message')}")
    mem = report.get("memory")
    if mem is not None:
        add("")
        add("## Where did the bytes go")
        acct = mem.get("account")
        if acct is not None:
            add(
                f"- static account (model {acct.get('model')}, mesh "
                f"{acct.get('mesh')}): compiled peak "
                f"{int(acct.get('peak_bytes', 0)):,} B "
                f"({_fmt(acct.get('peak_gib'))} GiB) vs budget "
                f"{_fmt(acct.get('hbm_budget_gib'))} GiB — "
                + ("fits" if acct.get("fits_budget") else "**OVER BUDGET**")
                + f" (headroom {_fmt(acct.get('hbm_headroom_gib'))} GiB, "
                f"additivity gap {int(acct.get('additivity_gap_bytes', 0)):,} B)"
            )
            add("")
            add("| bucket | bytes | GiB | share of peak |")
            add("|---|---|---|---|")
            peak = max(1, int(acct.get("peak_bytes", 0)))
            for bucket, b in sorted(
                (acct.get("buckets_bytes") or {}).items(),
                key=lambda kv: -kv[1],
            ):
                add(
                    f"| {bucket} | {int(b):,} | {b / 1024**3:.3f} | "
                    f"{b / peak:.1%} |"
                )
            add("")
            for row in (acct.get("largest_buffers") or [])[:8]:
                add(
                    f"- {row.get('name')}: {int(row.get('bytes', 0)):,} B "
                    f"(shard {row.get('shard_shape')} {row.get('dtype')}"
                    + (
                        f", module {row['module']}"
                        if row.get("module")
                        else ""
                    )
                    + ")"
                )
        sa = mem.get("serve_account")
        if sa is not None:
            buckets = sa.get("buckets_bytes") or {}
            add(
                f"- serving account: params {int(buckets.get('params', 0)):,} B"
                f" + kv_cache {int(buckets.get('kv_cache', 0)):,} B = "
                f"{int(sa.get('peak_bytes', 0)):,} B vs budget "
                f"{_fmt(sa.get('hbm_budget_gib'))} GiB — "
                + ("fits" if sa.get("fits_budget") else "**OVER BUDGET**")
            )
        rt = mem.get("runtime")
        if rt is not None:
            add(
                f"- runtime ({rt.get('windows')} memory_window samples): "
                f"bytes in use ≤ {rt.get('max_bytes_in_use', 0):,} B, "
                f"process peak {rt.get('peak_bytes_in_use', 0):,} B, "
                f"largest per-window watermark delta "
                f"{rt.get('max_watermark_delta_bytes', 0):,} B"
            )
        elif mem.get("static_only"):
            reason = (mem.get("skips") or [None])[0]
            add(
                "- runtime: static-only"
                + (f" — {reason}" if reason else "")
            )
        for p, b in sorted((mem.get("postmortems") or {}).items()):
            add(
                f"- **OOM postmortem** p{p} at step {b.get('step')}: "
                f"{b.get('reason')} ({b.get('watermark_samples')} watermark "
                f"samples, account "
                + ("attached" if b.get("has_account") else "absent")
                + ")"
            )
    lg = report.get("loadgen")
    if lg is not None:
        add("")
        add("## Open-loop load sweep")
        knee = lg.get("knee_qps")
        add(
            f"- process={lg.get('process')} seed={lg.get('seed')} "
            f"slo={_fmt(lg.get('ttft_slo_ms'))}ms "
            f"requests/point={lg.get('requests_per_point')} — knee: "
            + (
                f"**{_fmt(knee)} QPS** (first saturated offered rate)"
                if knee is not None
                else "not reached on this grid"
            )
        )
        add("")
        add("| offered QPS | achieved | goodput | SLO attain | ttft p50 ms "
            "| p95 | p99 | qdelay p99 ms | growing | shed | unfinished |")
        add("|---" * 11 + "|")
        for pt in lg.get("points", []):
            add(
                f"| {_fmt(pt.get('offered_qps'))} | "
                f"{_fmt(pt.get('achieved_qps'))} | "
                f"{_fmt(pt.get('goodput_qps'))} | "
                f"{_fmt(pt.get('slo_attainment'))} | "
                f"{_fmt(pt.get('ttft_p50_ms'))} | "
                f"{_fmt(pt.get('ttft_p95_ms'))} | "
                f"{_fmt(pt.get('ttft_p99_ms'))} | "
                f"{_fmt(pt.get('queue_delay_p99_ms'))} | "
                f"{'yes' if pt.get('queue_growing') else ''} | "
                f"{_fmt(pt.get('shed'))} | {_fmt(pt.get('unfinished'))} |"
            )
    px = report.get("prefix")
    if px is not None:
        add("")
        add("## Prefix cache")
        add(
            f"- scope={px.get('scope')} engines={px.get('engines')} "
            f"budget={_fmt(px.get('budget_gib'))} GiB — hit rate: "
            f"**{_fmt(px.get('hit_rate'))}** "
            f"({_fmt(px.get('hits'))}/{_fmt(px.get('lookups'))} lookups)"
        )
        add(
            f"- prefill tokens saved: {_fmt(px.get('prefill_tokens_saved'))}"
            f"/{_fmt(px.get('prefill_tokens_total'))} "
            f"({_fmt(px.get('prefill_tokens_saved_frac'))} of all prefill) — "
            f"warm set {_fmt(px.get('pool_blocks_warm'))} blocks / "
            f"{_fmt(px.get('warm_bytes'))} bytes at last summary"
        )
    sp = report.get("spec")
    if sp is not None:
        add("")
        add("## Speculative decode")
        add(
            f"- scope={sp.get('scope')} engines={sp.get('engines')} "
            f"k={_fmt(sp.get('spec_tokens'))} "
            f"draft={_fmt(sp.get('draft_model'))} — accepted tokens per "
            f"step: **{_fmt(sp.get('accepted_tokens_per_step'))}** "
            "(plain decode = 1.0)"
        )
        add(
            f"- draft acceptance: {_fmt(sp.get('accepted_tokens'))}"
            f"/{_fmt(sp.get('drafted_tokens'))} proposals "
            f"(rate {_fmt(sp.get('acceptance_rate'))}) over "
            f"{_fmt(sp.get('spec_steps'))} verify rounds"
        )
    rec = report.get("recovery") or {}
    add("")
    add("## Recovery timeline")
    if rec.get("injections"):
        add(
            "- chaos injections: "
            + ", ".join(f"{i['kind']}@{i['step']}" for i in rec["injections"])
        )
    for a in rec.get("actions", []):
        if a.get("action") == "rewind":
            add(
                f"- **rewind** {a.get('rewind_index')}: anomaly "
                f"[{a.get('code')}] at step {a.get('step')} → restored step "
                f"{a.get('restored_step')} ({a.get('steps_lost')} steps lost, "
                f"{_fmt(a.get('recovery_wall_s'))} s)"
            )
        else:
            add(
                f"- **{a.get('action')}**: anomaly [{a.get('code')}] at step "
                f"{a.get('step')} — {a.get('reason', '')}"
            )
    for t in rec.get("topology", []):
        add(
            f"- **topology change** at step {t.get('step')} "
            f"(policy {t.get('policy')}): mesh was {t.get('old_mesh')} over "
            f"{t.get('old_processes')} process(es)"
            + (f" — {t['reason']}" if t.get("reason") else "")
        )
    for r in rec.get("reshards", []):
        add(
            f"- **reshard restore**: step {r.get('step')} re-laid "
            f"{r.get('old_mesh')}×{r.get('old_processes')}p → "
            f"{r.get('new_mesh')}×{r.get('new_processes')}p "
            f"(ef {r.get('ef_mode')}, {r.get('steps_lost', 0)} steps lost, "
            f"{_fmt(r.get('reshard_wall_s'))} s)"
        )
    for q in rec.get("quarantines", []):
        add(
            f"- quarantined batch (epoch {q.get('epoch')}, epoch_step "
            f"{q.get('epoch_step')}): {q.get('reason', '')}"
        )
    if rec.get("rewinds"):
        add(
            f"- {rec['rewinds']} rewind(s), {rec['steps_lost_total']} optimizer "
            f"steps lost, MTTR {_fmt(rec.get('mttr_s'))} s"
        )
    serving = rec.get("serving")
    if serving:
        for t in serving.get("replica_transitions", []):
            add(
                f"- **replica {t.get('replica')}** {t.get('from')} → "
                f"{t.get('to')} at tick {t.get('tick')}"
                + (f" [{t['cause']}]" if t.get("cause") else "")
                + f": {t.get('reason', '')}"
            )
        add(
            f"- serving tier: {serving.get('replicas_lost', 0)} replica(s) "
            f"lost, {serving.get('retries', 0)} request retr"
            f"{'y' if serving.get('retries', 0) == 1 else 'ies'}, "
            f"{serving.get('shed', 0)} shed "
            f"({serving.get('shed_by_reason') or {}}), request MTTR "
            f"{_fmt(serving.get('request_mttr_s'))} s, retry rate "
            f"{_fmt(serving.get('request_retry_rate'))}, goodput frac "
            f"{_fmt(serving.get('goodput_frac'))}"
        )
    for s in rec.get("host_loss_suspects", []):
        add(
            f"- **host_loss_suspect**: rank {s.get('rank')} named laggard "
            f"{s.get('consecutive_beats')} consecutive heartbeat(s) by "
            f"step {s.get('step')} (detection only — go look at that host)"
        )
    injected = [f for f in rec.get("faults", []) if f["injected"]]
    organic = rec.get("organic_faults", [])
    if not rec.get("faults"):
        add("- no faults observed")
    else:
        add(f"- faults: {len(injected)} injected, {len(organic)} organic")
        for f in organic:
            add(
                f"  - **organic** {f['kind']} at step {f['step']}: {f['detail']}"
            )
    add("")
    add(f"## Anomalies ({len(report['anomalies'])})")
    for a in report["anomalies"]:
        add(
            f"- step {a.get('step')} [{a.get('code')}] ranks {a.get('ranks')} "
            f"policy {a.get('policy')}: {a.get('detail', '')}"
        )
    for proc, rec in report["recorders"].items():
        add(
            f"- flight recorder p{proc}: reason {rec['reason']!r} at step "
            f"{rec['step']}, {rec['steps_recorded']} steps recorded"
        )
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llms_example_tpu.obs.report",
        description=__doc__,
    )
    p.add_argument("output_dir", help="a run's --output-dir (containing obs/)")
    p.add_argument("--json", action="store_true", help="emit the full report as JSON")
    p.add_argument("--last", type=int, default=20, help="timeline rows to render")
    p.add_argument(
        "--strict", action="store_true",
        help="nonzero exit on any schema-invalid line OR any ORGANIC fault "
             "(one no chaos_injection event explains) — a chaos run is "
             "green only when every fault it saw is one it caused — OR a "
             "wall-weighted dispatch_efficiency below "
             "--min-dispatch-efficiency",
    )
    p.add_argument(
        "--min-dispatch-efficiency", type=float, default=0.0,
        help="with --strict: fail when the run's wall-weighted "
             "dispatch_efficiency (step_budget events) falls below this "
             "floor (0 = no floor) — the trainer-loop-gap CI gate",
    )
    p.add_argument(
        "--min-overlap-frac", type=float, default=0.0,
        help="with --strict: fail when any rank's device_account shows "
             "collective device time with overlap_frac below this floor "
             "(0 = no floor), and fail when a profile was captured but NO "
             "device_account was emitted — a missing device measurement "
             "must never read as a pass",
    )
    p.add_argument(
        "--max-gradient-bytes-per-step", type=float, default=0.0,
        help="with --strict: fail when the startup gauges' collective "
             "byte account (obs_gauges.comm.gradient_bytes) exceeds this "
             "ceiling, or when NO obs_gauges record exists (0 = no "
             "ceiling) — the compression gate: a run that silently loses "
             "--grad-compression (flag ignored, partitioner folded the "
             "wire back to fp32) fails here instead of passing on "
             "wall-clock luck",
    )
    p.add_argument(
        "--max-request-retry-rate", type=float, default=-1.0,
        help="with --strict: fail when the serving router's "
             "request_retry_rate (router_summary) exceeds this ceiling, "
             "or when NO router_summary exists (-1 = the gate is off; 0 "
             "is a valid ceiling: any retry fails) — the serve-router "
             "retry-storm gate",
    )
    p.add_argument(
        "--min-serve-goodput-frac", type=float, default=0.0,
        help="with --strict: fail when the serving router's goodput_frac "
             "(requests completed within the TTFT SLO over requests "
             "submitted, router_summary) falls below this floor, or when "
             "NO router_summary exists (0 = the gate is off) — a missing "
             "serving measurement must never read as a pass",
    )
    p.add_argument(
        "--min-slo-attainment", type=float, default=0.0,
        help="with --strict: fail when the open-loop load sweep's BEST "
             "per-point slo_attainment (loadgen_point/loadgen_summary "
             "events) falls below this floor — if even the best offered "
             "rate cannot meet it, the deployment cannot — or when NO "
             "loadgen measurement exists (0 = the gate is off); a "
             "missing measurement must never read as a pass",
    )
    p.add_argument(
        "--max-p99-ttft-ms", type=float, default=0.0,
        help="with --strict: fail when the open-loop load sweep's lowest "
             "MEASURED per-point p99 TTFT (from arrival) exceeds this "
             "ceiling, or when no point measured one (nothing finished, "
             "or no loadgen run at all) (0 = the gate is off); a missing "
             "measurement must never read as a pass",
    )
    p.add_argument(
        "--min-prefix-hit-rate", type=float, default=0.0,
        help="with --strict: fail when the prefix cache's hit rate "
             "(prefix_hit_rate — the router aggregate when one exists, "
             "else the last prefix-enabled serve_summary) falls below "
             "this floor, or when NO prefix-enabled summary exists at "
             "all (0 = the gate is off); a run that silently loses "
             "--prefix-cache must fail here, never pass unmeasured",
    )
    p.add_argument(
        "--min-acceptance-rate", type=float, default=0.0,
        help="with --strict: fail when speculative decode's draft "
             "acceptance rate (acceptance_rate — the router aggregate "
             "when one exists, else the last spec-enabled serve_summary) "
             "falls below this floor, or when NO spec-enabled summary "
             "exists at all (0 = the gate is off); a run that silently "
             "loses --spec-tokens must fail here, never pass unmeasured",
    )
    p.add_argument(
        "--max-peak-hbm-frac", type=float, default=0.0,
        help="with --strict: fail when the measured HBM peak (the runtime "
             "memory_window peak where sampled, else the static account's "
             "compiled peak) exceeds this fraction of the account's "
             "--hbm-budget-gib ceiling, or when NO memory measurement "
             "exists at all (0 = the gate is off); a missing measurement "
             "must never read as a pass",
    )
    p.add_argument(
        "--min-hbm-headroom-gib", type=float, default=0.0,
        help="with --strict: fail when any memory account's "
             "hbm_headroom_gib (budget minus peak) falls below this floor, "
             "or when NO memory account exists (0 = the gate is off); a "
             "missing measurement must never read as a pass",
    )
    p.add_argument(
        "--trace", type=str, default="",
        help="also export the merged Chrome-trace/Perfetto JSON here "
             "(every rank's spans aligned on shared step boundaries, "
             "budget counters, serving request lifecycles) — open at "
             "ui.perfetto.dev",
    )
    args = p.parse_args(argv)
    if not os.path.isdir(os.path.join(args.output_dir, "obs")):
        print(f"no obs/ directory under {args.output_dir}", file=sys.stderr)
        return 2
    report = build_report(args.output_dir)
    if args.json:
        print(json.dumps(report))
    else:
        print(render_markdown(report, last=args.last), end="")
    if args.trace:
        from distributed_llms_example_tpu.obs.trace import export_chrome_trace

        summary = export_chrome_trace(args.output_dir, args.trace)
        print(
            f"trace: {summary['events']} events from ranks "
            f"{summary['ranks']} → {summary['path']}",
            file=sys.stderr,
        )
    rc = 0
    if args.strict:
        if report["schema_errors"] or report["recovery"]["organic_faults"]:
            rc = 1
        floor = args.min_dispatch_efficiency
        budget = report.get("budget")
        if floor > 0:
            eff = budget["dispatch_efficiency"] if budget else None
            if eff is None:
                print(
                    "strict: --min-dispatch-efficiency set but no "
                    "step_budget records found", file=sys.stderr,
                )
                rc = 1
            elif eff < floor:
                print(
                    f"strict: dispatch_efficiency {eff} below the "
                    f"{floor} floor", file=sys.stderr,
                )
                rc = 1
        grad_ceiling = args.max_gradient_bytes_per_step
        if grad_ceiling > 0:
            comm = report.get("comm")
            worst = None
            if comm is not None and isinstance(comm.get("comm"), dict):
                worst = float(comm["comm"].get("gradient_bytes", 0))
            if worst is None:
                print(
                    "strict: --max-gradient-bytes-per-step set but no "
                    "obs_gauges byte account found (run with --obs-gauges "
                    "on) — a missing measurement must never read as a pass",
                    file=sys.stderr,
                )
                rc = 1
            elif worst > grad_ceiling:
                print(
                    f"strict: gradient_bytes per step {worst:.0f} exceeds "
                    f"the {grad_ceiling:.0f} ceiling — compression lost or "
                    "never engaged (check grad_compression in the "
                    "obs_gauges record)",
                    file=sys.stderr,
                )
                rc = 1
        serving = report["recovery"].get("serving")
        if args.max_request_retry_rate >= 0:
            rate = (serving or {}).get("request_retry_rate")
            if rate is None:
                print(
                    "strict: --max-request-retry-rate set but no "
                    "router_summary record found (serve-router run "
                    "required) — a missing measurement must never read "
                    "as a pass", file=sys.stderr,
                )
                rc = 1
            elif rate > args.max_request_retry_rate:
                print(
                    f"strict: request_retry_rate {rate} exceeds the "
                    f"{args.max_request_retry_rate} ceiling — the pool is "
                    "retry-storming (dying replicas or a too-tight "
                    "deadline/backoff config)", file=sys.stderr,
                )
                rc = 1
        if args.min_serve_goodput_frac > 0:
            frac = (serving or {}).get("goodput_frac")
            if frac is None:
                print(
                    "strict: --min-serve-goodput-frac set but no "
                    "router_summary record found (serve-router run "
                    "required) — a missing measurement must never read "
                    "as a pass", file=sys.stderr,
                )
                rc = 1
            elif frac < args.min_serve_goodput_frac:
                print(
                    f"strict: goodput_frac {frac} below the "
                    f"{args.min_serve_goodput_frac} floor — requests are "
                    "being shed or missing the TTFT SLO", file=sys.stderr,
                )
                rc = 1
        lg = report.get("loadgen")
        if args.min_slo_attainment > 0:
            best = (lg or {}).get("best_slo_attainment")
            if best is None:
                print(
                    "strict: --min-slo-attainment set but no loadgen "
                    "measurement found (run the open-loop load sweep — "
                    "serving/loadgen.py) — a missing measurement must "
                    "never read as a pass", file=sys.stderr,
                )
                rc = 1
            elif best < args.min_slo_attainment:
                print(
                    f"strict: best per-point slo_attainment {best} below "
                    f"the {args.min_slo_attainment} floor — no offered "
                    "rate on the sweep grid meets the SLO",
                    file=sys.stderr,
                )
                rc = 1
        if args.max_p99_ttft_ms > 0:
            best = (lg or {}).get("best_ttft_p99_ms")
            if best is None:
                print(
                    "strict: --max-p99-ttft-ms set but no measured p99 "
                    "TTFT found (no loadgen run, or nothing finished at "
                    "any offered rate) — a missing measurement must "
                    "never read as a pass", file=sys.stderr,
                )
                rc = 1
            elif best > args.max_p99_ttft_ms:
                print(
                    f"strict: best per-point p99 TTFT {best} ms exceeds "
                    f"the {args.max_p99_ttft_ms} ms ceiling at every "
                    "offered rate on the sweep grid", file=sys.stderr,
                )
                rc = 1
        if args.min_prefix_hit_rate > 0:
            rate = (report.get("prefix") or {}).get("hit_rate")
            if rate is None:
                print(
                    "strict: --min-prefix-hit-rate set but no "
                    "prefix-enabled serve_summary found (run with "
                    "--prefix-cache on a paged engine) — a missing "
                    "measurement must never read as a pass",
                    file=sys.stderr,
                )
                rc = 1
            elif rate < args.min_prefix_hit_rate:
                print(
                    f"strict: prefix_hit_rate {rate} below the "
                    f"{args.min_prefix_hit_rate} floor — the workload is "
                    "not sharing prefixes, the warm budget is too small, "
                    "or custom attention masks made requests ineligible",
                    file=sys.stderr,
                )
                rc = 1
        if args.min_acceptance_rate > 0:
            rate = (report.get("spec") or {}).get("acceptance_rate")
            if rate is None:
                print(
                    "strict: --min-acceptance-rate set but no "
                    "spec-enabled serve_summary found (run with "
                    "--spec-tokens > 0) — a missing measurement must "
                    "never read as a pass",
                    file=sys.stderr,
                )
                rc = 1
            elif rate < args.min_acceptance_rate:
                print(
                    f"strict: acceptance_rate {rate} below the "
                    f"{args.min_acceptance_rate} floor — the drafter is "
                    "mispredicting this workload (try a draft model, "
                    "fewer --spec-tokens, or a more repetitive mix)",
                    file=sys.stderr,
                )
                rc = 1
        mem = report.get("memory")
        if args.max_peak_hbm_frac > 0:
            frac = (mem or {}).get("peak_frac_of_budget")
            if frac is None:
                print(
                    "strict: --max-peak-hbm-frac set but no memory "
                    "measurement found (no memory_window samples and no "
                    "memory_account — run with --obs jsonl so the startup "
                    "gauges emit the static account) — a missing "
                    "measurement must never read as a pass", file=sys.stderr,
                )
                rc = 1
            elif frac > args.max_peak_hbm_frac:
                src = (mem or {}).get("measured_peak_source")
                print(
                    f"strict: HBM peak at {frac} of the budget "
                    f"(source: {src}) exceeds the {args.max_peak_hbm_frac} "
                    "ceiling — where the bytes went is in the report's "
                    "memory section", file=sys.stderr,
                )
                rc = 1
        if args.min_hbm_headroom_gib > 0:
            headroom = (mem or {}).get("min_headroom_gib")
            if headroom is None:
                print(
                    "strict: --min-hbm-headroom-gib set but no memory "
                    "account found (run with --obs jsonl so the startup "
                    "gauges emit the static account) — a missing "
                    "measurement must never read as a pass", file=sys.stderr,
                )
                rc = 1
            elif headroom < args.min_hbm_headroom_gib:
                print(
                    f"strict: hbm_headroom_gib {headroom} below the "
                    f"{args.min_hbm_headroom_gib} GiB floor — the config "
                    "is one allocation spike from an OOM", file=sys.stderr,
                )
                rc = 1
        ov_floor = args.min_overlap_frac
        if ov_floor > 0:
            device = report.get("device")
            if device is None or not device["ranks"]:
                # a capture with no parsed account is a broken pipeline;
                # no capture at all is a missing measurement — both fail
                # a gate that was explicitly asked to look at overlap
                print(
                    "strict: --min-overlap-frac set but no device_account "
                    "records found"
                    + (
                        f" ({len(device['captures'])} profile capture(s) "
                        "landed without one)"
                        if device is not None
                        else ""
                    ),
                    file=sys.stderr,
                )
                rc = 1
            else:
                for rank, acct in sorted(device["ranks"].items()):
                    frac = (acct.get("overlap") or {}).get("overlap_frac")
                    if frac is not None and frac < ov_floor:
                        print(
                            f"strict: rank {rank} overlap_frac {frac} below "
                            f"the {ov_floor} floor (exposed collective time)",
                            file=sys.stderr,
                        )
                        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
