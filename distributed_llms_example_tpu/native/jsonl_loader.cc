// Native JSONL record loader.
//
// The reference delegates data loading to `datasets.load_dataset('json')`
// (reference train-torchrun.py:153-159), whose hot path is Arrow's C++
// JSON reader — i.e. the reference's data layer is native code consumed
// through a Python API.  This is the TPU framework's equivalent: a small
// C++ parser for line-delimited JSON records that the Python data layer
// (data/dataset.py) uses for large corpus files, with the pure-Python
// json.loads path as the always-available fallback.
//
// Scope: one JSON *object* per line (the JSONL the summarization corpora
// use).  String values are unescaped here (including \uXXXX surrogate
// pairs -> UTF-8); non-string values (numbers, bools, null, nested
// arrays/objects) are returned as raw JSON text tagged kind=1 for the
// Python side to json.loads on demand — flat string records never touch
// Python's parser at all.
//
// ABI: everything is packed into contiguous arrays (one arena of bytes +
// offset/length arrays indexed per field, plus a per-record field-range
// array), so the ctypes wrapper does O(1) pointer reads per *load*, not
// per field.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Parsed {
  std::string arena;             // all key/value bytes, concatenated
  std::vector<int64_t> rec_start;  // n_records+1 entries into field arrays
  std::vector<int64_t> key_off, key_len, val_off, val_len;
  std::vector<int8_t> kind;      // 0 = string (unescaped), 1 = raw JSON text
  std::string error;             // non-empty => load failed
};

struct Cursor {
  const char* p;
  const char* end;
  int64_t line;  // 1-based, for error messages
};

void skip_ws(Cursor& c) {
  while (c.p < c.end && (*c.p == ' ' || *c.p == '\t' || *c.p == '\r')) c.p++;
}

bool fail(Parsed& out, const Cursor& c, const char* msg) {
  char buf[160];
  snprintf(buf, sizeof(buf), "line %lld: %s", static_cast<long long>(c.line), msg);
  out.error = buf;
  return false;
}

// Appends the UTF-8 encoding of `cp` to `arena`.
void utf8_append(std::string& arena, uint32_t cp) {
  if (cp <= 0x7F) {
    arena.push_back(static_cast<char>(cp));
  } else if (cp <= 0x7FF) {
    arena.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    arena.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp <= 0xFFFF) {
    arena.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    arena.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    arena.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    arena.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    arena.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    arena.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    arena.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// Strict UTF-8 well-formedness (RFC 3629: continuation ranges, no
// overlongs, no surrogates, max U+10FFFF).  Invalid bytes must fail at
// PARSE time — past load, the Python fallback can no longer engage and a
// bad byte would surface as UnicodeDecodeError at record-access time.
bool utf8_valid(const unsigned char* s, size_t n) {
  size_t i = 0;
  while (i < n) {
    unsigned char b = s[i];
    if (b < 0x80) {
      i++;
    } else if (b >= 0xC2 && b <= 0xDF) {
      if (i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return false;
      i += 2;
    } else if (b == 0xE0) {
      if (i + 2 >= n || s[i + 1] < 0xA0 || s[i + 1] > 0xBF || (s[i + 2] & 0xC0) != 0x80) return false;
      i += 3;
    } else if ((b >= 0xE1 && b <= 0xEC) || b == 0xEE || b == 0xEF) {
      if (i + 2 >= n || (s[i + 1] & 0xC0) != 0x80 || (s[i + 2] & 0xC0) != 0x80) return false;
      i += 3;
    } else if (b == 0xED) {  // exclude surrogates U+D800..U+DFFF
      if (i + 2 >= n || s[i + 1] < 0x80 || s[i + 1] > 0x9F || (s[i + 2] & 0xC0) != 0x80) return false;
      i += 3;
    } else if (b == 0xF0) {
      if (i + 3 >= n || s[i + 1] < 0x90 || s[i + 1] > 0xBF || (s[i + 2] & 0xC0) != 0x80 ||
          (s[i + 3] & 0xC0) != 0x80)
        return false;
      i += 4;
    } else if (b >= 0xF1 && b <= 0xF3) {
      if (i + 3 >= n || (s[i + 1] & 0xC0) != 0x80 || (s[i + 2] & 0xC0) != 0x80 ||
          (s[i + 3] & 0xC0) != 0x80)
        return false;
      i += 4;
    } else if (b == 0xF4) {  // cap at U+10FFFF
      if (i + 3 >= n || s[i + 1] < 0x80 || s[i + 1] > 0x8F || (s[i + 2] & 0xC0) != 0x80 ||
          (s[i + 3] & 0xC0) != 0x80)
        return false;
      i += 4;
    } else {
      return false;  // 0x80-0xC1 (stray continuation / overlong), 0xF5+
    }
  }
  return true;
}

int hex_val(char ch) {
  if (ch >= '0' && ch <= '9') return ch - '0';
  if (ch >= 'a' && ch <= 'f') return ch - 'a' + 10;
  if (ch >= 'A' && ch <= 'F') return ch - 'A' + 10;
  return -1;
}

bool parse_u16(Cursor& c, Parsed& out, uint32_t* v) {
  if (c.end - c.p < 4) return fail(out, c, "truncated \\u escape");
  uint32_t x = 0;
  for (int i = 0; i < 4; i++) {
    int h = hex_val(c.p[i]);
    if (h < 0) return fail(out, c, "bad hex digit in \\u escape");
    x = (x << 4) | static_cast<uint32_t>(h);
  }
  c.p += 4;
  *v = x;
  return true;
}

// Parses a JSON string (cursor on the opening quote); appends the decoded
// bytes to out.arena and records [off, len).
bool parse_string(Cursor& c, Parsed& out, int64_t* off, int64_t* len) {
  if (*c.p != '"') return fail(out, c, "expected string");
  c.p++;
  *off = static_cast<int64_t>(out.arena.size());
  while (c.p < c.end) {
    unsigned char ch = static_cast<unsigned char>(*c.p);
    if (ch == '"') {
      c.p++;
      *len = static_cast<int64_t>(out.arena.size()) - *off;
      // escape-decoded bytes are valid by construction; raw bytes copied
      // from the input may not be — validate the completed value once
      if (!utf8_valid(
              reinterpret_cast<const unsigned char*>(out.arena.data()) + *off,
              static_cast<size_t>(*len)))
        return fail(out, c, "invalid UTF-8 in string");
      return true;
    }
    if (ch == '\\') {
      c.p++;
      if (c.p >= c.end) return fail(out, c, "truncated escape");
      char e = *c.p++;
      switch (e) {
        case '"': out.arena.push_back('"'); break;
        case '\\': out.arena.push_back('\\'); break;
        case '/': out.arena.push_back('/'); break;
        case 'b': out.arena.push_back('\b'); break;
        case 'f': out.arena.push_back('\f'); break;
        case 'n': out.arena.push_back('\n'); break;
        case 'r': out.arena.push_back('\r'); break;
        case 't': out.arena.push_back('\t'); break;
        case 'u': {
          uint32_t cp;
          if (!parse_u16(c, out, &cp)) return false;
          if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
            if (c.end - c.p >= 6 && c.p[0] == '\\' && c.p[1] == 'u') {
              c.p += 2;
              uint32_t lo;
              if (!parse_u16(c, out, &lo)) return false;
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              } else {
                return fail(out, c, "unpaired surrogate in \\u escape");
              }
            } else {
              return fail(out, c, "unpaired surrogate in \\u escape");
            }
          } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
            // a lone LOW surrogate would encode as invalid UTF-8 and blow
            // up at record-access time, past the Python-fallback window —
            // reject at parse time like the lone-high case
            return fail(out, c, "unpaired surrogate in \\u escape");
          }
          utf8_append(out.arena, cp);
          break;
        }
        default:
          return fail(out, c, "unknown escape character");
      }
      continue;
    }
    if (ch == '\n') return fail(out, c, "unescaped newline inside string");
    out.arena.push_back(static_cast<char>(ch));
    c.p++;
  }
  return fail(out, c, "unterminated string");
}

// Shallow validity check for a raw (non-string) value: exact keyword, a
// well-formed number, or a container (whose innards json.loads re-checks
// lazily on the Python side when the field is actually read).
bool valid_raw(const char* s, const char* end) {
  size_t n = static_cast<size_t>(end - s);
  if (n == 0) return false;
  if (*s == '{' || *s == '[') return true;
  if (n == 4 && memcmp(s, "true", 4) == 0) return true;
  if (n == 4 && memcmp(s, "null", 4) == 0) return true;
  if (n == 5 && memcmp(s, "false", 5) == 0) return true;
  // number: -?int(.frac)?((e|E)(+|-)?digits)?
  const char* p = s;
  if (p < end && *p == '-') p++;
  const char* digits0 = p;
  while (p < end && *p >= '0' && *p <= '9') p++;
  if (p == digits0) return false;
  if (p < end && *p == '.') {
    p++;
    const char* frac0 = p;
    while (p < end && *p >= '0' && *p <= '9') p++;
    if (p == frac0) return false;
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    p++;
    if (p < end && (*p == '+' || *p == '-')) p++;
    const char* exp0 = p;
    while (p < end && *p >= '0' && *p <= '9') p++;
    if (p == exp0) return false;
  }
  return p == end;
}

// Raw-scans one non-string JSON value (number/true/false/null/array/object)
// verbatim into the arena.  Balanced-bracket scan that respects strings.
bool parse_raw(Cursor& c, Parsed& out, int64_t* off, int64_t* len) {
  *off = static_cast<int64_t>(out.arena.size());
  const char* start = c.p;
  int depth = 0;
  bool in_str = false;
  while (c.p < c.end) {
    char ch = *c.p;
    if (in_str) {
      if (ch == '\\') {
        if (c.p + 2 > c.end) return fail(out, c, "truncated escape");
        if (c.p[1] == '\n') return fail(out, c, "unescaped newline inside string");
        c.p += 2;
        continue;
      }
      if (ch == '"') in_str = false;
      if (ch == '\n') return fail(out, c, "unescaped newline inside string");
      c.p++;
      continue;
    }
    if (ch == '"') {
      in_str = true;
      c.p++;
      continue;
    }
    if (ch == '{' || ch == '[') depth++;
    if (ch == '}' || ch == ']') {
      if (depth == 0) break;  // the enclosing object's '}' or a bare ']' — stop
      depth--;
    }
    if (depth == 0 && (ch == ',' || ch == '\n')) break;
    c.p++;
  }
  // trim trailing whitespace from the raw slice
  const char* stop = c.p;
  while (stop > start && (stop[-1] == ' ' || stop[-1] == '\t' || stop[-1] == '\r')) stop--;
  if (stop == start) return fail(out, c, "empty value");
  if (!valid_raw(start, stop)) return fail(out, c, "invalid JSON value");
  out.arena.append(start, static_cast<size_t>(stop - start));
  *len = static_cast<int64_t>(out.arena.size()) - *off;
  return true;
}

// Parses one `{...}` object (cursor on '{'); records fields into `out`.
bool parse_object(Cursor& c, Parsed& out) {
  if (*c.p != '{') return fail(out, c, "expected '{' at record start");
  c.p++;
  skip_ws(c);
  if (c.p < c.end && *c.p == '}') {
    c.p++;
    return true;
  }
  while (true) {
    skip_ws(c);
    int64_t ko, kl;
    if (c.p >= c.end) return fail(out, c, "truncated record");
    if (!parse_string(c, out, &ko, &kl)) return false;
    skip_ws(c);
    if (c.p >= c.end || *c.p != ':') return fail(out, c, "expected ':'");
    c.p++;
    skip_ws(c);
    if (c.p >= c.end) return fail(out, c, "truncated record");
    int64_t vo, vl;
    int8_t kind;
    if (*c.p == '"') {
      if (!parse_string(c, out, &vo, &vl)) return false;
      kind = 0;
    } else {
      if (!parse_raw(c, out, &vo, &vl)) return false;
      kind = 1;
    }
    out.key_off.push_back(ko);
    out.key_len.push_back(kl);
    out.val_off.push_back(vo);
    out.val_len.push_back(vl);
    out.kind.push_back(kind);
    skip_ws(c);
    if (c.p >= c.end) return fail(out, c, "truncated record");
    if (*c.p == ',') {
      c.p++;
      continue;
    }
    if (*c.p == '}') {
      c.p++;
      return true;
    }
    return fail(out, c, "expected ',' or '}'");
  }
}

}  // namespace

extern "C" {

struct DllmJsonl {
  Parsed* parsed;
  // flat view for ctypes
  int64_t n_records;
  int64_t n_fields;
  const char* arena;
  int64_t arena_len;
  const int64_t* rec_start;
  const int64_t* key_off;
  const int64_t* key_len;
  const int64_t* val_off;
  const int64_t* val_len;
  const int8_t* kind;
  const char* error;  // non-null => failed load (handle still must be freed)
};

DllmJsonl* dllm_jsonl_parse(const char* data, int64_t size) {
  auto* h = new DllmJsonl();
  auto* out = new Parsed();
  h->parsed = out;
  // reserve using a cheap heuristic to avoid repeated arena reallocation
  out->arena.reserve(static_cast<size_t>(size));

  Cursor c{data, data + size, 1};
  while (c.p < c.end) {
    skip_ws(c);
    if (c.p < c.end && *c.p == '\n') {  // blank line
      c.p++;
      c.line++;
      continue;
    }
    if (c.p >= c.end) break;
    out->rec_start.push_back(static_cast<int64_t>(out->key_off.size()));
    if (!parse_object(c, *out)) break;
    skip_ws(c);
    if (c.p < c.end) {
      if (*c.p != '\n') {
        fail(*out, c, "trailing characters after record");
        break;
      }
      c.p++;
      c.line++;
    }
  }

  if (!out->error.empty()) {
    h->error = out->error.c_str();
    h->n_records = 0;
    return h;
  }
  out->rec_start.push_back(static_cast<int64_t>(out->key_off.size()));
  h->error = nullptr;
  h->n_records = static_cast<int64_t>(out->rec_start.size()) - 1;
  h->n_fields = static_cast<int64_t>(out->key_off.size());
  h->arena = out->arena.data();
  h->arena_len = static_cast<int64_t>(out->arena.size());
  h->rec_start = out->rec_start.data();
  h->key_off = out->key_off.data();
  h->key_len = out->key_len.data();
  h->val_off = out->val_off.data();
  h->val_len = out->val_len.data();
  h->kind = out->kind.data();
  return h;
}

DllmJsonl* dllm_jsonl_load(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    auto* h = new DllmJsonl();
    auto* out = new Parsed();
    out->error = std::string("cannot open ") + path;
    h->parsed = out;
    h->error = out->error.c_str();
    h->n_records = 0;
    return h;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  size_t got = fread(buf.data(), 1, static_cast<size_t>(size), f);
  fclose(f);
  return dllm_jsonl_parse(buf.data(), static_cast<int64_t>(got));
}

void dllm_jsonl_free(DllmJsonl* h) {
  if (!h) return;
  delete h->parsed;
  delete h;
}

}  // extern "C"
