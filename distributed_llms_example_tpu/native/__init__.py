"""Native (C++) runtime components: the JSONL record loader.

The reference's data layer bottoms out in native code too — `datasets.
load_dataset('json')` (reference train-torchrun.py:153-159) runs Arrow's
C++ JSON reader.  Here the equivalent is ``jsonl_loader.cc``: a C++ parser
for line-delimited JSON records, compiled on demand with the toolchain's
g++ into ``_jsonl.so`` next to this file, consumed through a zero-copy
ctypes view.  ``data/dataset.py`` routes large JSONL files through it and
keeps the pure-Python ``json.loads`` path as the always-available fallback
(``available()`` gates every use).

Record values that are JSON strings are unescaped in C++; anything else
(numbers, bools, null, nested values) arrives as raw JSON text and is
parsed by ``json.loads`` only when that field is actually read.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import Iterator, Sequence

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "jsonl_loader.cc")
_SO = os.path.join(_DIR, "_jsonl.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_error: str | None = None


class _DllmJsonl(ctypes.Structure):
    _fields_ = [
        ("parsed", ctypes.c_void_p),
        ("n_records", ctypes.c_int64),
        ("n_fields", ctypes.c_int64),
        ("arena", ctypes.c_void_p),
        ("arena_len", ctypes.c_int64),
        ("rec_start", ctypes.POINTER(ctypes.c_int64)),
        ("key_off", ctypes.POINTER(ctypes.c_int64)),
        ("key_len", ctypes.POINTER(ctypes.c_int64)),
        ("val_off", ctypes.POINTER(ctypes.c_int64)),
        ("val_len", ctypes.POINTER(ctypes.c_int64)),
        ("kind", ctypes.POINTER(ctypes.c_int8)),
        ("error", ctypes.c_char_p),
    ]


def _build() -> str | None:
    """Compile the shared library if needed; returns an error string or None.

    Compiles to a per-process temp name and renames into place: the rename
    is atomic, so concurrent builders race harmlessly and an interrupted
    build can never leave a truncated ``_jsonl.so`` that passes the mtime
    check forever."""
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return None
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-std=c++17", "-O2", "-shared", "-fPIC", _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ unavailable: {e}"
    if proc.returncode != 0:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return f"g++ failed: {proc.stderr[-500:]}"
    os.replace(tmp, _SO)
    return None


def _load_lib() -> ctypes.CDLL | None:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            _build_error = str(e)
            return None
        lib.dllm_jsonl_load.argtypes = [ctypes.c_char_p]
        lib.dllm_jsonl_load.restype = ctypes.POINTER(_DllmJsonl)
        lib.dllm_jsonl_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.dllm_jsonl_parse.restype = ctypes.POINTER(_DllmJsonl)
        lib.dllm_jsonl_free.argtypes = [ctypes.POINTER(_DllmJsonl)]
        lib.dllm_jsonl_free.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native loader compiled and loaded on this machine."""
    return _load_lib() is not None


def build_error() -> str | None:
    """Why ``available()`` is False (None while it's True/untried)."""
    return _build_error


class JsonlRecords(Sequence):
    """Zero-copy lazy view over a parsed JSONL file.

    ``records[i]`` materializes one dict; string fields are decoded
    straight out of the C++ arena, non-string fields go through
    ``json.loads`` of their raw text.  Works as the ``records`` sequence
    the (lazy) datasets consume — nothing is materialized until accessed.
    """

    def __init__(self, handle, lib: ctypes.CDLL):
        self._h = handle
        self._lib = lib
        c = handle.contents
        self._n = int(c.n_records)
        self._arena = (ctypes.c_char * c.arena_len).from_address(c.arena) if c.arena_len else b""

    def __len__(self) -> int:
        return self._n

    def _field(self, j: int) -> tuple[str, object]:
        c = self._h.contents
        # slicing a ctypes char array already yields fresh bytes — no copy
        key = self._arena[c.key_off[j] : c.key_off[j] + c.key_len[j]].decode("utf-8")
        raw = self._arena[c.val_off[j] : c.val_off[j] + c.val_len[j]]
        if c.kind[j] == 0:
            return key, raw.decode("utf-8")
        return key, json.loads(raw)

    def __getitem__(self, i: int) -> dict:
        if i < 0:
            i += self._n  # list-parity negative indexing
        if not 0 <= i < self._n:
            raise IndexError(i)
        c = self._h.contents
        return dict(self._field(j) for j in range(c.rec_start[i], c.rec_start[i + 1]))

    def __iter__(self) -> Iterator[dict]:
        for i in range(self._n):
            yield self[i]

    def __del__(self) -> None:
        h, self._h = getattr(self, "_h", None), None
        if h is not None:
            self._lib.dllm_jsonl_free(h)


def load_jsonl(path: str) -> JsonlRecords:
    """Parse a JSONL file with the native loader.

    Raises ``RuntimeError`` if the loader isn't available (callers gate on
    ``available()``) and ``ValueError`` on malformed input, with the line
    number from the C++ parser.
    """
    lib = _load_lib()
    if lib is None:
        raise RuntimeError(f"native jsonl loader unavailable: {_build_error}")
    handle = lib.dllm_jsonl_load(os.fspath(path).encode())
    if handle.contents.error:
        msg = handle.contents.error.decode()
        lib.dllm_jsonl_free(handle)
        raise ValueError(f"{path}: {msg}")
    return JsonlRecords(handle, lib)
