"""Activation-checkpointing (remat) policies for transformer blocks.

``--remat`` trades compute for memory by recomputing block activations in
the backward pass.  The *policy* decides what still gets saved:

- ``full``: save nothing — maximum memory savings, recomputes the whole
  block (the ~27%-throughput cost measured in bench.py's comment).
- ``dots``: ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``
  — save matmul outputs (cheap to store, expensive to recompute on the
  MXU) and recompute only the elementwise/softmax glue (cheap to
  recompute, expensive to store).  The standard middle ground for
  7B-class models that fit activations-of-matmuls but not everything.

Numerics are identical across policies (remat never changes math, only
what is recomputed); ``tests/test_train_step.py`` asserts it.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax

POLICIES: dict[str, Any] = {
    "full": None,
    "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}

# keep the CLI choices (core/config.py, importable without jax) in sync
from distributed_llms_example_tpu.core.config import REMAT_POLICIES  # noqa: E402

assert set(REMAT_POLICIES) == set(POLICIES), (REMAT_POLICIES, tuple(POLICIES))


def remat_block(cls: Any, static_argnums: Sequence[int], policy: str = "full") -> Any:
    """``nn.remat`` wrapper honoring a named checkpoint policy."""
    try:
        chosen = POLICIES[policy]
    except KeyError:
        raise ValueError(
            f"remat_policy={policy!r}: must be one of {sorted(POLICIES)}"
        ) from None
    if chosen is None:
        return nn.remat(cls, static_argnums=tuple(static_argnums))
    return nn.remat(cls, static_argnums=tuple(static_argnums), policy=chosen)
