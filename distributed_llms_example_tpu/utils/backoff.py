"""Capped-exponential retry backoff — the ONE owner of retry sleeps.

Every transient-failure retry in the package (checkpoint save, dataset
reads, the trainer's injected data-error path, the serving router's
wall-clock waits) sleeps through ``sleep_backoff`` so the retry policy
has a single definition: capped exponential growth, never negative,
always logged by the caller BEFORE the sleep (the event carries the
delay, so a stuck run's log says what it is waiting for).

Repo-lint rule 12 enforces the ownership: a ``time.sleep`` inside an
``except`` handler anywhere else in the package is an ad-hoc retry loop
— unbounded, uncapped, invisible to this policy — and fails the lint.
The serving router's retry backoff is TICK-based (deterministic router
scheduling, no wall sleeps); this module is for the paths that genuinely
wait on wall-clock external state (storage, filesystems).
"""

from __future__ import annotations

import time


def sleep_backoff(delay_s: float, *, cap_s: float, factor: float = 2.0) -> float:
    """Sleep ``delay_s`` seconds and return the NEXT delay in the capped
    exponential schedule (``min(delay_s * factor, cap_s)``) — callers
    fold it back into their loop variable:

        delay = sleep_backoff(delay, cap_s=2.0)
    """
    time.sleep(max(0.0, float(delay_s)))
    return min(float(delay_s) * float(factor), float(cap_s))


def backoff_ticks(retries: int, *, base: int = 2, cap: int = 16) -> int:
    """The deterministic (tick-unit) twin of ``sleep_backoff`` for the
    serving router: how many scheduler ticks a request waits before its
    ``retries``-th re-dispatch.  No wall clock, no sleep — the router's
    failure handling stays reproducible under test."""
    if retries <= 0:
        return 0
    return min(int(base) * (2 ** (int(retries) - 1)), int(cap))
