from distributed_llms_example_tpu.utils.jsonlog import MetricLogger, log_json

__all__ = ["MetricLogger", "log_json"]
