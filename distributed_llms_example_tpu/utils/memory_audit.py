"""Compile-only per-device memory audit for the large BASELINE configs.

BASELINE.md configs 4-5 (flan-t5-xl FSDP, llama-2-7b bf16 + grad
checkpointing) must fit a v5e chip's 16 GB HBM.  Rather than hoping, this
audits the ACTUAL compiled train step: the full sharded program is lowered
and compiled ahead-of-time from abstract (ShapeDtypeStruct) arguments — no
parameters are ever materialized — and XLA's ``memory_analysis()`` reports
per-device argument/output/temp sizes, from which the peak is

    peak ≈ arguments + temps + (outputs - aliased)

(donated state aliases its output buffers, so steady-state outputs are
nearly free).  Run as a module for the audit JSON line:

    python -m distributed_llms_example_tpu.utils.memory_audit \
        --model llama-2-7b --mesh fsdp=8 --batch 8 --remat

Two views are reported:

- ``compiled_*``: XLA's own buffer accounting for the current backend.
  Authoritative when that backend is TPU; on the CPU test mesh XLA's
  buffer assignment is far more conservative (measured: remat does not
  reduce CPU temp bytes at all), so the compiled figures OVERSTATE TPU
  usage there and are reported for reference only.
- ``analytic_*``: exact sharding-aware byte counts for state/grads (from
  ``NamedSharding.shard_shape``, no estimation) plus a structural model of
  the remat activation footprint (per-block boundary saves + one block's
  recompute working set + fp32 logits/loss buffers).  Backend-independent;
  this is what the fit assertion uses off-TPU.
"""

from __future__ import annotations

import argparse
from typing import Any

HBM_BYTES_V5E = 16 * 1024**3


def _shard_bytes(tree: Any, shardings: Any) -> int:
    """Exact per-device bytes of a sharded pytree (max shard per leaf)."""
    import jax
    import numpy as np

    total = 0
    for leaf, sh in zip(jax.tree.leaves(tree), jax.tree.leaves(shardings)):
        shard_shape = sh.shard_shape(leaf.shape)
        total += int(np.prod(shard_shape)) * leaf.dtype.itemsize
    return total


def compiled_byte_view(ma: Any) -> dict:
    """XLA's ``memory_analysis()`` as per-device byte counts, with the ONE
    peak formula (donation credited: the state argument aliases its output
    buffers, so steady-state outputs cost only the non-aliased slack)

        peak = arguments + temps + max(0, outputs - aliased)

    Both the audit's ``compiled_*`` view and ``obs/memprof.py``'s bucketed
    account read XLA through this function — single owner, no forked
    arithmetic."""
    args_b = int(ma.argument_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    alias_b = int(ma.alias_size_in_bytes)
    temp_b = int(ma.temp_size_in_bytes)
    return {
        "arguments_bytes": args_b,
        "output_bytes": out_b,
        "aliased_bytes": alias_b,
        "temp_bytes": temp_b,
        "peak_bytes": args_b + temp_b + max(0, out_b - alias_b),
    }


# TrainState field → shared memory-bucket taxonomy (obs/memprof.py BUCKETS).
# ``ef`` is the per-worker fp32 error-feedback carry from --grad-compression,
# i.e. gradient-accumulation state that persists across steps.
_STATE_FIELD_BUCKETS = {
    "params": "params",
    "opt_state": "optimizer_state",
    "ef": "grad_accum",
}


def state_bucket_bytes(a_state: Any, sh: Any) -> dict[str, int]:
    """Per-device shard bytes of the train state, split by top-level
    TrainState field into the shared bucket taxonomy.  Per-leaf additive,
    so ``sum(values)`` EQUALS ``_shard_bytes(a_state, sh)`` — the audit's
    ``analytic_state_bytes`` and memprof's params/optimizer buckets are
    the same numbers from this one function."""
    import dataclasses

    buckets: dict[str, int] = {}
    if dataclasses.is_dataclass(a_state):
        for f in dataclasses.fields(a_state):
            bucket = _STATE_FIELD_BUCKETS.get(f.name, "other")
            buckets[bucket] = buckets.get(bucket, 0) + _shard_bytes(
                getattr(a_state, f.name), getattr(sh, f.name)
            )
    else:
        buckets["other"] = _shard_bytes(a_state, sh)
    return buckets


def _activation_bytes(
    config: Any, b_loc: int, src: int, tgt: int, dtype_bytes: int, remat: bool,
) -> dict:
    """Structural activation model, per device.

    Under block-level remat the backward holds: every block's boundary
    activation (batch, seq, d_model), ONE block's recomputed internals
    (attention scores in fp32 — assume the XLA path, which is conservative
    vs the flash kernel — plus MLP inner), and the fp32 logits/loss
    buffers.  Without remat EVERY block's internals are saved residuals, so
    the working-set term multiplies by the layer count.  Batch is sharded
    over (data, fsdp) so ``b_loc`` is the per-device batch."""
    name = type(config).__name__
    if name == "LlamaConfig":
        h, inter, layers = config.hidden_size, config.intermediate_size, config.num_hidden_layers
        heads, vocab = config.num_attention_heads, config.vocab_size
        boundaries = layers * b_loc * src * h * dtype_bytes
        scores = b_loc * heads * src * src * 4
        mlp_inner = 3 * b_loc * src * inter * dtype_bytes  # gate, up, silu*up
        if remat:
            block_ws = 2 * max(scores, mlp_inner)  # recomputed fwd + its bwd temps
        else:
            block_ws = layers * (scores + mlp_inner)  # all residuals saved
        logits = 2 * b_loc * src * vocab * 4  # fp32 logits + softmax-grad temp
    else:  # T5/BART seq2seq: encoder + decoder with cross attention
        h = getattr(config, "d_model", None)
        layers_e = getattr(config, "num_layers", None) or config.encoder_layers
        layers_d = getattr(config, "decoder_layers", layers_e)
        inter = getattr(config, "d_ff", None) or config.encoder_ffn_dim
        heads = getattr(config, "num_heads", None) or config.encoder_attention_heads
        vocab = config.vocab_size
        boundaries = (layers_e * b_loc * src * h + layers_d * b_loc * tgt * h) * dtype_bytes
        boundaries += b_loc * src * h * dtype_bytes  # encoder output, live all decode
        scores = max(
            b_loc * heads * src * src * 4,  # encoder self
            b_loc * heads * tgt * src * 4,  # cross
        )
        mlp_inner = 2 * b_loc * max(src, tgt) * inter * dtype_bytes
        if remat:
            block_ws = 2 * max(scores, mlp_inner)
        else:
            block_ws = (layers_e + layers_d) * (scores + mlp_inner)
        logits = 2 * b_loc * tgt * vocab * 4
    return {
        "boundaries_bytes": int(boundaries),
        "block_working_set_bytes": int(block_ws),
        "logits_bytes": int(logits),
    }


def abstract_train_setup(
    model_name: str,
    mesh: Any,
    *,
    dtype: str = "bfloat16",
    remat: bool = True,
    remat_policy: str = "full",
    grad_compression: str = "",
):
    """Model + train state as pure ShapeDtypeStructs with shardings — no
    weights, no devices touched.  Returns ``(lm, tx, schedule, a_params,
    a_state, sh)``.  Shared by the memory audit and the analysis/ IR lint
    so the two always reason about the SAME abstract program."""
    import jax

    from distributed_llms_example_tpu.core.precision import parse_dtype
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.train.optim import make_optimizer
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        state_shardings,
    )

    lm = load_model(
        model_name, dtype=parse_dtype(dtype), remat=remat, load_weights=False,
        remat_policy=remat_policy,
    )
    tx, schedule = make_optimizer(total_steps=1000)
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    workers = 1
    if grad_compression and grad_compression != "off":
        from distributed_llms_example_tpu.ops.quant_collectives import (
            worker_count,
        )

        workers = worker_count(dict(mesh.shape))
    a_state = jax.eval_shape(
        lambda p: create_train_state(
            p, tx,
            grad_compression=grad_compression or "off", workers=workers,
        ),
        a_params,
    )
    sh = state_shardings(a_state, mesh)
    a_state = jax.tree.map(
        lambda s, shd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shd),
        a_state, sh,
    )
    return lm, tx, schedule, a_params, a_state, sh


def aot_compile_train_step(
    model_name: str,
    mesh: Any,
    *,
    global_batch: int = 8,
    src_len: int = 1024,
    tgt_len: int = 128,
    dtype: str = "bfloat16",
    remat: bool = True,
    remat_policy: str = "full",
    grad_accum_steps: int = 1,
    optim_impl: str = "",
    grad_compression: str = "",
):
    """AOT-lower and compile the sharded train step from abstract args
    (no parameter is ever materialized).  Returns ``(compiled, lm,
    a_params, a_state, sh)`` — the compiled object serves both XLA's
    ``memory_analysis()`` (the audit) and ``as_text()`` (the IR lint)."""
    import jax
    import jax.numpy as jnp

    from distributed_llms_example_tpu.parallel.activation import activation_mesh
    from distributed_llms_example_tpu.parallel.sharding import batch_sharding
    from distributed_llms_example_tpu.train.step import make_train_step

    lm, tx, schedule, a_params, a_state, sh = abstract_train_setup(
        model_name, mesh, dtype=dtype, remat=remat, remat_policy=remat_policy,
        grad_compression=grad_compression,
    )
    bsh = batch_sharding(mesh)
    shapes = {
        "input_ids": (global_batch, src_len),
        "attention_mask": (global_batch, src_len),
        "labels": (global_batch, tgt_len if lm.is_seq2seq else src_len),
    }
    a_batch = {
        k: jax.ShapeDtypeStruct(v, jnp.int32, sharding=bsh) for k, v in shapes.items()
    }
    optim_spec = None
    if optim_impl:
        # rebuild the SAME chain with its spec so the compiled program
        # runs the requested --optim-impl apply (the IR lint proves the
        # fused in-place/once-per-step contracts on this program)
        from distributed_llms_example_tpu.train.optim import make_optimizer_bundle

        tx, schedule, optim_spec = make_optimizer_bundle(total_steps=1000)
    build = make_train_step(
        lm.module,
        lm.config,
        tx,
        schedule,
        mesh,
        grad_accum_steps=grad_accum_steps,
        is_seq2seq=lm.is_seq2seq,
        optim_spec=optim_spec,
        optim_impl=optim_impl or None,
        grad_compression=grad_compression or "off",
    )
    step_fn, _ = build(a_state)
    with activation_mesh(mesh):
        compiled = step_fn.jitted.lower(a_state, a_batch).compile()
    return compiled, lm, a_params, a_state, sh


def audit_train_step_memory(
    model_name: str,
    *,
    mesh_config: Any = None,
    global_batch: int = 8,
    src_len: int = 1024,
    tgt_len: int = 128,
    dtype: str = "bfloat16",
    remat: bool = True,
    remat_policy: str = "full",
    grad_accum_steps: int = 1,
    compile: bool = True,
) -> dict:
    """Compile the sharded train step AOT and return per-device byte counts.

    Returns a dict with ``arguments_bytes``, ``temp_bytes``,
    ``output_bytes``, ``aliased_bytes``, ``peak_bytes`` (all per device),
    plus ``params`` and ``fits_v5e_hbm``.
    """
    import jax
    import jax.numpy as jnp

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.core.precision import parse_dtype
    from distributed_llms_example_tpu.train.step import state_shardings

    cfg = mesh_config or MeshConfig(data=1, fsdp=-1, sequence=1, tensor=1)
    if compile:
        mesh = build_mesh(cfg)
    else:
        # analytic-only audits never place data on devices, so the mesh can
        # be abstract — this also allows auditing shapes LARGER than the
        # attached device count (e.g. a 16-way multi-host mesh from one dev
        # box with 8 virtual devices)
        sizes = dict(cfg.axis_sizes())
        if -1 in sizes.values():
            known = 1
            for v in sizes.values():
                if v != -1:
                    known *= v
            # floor at 1: with an abstract mesh the wildcard may not be
            # satisfiable from local devices (e.g. --mesh fsdp=16 on 8)
            sizes = {
                k: (max(1, jax.device_count() // known) if v == -1 else v)
                for k, v in sizes.items()
            }
        try:
            mesh = jax.sharding.AbstractMesh(tuple(sizes.values()), tuple(sizes.keys()))
        except TypeError:  # pre-0.5 signature: one ((name, size), ...) tuple
            mesh = jax.sharding.AbstractMesh(tuple(sizes.items()))
    ma = None
    if compile:
        compiled, lm, a_params, a_state, sh = aot_compile_train_step(
            model_name, mesh,
            global_batch=global_batch, src_len=src_len, tgt_len=tgt_len,
            dtype=dtype, remat=remat, remat_policy=remat_policy,
            grad_accum_steps=grad_accum_steps,
        )
        ma = compiled.memory_analysis()
    else:
        lm, _, _, a_params, a_state, sh = abstract_train_setup(
            model_name, mesh, dtype=dtype, remat=remat, remat_policy=remat_policy,
        )

    # ---- analytic per-device accounting (backend-independent) ----
    state_buckets = state_bucket_bytes(a_state, sh)
    state_b = sum(state_buckets.values())
    # gradients: fp32, sharded like the params (one full tree live at the
    # optimizer update, alongside a comparable fused-update temporary)
    params_sh = state_shardings(a_params, mesh)
    grads_b = _shard_bytes(
        jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, jnp.float32), a_params), params_sh,
    )
    micro_batch = global_batch // max(1, grad_accum_steps)
    batch_shards = 1
    for ax in ("data", "fsdp", "expert"):
        batch_shards *= mesh.shape.get(ax, 1)
    b_loc = max(1, micro_batch // batch_shards)
    dtype_bytes = jnp.dtype(parse_dtype(dtype)).itemsize
    act = _activation_bytes(
        lm.config, b_loc, src_len, tgt_len if lm.is_seq2seq else src_len, dtype_bytes, remat,
    )
    # Gradient liveness bounds the verdict from both sides:
    # - optimistic (1.25x): XLA fuses each layer's gradient into the scan
    #   accumulator / update as it is produced, so only one full tree plus
    #   fused-op slack is ever live (donation reuses grad buffers for the
    #   updates tree at the optimizer step);
    # - conservative (2x under grad accumulation): the scan carry g_acc and
    #   a fully materialized fresh microbatch tree coexist at the
    #   tree-map add (train/step.py scan body) if XLA does not fuse.
    grad_factor_conservative = 2.0 if grad_accum_steps > 1 else 1.25
    analytic_peak = state_b + int(1.25 * grads_b) + sum(act.values())
    analytic_peak_conservative = (
        state_b + int(grad_factor_conservative * grads_b) + sum(act.values())
    )

    backend = jax.default_backend()
    if ma is not None:
        view = compiled_byte_view(ma)
        args_b = view["arguments_bytes"]
        out_b = view["output_bytes"]
        alias_b = view["aliased_bytes"]
        temp_b = view["temp_bytes"]
        compiled_peak = view["peak_bytes"]
    else:
        args_b = out_b = alias_b = temp_b = compiled_peak = 0
    # the fit verdict: compiled stats when compiled for TPU, analytic model
    # otherwise (CPU buffer assignment ignores remat — measured)
    peak = compiled_peak if (backend == "tpu" and ma is not None) else analytic_peak
    n_params = int(sum(x.size for x in jax.tree.leaves(a_params)))
    return {
        "model": model_name,
        "mesh": dict(mesh.shape),
        "global_batch": global_batch,
        "src_len": src_len,
        "tgt_len": tgt_len,
        "dtype": dtype,
        "remat": remat,
        "remat_policy": remat_policy,
        # the analytic activation model assumes policy="full" (block-boundary
        # saves only); "dots" additionally saves matmul outputs, so analytic
        # figures UNDER-estimate it — trust the compiled stats for dots
        "analytic_assumes_full_remat": remat_policy != "full",
        "params": n_params,
        "backend": backend,
        "analytic_state_bytes": state_b,
        "analytic_state_bucket_bytes": state_buckets,
        "analytic_grad_bytes": grads_b,
        "analytic_activation_bytes": act,
        "analytic_peak_bytes": analytic_peak,
        "analytic_peak_conservative_bytes": analytic_peak_conservative,
        "compiled_arguments_bytes": args_b,
        "compiled_temp_bytes": temp_b,
        "compiled_output_bytes": out_b,
        "compiled_aliased_bytes": alias_b,
        "compiled_peak_bytes": compiled_peak,
        "peak_bytes": peak,
        "peak_gib": round(peak / 1024**3, 3),
        "hbm_bytes": HBM_BYTES_V5E,
        "fits_v5e_hbm": peak < HBM_BYTES_V5E,
        # safety verdict: true only if even the conservative bound fits
        # (compiled TPU stats override the analytic bounds when available)
        "fits_v5e_hbm_conservative": (
            compiled_peak < HBM_BYTES_V5E
            if (backend == "tpu" and ma is not None)
            else analytic_peak_conservative < HBM_BYTES_V5E
        ),
    }


def main(argv: list[str] | None = None) -> int:
    from distributed_llms_example_tpu.core.config import parse_mesh_arg

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", required=True)
    p.add_argument("--mesh", type=str, default="fsdp=-1")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--src-len", type=int, default=1024)
    p.add_argument("--tgt-len", type=int, default=128)
    p.add_argument("--dtype", type=str, default="bfloat16")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat-policy", type=str, default="full")
    p.add_argument("--grad-accum-steps", type=int, default=1)
    p.add_argument(
        "--analytic",
        action="store_true",
        help="skip the AOT compile: seconds instead of minutes, and allows "
        "meshes larger than the attached device count",
    )
    p.add_argument(
        "--strict",
        action="store_true",
        help="CI mode: also exit nonzero unless the CONSERVATIVE "
        "gradient-liveness bound fits the chip HBM budget (the default "
        "verdict uses the optimistic fused-accumulation bound)",
    )
    args = p.parse_args(argv)
    report = audit_train_step_memory(
        args.model,
        mesh_config=parse_mesh_arg(args.mesh),
        global_batch=args.batch,
        src_len=args.src_len,
        tgt_len=args.tgt_len,
        dtype=args.dtype,
        remat=args.remat,
        remat_policy=args.remat_policy,
        grad_accum_steps=args.grad_accum_steps,
        compile=not args.analytic,
    )
    # the audit JSON line rides the metric sink (scripts/repo_lint.py
    # forbids direct print(json.dumps(...)) emission outside obs/)
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    log_json(report)
    fits = report["fits_v5e_hbm"] and (
        not args.strict or report["fits_v5e_hbm_conservative"]
    )
    return 0 if fits else 1


if __name__ == "__main__":
    raise SystemExit(main())
