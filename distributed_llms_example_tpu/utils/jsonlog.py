"""JSON-lines metric emission — the Valohai metadata channel.

The reference's observability contract is "print one JSON object per line to
stdout; the platform parses it as execution metadata".  Three producers in
the reference implement it (train-torchrun.py:144-147 PrinterCallback,
train-accelerator.py:230-232 loss dumps, train-task.py:301-303), each with
its own rank-noise control (non-main ranks silenced via log levels,
train-accelerator.py:45-51).  Here there is one producer and it is
process-0-only by construction.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Mapping

import jax


def _to_scalar(v: Any) -> Any:
    """Device arrays / numpy scalars → plain Python for json.dumps."""
    if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
        v = v.item()
    if isinstance(v, float):
        return round(v, 6)
    return v


def log_json(metrics: Mapping[str, Any], *, all_processes: bool = False, file=None) -> None:
    """Print ``metrics`` as a single JSON line from process 0 (parity with
    the reference's PrinterCallback, train-torchrun.py:144-147, which strips
    the ``total_flos`` noise key — callers here just don't add noise)."""
    if not all_processes and jax.process_index() != 0:
        return
    out = {k: _to_scalar(v) for k, v in metrics.items()}
    print(json.dumps(out), file=file or sys.stdout, flush=True)


class MetricLogger:
    """Step-cadence metric logger with tokens/sec accounting.

    Cadence control replaces the reference's three hardcoded cadences
    (10/300/100 steps — train-torchrun.py:122, train-accelerator.py:230,
    train-task.py:301) with one configurable ``every``.
    """

    def __init__(self, every: int = 100):
        self.every = max(1, int(every))
        self._t0 = time.perf_counter()
        self._tokens_since = 0
        self._steps_since = 0

    def step(self, step: int, loss: Any, lr: Any = None, tokens: int = 0, **extra: Any) -> None:
        """``loss``/``lr`` may be 0-d device arrays: they are converted to
        host floats ONLY on emitting steps (``log_json``'s ``.item()``), so
        non-logging steps cost zero device syncs and async dispatch keeps
        pipelining across the logging cadence."""
        self._tokens_since += tokens
        self._steps_since += 1
        if step % self.every != 0:
            return
        dt = time.perf_counter() - self._t0
        m: dict[str, Any] = {"step": step, "loss": loss}
        if lr is not None:
            m["learning_rate"] = lr
        if dt > 0 and self._tokens_since:
            m["tokens_per_sec"] = self._tokens_since / dt
            m["steps_per_sec"] = self._steps_since / dt
        m.update(extra)
        log_json(m)
        self._t0 = time.perf_counter()
        self._tokens_since = 0
        self._steps_since = 0
