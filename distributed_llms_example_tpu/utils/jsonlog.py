"""JSON-lines metric emission — the Valohai metadata channel.

The reference's observability contract is "print one JSON object per line to
stdout; the platform parses it as execution metadata".  Three producers in
the reference implement it (train-torchrun.py:144-147 PrinterCallback,
train-accelerator.py:230-232 loss dumps, train-task.py:301-303), each with
its own rank-noise control (non-main ranks silenced via log levels,
train-accelerator.py:45-51).  Here there is one producer and it is
process-0-only by construction.

Since the obs subsystem landed, ``log_json`` routes through the pluggable
sink (obs/sink.py): the stdout channel stays byte-for-byte what it always
printed (the Valohai contract — guarded by tests/test_obs.py), and
``--obs jsonl`` tees the same records, ``schema_version``-stamped, into a
JSONL file under the output dir.
"""

from __future__ import annotations

import json
import time
from typing import Any, Mapping

import jax


def _to_scalar(v: Any) -> Any:
    """Device arrays / numpy scalars → plain Python for json.dumps."""
    if hasattr(v, "item") and getattr(v, "ndim", 1) == 0:
        v = v.item()
    if isinstance(v, float):
        return round(v, 6)
    return v


def log_json(metrics: Mapping[str, Any], *, all_processes: bool = False, file=None) -> None:
    """Emit ``metrics`` as a single JSON line through the active sink
    (stdout by default, process-0 gated: parity with the reference's
    PrinterCallback, train-torchrun.py:144-147).  An explicit ``file``
    bypasses the sink (callers that capture findings into a buffer).

    The sink/process gate runs BEFORE scalar conversion: on non-emitting
    processes the device values are never ``.item()``-ed, so non-logging
    ranks keep costing zero device syncs."""
    if file is not None:
        if not all_processes and jax.process_index() != 0:  # pod-agreed: p0 emission gate; local print only, no collectives downstream
            return
        out = {k: _to_scalar(v) for k, v in metrics.items()}
        print(json.dumps(out), file=file, flush=True)
        return
    from distributed_llms_example_tpu.obs import sink

    if not sink.wants(all_processes=all_processes):
        return
    out = {k: _to_scalar(v) for k, v in metrics.items()}
    sink.emit(out, all_processes=all_processes)


class MetricLogger:
    """Step-cadence metric logger with tokens/sec accounting.

    Cadence control replaces the reference's three hardcoded cadences
    (10/300/100 steps — train-torchrun.py:122, train-accelerator.py:230,
    train-task.py:301) with one configurable ``every``.  The first report
    lands at step ``every`` — never at step 0, whose window would be
    empty — and ``flush()`` (called by the Trainer at epoch/run end)
    emits the final partial window instead of dropping it.
    """

    def __init__(self, every: int = 100):
        self.every = max(1, int(every))
        self._t0 = time.perf_counter()
        self._tokens_since = 0
        self._steps_since = 0
        self._last: tuple[Any, Any] | None = None  # (loss, lr) of newest step

    def step(self, step: int, loss: Any, lr: Any = None, tokens: int = 0, **extra: Any) -> None:
        """``loss``/``lr`` may be 0-d device arrays: they are converted to
        host floats ONLY on emitting steps (``log_json``'s ``.item()``), so
        non-logging steps cost zero device syncs and async dispatch keeps
        pipelining across the logging cadence."""
        self._tokens_since += tokens
        self._steps_since += 1
        self._last = (loss, lr)
        if step == 0 or step % self.every != 0:
            return
        self._emit(step, loss, lr, extra)

    def flush(self, step: int, **extra: Any) -> None:
        """Emit the pending partial window (no-op when the last report
        already covered every step).  Uses the most recent step's
        loss/lr — still device scalars, converted only here."""
        if self._steps_since == 0 or self._last is None:
            return
        loss, lr = self._last
        self._emit(step, loss, lr, extra)

    def _emit(self, step: int, loss: Any, lr: Any, extra: Mapping[str, Any]) -> None:
        dt = time.perf_counter() - self._t0
        m: dict[str, Any] = {"step": step, "loss": loss}
        if lr is not None:
            m["learning_rate"] = lr
        if dt > 0 and self._tokens_since:
            m["tokens_per_sec"] = self._tokens_since / dt
            m["steps_per_sec"] = self._steps_since / dt
        m.update(extra)
        log_json(m)
        self._t0 = time.perf_counter()
        self._tokens_since = 0
        self._steps_since = 0
