"""Orbax checkpointing with step-resume and integrity verification.

The reference saves exactly once, at the very end of training
(reference train-accelerator.py:277-280; HF Trainer's periodic save is
disabled via ``save_steps=1e6``, train-torchrun.py:125) and has **no
resume path at all** (SURVEY.md §5).  Here checkpointing is first-class:
periodic async saves of the full TrainState (params + optimizer state +
step), retention, and restore-latest — sharded arrays are written/read
directly from/to their mesh placement by Orbax, so a multi-host restore
never materializes the full model on one host.

Integrity (ISSUE 6): at TPU-pod scale the storage between a run and its
checkpoints is itself a fault domain — a preemption mid-finalize or a
flaky filesystem leaves a torn or silently corrupted highest step, and
trusting it unconditionally turns the NEXT run's restore into the crash.
Three guards close that hole:

- every finalized checkpoint gets an atomically-written **checksum
  manifest** sidecar (``integrity-<step>.json``: crc32 + size per file
  under the step directory, written tmp+fsync+rename by process 0);
- ``save`` **retries with capped exponential backoff** on transient I/O
  errors before giving up;
- ``restore_latest`` **verifies before restoring** and falls back to the
  newest older retained step when the manifest mismatches (or the
  restore itself raises) — emitting ``ckpt_verify_failed`` /
  ``ckpt_restore_failed`` events instead of crashing the resume.  In a
  multi-process run process 0 verifies once and broadcasts its verdict
  over the heartbeat allgather channel so the pod restores ONE step.

Everything outside this module goes through these wrappers — the repo
lint (scripts/repo_lint.py rule 6) forbids bare ``manager.save`` /
``manager.restore`` calls elsewhere, so no call site can silently skip
verification.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Callable

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributed_llms_example_tpu.core.config import AXES
from distributed_llms_example_tpu.utils.backoff import sleep_backoff
from distributed_llms_example_tpu.utils.jsonlog import log_json

# sidecars live next to the step dirs, never inside them: orbax owns the
# step directory's contents (a foreign file there could be mistaken for a
# checkpoint item).  integrity-<step>.json = the checksum manifest;
# recovery-<step>.json = the trainer's data-cursor + quarantine snapshot
# (written by train/trainer.py, GC'd here with the step)
_MANIFEST_PREFIX = "integrity-"
RECOVERY_PREFIX = "recovery-"
_SIDECAR_PREFIXES = (_MANIFEST_PREFIX, RECOVERY_PREFIX)

# The mesh-layout payload leaf (ISSUE 14): every checkpoint records the
# topology it was written under — mesh axis sizes in AXES order, the
# process count, and the error-feedback worker count — as an ARRAY leaf
# riding the payload (like the stacked-block layout identity: a sidecar
# can be separated from the arrays it describes, a payload leaf cannot).
# The resharding restore reads the live structure from orbax metadata
# and this leaf only confirms it; the FAIL-FAST pre-check reads the same
# facts from the recovery sidecar, which is available without a restore.
MESH_LAYOUT_KEY = "mesh_layout"


class ReshardError(ValueError):
    """A checkpoint's recorded topology cannot map onto the live mesh.

    Raised by the resharding restore pre-checks (the named, fail-fast
    alternative to an opaque orbax structure error deep in the
    newest-first walk-back) — the message always names BOTH
    factorizations."""


def mesh_layout_array(
    mesh_axes: dict, process_count: int, ef_workers: int
) -> np.ndarray:
    """The mesh-layout leaf: int32 ``[*axis sizes in AXES order,
    process_count, ef_workers]`` (``ef_workers`` 0 = no error-feedback
    tree in the payload)."""
    return np.asarray(
        [int(mesh_axes.get(a, 1) or 1) for a in AXES]
        + [int(process_count), int(ef_workers)],
        np.int32,
    )


def parse_mesh_layout(leaf: Any) -> dict:
    """Inverse of :func:`mesh_layout_array`:
    ``{"axes": {axis: size}, "processes": int, "ef_workers": int}``."""
    v = [int(x) for x in np.asarray(leaf).reshape(-1)]
    if len(v) != len(AXES) + 2:
        raise ValueError(
            f"mesh-layout leaf has {len(v)} entries, expected "
            f"{len(AXES) + 2} ([{', '.join(AXES)}, processes, ef_workers])"
        )
    return {
        "axes": dict(zip(AXES, v[: len(AXES)])),
        "processes": v[len(AXES)],
        "ef_workers": v[len(AXES) + 1],
    }


def describe_factorization(layout: dict | None) -> str:
    """One-line human name for a recorded topology (error messages)."""
    if not layout:
        return "<unrecorded>"
    axes = layout.get("axes", {})
    body = ",".join(f"{a}={axes.get(a, 1)}" for a in AXES if axes.get(a, 1) != 1)
    return (
        f"{{{body or 'all axes 1'}}} over {layout.get('processes', '?')} "
        f"process(es)"
    )


def _crc32_file(path: str, chunk: int = 1 << 20) -> tuple[int, int]:
    """(crc32, size) of one file, streamed."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            size += len(buf)
    return crc & 0xFFFFFFFF, size


def compute_file_manifest(step_dir: str) -> dict[str, dict[str, int]]:
    """Relative path → {crc32, size} for every file under a finalized
    checkpoint step directory.  Per-file granularity: orbax writes each
    (aggregation of) pytree leaves as its own file, so a flipped byte in
    any leaf's storage lands on exactly one manifest entry."""
    out: dict[str, dict[str, int]] = {}
    for dirpath, _, files in os.walk(step_dir):
        for name in sorted(files):
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, step_dir)
            crc, size = _crc32_file(path)
            out[rel] = {"crc32": crc, "size": size}
    return out


class Checkpointer:
    def __init__(
        self,
        directory: str,
        *,
        save_every_steps: int = 0,
        keep: int = 3,
        async_save: bool = True,
        save_retries: int = 3,
        retry_backoff_s: float = 0.5,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.save_every_steps = save_every_steps
        self.save_retries = max(0, int(save_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep,
            save_interval_steps=max(1, save_every_steps),
            enable_async_checkpointing=async_save,
        )
        # the registered handler is what makes ``item_metadata`` work on
        # a manager that has not saved in THIS session (a resumed run's
        # first act is reading the saved payload's structure for the
        # resharding target) — save/restore still route through the
        # StandardSave/StandardRestore args as before
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=options,
            item_handlers=ocp.StandardCheckpointHandler(),
        )
        # steps THIS instance saved: only the writer may author a step's
        # manifest.  Manufacturing one at restore time for a pre-existing
        # step would checksum possibly-already-corrupt files and baptize
        # the corruption as verified; steps without a manifest stay
        # "legacy" (accepted, but un-verifiable).
        self._saved_steps: set[int] = set()

    # -- paths -----------------------------------------------------------

    def step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(step))

    def manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_MANIFEST_PREFIX}{step}.json")

    # -- saving ----------------------------------------------------------

    def should_save(self, step: int) -> bool:
        return self.save_every_steps > 0 and step % self.save_every_steps == 0

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save with retry-with-backoff on transient I/O failure
        (single-process; a multi-process save gets one attempt with a
        pod-agreed outcome instead — see the inline rationale).

        Before submitting, the PREVIOUS async save is finalized and its
        manifest written — orbax serializes overlapping saves anyway, so
        the wait adds nothing the manager would not impose; the real
        added cost is process 0 re-reading the prior checkpoint once to
        crc32 it.  That read rides the checkpoint span (obs-visible) and
        amortizes over the save cadence; moving it off-thread would buy
        latency at the price of a manifest/restore race, the wrong trade
        for the integrity layer.  Finalizing here keeps the manifest at
        most one save cadence behind the checkpoint it describes
        (``wait``/``close`` cover the final one).

        Known limit: the retry covers SUBMISSION (and the whole write on
        the sync path).  Under async checkpointing a background-commit
        failure surfaces later, at the next ``wait_until_finished`` —
        re-submitting that step would mean tearing down orbax's
        half-committed state, so it propagates unretried (the next run's
        ``restore_latest`` treats the torn step as unverified and falls
        back past it)."""
        if step in self.manager.all_steps():
            return False  # e.g. re-saving the final step after a no-op resume
        self._finalize_manifests()
        if jax.process_count() > 1:
            # ONE attempt, pod-agreed outcome: manager.save is a
            # collective (internal sync barriers), so a rank retrying
            # locally while its peers proceeded would re-enter it out of
            # lockstep and hang the pod — and a retry after a peer
            # half-committed would fight orbax's step state.  An agreed
            # failure surfaces loudly; the torn step is exactly what
            # restore_latest's verify-with-fallback walks past.
            err: Exception | None = None
            saved = False
            try:
                saved = self.manager.save(
                    step, args=ocp.args.StandardSave(state), force=force
                )
            except Exception as e:
                err = e
            if not self._agreed_ok(err is None):
                raise err if err is not None else RuntimeError(
                    f"checkpoint save of step {step} failed on a peer process"
                )
            if saved:
                self._saved_steps.add(int(step))
            return saved
        delay = self.retry_backoff_s
        for attempt in range(self.save_retries + 1):
            try:
                saved = self.manager.save(
                    step, args=ocp.args.StandardSave(state), force=force
                )
                if saved:
                    self._saved_steps.add(int(step))
                return saved
            except Exception as e:  # orbax wraps backend I/O errors variously
                if attempt == self.save_retries:
                    raise
                log_json({
                    "event": "ckpt_save_retry",
                    "step": int(step),
                    "attempt": attempt + 1,
                    "backoff_s": round(delay, 3),
                    "error": str(e)[:200],
                })
                delay = sleep_backoff(delay, cap_s=8.0)
        return False  # unreachable

    def _finalize_manifests(self) -> None:
        """Write the checksum manifest for every finalized step that lacks
        one, and drop manifests whose step retention deleted.  Process 0
        writes (the step dir is shared storage — one writer suffices);
        the write is atomic (tmp + fsync + rename) so a reader never sees
        a torn manifest."""
        self.manager.wait_until_finished()
        steps = set(self.manager.all_steps())
        if jax.process_index() != 0:
            return
        for step in sorted(steps & self._saved_steps):
            path = self.manifest_path(step)
            step_dir = self.step_dir(step)
            if os.path.exists(path) or not os.path.isdir(step_dir):
                continue
            manifest = {
                "step": int(step),
                "files": compute_file_manifest(step_dir),
            }
            tmp = path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(manifest, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError as e:
                # integrity is best-effort on the write side (the verify
                # side treats a missing manifest as legacy); never let a
                # sidecar write take down the save path
                log_json({
                    "event": "ckpt_manifest_write_failed",
                    "step": int(step),
                    "error": str(e)[:200],
                })
        # GC sidecars for steps retention removed
        for name in os.listdir(self.directory):
            for prefix in _SIDECAR_PREFIXES:
                if not (name.startswith(prefix) and name.endswith(".json")):
                    continue
                stem = name[len(prefix):-len(".json")]
                if stem.isdigit() and int(stem) not in steps:
                    try:
                        os.remove(os.path.join(self.directory, name))
                    except OSError:
                        pass

    # -- verification ----------------------------------------------------

    def verify(self, step: int) -> str | None:
        """Check the step directory against its checksum manifest.
        Returns None when the checkpoint verifies (or predates the
        manifest scheme — a missing sidecar is legacy, not corruption),
        else a human-readable mismatch description."""
        path = self.manifest_path(step)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return f"unreadable manifest {path}: {e}"
        expected = manifest.get("files", {})
        actual = compute_file_manifest(self.step_dir(step))
        problems = []
        for rel, meta in expected.items():
            got = actual.get(rel)
            if got is None:
                problems.append(f"missing file {rel}")
            elif got != meta:
                problems.append(
                    f"{rel}: crc32/size {got['crc32']}/{got['size']} != "
                    f"manifest {meta['crc32']}/{meta['size']}"
                )
        for rel in actual:
            if rel not in expected:
                problems.append(f"unexpected file {rel}")
        if problems:
            return "; ".join(problems[:5])
        return None

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def payload_metadata(self, step: int) -> Any | None:
        """The SAVED payload's structure (a tree of orbax ArrayMetadata:
        shapes + dtypes, no array reads) — what the resharding restore
        builds its per-step abstract target from, so the target always
        matches the structure on disk (legacy bare-TrainState vs layout
        payload, error-feedback tree present or not, and the EF worker
        dim as saved) while the SHARDINGS come from the live mesh.
        Deterministic on every rank (one _METADATA file on shared
        storage); None when the step predates orbax's metadata file.
        Only the genuinely-absent case (FileNotFoundError) maps to None
        — any other storage error propagates LOUDLY: swallowing it on
        one rank would hand that rank a different candidate-target list
        than its peers and desynchronize the per-attempt restore
        agreements."""
        try:
            return self.manager.item_metadata(step)
        except FileNotFoundError:
            return None

    def all_steps(self) -> list[int]:
        return sorted(self.manager.all_steps())

    # -- restoring -------------------------------------------------------

    def _agreed_step(self, candidate: int | None) -> int | None:
        """Broadcast process 0's verification verdict over the heartbeat
        allgather channel (every rank contributes a row; row 0 IS the
        verdict).  One verifier — instead of every rank crc-reading the
        full checkpoint tree against the same shared storage — costs 1/N
        the storage traffic and cannot produce the split verdict a
        manifest landing between two ranks' reads could (a split restore
        target would deadlock orbax's collective restore).
        Single-process: the local verdict."""
        if jax.process_count() == 1:
            return candidate
        import numpy as np

        from distributed_llms_example_tpu.obs.heartbeat import gather_probe

        local = np.asarray([candidate if candidate is not None else -1], np.int32)
        gathered = gather_probe(local)
        agreed = int(gathered[0, 0])
        return None if agreed < 0 else agreed

    def _agreed_count(self, n: int) -> int:
        """Pod-agreed attempt count for one step's candidate targets:
        the MAX across ranks.  The target builder is deterministic on
        shared metadata, but if one rank ever sees a different local
        list, padding the shorter lists (the caller repeats the last
        candidate) keeps every rank running the SAME number of
        per-attempt agreements instead of desynchronizing the
        collective sequence."""
        if jax.process_count() == 1:
            return n
        import numpy as np

        from distributed_llms_example_tpu.obs.heartbeat import gather_probe

        counts = gather_probe(np.asarray([n], np.int32))
        return int(counts[:, 0].max())

    def _agreed_ok(self, ok: bool) -> bool:
        """Pod-uniform restore outcome: a restore exception on ONE rank
        must fail the step for EVERY rank — otherwise the failing rank
        walks back into another collective while its peers have already
        returned, and the pod deadlocks.  Every rank calls this exactly
        once per restore attempt, success or failure."""
        if jax.process_count() == 1:
            return ok
        import numpy as np

        from distributed_llms_example_tpu.obs.heartbeat import gather_probe

        flags = gather_probe(np.asarray([1 if ok else 0], np.int32))
        return bool(int(flags[:, 0].min()))

    def restore_latest(
        self,
        abstract_state: Any,
        *,
        max_step: int | None = None,
        target_for: Callable[[int], Any] | None = None,
    ) -> tuple[Any, int] | None:
        """Restore the newest VERIFIED checkpoint into the given abstract
        (shape/dtype/sharding) pytree; returns (state, step) or None.

        Steps are tried newest-first (optionally capped at ``max_step``).
        A step failing checksum verification — or whose restore raises —
        is reported and skipped, so a corrupt or partially-written
        highest step degrades to the previous retained step instead of
        crashing the resume.

        THE RESHARDING PATH (ISSUE 14): when ``target_for`` is given,
        the abstract target is built PER CANDIDATE STEP —
        ``target_for(step)`` (typically from :meth:`payload_metadata`,
        so the target's structure matches what that step actually
        stored while its shardings come from the live mesh) — which is
        what lets a checkpoint written under one ``data×fsdp``
        factorization (or process count) restore onto another.  The
        verify-before-restore, the pod-agreed single-verifier verdict,
        and the newest-first fallback walk are all unchanged; a
        :class:`ReshardError` from the builder propagates immediately
        (an unmappable topology must fail fast and named, not walk back
        through N misleading restore attempts)."""
        # finalize any pending async save (and its manifest) first: an
        # in-flight step must be either fully committed+checksummed or
        # absent before we enumerate candidates — never half-written
        self._finalize_manifests()
        remaining = [
            s for s in sorted(self.manager.all_steps(), reverse=True)
            if max_step is None or s <= max_step
        ]
        while True:
            chosen: int | None = None
            if jax.process_index() == 0:
                # process 0 is the single verifier (_agreed_step
                # broadcasts its verdict): one full crc read of each
                # candidate instead of N identical ones
                for step in remaining:
                    problem = self.verify(step)
                    if problem is not None:
                        log_json({
                            "event": "ckpt_verify_failed",
                            "step": int(step),
                            "detail": problem[:300],
                        })
                        continue
                    chosen = step
                    break
            chosen = self._agreed_step(chosen)
            if chosen is None:
                return None
            targets = (
                abstract_state if target_for is None else target_for(chosen)
            )
            # a builder may return SEVERAL candidate structures for one
            # step (a dir with no orbax metadata cannot be classified:
            # layout payload vs legacy bare state) — attempted in order,
            # deterministic on every rank so the per-attempt agreement
            # below stays pod-uniform
            if not isinstance(targets, (list, tuple)):
                targets = [targets]
            targets = list(targets)
            # pod-uniform attempt count (ONE collective, not one per
            # iteration): a rank with a shorter local list repeats its
            # last candidate so the per-attempt _agreed_ok sequence
            # stays aligned across the pod
            n_attempts = self._agreed_count(len(targets))
            while len(targets) < n_attempts:
                targets.append(targets[-1])
            state, err = None, None
            for target in targets:
                state, err = None, None
                try:
                    state = self.manager.restore(
                        chosen, args=ocp.args.StandardRestore(target)
                    )
                except Exception as e:
                    err = e
                # pod-uniform verdict BEFORE anyone returns: a rank whose
                # restore raised must not walk back into a collective its
                # peers (who succeeded and returned) will never join
                if self._agreed_ok(err is None):
                    return state, chosen
                if err is None:
                    # a PEER failed; this rank's restored state is
                    # discarded so the pod walks back together
                    err = RuntimeError(
                        f"restore of step {chosen} failed on a peer process"
                    )
            if not os.path.exists(self.manifest_path(chosen)):
                # a manifest-less (legacy) step whose restore raised is
                # almost certainly payload-structure drift, which every
                # older step shares — re-raise straight to the caller's
                # legacy-payload path instead of walking back through N
                # collective restore attempts (and N misleading events)
                raise err
            # the step VERIFIED but its restore failed (corruption the
            # per-file checksums cannot see): report it and fall back
            log_json({
                "event": "ckpt_restore_failed",
                "step": int(chosen),
                "error": str(err)[:300],
            })
            remaining = [s for s in remaining if s < chosen]
            if not remaining:
                raise err

    def restore_before(
        self,
        step: int,
        abstract_state: Any,
        *,
        target_for: Callable[[int], Any] | None = None,
    ) -> tuple[Any, int] | None:
        """Restore the newest verified checkpoint STRICTLY OLDER than
        ``step`` — the rewind target: a checkpoint saved at or after the
        anomaly step may already hold the poisoned state.  ``target_for``
        is the per-step resharding target builder (see
        :meth:`restore_latest`)."""
        return self.restore_latest(
            abstract_state, max_step=step - 1, target_for=target_for
        )

    def delete_after(self, step: int) -> list[int]:
        """Drop every retained step NEWER than ``step`` (checkpoints and
        manifests).  The rewind path calls this after restoring: a
        checkpoint saved at/after the anomaly step may hold semantically
        poisoned state that CHECKSUMS CLEAN (the corruption happened in
        compute, not storage), and because ``save`` refuses steps already
        on disk the replay could never refresh it — a later rewind or
        resume would restore the poison.  Deleting lets the replay
        re-save those steps from recovered state.  ``manager.delete`` is
        collective (multihost barrier): every process calls this
        together, right after the collective restore."""
        self.manager.wait_until_finished()
        doomed = [s for s in sorted(self.manager.all_steps()) if s > step]
        for s in doomed:
            self.manager.delete(s)
            self._saved_steps.discard(s)
        if doomed:
            log_json({"event": "ckpt_deleted_after_rewind", "steps": doomed})
            if jax.process_index() == 0:
                for s in doomed:
                    for prefix in _SIDECAR_PREFIXES:
                        try:
                            os.remove(os.path.join(
                                self.directory, f"{prefix}{s}.json"
                            ))
                        except OSError:
                            pass
        return doomed

    def wait(self) -> None:
        self.manager.wait_until_finished()
        self._finalize_manifests()

    def close(self) -> None:
        self.wait()
        self.manager.close()


def abstract_like(state: Any, shardings: Any | None = None) -> Any:
    """ShapeDtypeStruct pytree (with shardings if given) for restore targets."""
    if shardings is None:
        return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s), state, shardings
    )
