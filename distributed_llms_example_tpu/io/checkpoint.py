"""Orbax checkpointing with step-resume.

The reference saves exactly once, at the very end of training
(reference train-accelerator.py:277-280; HF Trainer's periodic save is
disabled via ``save_steps=1e6``, train-torchrun.py:125) and has **no
resume path at all** (SURVEY.md §5).  Here checkpointing is first-class:
periodic async saves of the full TrainState (params + optimizer state +
step), retention, and restore-latest — sharded arrays are written/read
directly from/to their mesh placement by Orbax, so a multi-host restore
never materializes the full model on one host.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    def __init__(
        self,
        directory: str,
        *,
        save_every_steps: int = 0,
        keep: int = 3,
        async_save: bool = True,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.save_every_steps = save_every_steps
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep,
            save_interval_steps=max(1, save_every_steps),
            enable_async_checkpointing=async_save,
        )
        self.manager = ocp.CheckpointManager(self.directory, options=options)

    def should_save(self, step: int) -> bool:
        return self.save_every_steps > 0 and step % self.save_every_steps == 0

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        if step in self.manager.all_steps():
            return False  # e.g. re-saving the final step after a no-op resume
        return self.manager.save(step, args=ocp.args.StandardSave(state), force=force)

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def restore_latest(self, abstract_state: Any) -> tuple[Any, int] | None:
        """Restore the newest checkpoint into the given abstract (shape/
        dtype/sharding) pytree; returns (state, step) or None."""
        step = self.manager.latest_step()
        if step is None:
            return None
        state = self.manager.restore(step, args=ocp.args.StandardRestore(abstract_state))
        return state, step

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()


def abstract_like(state: Any, shardings: Any | None = None) -> Any:
    """ShapeDtypeStruct pytree (with shardings if given) for restore targets."""
    if shardings is None:
        return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s), state, shardings
    )
