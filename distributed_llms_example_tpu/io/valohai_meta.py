"""Valohai dataset-version metadata sidecars.

Byte-parity reimplementation of the reference's artifact layer
(reference helpers.py:12-40): after saving model files, write a
``{file}.metadata.json`` next to each output declaring a dataset version
``dataset://llm-models/{project}_{exec_id}`` with a ``dev-{date}-model``
alias and ``['dev', 'llm']`` tags.  Run identity comes from
``/valohai/config/execution.json`` with the same local fallback
(``('test', unix-time)``, helpers.py:37-39).  The only deliberate change:
no dependency on the ``valohai`` package — ``valohai.outputs().path`` is
an identity transform when outputs are already written to the configured
output directory.
"""

from __future__ import annotations

import datetime
import json
import os
import time

EXECUTION_CONFIG_PATH = "/valohai/config/execution.json"


def get_run_identification(config_path: str = EXECUTION_CONFIG_PATH) -> tuple[str, str]:
    """(project_name, execution_id), with the reference's local fallback."""
    try:
        with open(config_path) as f:
            exec_details = json.load(f)
        project_name = exec_details["valohai.project-name"].split("/")[1]
        exec_id = exec_details["valohai.execution-id"]
    except FileNotFoundError:
        project_name = "test"
        exec_id = str(int(time.time()))
    return project_name, exec_id


def dataset_version_metadata(config_path: str = EXECUTION_CONFIG_PATH) -> dict:
    project_name, exec_id = get_run_identification(config_path)
    return {
        "valohai.dataset-versions": [
            {
                "uri": f"dataset://llm-models/{project_name}_{exec_id}",
                "targeting_aliases": [f"dev-{datetime.date.today()}-model"],
                "valohai.tags": ["dev", "llm"],
            },
        ],
    }


def save_valohai_metadata(output_dir: str, config_path: str = EXECUTION_CONFIG_PATH) -> list[str]:
    """Write a metadata sidecar for every file in ``output_dir``; returns the
    sidecar paths.  (The reference iterates ``os.listdir`` after
    ``save_pretrained``, helpers.py:24-28 — same here, skipping sidecars
    themselves so repeated calls don't stack ``.metadata.json.metadata.json``.)"""
    metadata = dataset_version_metadata(config_path)
    written = []
    for file in sorted(os.listdir(output_dir)):
        if file.endswith(".metadata.json"):
            continue
        md_path = os.path.join(output_dir, f"{file}.metadata.json")
        with open(md_path, "w") as outfile:
            json.dump(metadata, outfile)
        written.append(md_path)
    return written
