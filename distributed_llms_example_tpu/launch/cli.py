"""Unified training CLI — replaces the reference's three entry-point scripts.

One command serves all three of the reference's launch modes (SURVEY.md §7):

- local / single host:   ``python -m distributed_llms_example_tpu.launch.cli
                           --train-file train.json --val-file val.json``
- multi-host (the train-task equivalent): same command per host; rendezvous
  facts come from ``--coordinator-address/--num-processes/--process-id``,
  the ``valohai.distributed`` platform config, or VH_*/torchrun env vars
  (reference train-task.py:420-425 consumed the same triple);
- Valohai step: dataset files resolve via ``valohai.inputs('dataset')``
  exactly like the reference's ``run()`` functions
  (reference train-torchrun.py:151-159) when no --train-file is given.

Observability: ``--obs jsonl`` tees every metric line into
``<output-dir>/obs/metrics-p*.jsonl`` and turns on the derived gauges
(MFU, collective-traffic account); ``--obs-heartbeat-steps N`` adds the
multi-host liveness probe; ``--profile-steps 100:105`` captures a
jax.profiler trace for that step window (see README "Observability").

Dropout & RNG: ``--dropout-impl auto|fused|xla`` picks the dropout
execution path (auto = the fused Pallas kernel on TPU — in-kernel RNG,
no mask in HBM, seed-recompute backward; see README "Dropout & RNG
performance") and ``--prng-impl auto|threefry|rbg`` the key stream
(auto = TPU hardware RNG on TPU, bit-reproducible threefry elsewhere);
the resolved pair is logged as an ``rng_config`` event at startup.

Training health: ``--health`` (auto under ``--obs jsonl``) makes the
compiled step return in-graph numerics (param norm, per-bucket update
ratios, non-finite counts — zero extra device syncs) and arms the
anomaly watchdog; ``--on-anomaly warn|halt|checkpoint`` sets the agreed
policy; ``--recorder-steps N`` keeps a flight-recorder ring dumped on
anomaly/SIGTERM/crash.  Post-mortem: ``python -m
distributed_llms_example_tpu.obs.report <output-dir>`` merges the
per-process JSONL into a cross-host timeline (see README "Training
health & post-mortem").
"""

from __future__ import annotations

import argparse
import os
import sys

from distributed_llms_example_tpu.core.config import (
    add_reference_args,
    add_tpu_args,
    config_from_args,
)
from distributed_llms_example_tpu.core.mesh import initialize_distributed
from distributed_llms_example_tpu.data.dataset import load_json_records


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllm-train", description=__doc__)
    add_reference_args(p)
    add_tpu_args(p)
    p.add_argument("--train-file", type=str, default="", help="path to train.json (JSON array or JSONL)")
    p.add_argument("--val-file", type=str, default="", help="path to val.json")
    p.add_argument("--source-column", type=str, default="")
    p.add_argument("--target-column", type=str, default="")
    p.add_argument("--dry-run", action="store_true", help="print resolved config and exit")
    p.add_argument(
        "--lint", type=str, default="warn", choices=("off", "warn", "strict"),
        help="run the static sharding lint (analysis/) at startup: warn "
             "logs findings and proceeds (default); strict aborts on any "
             "error-level finding",
    )
    return p


def resolve_dataset_files(train_file: str, val_file: str) -> tuple[str, str]:
    """Explicit paths win; otherwise resolve train.json/val.json beside the
    first Valohai 'dataset' input (reference train-torchrun.py:152-159)."""
    if train_file:
        return train_file, val_file
    try:
        import valohai  # type: ignore

        base = os.path.dirname(valohai.inputs("dataset").path())
        return os.path.join(base, "train.json"), os.path.join(base, "val.json")
    except Exception:
        raise SystemExit(
            "no --train-file given and no Valohai 'dataset' input available; "
            "pass --train-file/--val-file"
        ) from None


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if args.source_column:
        cfg = cfg.replace(source_column=args.source_column)
    if args.target_column:
        cfg = cfg.replace(target_column=args.target_column)
    if args.dry_run:
        print(cfg.to_json())
        return 0
    initialize_distributed(args.coordinator_address, args.num_processes, args.process_id)
    if args.lint != "off":
        # spec + composition passes from abstract shapes — milliseconds,
        # and a typo'd spec or known-crash combo surfaces BEFORE minutes
        # of weight loading and compilation.  Must run AFTER
        # initialize_distributed: the lint touches the jax backend
        # (device_count, eval_shape), and jax.distributed.initialize
        # refuses to run once any computation has initialized XLA — and
        # the lint wants the GLOBAL device count anyway.
        from distributed_llms_example_tpu.analysis.findings import (
            emit as emit_findings,
            has_errors,
        )
        from distributed_llms_example_tpu.analysis.lint import startup_lint

        findings = startup_lint(cfg)
        emit_findings(findings, as_json=True)
        if args.lint == "strict" and has_errors(findings):
            raise SystemExit(
                "startup lint found error-level findings (see lint_finding "
                "lines above); rerun with --lint warn to proceed anyway"
            )
    train_path, val_path = resolve_dataset_files(args.train_file, args.val_file)
    train_records = load_json_records(train_path)
    val_records = load_json_records(val_path) if val_path and os.path.exists(val_path) else None

    from distributed_llms_example_tpu.train.trainer import Trainer

    trainer = Trainer(cfg, train_records=train_records, val_records=val_records)
    try:
        trainer.train()
    finally:
        # flush the JSONL file channel (--obs jsonl) even on a crash —
        # the telemetry written so far is exactly what the postmortem needs
        from distributed_llms_example_tpu.obs.sink import current_sink

        current_sink().close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
