"""Unified training CLI — replaces the reference's three entry-point scripts.

Subcommand ``serve`` runs the continuous-batching inference engine over a
prompts file (``python -m distributed_llms_example_tpu.launch.cli serve
--model-ckpt ... --prompts-file prompts.json``): prefill/decode split,
sharded KV-cache slots, admit/evict per token step, serve_window /
serve_summary obs events — see README "Serving" and serving/engine.py.
``serve-router`` fronts N engine replicas with the fault-tolerant
router; ``serve-loadgen`` drives either through the open-loop QPS sweep
(serving/loadgen.py): seeded Poisson/bursty/ramp arrivals, goodput and
TTFT-percentile curves per offered rate, a detected saturation knee —
see README "Open-loop load testing & SLO curves".

One (sub)command serves all three of the reference's launch modes (SURVEY.md §7):

- local / single host:   ``python -m distributed_llms_example_tpu.launch.cli
                           --train-file train.json --val-file val.json``
- multi-host (the train-task equivalent): same command per host; rendezvous
  facts come from ``--coordinator-address/--num-processes/--process-id``,
  the ``valohai.distributed`` platform config, or VH_*/torchrun env vars
  (reference train-task.py:420-425 consumed the same triple);
- Valohai step: dataset files resolve via ``valohai.inputs('dataset')``
  exactly like the reference's ``run()`` functions
  (reference train-torchrun.py:151-159) when no --train-file is given.

Observability: ``--obs jsonl`` tees every metric line into
``<output-dir>/obs/metrics-p*.jsonl`` and turns on the derived gauges
(MFU, collective-traffic account); ``--obs-heartbeat-steps N`` adds the
multi-host liveness probe; ``--profile-steps 100:105`` captures a
jax.profiler trace for that step window; ``--obs-budget`` (auto-on)
closes every logging window into a ``step_budget`` account — wall time
decomposed into data_wait / dispatch / device_busy / sync_block /
host_overhead, a ``dispatch_efficiency`` gauge, and a runtime tripwire
for host-blocking transfers off the log cadence.  Post-run, ``python -m
distributed_llms_example_tpu.obs.report <output-dir> --trace trace.json``
merges every rank's spans, budget gauges and serving request lifecycles
into one Perfetto-loadable timeline (see README "Observability").

Dropout & RNG: ``--dropout-impl auto|fused|xla`` picks the dropout
execution path (auto = the fused Pallas kernel on TPU — in-kernel RNG,
no mask in HBM, seed-recompute backward; see README "Dropout & RNG
performance") and ``--prng-impl auto|threefry|rbg`` the key stream
(auto = TPU hardware RNG on TPU, bit-reproducible threefry elsewhere);
the resolved pair is logged as an ``rng_config`` event at startup.

Optimizer: ``--optim-impl auto|fused|xla`` picks the optimizer apply
(auto = the fused Pallas clip+AdamW kernel on TPU — one in-place pass
per leaf-shard, ``--health`` stats from the same pass; the optax chain
elsewhere; see README "Optimizer & step overhead").  Both impls run the
identical op sequence (equal up to XLA float contraction) and write the
SAME optax opt-state pytree, so checkpoints roam between them; the
resolved impl is logged as an ``optim_config`` event at startup.

Gradient compression: ``--grad-compression off|int8`` (off = compiled
step bit-identical to the uncompressed path; int8 = the cross-replica
gradient reduction on an s8 wire with stochastic rounding, int-safe
partial sums and a checkpointed error-feedback tree — see README
"Gradient compression").

Training health: ``--health`` (auto under ``--obs jsonl``) makes the
compiled step return in-graph numerics (param norm, per-bucket update
ratios, non-finite counts — zero extra device syncs) and arms the
anomaly watchdog; ``--on-anomaly warn|halt|checkpoint`` sets the agreed
policy; ``--recorder-steps N`` keeps a flight-recorder ring dumped on
anomaly/SIGTERM/crash.  Post-mortem: ``python -m
distributed_llms_example_tpu.obs.report <output-dir>`` merges the
per-process JSONL into a cross-host timeline (see README "Training
health & post-mortem").
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from distributed_llms_example_tpu.core.config import (
    add_reference_args,
    add_tpu_args,
    config_from_args,
)
from distributed_llms_example_tpu.core.mesh import initialize_distributed
from distributed_llms_example_tpu.data.dataset import load_json_records


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllm-train", description=__doc__)
    add_reference_args(p)
    add_tpu_args(p)
    p.add_argument("--train-file", type=str, default="", help="path to train.json (JSON array or JSONL)")
    p.add_argument("--val-file", type=str, default="", help="path to val.json")
    p.add_argument("--source-column", type=str, default="")
    p.add_argument("--target-column", type=str, default="")
    p.add_argument("--dry-run", action="store_true", help="print resolved config and exit")
    p.add_argument(
        "--lint", type=str, default="warn", choices=("off", "warn", "strict"),
        help="run the static sharding lint (analysis/) at startup: warn "
             "logs findings and proceeds (default); strict aborts on any "
             "error-level finding",
    )
    return p


def resolve_dataset_files(train_file: str, val_file: str) -> tuple[str, str]:
    """Explicit paths win; otherwise resolve train.json/val.json beside the
    first Valohai 'dataset' input (reference train-torchrun.py:152-159)."""
    if train_file:
        return train_file, val_file
    try:
        import valohai  # type: ignore

        base = os.path.dirname(valohai.inputs("dataset").path())
        return os.path.join(base, "train.json"), os.path.join(base, "val.json")
    except Exception:
        raise SystemExit(
            "no --train-file given and no Valohai 'dataset' input available; "
            "pass --train-file/--val-file"
        ) from None


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dllm-train serve",
        description="continuous-batching inference over a prompts file "
                    "(serving/engine.py): prefill/decode split, sharded "
                    "KV-cache slots, admit/evict per token step",
    )
    p.add_argument("--model-ckpt", type=str, default="t5-small")
    p.add_argument("--tokenizer", type=str, default="")
    p.add_argument("--prompts-file", type=str, required=True,
                   help="JSON array / JSONL of records (source column "
                        "resolved like training data) or plain strings")
    p.add_argument("--source-column", type=str, default="")
    p.add_argument("--output-file", type=str, default="",
                   help="write {prompt, output, tokens} JSONL here "
                        "(default: stdout)")
    p.add_argument("--num-prompts", type=int, default=0, help="0 = all")
    p.add_argument("--max-slots", type=int, default=8,
                   help="concurrent decode slots (the fixed serving batch)")
    p.add_argument("--prefill-batch", type=int, default=0,
                   help="sequences prefilled per admission chunk "
                        "(0 = max-slots, which always shards when the "
                        "slot count does)")
    p.add_argument("--max-new-tokens", type=int, default=128)
    p.add_argument("--max-source-length", type=int, default=1024)
    p.add_argument("--log-every-steps", type=int, default=50)
    p.add_argument("--ttft-slo-ms", type=float, default=0.0,
                   help="first-token SLO for the serve_summary goodput "
                        "fields (useful tokens/sec + slo_attainment); "
                        "0 = no SLO")
    p.add_argument("--kv-cache-dtype", type=str, default="f32",
                   choices=("f32", "int8"),
                   help="KV-cache storage dtype: int8 quantizes on cache "
                        "write (per-head per-position scales, ~4x less "
                        "cache HBM and decode traffic at a token-match "
                        "tolerance; README 'Serving capacity')")
    p.add_argument("--prefill-buckets", type=str, default="",
                   help="comma list of compiled admission widths (e.g. "
                        "128,256,512); each chunk pads to the smallest "
                        "covering bucket instead of max-source-length, "
                        "all AOT-warmed before the first request")
    p.add_argument("--paged-kv", action="store_true",
                   help="causal families: slots hold block lists over a "
                        "shared pool (serving/cache_pool.py) so short "
                        "prompts stop paying worst-case cache memory; "
                        "bit-identical tokens to the flat cache")
    p.add_argument("--pool-blocks", type=int, default=0,
                   help="paged: shared pool size in blocks (0 = worst "
                        "case, every slot at full width — shrink it to "
                        "trade admission deferrals for memory)")
    p.add_argument("--kv-block-size", type=int, default=0,
                   help="paged: block length in cache positions (0 = the "
                        "kv tile size for the cache width)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="paged: content-hash full blocks and share them "
                        "across requests — admission walks the longest "
                        "cached prefix, bumps refcounts, and prefills "
                        "only the uncached tail (copy-on-write at the "
                        "first divergent block); tokens stay bit-identical "
                        "to cold admission")
    p.add_argument("--prefix-cache-budget-gib", type=float, default=0.0,
                   help="prefix cache: per-replica LRU byte budget for "
                        "keeping FINISHED requests' blocks warm (evicted "
                        "strictly at refcount 0), so a follow-up turn "
                        "prefills only its delta (0 = no warm retention; "
                        "live sharing still applies)")
    p.add_argument("--spec-tokens", type=int, default=0,
                   help="causal families: speculative decode — draft this "
                        "many tokens per slot per round and verify all "
                        "k+1 positions in ONE decode call "
                        "(serving/spec.py); output stays bit-identical "
                        "to plain greedy, only cheaper per token "
                        "(0 = off, max 7 = the flash-decode q-row cap "
                        "minus the bonus row)")
    p.add_argument("--spec-draft-model", type=str, default="",
                   help="registry name of a shrunk causal draft model "
                        "sharing the target's vocab ('' = n-gram "
                        "self-drafting over each slot's own prompt + "
                        "generated tokens, zero extra model)")
    p.add_argument("--hbm-budget-gib", type=float, default=16.0,
                   help="per-chip HBM ceiling in GiB for the serve "
                        "summary's bucketed memory account (obs/memprof.py "
                        "fit verdict; v5e = 16)")
    p.add_argument("--postmortem-dir", type=str, default="",
                   help="where a RESOURCE_EXHAUSTED mid-serve dumps its "
                        "atomic memory-postmortem-p*.json bundle "
                        "('' = tripwire off)")
    p.add_argument("--mesh", type=str, default="data=-1")
    p.add_argument("--compute-dtype", type=str, default="bfloat16")
    p.add_argument("--attention-impl", type=str, default="",
                   choices=("", "auto", "flash", "ring", "xla"))
    p.add_argument("--lint", type=str, default="warn",
                   choices=("off", "warn", "strict"),
                   help="serving startup lint: cache sharding rules vs the "
                        "mesh + the decode composition rows")
    return p


def _prompt_text(record, source_column: str) -> str:
    if isinstance(record, str):
        return record
    if source_column:
        return str(record[source_column])
    for col in ("dialogue", "article", "prompt", "text", "source"):
        if col in record:
            return str(record[col])
    raise SystemExit(
        f"cannot resolve a prompt column in record keys {sorted(record)}; "
        "pass --source-column"
    )


def _serve_setup(args, *, extra_flags: tuple = ()):
    """The shared serve/serve-router prologue: prompts → model → mesh →
    startup lint → tokenizer → sharded params → encoded requests.
    Returns (lm, mesh, tok, params, prompts, requests)."""
    import jax

    from distributed_llms_example_tpu.core.config import parse_mesh_arg
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.core.precision import parse_dtype
    from distributed_llms_example_tpu.data.tokenizer import get_tokenizer
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import shard_params

    if jax.process_count() > 1:  # pod-agreed: pod-uniform guard; every rank fails fast together
        raise SystemExit(
            "the serving engine is single-controller; run one process "
            "(the serve-router replica pool is in-process — multi-host "
            "serving is a network tier above it, not a collective)"
        )
    records = load_json_records(args.prompts_file)
    if args.num_prompts > 0:
        records = records[: args.num_prompts]
    prompts = [_prompt_text(r, args.source_column) for r in records]
    lm = load_model(
        args.model_ckpt,
        dtype=parse_dtype(args.compute_dtype),
        attention_impl=args.attention_impl or None,
    )
    mesh = build_mesh(parse_mesh_arg(args.mesh))
    if args.lint != "off":
        from distributed_llms_example_tpu.analysis.composition import (
            check_composition,
        )
        from distributed_llms_example_tpu.analysis.findings import (
            emit as emit_findings,
            has_errors,
        )
        from distributed_llms_example_tpu.analysis.spec_lint import (
            lint_cache_sharding,
        )
        from distributed_llms_example_tpu.evaluation.generation import abstract_cache

        a_params = jax.eval_shape(lambda: lm.init_params(0))
        findings = lint_cache_sharding(
            abstract_cache(
                lm.module, a_params,
                batch=args.max_slots, max_new_tokens=args.max_new_tokens,
                src_len=args.max_source_length, is_seq2seq=lm.is_seq2seq,
                kv_cache_dtype=args.kv_cache_dtype,
            ),
            dict(mesh.shape),
        )
        if args.paged_kv:
            # the pool is the resident serving tree under --paged-kv:
            # spec-lint it like CACHE_RULES (POOL_RULES is its rule set)
            from distributed_llms_example_tpu.ops.flash_attention import (
                auto_block,
            )
            from distributed_llms_example_tpu.parallel.sharding import (
                pool_rules,
            )
            from distributed_llms_example_tpu.serving.cache_pool import (
                pool_cache_tree,
            )

            width = args.max_source_length + args.max_new_tokens
            bs = args.kv_block_size or auto_block(width) or width
            a_cache = abstract_cache(
                lm.module, a_params,
                batch=args.max_slots, max_new_tokens=args.max_new_tokens,
                src_len=args.max_source_length, is_seq2seq=lm.is_seq2seq,
                kv_cache_dtype=args.kv_cache_dtype,
            )
            n_blocks = args.pool_blocks or args.max_slots * max(width // bs, 1)
            findings += lint_cache_sharding(
                jax.eval_shape(
                    lambda: pool_cache_tree(a_cache, n_blocks, bs)
                ),
                dict(mesh.shape),
                rules=pool_rules(),
            )
        findings += check_composition(
            family=lm.family, mesh_axes=dict(mesh.shape),
            flags=("decode", "seq2seq" if lm.is_seq2seq else "causal")
            + tuple(extra_flags),
        )
        # Layer 1 of the pod-agreement analysis: a rank-divergent branch
        # into a collective hangs the serve replica pool the same way it
        # hangs a train pod — same AST pass as the trainer startup lint
        from distributed_llms_example_tpu.analysis.divergence import (
            analyze_tree as divergence_tree,
        )

        div_findings, _ = divergence_tree()
        findings += div_findings
        emit_findings(findings, as_json=True)
        if args.lint == "strict" and has_errors(findings):
            raise SystemExit(
                "serving lint found error-level findings; rerun with "
                "--lint warn to proceed anyway"
            )
    tok = get_tokenizer(args.tokenizer, args.model_ckpt)
    params = lm.params if lm.params is not None else lm.init_params(0)
    params = shard_params(params, mesh)
    encode = tok.encode_source if lm.is_seq2seq else tok.encode_prompt
    requests = [encode(t, args.max_source_length) for t in prompts]
    return lm, mesh, tok, params, prompts, requests


def _serve_config_from_args(args):
    from distributed_llms_example_tpu.serving.engine import ServeConfig

    return ServeConfig(
        max_slots=args.max_slots,
        prefill_batch=args.prefill_batch,
        max_new_tokens=args.max_new_tokens,
        max_source_length=args.max_source_length,
        log_every_steps=args.log_every_steps,
        ttft_slo_ms=args.ttft_slo_ms,
        kv_cache_dtype=args.kv_cache_dtype,
        prefill_buckets=tuple(
            int(b) for b in args.prefill_buckets.split(",") if b.strip()
        ),
        paged_kv=args.paged_kv,
        pool_blocks=args.pool_blocks,
        kv_block_size=args.kv_block_size,
        prefix_cache=args.prefix_cache,
        prefix_cache_budget_gib=args.prefix_cache_budget_gib,
        spec_tokens=getattr(args, "spec_tokens", 0),
        spec_draft_model=getattr(args, "spec_draft_model", ""),
        hbm_budget_gib=args.hbm_budget_gib,
        postmortem_dir=args.postmortem_dir,
    )


def _write_serve_output(args, lm, tok, prompts, outputs, *, extra=None):
    """Request OUTPUTS (the served product), not telemetry: a plain
    JSONL document through the crash-safe product writer (obs/sink.py
    ``ProductJsonlWriter``: one os-level write per line + fsync on
    close), so a killed serve run leaves no torn output lines — the
    metric/obs channel stays log_json's."""
    from distributed_llms_example_tpu.obs.sink import ProductJsonlWriter
    from distributed_llms_example_tpu.serving.engine import trim_eos
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    eos, pad = lm.config.eos_token_id, lm.config.pad_token_id
    lines = []
    for i, (prompt, ids) in enumerate(zip(prompts, outputs)):
        kept = [t for t in trim_eos(ids, eos, pad) if t != eos]
        rec = {"prompt": prompt, "output": tok.decode(kept), "tokens": len(kept)}
        if extra is not None:
            rec.update(extra[i])
        lines.append(rec)
    if not args.output_file:
        for rec in lines:
            sys.stdout.write(json.dumps(rec) + "\n")
        return
    writer = ProductJsonlWriter(args.output_file)
    try:
        for rec in lines:
            writer.write(rec)
    finally:
        writer.close()
    log_json({
        "event": "serve_output",
        "path": args.output_file,
        "records": len(lines),
    })


def serve_main(argv: list[str] | None = None) -> int:
    """The ``serve`` subcommand: load → shard → continuous-batching decode."""
    args = build_serve_parser().parse_args(argv)
    from distributed_llms_example_tpu.serving.engine import ServingEngine

    lm, mesh, tok, params, prompts, requests = _serve_setup(args)
    engine = ServingEngine(
        lm.module, lm.config, mesh, _serve_config_from_args(args),
        is_seq2seq=lm.is_seq2seq,
    )
    outputs = engine.generate(params, requests)
    _write_serve_output(args, lm, tok, prompts, outputs)
    return 0


def build_router_parser() -> argparse.ArgumentParser:
    """``serve-router`` = every serve flag + the router tier's knobs."""
    p = build_serve_parser()
    p.prog = "dllm-train serve-router"
    p.description = (
        "fault-tolerant serving tier (serving/router.py): N in-process "
        "engine replicas behind a router with session affinity, "
        "queue-depth dispatch, bounded retry/re-prefill on replica "
        "failure, admission control, graceful drain, and the serving "
        "chaos kinds (replica_crash/replica_stall/request_storm)"
    )
    p.add_argument("--replicas", type=int, default=2,
                   help="engine replicas in the pool (each owns its own "
                        "compiled programs and slot state)")
    p.add_argument("--max-retries", type=int, default=2,
                   help="re-dispatch budget per request after replica "
                        "failures; exceeding it sheds the request")
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="per-request wall deadline while waiting for "
                        "dispatch (0 = none); expired requests shed with "
                        "reason 'deadline'")
    p.add_argument("--router-max-queue", type=int, default=0,
                   help="router queue bound (0 = unbounded); submissions "
                        "over it shed or defer per --shed-policy")
    p.add_argument("--shed-policy", type=str, default="defer",
                   choices=("defer", "shed"),
                   help="what happens to a submission over the queue "
                        "bound: defer parks it client-side, shed rejects")
    p.add_argument("--suspect-after-ticks", type=int, default=3,
                   help="missed heartbeats (router ticks without replica "
                        "progress) before live -> suspect")
    p.add_argument("--dead-after-ticks", type=int, default=6,
                   help="missed heartbeats before suspect -> dead "
                        "(in-flight requests re-prefill elsewhere)")
    p.add_argument("--chaos", type=str, default="",
                   help="serving chaos grammar (obs/chaos.py): "
                        "replica_crash@K,replica_stall@K,request_storm@K "
                        "with K a router scheduler tick")
    return p


def serve_router_main(argv: list[str] | None = None) -> int:
    """The ``serve-router`` subcommand: load once, shard once, N engine
    replicas over the one mesh, route to completion."""
    args = build_router_parser().parse_args(argv)
    from distributed_llms_example_tpu.obs.chaos import parse_chaos
    from distributed_llms_example_tpu.serving.engine import ServingEngine
    from distributed_llms_example_tpu.serving.router import (
        ReplicaRouter,
        RouterConfig,
    )

    if args.replicas < 1:
        raise SystemExit("--replicas must be >= 1")
    lm, mesh, tok, params, prompts, requests = _serve_setup(
        args, extra_flags=("router",)
    )
    serve_cfg = _serve_config_from_args(args)
    engines = [
        ServingEngine(
            lm.module, lm.config, mesh, serve_cfg, is_seq2seq=lm.is_seq2seq
        )
        for _ in range(args.replicas)
    ]
    router = ReplicaRouter(
        engines, params,
        RouterConfig(
            max_retries=args.max_retries,
            deadline_s=args.deadline_ms / 1e3,
            max_queue=args.router_max_queue,
            shed_policy=args.shed_policy,
            suspect_after_ticks=args.suspect_after_ticks,
            dead_after_ticks=args.dead_after_ticks,
            log_every_ticks=args.log_every_steps,
            chaos=parse_chaos(args.chaos) if args.chaos else None,
        ),
    )
    outputs = router.serve(requests)
    extra = [
        {"shed": q.shed_reason} if q.shed else {}
        for q in router.requests
        if not q.synthetic
    ]
    _write_serve_output(args, lm, tok, prompts, outputs, extra=extra)
    return 0


def build_loadgen_parser() -> argparse.ArgumentParser:
    """``serve-loadgen`` = every serve-router flag + the open-loop sweep
    knobs.  ``--replicas`` is repurposed: 0 (the default here) drives a
    bare engine session; >= 1 drives a ReplicaRouter pool, which is how
    the sweep composes with ``--chaos``."""
    p = build_router_parser()
    p.prog = "dllm-train serve-loadgen"
    p.description = (
        "open-loop load sweep (serving/loadgen.py): seeded arrival "
        "schedules (arrivals never wait for completions, so queues "
        "genuinely build) over an offered-QPS grid, producing "
        "offered-vs-goodput and TTFT-percentile curves with a detected "
        "saturation knee; --replicas 0 drives one engine session, >= 1 "
        "a router pool (composable with --chaos)"
    )
    p.set_defaults(replicas=0)
    p.add_argument("--arrival-process", type=str, default="poisson",
                   choices=("poisson", "bursty", "ramp"),
                   help="arrival process: exponential inter-arrivals, "
                        "bursts of --burst-size, or a linear rate ramp "
                        "from --ramp-start-frac x rate")
    p.add_argument("--loadgen-seed", type=int, default=0,
                   help="arrival-schedule RNG seed (same seed + config = "
                        "bit-identical schedule)")
    p.add_argument("--qps-grid", type=str, default="1,2,4,8",
                   help="comma list of ascending offered QPS points")
    p.add_argument("--burst-size", type=int, default=4,
                   help="bursty: simultaneous arrivals per burst")
    p.add_argument("--ramp-start-frac", type=float, default=0.25,
                   help="ramp: starting rate as a fraction of the "
                        "point's offered rate")
    p.add_argument("--max-wall-s", type=float, default=0.0,
                   help="per-point wall cap (0 = none); a point far past "
                        "saturation stops here and reports its "
                        "unfinished tail")
    p.add_argument("--track-tol", type=float, default=0.9,
                   help="knee sensitivity: a point with achieved QPS "
                        "below track-tol x offered has saturated")
    p.add_argument("--workload", type=str, default="random",
                   choices=("random", "chatbot"),
                   help="request mix: 'random' drives the prompts file; "
                        "'chatbot' generates the seeded shared-prefix "
                        "multi-turn mix (serving/loadgen.py "
                        "chatbot_requests — >=90%% shared system prompt, "
                        "growing per-session history, session keys for "
                        "router affinity), ignoring the prompts file")
    p.add_argument("--chat-sessions", type=int, default=8,
                   help="chatbot: concurrent conversation sessions")
    p.add_argument("--chat-turns", type=int, default=4,
                   help="chatbot: turns per session (turn-major order)")
    p.add_argument("--chat-shared-frac", type=float, default=0.9,
                   help="chatbot: fraction of sessions opening with the "
                        "one shared system prompt")
    return p


def serve_loadgen_main(argv: list[str] | None = None) -> int:
    """The ``serve-loadgen`` subcommand: load once, shard once, one
    fresh session (or router pool) per offered-QPS grid point."""
    args = build_loadgen_parser().parse_args(argv)
    from distributed_llms_example_tpu.serving.engine import ServingEngine
    from distributed_llms_example_tpu.serving.loadgen import (
        EngineTarget,
        LoadgenConfig,
        RouterTarget,
        sweep_qps,
    )

    lm, mesh, tok, params, prompts, requests = _serve_setup(
        args, extra_flags=("router",) if args.replicas >= 1 else ()
    )
    sessions = None
    budgets = None
    if args.workload == "chatbot":
        from distributed_llms_example_tpu.serving.loadgen import (
            chatbot_requests,
        )

        # synthetic seeded token streams (prompts file ignored): the
        # shared-prefix structure, not the text, is what the mix drives;
        # the scripted reply lengths become per-request decode budgets
        # so every sweep over one seed decodes the same token counts
        requests, sessions, budgets = chatbot_requests(
            sessions=args.chat_sessions,
            turns=args.chat_turns,
            seed=args.loadgen_seed,
            vocab=int(lm.config.vocab_size),
            shared_frac=args.chat_shared_frac,
            max_len=args.max_source_length,
            with_budgets=True,
        )
    serve_cfg = _serve_config_from_args(args)
    cfg = LoadgenConfig(
        process=args.arrival_process,
        seed=args.loadgen_seed,
        burst_size=args.burst_size,
        ramp_start_frac=args.ramp_start_frac,
        qps_grid=tuple(
            float(q) for q in args.qps_grid.split(",") if q.strip()
        ),
        # the serve parser's SLO default (0 = no SLO) would make
        # attainment vacuous; the sweep judges against a real bar
        ttft_slo_ms=args.ttft_slo_ms or 500.0,
        max_wall_s=args.max_wall_s,
        track_tol=args.track_tol,
    )
    if args.replicas >= 1:
        from distributed_llms_example_tpu.obs.chaos import parse_chaos
        from distributed_llms_example_tpu.serving.router import (
            ReplicaRouter,
            RouterConfig,
        )

        router_cfg = RouterConfig(
            max_retries=args.max_retries,
            deadline_s=args.deadline_ms / 1e3,
            max_queue=args.router_max_queue,
            shed_policy=args.shed_policy,
            suspect_after_ticks=args.suspect_after_ticks,
            dead_after_ticks=args.dead_after_ticks,
            log_every_ticks=args.log_every_steps,
            chaos=parse_chaos(args.chaos) if args.chaos else None,
        )

        def target_factory():
            engines = [
                ServingEngine(
                    lm.module, lm.config, mesh, serve_cfg,
                    is_seq2seq=lm.is_seq2seq,
                )
                for _ in range(args.replicas)
            ]
            return RouterTarget(ReplicaRouter(engines, params, router_cfg))
    else:
        engine = ServingEngine(
            lm.module, lm.config, mesh, serve_cfg, is_seq2seq=lm.is_seq2seq
        )

        def target_factory():
            return EngineTarget(engine.open(params))

    summary = sweep_qps(
        target_factory, requests, cfg, sessions=sessions, budgets=budgets
    )
    if args.output_file:
        from distributed_llms_example_tpu.obs.sink import ProductJsonlWriter

        writer = ProductJsonlWriter(args.output_file)
        try:
            writer.write(summary)
        finally:
            writer.close()
    return 0


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    if argv and argv[0] == "serve-router":
        return serve_router_main(argv[1:])
    if argv and argv[0] == "serve-loadgen":
        return serve_loadgen_main(argv[1:])
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if args.source_column:
        cfg = cfg.replace(source_column=args.source_column)
    if args.target_column:
        cfg = cfg.replace(target_column=args.target_column)
    if args.dry_run:
        print(cfg.to_json())
        return 0
    initialize_distributed(args.coordinator_address, args.num_processes, args.process_id)
    if args.lint != "off":
        # spec + composition passes from abstract shapes — milliseconds,
        # and a typo'd spec or known-crash combo surfaces BEFORE minutes
        # of weight loading and compilation.  Must run AFTER
        # initialize_distributed: the lint touches the jax backend
        # (device_count, eval_shape), and jax.distributed.initialize
        # refuses to run once any computation has initialized XLA — and
        # the lint wants the GLOBAL device count anyway.
        from distributed_llms_example_tpu.analysis.findings import (
            emit as emit_findings,
            has_errors,
        )
        from distributed_llms_example_tpu.analysis.lint import startup_lint

        findings = startup_lint(cfg)
        emit_findings(findings, as_json=True)
        if args.lint == "strict" and has_errors(findings):
            raise SystemExit(
                "startup lint found error-level findings (see lint_finding "
                "lines above); rerun with --lint warn to proceed anyway"
            )
    train_path, val_path = resolve_dataset_files(args.train_file, args.val_file)
    train_records = load_json_records(train_path)
    val_records = load_json_records(val_path) if val_path and os.path.exists(val_path) else None

    from distributed_llms_example_tpu.train.trainer import Trainer

    trainer = Trainer(cfg, train_records=train_records, val_records=val_records)
    try:
        trainer.train()
    finally:
        # flush the JSONL file channel (--obs jsonl) even on a crash —
        # the telemetry written so far is exactly what the postmortem needs
        from distributed_llms_example_tpu.obs.sink import current_sink

        current_sink().close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
