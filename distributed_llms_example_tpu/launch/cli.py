"""Unified training CLI — replaces the reference's three entry-point scripts.

Placeholder for the full trainer wiring (built in a later milestone); the
argument surface (the reference's six flags plus TPU knobs) is already final.
"""

from __future__ import annotations

import argparse
import sys

from distributed_llms_example_tpu.core.config import (
    add_reference_args,
    add_tpu_args,
    config_from_args,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllm-train", description=__doc__)
    add_reference_args(p)
    add_tpu_args(p)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    print(cfg.to_json())
    print("error: trainer not yet wired to the CLI (work in progress)", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
