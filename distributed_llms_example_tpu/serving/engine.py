"""Continuous-batching serving engine: padded decode slots, admit/evict per step.

The static eval path (evaluation/generation.py) decodes one padded batch to
completion — every finished row keeps "decoding" pads until the SLOWEST row
is done, so utilization decays over the batch's lifetime.  This engine is
the Orca-style iteration-level alternative (arXiv:2412.14374's serving
discussion): a fixed set of ``max_slots`` decode slots, each holding ONE
in-flight sequence at its own offset, with finished sequences EVICTED and
new ones ADMITTED between per-token steps.  The compiled programs stay
fixed-shape (slot count never changes); only the host-side slot bookkeeping
moves.

Three compiled programs per model, all traced under the ambient mesh so
cache/activation sharding constraints bake in (batch rows over
data×fsdp×expert, heads over tensor — ``CACHE_RULES``):

- **prefill** (once per admitted chunk): the encoder + cross-KV projection
  (seq2seq) or the prompt pass into a chunk-sized cache (causal).
- **admit** (scatter): chunk rows land in their slots via ``.at[idx].set``
  with ``mode="drop"`` — an out-of-range index is a no-op, which is how
  partially-filled chunks park their padding rows.  Slot caches are NOT
  zeroed on reuse: every read is masked to ``k_pos <= offset``, so stale
  K/V from the previous occupant is unreachable by construction (the
  determinism test pins engine output == static-batch output through slot
  reuse).
- **decode step** (every token): one token per slot, per-slot offsets
  (``cache_positions`` per-row cache writes), idle slots parked at an
  out-of-range offset so their writes drop.

Host loop per step: admit into free slots (if any), run the step, read the
(slots,) token vector back, append/evict.  Greedy only — beam search keeps
the static split path (the per-step beam reorder has no per-slot form).
Single-controller: multi-process serving is a queueing layer above this,
not a collective program.

Obs events (utils/jsonlog → obs sink): ``serve_window`` at the log cadence
(decode tokens/sec[/chip], slot occupancy, queue depth, the window's
prefill-vs-decode time split), a ``serve_request`` lifecycle record per
finished request (queue-wait → prefill → first-token → decode → evict,
with times relative to the batch's submit instant so ``obs.report
--trace`` can draw each request as a slot-track slice), and a final
``serve_summary`` (tokens/sec/chip, TTFT p50/p95 **with its queue-vs-
prefill decomposition**, occupancy, evictions, and the **goodput
fields** — useful tokens/sec and the SLO-attainment fraction at the
configured ``ttft_slo_ms``, the router tier's dispatch inputs) — TTFT
p95 stops being one opaque aggregate and becomes "the tail waited in
queue" vs "prefill is slow".
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llms_example_tpu.evaluation.generation import (
    _causal_prefill,
    _init_cache,
)
from distributed_llms_example_tpu.parallel.activation import (
    BATCH_AXES,
    activation_mesh,
    constrain_cache,
    kv_cache_context,
)
from distributed_llms_example_tpu.serving import cache_pool
from distributed_llms_example_tpu.serving import spec as spec_decode
from distributed_llms_example_tpu.utils.jsonlog import log_json


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape/behavior knobs (all compiled shapes derive from these).

    ``max_slots``: concurrent in-flight sequences — the decode batch.
    ``prefill_batch``: sequences prefilled per admission chunk (one compile
    at this batch; fewer pending sequences ride the same program with
    dropped padding rows); 0 = auto (``max_slots`` — always divides the
    mesh's batch shards when the slot count does, so the defaults work on
    any mesh).  ``max_source_length``: fixed prompt width (prompts are
    padded to it; the serving twin of the trainer's bucketed max).
    ``max_new_tokens``: decode budget per sequence = the KV-cache length
    (seq2seq) or its decode tail (causal).  ``request_spans``: emit one
    ``serve_request`` lifecycle event per finished request (queue-wait /
    prefill / ttft / decode breakdown — the trace exporter's feed).
    ``ttft_slo_ms``: the first-token SLO the goodput fields are judged
    against (0 = no SLO: every finished request's tokens are useful) —
    the router tier's dispatch inputs (``serve_summary``:
    ``goodput_tokens_per_sec`` + ``slo_attainment``).

    Decode-capacity knobs (README "Serving capacity"):

    ``kv_cache_dtype``: "f32" (store K/V at compute dtype) or "int8"
    (quantize on cache write, per-head per-position scales; ~4× less
    cache HBM and decode traffic at a token-match-rate tolerance — the
    paged/bucketed knobs below stay BIT-exact instead).
    ``prefill_buckets``: ascending compiled admission widths (e.g.
    ``(128, 256, 512)``); each admission chunk pads to the smallest
    bucket covering it instead of always paying ``max_source_length``,
    and every bucket's programs are AOT-warmed before the first request
    so no request ever hits a compile.  ``max_source_length`` is always
    an implicit last bucket.
    ``paged_kv`` (causal families only): slots hold block lists over a
    shared pool (serving/cache_pool.py) instead of worst-case-width
    rows; ``pool_blocks`` (0 = worst case: every slot at full width) and
    ``kv_block_size`` (0 = auto kv tile size) shape the pool.  Admission
    defers while the free list is short; eviction returns all blocks.
    ``prefix_cache`` (requires ``paged_kv``): share immutable full
    prompt blocks across requests by chained content hash — admission
    walks its longest cached prefix, bumps refcounts on the matched
    chain, and prefills only the uncached tail (README "Prefix caching
    & multi-turn sessions"; tokens stay BIT-identical to cold-start).
    ``prefix_cache_budget_gib``: warm-retention LRU budget for finished
    requests' prefix blocks, evicted strictly at refcount 0 (0 = no
    retention: sharing only among concurrently-live requests).
    ``spec_tokens`` (causal families only): speculative decode — draft
    k tokens per slot per round and verify all k+1 positions in ONE
    decode call (serving/spec.py); output is BIT-identical to plain
    greedy, only cheaper per token (0 = off; at most
    ``core.config.SPEC_MAX_DRAFT_TOKENS``, the flash-decode q-row cap
    minus the bonus row).  ``spec_draft_model``: registry name of a
    shrunk causal draft model sharing the target's vocab ("" = n-gram
    self-drafting, zero extra model)."""

    max_slots: int = 8
    prefill_batch: int = 0  # 0 = max_slots
    max_new_tokens: int = 128
    max_source_length: int = 1024
    log_every_steps: int = 50
    request_spans: bool = True
    ttft_slo_ms: float = 0.0
    kv_cache_dtype: str = "f32"
    prefill_buckets: tuple = ()
    paged_kv: bool = False
    pool_blocks: int = 0  # 0 = worst case (max_slots x tiles per slot)
    kv_block_size: int = 0  # 0 = auto (the kv tile size for the cache width)
    prefix_cache: bool = False
    prefix_cache_budget_gib: float = 0.0
    spec_tokens: int = 0  # speculative decode: drafts per verify round (0 = off)
    spec_draft_model: str = ""  # registry draft model ("" = n-gram self-draft)
    # the bucketed HBM account (obs/memprof.py): the capacity gauges'
    # cache-bytes arithmetic lands in the shared params/kv_cache taxonomy
    # and the serve_summary carries its fit verdict against this ceiling
    hbm_budget_gib: float = 16.0
    # where a RESOURCE_EXHAUSTED mid-serve dumps its atomic
    # memory-postmortem-p*.json bundle ("" = tripwire off)
    postmortem_dir: str = ""


@dataclasses.dataclass
class ServeStats:
    """Filled by ``ServingEngine.generate`` — the bench/obs read surface."""

    sequences: int = 0
    decode_steps: int = 0
    decode_tokens: int = 0
    decode_seconds: float = 0.0
    prefill_seconds: float = 0.0
    slot_occupancy: float = 0.0
    # capacity gauges (static byte accounting — measured, not inferred):
    # resident = the serving state's fixed allocation; in_use = what live
    # requests actually hold (= resident on the flat path; blocks×block
    # bytes on the paged path); bytes_per_live_token averages in_use over
    # the live tokens at each decode step
    cache_bytes_resident: int = 0
    peak_cache_bytes_in_use: int = 0
    bytes_per_live_token: float = 0.0
    admit_deferrals: int = 0  # paged: admissions deferred on a short free list
    # prefix-cache gauges (prefix_cache only): a lookup per admitted
    # eligible request, a hit when its longest cached chain is >= 1
    # block; tokens saved = prompt tokens served from shared blocks
    # instead of re-prefilled
    prefix_lookups: int = 0
    prefix_hits: int = 0
    prefill_tokens_total: int = 0
    prefill_tokens_saved: int = 0
    # speculative-decode ledger (spec_tokens > 0 only): a step is one
    # verify round; drafted counts k proposals per live slot, accepted
    # the drafts the target's argmax confirmed, emitted every appended
    # token (accepted + the bonus token).  slot_rounds counts one per
    # LIVE slot per verify round, so accepted_tokens_per_step =
    # spec_emitted / spec_slot_rounds is the per-sequence multi-token
    # yield in [1, k+1] — plain decode is 1.0 by construction, so > 1.0
    # is the speculative win (a batch-wide tokens/round reading would
    # exceed 1 with two live slots even with every draft rejected)
    spec_steps: int = 0
    spec_slot_rounds: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_emitted: int = 0
    ttft_s: list[float] = dataclasses.field(default_factory=list)
    # per-request TTFT decomposition (same order as ttft_s): time spent
    # waiting for a slot vs inside the request's prefill call
    queue_wait_s: list[float] = dataclasses.field(default_factory=list)
    prefill_share_s: list[float] = dataclasses.field(default_factory=list)
    # goodput fields (filled by generate): useful tokens/sec at the
    # configured TTFT SLO + the attainment fraction — the router tier's
    # dispatch inputs
    goodput: dict = dataclasses.field(default_factory=dict)

    def tokens_per_sec(self) -> float:
        return self.decode_tokens / max(self.decode_seconds, 1e-9)

    def ttft_percentiles(self) -> tuple[float, float]:
        from distributed_llms_example_tpu.obs.spans import percentiles

        if not self.ttft_s:
            return 0.0, 0.0
        p50, p95 = percentiles(self.ttft_s, (0.50, 0.95))
        return p50, p95

    def ttft_decomposition(self) -> dict:
        """Queue-wait vs prefill share of TTFT over finished requests —
        the serve_summary fields that make a fat TTFT p95 actionable
        (admit more slots vs speed up prefill)."""
        from distributed_llms_example_tpu.obs.spans import percentiles

        q50, q95 = percentiles(self.queue_wait_s, (0.50, 0.95))
        p50, p95 = percentiles(self.prefill_share_s, (0.50, 0.95))
        total = sum(self.ttft_s)
        return {
            "ttft_queue_p50_ms": round(q50 * 1e3, 1),
            "ttft_queue_p95_ms": round(q95 * 1e3, 1),
            "ttft_prefill_p50_ms": round(p50 * 1e3, 1),
            "ttft_prefill_p95_ms": round(p95 * 1e3, 1),
            "ttft_queue_share": round(sum(self.queue_wait_s) / total, 4) if total else 0.0,
            "ttft_prefill_share": round(sum(self.prefill_share_s) / total, 4) if total else 0.0,
        }


def compute_goodput(
    ttft_s: Sequence[float | None],
    tokens_out: Sequence[int],
    *,
    wall_s: float,
    ttft_slo_ms: float,
    n_chips: int,
) -> dict:
    """Goodput: USEFUL tokens per wall second, + SLO attainment.

    Useful = tokens of requests whose first token met the TTFT SLO (all
    FINISHED requests when no SLO is set — ``ttft_s[i] is None`` marks an
    unfinished request); wall = submit → batch done, so queue-wait and
    prefill stalls cost goodput the way they cost a user.
    ``slo_attainment`` is the fraction of finished requests served within
    the SLO — the router tier's per-replica health signal.  Pure
    host-float arithmetic; shared by the engine summary and tests so the
    numbers are pinnable."""
    wall_s = max(float(wall_s), 1e-9)
    slo_s = float(ttft_slo_ms) / 1e3
    finished = [
        (i, t) for i, t in enumerate(ttft_s) if t is not None
    ]
    met = [i for i, t in finished if slo_s <= 0 or t <= slo_s]
    useful = sum(int(tokens_out[i]) for i in met)
    out = {
        "goodput_tokens_per_sec": round(useful / wall_s, 1),
        "goodput_tokens_per_sec_chip": round(useful / wall_s / max(n_chips, 1), 1),
    }
    if slo_s > 0:
        out["ttft_slo_ms"] = round(float(ttft_slo_ms), 1)
        out["slo_attainment"] = (
            round(len(met) / len(finished), 4) if finished else 0.0
        )
    return out


def device_peak_bytes() -> int | None:
    """Peak allocator bytes where the backend supports ``memory_stats``
    (TPU/GPU); None on CPU — callers fall back to the static account,
    which is why the capacity gauges never claim a live number they
    didn't measure.  Delegates to the one raw-read owner
    (obs/memprof.py, repo-lint rule 15)."""
    try:
        from distributed_llms_example_tpu.obs import memprof

        stats = memprof.hbm_stats()
    except Exception:
        return None
    if not stats:
        return None
    return max(s["peak_bytes_in_use"] for s in stats)


class ServingEngine:
    """Greedy continuous-batching decode over a fixed slot set.

    ``model``/``config`` as in the Evaluator; ``mesh`` (or None) is the
    ambient mesh every program traces under.  ``is_seq2seq`` picks the
    adapter: encoder+cross-KV slots (BART/T5) or prompt-cache slots
    (LLaMA-family)."""

    def __init__(self, model: Any, config: Any, mesh: Any,
                 serve: ServeConfig | None = None, *, is_seq2seq: bool = True):
        self.model, self.config, self.mesh = model, config, mesh
        self.serve = serve or ServeConfig()
        self.is_seq2seq = is_seq2seq
        self.eos = config.eos_token_id
        self.pad = config.pad_token_id
        self.start = getattr(config, "decoder_start_token_id", None)
        self.forced_bos = getattr(config, "forced_bos_token_id", None)
        self.forced_eos = getattr(config, "forced_eos_token_id", None)
        self.L = self.serve.max_new_tokens
        self.S = self.serve.max_slots
        self.W = self.serve.max_source_length
        self.prefill_batch = self.serve.prefill_batch or self.S  # 0 = auto
        if self.prefill_batch < 1 or self.prefill_batch > self.S:
            raise ValueError(
                f"prefill_batch {self.prefill_batch} must be in "
                f"[1, max_slots={self.S}]"
            )
        if self.serve.kv_cache_dtype not in ("f32", "int8"):
            raise ValueError(
                f"kv_cache_dtype={self.serve.kv_cache_dtype!r}: "
                "must be 'f32' or 'int8'"
            )
        # admission buckets: ascending widths, max_source_length always the
        # implicit last bucket (every prompt fits somewhere)
        self.buckets = tuple(
            sorted({int(b) for b in self.serve.prefill_buckets if 0 < int(b) < self.W})
        ) + (self.W,)
        self.paged = bool(self.serve.paged_kv)
        self.pool: cache_pool.CachePool | None = None
        if self.paged:
            if self.is_seq2seq:
                raise ValueError(
                    "paged_kv applies to the causal KV cache (prompt + "
                    "decode tail in one buffer); the seq2seq slot state is "
                    "encoder output + cross-KV, which pages nothing — run "
                    "the flat cache for seq2seq families"
                )
            from distributed_llms_example_tpu.ops.flash_attention import auto_block

            width = self.W + self.L
            bs = self.serve.kv_block_size
            if not bs:
                # the block size must tile the cache width AND every
                # admission bucket (decode tiles start on tile boundaries),
                # so the auto default divides their gcd — kernel-preferred
                # tile when the gcd allows, else the gcd itself (8-aligned)
                g = math.gcd(width, *self.buckets)
                bs = auto_block(g) or (g if g >= 8 and g % 8 == 0 else 0)
            if not bs or width % bs:
                raise ValueError(
                    f"kv_block_size={self.serve.kv_block_size} does not tile "
                    f"the cache width {width} (prompt {self.W} + decode "
                    f"{self.L}); pass an explicit 8-aligned divisor of "
                    f"gcd(width, buckets) = "
                    f"{math.gcd(width, *self.buckets)}"
                )
            for b in self.buckets:
                if b % bs:
                    raise ValueError(
                        f"prefill bucket {b} is not a multiple of the kv "
                        f"block size {bs} — decode tiles must start on a "
                        "tile boundary"
                    )
            self.block_size = int(bs)
            self.n_tiles = width // self.block_size
            n_blocks = self.serve.pool_blocks or self.S * self.n_tiles
            worst = cache_pool.blocks_needed(self.W, self.L, self.block_size)
            if n_blocks < worst:
                raise ValueError(
                    f"pool_blocks={n_blocks} cannot hold even one "
                    f"worst-case request ({worst} blocks at block size "
                    f"{self.block_size}) — admission would livelock"
                )
            self.pool = cache_pool.CachePool(n_blocks, self.block_size)
        self.prefix = bool(self.serve.prefix_cache)
        if self.prefix and not self.paged:
            raise ValueError(
                "prefix_cache shares paged pool blocks — it requires "
                "paged_kv (the flat cache has no block identity to share)"
            )
        # speculative decode (serving/spec.py): the verify q block is
        # spec_tokens + 1 rows, capped by the flash-decode kernel's q-row
        # limit (ops/flash_attention.py MAX_DECODE_Q_ROWS)
        self.spec = int(self.serve.spec_tokens or 0)
        self.drafter: spec_decode.DraftRunner | None = None
        if self.spec:
            from distributed_llms_example_tpu.core.config import (
                SPEC_MAX_DRAFT_TOKENS,
            )

            if self.is_seq2seq:
                raise ValueError(
                    "spec_tokens applies to causal decode (the verify q "
                    "block rides the causal decode cache's staggered "
                    "per-row offsets); seq2seq families run plain decode"
                )
            if not 1 <= self.spec <= SPEC_MAX_DRAFT_TOKENS:
                raise ValueError(
                    f"spec_tokens={self.spec} must be in "
                    f"[1, {SPEC_MAX_DRAFT_TOKENS}]: the verify step "
                    "scores spec_tokens + 1 positions in one decode call "
                    "and the flash decode q block caps at "
                    f"{SPEC_MAX_DRAFT_TOKENS + 1} rows"
                )
        mesh_axes = dict(mesh.shape) if mesh is not None else {}
        # known-bad serving compositions are matrix rows, not scattered
        # raises — same table the trainer/lint consult
        from distributed_llms_example_tpu.analysis.composition import (
            validate_composition,
        )

        validate_composition(
            family=None, schedule=None, mesh_axes=mesh_axes,
            flags=("decode", "seq2seq" if is_seq2seq else "causal"),
        )
        batch_shards = 1
        for a in BATCH_AXES:
            batch_shards *= mesh_axes.get(a, 1)
        for what, n in (("max_slots", self.S), ("prefill_batch", self.prefill_batch)):
            if n % max(batch_shards, 1):
                raise ValueError(
                    f"{what}={n} must divide evenly over the mesh's "
                    f"{batch_shards} batch shards (data×fsdp×expert) — "
                    "uneven slot rows cannot shard"
                )
        # per-program Python trace counts: a retrace IS a recompile, so the
        # zero-recompile contract (AOT-warmed buckets, fixed-shape churn)
        # is pinnable by comparing these before/after serving traffic
        self.trace_counts: dict[str, int] = {}
        self._warmed = False
        if self.spec and self.serve.spec_draft_model:
            from distributed_llms_example_tpu.models.registry import load_model

            dm = load_model(self.serve.spec_draft_model)
            if dm.is_seq2seq:
                raise ValueError(
                    f"spec_draft_model={self.serve.spec_draft_model!r} is "
                    "seq2seq — the draft model proposes causal decode "
                    "tokens, so it must be a causal family"
                )
            if dm.config.vocab_size != config.vocab_size:
                raise ValueError(
                    f"spec_draft_model={self.serve.spec_draft_model!r} "
                    f"vocab {dm.config.vocab_size} != target vocab "
                    f"{config.vocab_size} — draft proposals are token ids "
                    "compared against the target argmax, so the vocabs "
                    "must be the same id space"
                )
            self.drafter = spec_decode.DraftRunner(
                dm, slots=self.S, src_width=self.W, max_new=self.L,
                buckets=self.buckets, prefill_batch=self.prefill_batch,
                k=self.spec, pad=self.pad,
                kv_cache_dtype=self.serve.kv_cache_dtype, wrap=self._wrap,
            )
        self._build_programs()
        self.last_stats: ServeStats | None = None

    # ------------------------------------------------------------ programs
    def _wrap(self, fn, donate: tuple[int, ...] = (), name: str = ""):
        # donate the slot-state buffers where the backend supports it: the
        # engine holds the only reference and rebinds the result, so the
        # per-step cache update happens in place instead of copying the
        # whole serving state every token (CPU lacks donation — keep the
        # test backend quiet)
        if jax.default_backend() == "cpu":
            donate = ()
        name = name or getattr(fn, "__name__", "program")

        def counted(*args):
            # runs at TRACE time only: one bump per compiled specialization
            self.trace_counts[name] = self.trace_counts.get(name, 0) + 1
            return fn(*args)

        jitted = jax.jit(counted, donate_argnums=donate)

        def run(*args):
            with activation_mesh(self.mesh), kv_cache_context(
                self.serve.kv_cache_dtype
            ):
                return jitted(*args)

        return run

    @staticmethod
    def _pad_axis(x, axis: int, width: int):
        """Right-pad one axis to ``width`` with zeros — how a bucket-width
        admission chunk lands in full-width slot state.  The padding is
        mask-invisible: enc_mask/full_mask stay 0 there, so padded
        positions contribute exactly nothing (the bucketed == unbucketed
        bit-identity argument)."""
        if x.shape[axis] == width:
            return x
        pads = [(0, 0)] * x.ndim
        pads[axis] = (0, width - x.shape[axis])
        return jnp.pad(x, pads)

    def _build_programs(self) -> None:
        model, L, S = self.model, self.L, self.S

        if self.is_seq2seq:
            def prefill(params, ids, mask):
                enc = model.apply({"params": params}, ids, mask, method="encode")
                ckv = constrain_cache(model.apply({"params": params}, enc, method="cross_kv"))
                return enc, mask, ckv

            def admit(state, enc, mask, ckv, slot_idx):
                put = lambda dst, src: dst.at[slot_idx].set(src, mode="drop")  # noqa: E731
                # bucket-width chunks pad to the slot width here, inside
                # the (per-bucket-compiled) admit program
                enc = self._pad_axis(enc, 1, self.W)
                mask = self._pad_axis(mask, 1, self.W)
                ckv = jax.tree.map(
                    lambda x: self._pad_axis(x, 2, self.W) if x.ndim == 4 else x,
                    ckv,
                )
                return {
                    **state,
                    "enc": put(state["enc"], enc),
                    "enc_mask": put(state["enc_mask"], mask),
                    "ckv": jax.tree.map(put, state["ckv"], ckv),
                    "last": state["last"].at[slot_idx].set(
                        jnp.full((slot_idx.shape[0], 1), self.start, jnp.int32),
                        mode="drop",
                    ),
                }

            def step(params, state, offsets, active):
                # idle slots park at L: their cache writes drop
                # (mode="drop") and their tokens are masked to pad below
                offs = jnp.where(active, offsets, L)
                logits, mut = model.apply(
                    {"params": params, "cache": state["cache"]},
                    state["last"],
                    state["enc"],
                    state["enc_mask"],
                    use_cache=True,
                    cache_offset=offs,
                    max_kv_len=L,
                    cross_kv=state["ckv"],
                    method="decode",
                    mutable=["cache"],
                )
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                if self.forced_bos is not None:
                    nxt = jnp.where(offs == 0, self.forced_bos, nxt)
                if self.forced_eos is not None:
                    nxt = jnp.where(offs == L - 1, self.forced_eos, nxt)
                nxt = jnp.where(active, nxt, self.pad)
                return nxt, {
                    **state,
                    "cache": constrain_cache(mut["cache"]),
                    "last": nxt[:, None],
                }
        else:
            def prefill(params, ids, mask):
                cache, full_mask, lengths, first = _causal_prefill(
                    model, params, ids, mask, L
                )
                return cache, full_mask, lengths, jnp.argmax(first, axis=-1).astype(jnp.int32)

            width_full = self.W + L

            def _pad_cache_tree(cache):
                # bucket-width chunk cache → slot width; K/V on axis 2,
                # int8 scale leaves on axis 2 too, scalars untouched
                def pad(x):
                    if x.ndim >= 3:
                        return self._pad_axis(x, 2, width_full)
                    return x

                return jax.tree.map(pad, cache)

            if self.paged:
                n_blocks, bs = self.pool.num_blocks, self.block_size

                def admit(state, cache, full_mask, first_tok, slot_idx,
                          admit_blocks):
                    put = lambda dst, src: (  # noqa: E731
                        dst.at[slot_idx].set(src, mode="drop") if dst.ndim > 0 else dst
                    )
                    return {
                        **state,
                        "pool": cache_pool.scatter_admit(
                            state["pool"], cache, admit_blocks, bs
                        ),
                        "mask": put(state["mask"], self._pad_axis(full_mask, 1, width_full)),
                        "last": put(state["last"], first_tok),
                    }

                def warm_admit(params, state, ids_tail, mask_full, start,
                               tail_last, slot_idx, block_tables,
                               admit_blocks):
                    """Warm admission: the prompt's longest cached chain is
                    already pool-resident, so the model runs over ONLY the
                    uncached tail (``ids_tail``, at the tail bucket width)
                    against a gathered slot view — per-row absolute
                    positions starting at ``start`` (= cached prefix
                    length), per-row multi-token cache writes (the mha
                    ``cache_positions`` span contract).  The first output
                    token reads off the last valid tail position's logits,
                    exactly where cold prefill reads it; only fresh tail
                    tiles scatter back (``admit_blocks`` sentinels the
                    shared chain, which is never written)."""
                    view = constrain_cache(
                        cache_pool.gather_cache(state["pool"], block_tables)
                    )
                    positions = start[:, None] + jnp.arange(ids_tail.shape[1])[None, :]
                    logits, mut = model.apply(
                        {"params": params, "cache": view},
                        ids_tail,
                        mask_full,
                        use_cache=True,
                        positions=positions,
                        cache_positions=start,
                        mutable=["cache"],
                    )
                    first = jnp.take_along_axis(
                        logits, tail_last[:, None, None], axis=1
                    )[:, 0, :]
                    first_tok = jnp.argmax(first, axis=-1).astype(jnp.int32)
                    put = lambda dst, src: (  # noqa: E731
                        dst.at[slot_idx].set(src, mode="drop") if dst.ndim > 0 else dst
                    )
                    return first_tok, {
                        **state,
                        "pool": cache_pool.scatter_admit(
                            state["pool"], mut["cache"], admit_blocks, bs
                        ),
                        "mask": put(state["mask"], mask_full),
                        "last": put(state["last"], first_tok),
                    }

                self._warm_admit_core = warm_admit

                def step(params, state, block_tables, write_pos, rope_pos, active):
                    width = state["mask"].shape[1]
                    offs = jnp.where(active, write_pos, width)
                    mask = state["mask"].at[jnp.arange(S), offs].set(1, mode="drop")
                    # the slot view is a step-transient: only the pool is
                    # resident between steps (serving/cache_pool.py)
                    cache = constrain_cache(
                        cache_pool.gather_cache(state["pool"], block_tables)
                    )
                    logits, mut = model.apply(
                        {"params": params, "cache": cache},
                        state["last"][:, None],
                        mask,
                        use_cache=True,
                        positions=rope_pos[:, None],
                        cache_positions=offs,
                        mutable=["cache"],
                    )
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                    nxt = jnp.where(active, nxt, self.pad)
                    pool = cache_pool.scatter_step(
                        state["pool"], mut["cache"], block_tables, offs,
                        num_blocks=n_blocks, block_size=bs,
                    )
                    return nxt, {
                        **state,
                        "pool": pool,
                        "mask": mask,
                        "last": nxt,
                    }
            else:
                def admit(state, cache, full_mask, first_tok, slot_idx):
                    put = lambda dst, src: (  # noqa: E731
                        dst.at[slot_idx].set(src, mode="drop") if dst.ndim > 0 else dst
                    )
                    return {
                        **state,
                        "cache": jax.tree.map(put, state["cache"], _pad_cache_tree(cache)),
                        "mask": put(state["mask"], self._pad_axis(full_mask, 1, width_full)),
                        "last": put(state["last"], first_tok),
                    }

                def step(params, state, write_pos, rope_pos, active):
                    width = state["mask"].shape[1]
                    offs = jnp.where(active, write_pos, width)
                    mask = state["mask"].at[jnp.arange(S), offs].set(1, mode="drop")
                    logits, mut = model.apply(
                        {"params": params, "cache": state["cache"]},
                        state["last"][:, None],
                        mask,
                        use_cache=True,
                        positions=rope_pos[:, None],
                        cache_positions=offs,
                        mutable=["cache"],
                    )
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                    nxt = jnp.where(active, nxt, self.pad)
                    return nxt, {
                        **state,
                        "cache": constrain_cache(mut["cache"]),
                        "mask": mask,
                        "last": nxt,
                    }

        self._prefill_core = prefill
        self._prefill = self._wrap(prefill, name="prefill")
        self._admit = self._wrap(admit, donate=(0,), name="admit")
        self._step = self._wrap(step, donate=(1,), name="decode_step")
        if self.paged and self.prefix:
            self._warm_admit = self._wrap(
                self._warm_admit_core, donate=(1,), name="warm_admit"
            )
        if self.spec:
            verify = spec_decode.build_verify(
                model, slots=S, k=self.spec, pad=self.pad,
                paged=self.paged,
                num_blocks=self.pool.num_blocks if self.paged else 0,
                block_size=self.block_size if self.paged else 0,
            )
            self._verify = self._wrap(verify, donate=(1,), name="spec_verify")

    # --------------------------------------------------------------- state
    def _leaf_spec(self, path: str, x):
        from jax.sharding import PartitionSpec as P

        from distributed_llms_example_tpu.parallel.sharding import (
            kv_leaf_spec,
            kv_scale_spec,
            pool_rules,
        )

        mesh_axes = dict(self.mesh.shape)
        batch_shards = 1
        for a in BATCH_AXES:
            batch_shards *= mesh_axes.get(a, 1)
        nd = getattr(x, "ndim", 0)
        if nd == 0:
            return P()
        if path.startswith("pool"):
            # shared block pool: blocks belong to single slots, so the
            # block dim never shards over the batch axes — POOL_RULES
            leaf = path.rsplit("/", 1)[-1]
            return pool_rules().spec_for(leaf, nd)
        if nd == 4:  # cached/cross K/V: the ONE shared layout definition
            return kv_leaf_spec(x.shape, mesh_axes)
        if nd == 3 and path.endswith("_scale"):  # int8 KV scales
            return kv_scale_spec(x.shape, mesh_axes)
        batch = BATCH_AXES if x.shape[0] % max(batch_shards, 1) == 0 else None
        return P(batch, *([None] * (nd - 1)))

    def _place(self, tree):
        if self.mesh is None:
            return tree
        import jax.tree_util as jtu
        from jax.sharding import NamedSharding

        from distributed_llms_example_tpu.parallel.sharding import _path_str

        return jtu.tree_map_with_path(
            lambda p, x: jax.device_put(
                x, NamedSharding(self.mesh, self._leaf_spec(_path_str(p), x))
            ),
            tree,
        )

    def _init_state(self, params) -> dict:
        S, W, L = self.S, self.W, self.L
        zeros = lambda s: jax.tree.map(  # noqa: E731
            lambda a: jnp.zeros(a.shape, a.dtype), s
        )
        with kv_cache_context(self.serve.kv_cache_dtype):
            if self.is_seq2seq:
                ids = jnp.zeros((S, W), jnp.int32)
                mask = jnp.zeros((S, W), jnp.int32)
                a_enc, _, a_ckv = jax.eval_shape(
                    lambda p: self._prefill_core(p, ids, mask), params
                )
                enc0 = zeros(a_enc)
                state = {
                    "cache": _init_cache(self.model, params, S, L, enc0, mask),
                    "enc": enc0,
                    "enc_mask": mask,
                    "ckv": zeros(a_ckv),
                    "last": jnp.full((S, 1), self.pad, jnp.int32),
                }
            else:
                ids = jnp.zeros((S, W), jnp.int32)
                mask = jnp.zeros((S, W), jnp.int32)
                a_cache, a_mask, _, _ = jax.eval_shape(
                    lambda p: self._prefill_core(p, ids, mask), params
                )
                if self.paged:
                    state = {
                        "pool": cache_pool.pool_cache_tree(
                            a_cache, self.pool.num_blocks, self.block_size
                        ),
                        "mask": zeros(a_mask),
                        "last": jnp.full((S,), self.pad, jnp.int32),
                    }
                else:
                    state = {
                        "cache": zeros(a_cache),
                        "mask": zeros(a_mask),
                        "last": jnp.full((S,), self.pad, jnp.int32),
                    }
        return self._place(state)

    # ------------------------------------------------------------ capacity
    def _state_byte_account(self, state) -> tuple[int, int]:
        """(resident bytes, per-block bytes) of the serving K/V state —
        static accounting over the cache/pool/enc/ckv leaves (masks and
        token vectors are noise).  per-block is 0 on the flat path."""
        if self.paged:
            kv = state["pool"]
            resident = cache_pool.tree_bytes(kv)
            per_block = cache_pool.block_bytes(kv, self.pool.num_blocks)
            return resident, per_block
        keys = ("cache", "enc", "ckv") if self.is_seq2seq else ("cache",)
        resident = sum(cache_pool.tree_bytes(state[k]) for k in keys if k in state)
        return resident, 0

    def warm(self, params, state) -> Any:
        """AOT-warm every compiled program before the first real request:
        one prefill+admit trace per bucket (zeros, all writes dropped via
        out-of-range slot indices) and one all-slots-idle decode step —
        so no request ever pays a compile, and the trace counts are
        pinned BEFORE traffic (``trace_counts``).  Returns the (possibly
        donated-and-rebound) state."""
        if self._warmed:
            return state
        C, S = self.prefill_batch, self.S
        park = jnp.full((C,), S, jnp.int32)  # out of range: every write drops
        for bucket in self.buckets:
            ids = jnp.zeros((C, bucket), jnp.int32)
            mask = jnp.zeros((C, bucket), jnp.int32)
            pre = self._prefill(params, ids, mask)
            if self.is_seq2seq:
                enc, pmask, ckv = pre
                state = self._admit(state, enc, pmask, ckv, park)
            elif self.paged:
                cache, full_mask, _, first = pre
                ntc = (bucket + self.L) // self.block_size
                sentinel = jnp.full((C * ntc,), self.pool.num_blocks, jnp.int32)
                state = self._admit(state, cache, full_mask, first, park, sentinel)
            else:
                cache, full_mask, _, first = pre
                state = self._admit(state, cache, full_mask, first, park)
        if self.paged and self.prefix:
            # one warm-admission trace per tail bucket, all writes dropped
            # (park slots, sentinel block tables, out-of-range starts)
            width_full = self.W + self.L
            for bucket in self.buckets:
                _, state = self._warm_admit(
                    params, state,
                    jnp.zeros((C, bucket), jnp.int32),
                    jnp.zeros((C, width_full), jnp.int32),
                    jnp.full((C,), width_full, jnp.int32),
                    jnp.zeros((C,), jnp.int32),
                    park,
                    jnp.full((C, self.n_tiles), self.pool.num_blocks, jnp.int32),
                    jnp.full((C * self.n_tiles,), self.pool.num_blocks, jnp.int32),
                )
        idle = jnp.zeros((S,), bool)
        pos = jnp.zeros((S,), jnp.int32)
        if self.is_seq2seq:
            _, state = self._step(params, state, pos, idle)
        elif self.paged:
            bt = jnp.full((S, self.n_tiles), self.pool.num_blocks, jnp.int32)
            _, state = self._step(params, state, bt, pos, pos, idle)
        else:
            _, state = self._step(params, state, pos, pos, idle)
        if self.spec:
            # one all-idle verify round: the spec program joins the
            # zero-recompile contract alongside the plain step
            x0 = jnp.full((S, self.spec + 1), self.pad, jnp.int32)
            room0 = jnp.zeros((S,), jnp.int32)
            if self.paged:
                sbt = jnp.full(
                    (S, self.n_tiles), self.pool.num_blocks, jnp.int32
                )
                _, _, state = self._verify(
                    params, state, x0, sbt, pos, pos, idle, room0
                )
            else:
                _, _, state = self._verify(
                    params, state, x0, pos, pos, idle, room0
                )
        self._warmed = True
        return state

    # ---------------------------------------------------------------- loop
    def open(self, params: Any, *, replica: int | None = None) -> "ServeSession":
        """Open a stepwise serving session over this engine: ``submit``
        requests as they arrive, drive ``step()`` per scheduler round,
        ``finalize()`` at end of life.  ``generate`` below is the batch
        wrapper; the replica router (serving/router.py) drives one open
        session per replica.  ``replica`` stamps the serve events so the
        router tier's streams stay attributable per engine."""
        return ServeSession(self, params, replica=replica)

    def generate(
        self,
        params: Any,
        requests: Sequence[Sequence[int]],
        *,
        attention_masks: Sequence[Sequence[int]] | None = None,
        max_new: Sequence[int] | None = None,
    ) -> list[list[int]]:
        """Serve ``requests`` (token-id prompts, request order preserved)
        to completion; returns per-request generated ids (eos included when
        emitted).  ``max_new`` optionally caps each request below the
        engine-wide ``max_new_tokens`` (the per-request ``max_tokens`` of a
        real serving API — and the lever continuous batching exists for:
        a short request frees its slot the step it finishes).  Fills
        ``self.last_stats`` and emits serve_window / serve_summary obs
        events.  Thin wrapper over a ``ServeSession``: submit everything,
        step until drained, finalize."""
        if max_new is not None and len(max_new) != len(requests):
            raise ValueError(
                f"max_new has {len(max_new)} entries for {len(requests)} requests"
            )
        sess = self.open(params)
        for i, req in enumerate(requests):
            sess.submit(
                req,
                max_new=(max_new[i] if max_new is not None else None),
                attention_mask=(
                    attention_masks[i] if attention_masks is not None else None
                ),
            )
        while sess.has_work():
            sess.step()
        sess.finalize()
        return list(sess.outputs)


class ServeSession:
    """One serving lifetime over an engine, stepwise.

    The engine's former monolithic ``generate`` loop, split at the
    scheduler-round boundary so a tier ABOVE the engine can drive it:
    ``submit`` enqueues a request (any time, not just up front),
    ``step()`` runs one admit-then-decode round and returns the requests
    that finished during it, ``finalize()`` closes the books
    (serve_summary, ``engine.last_stats``).  All compiled programs, slot
    bookkeeping, byte accounting, and obs events are exactly the
    engine's — the split moves control flow, not semantics, which is why
    the engine-vs-static determinism pins keep covering every driver.

    The replica router (serving/router.py) opens one session per engine
    replica; ``progress`` (bumped on every admit chunk and decode step)
    is its per-replica heartbeat, ``take_pending`` is its drain path,
    and ``label`` lets it thread router-global request ids through the
    ``serve_request`` span stream."""

    def __init__(self, engine: ServingEngine, params: Any,
                 *, replica: int | None = None):
        import collections

        eng = self.eng = engine
        self.params = params
        self.replica = replica
        self.n_chips = max(jax.device_count(), 1)
        S = eng.S
        # per-request tables, session-local rid = index (grow on submit)
        self.requests: list[list[int]] = []
        self.attn_masks: list[Sequence[int] | None] = []
        self.budgets: list[int] = []
        self.labels: list[Any] = []
        self.outputs: list[list[int]] = []
        self.ttft: list[float | None] = []
        self.submit_t: list[float] = []
        # absolute arrival instant per request (perf_counter timeline).
        # Closed-loop drivers never pass one, so arrival == submit and the
        # arrival→submit queue delay reads 0; an open-loop driver
        # (serving/loadgen.py) stamps the SCHEDULED arrival, so the time a
        # request waited before the driver could even submit it becomes a
        # first-class, JSONL-visible queueing stage instead of vanishing
        self.arrival_t: list[float] = []
        self.first_tok_wall: list[float | None] = []
        self.admit_t: list[float | None] = []
        self.prefill_dt: list[float] = []
        self.pending: "collections.deque[int]" = collections.deque()
        self.stats = ServeStats()
        # the router's heartbeat: bumps on every admit chunk and decode
        # step — a replica whose counter stops moving while it has work
        # is stalled (live → suspect → dead in the router's machine)
        self.progress = 0
        # slot bookkeeping (the generate loop's former closure state)
        self.slot_req = np.full(S, -1, np.int64)  # request index per slot
        self.emitted = np.zeros(S, np.int64)
        self.lengths = np.zeros(S, np.int64)  # true prompt lengths
        self.base = np.full(S, eng.W, np.int64)  # causal: decode tail start
        self.active = np.zeros(S, bool)
        # paged bookkeeping: block ownership per slot + the block table
        # the step program reads (sentinel = num_blocks → reads fill
        # zeros, writes drop)
        self.slot_blocks: list[list[int]] = [[] for _ in range(S)]
        # prefix-cache bookkeeping: the slot's registered full-prompt
        # chain (root → tail order), a subset of slot_blocks — eviction
        # releases the chain tail-first so the LRU keeps roots longest
        # (a shorter prefix stays matchable after partial eviction)
        self.slot_chain: list[list[int]] = [[] for _ in range(S)]
        self.slot_bt = (
            np.full((S, eng.n_tiles), eng.pool.num_blocks, np.int32)
            if eng.paged
            else None
        )
        self.state = eng._init_state(params)
        self.state = eng.warm(params, self.state)
        self.t_open = time.perf_counter()
        self.stats.cache_bytes_resident, self._per_block = (
            eng._state_byte_account(self.state)
        )
        if eng.paged and eng.prefix:
            # the device pool tensor was just re-zeroed (_init_state), so
            # any warm chains a PREVIOUS session retained now index
            # garbage — matching them would splice zeros into a prompt.
            # Warm content is session-lifetime state: drop it with it.
            eng.pool.drop_warm()
            if self._per_block:
                # warm-retention budget in BLOCKS, derived from the byte
                # budget once the per-block byte account exists (0 = off)
                eng.pool.warm_capacity = int(
                    eng.serve.prefix_cache_budget_gib * (1 << 30)
                    // self._per_block
                )
        # loaded-weight bytes for the shared memory account (metadata
        # arithmetic only — no device fetch)
        self.params_bytes = int(sum(
            int(np.prod(x.shape)) * x.dtype.itemsize
            for x in jax.tree.leaves(params)
        ))
        self._bpt_samples: list[float] = []
        self._win_tokens, self._win_occ = 0, 0.0
        self._win_t0 = time.perf_counter()
        self._win_prefill, self._win_decode = 0.0, 0.0
        # queueing-telemetry window counters: submissions vs completions
        # inside the window — their imbalance IS the queue growing
        self._win_arrivals, self._win_done = 0, 0
        # speculative decode: what each slot appended last round (the
        # draft model's catch-up feed next round; None until the slot's
        # first post-admit round) + the draft model's own cache state
        self._spec_fed: list[list[int] | None] = [None] * S
        self.draft_state = None
        if eng.drafter is not None:
            self.draft_state = eng.drafter.init_state()
            self.draft_state = eng.drafter.warm(self.draft_state)
        self._win_spec_steps, self._win_spec_emitted = 0, 0
        self._finalized = False

    # ------------------------------------------------------------- intake
    def submit(
        self,
        tokens: Sequence[int],
        *,
        max_new: int | None = None,
        attention_mask: Sequence[int] | None = None,
        label: Any = None,
        arrival: float | None = None,
    ) -> int:
        """Enqueue one request; returns the session-local rid.  ``label``
        (default: the rid) is what the ``serve_request`` event carries as
        ``request`` — the router passes its global request id.
        ``arrival`` (absolute perf_counter instant, default: now) is when
        the request ARRIVED, which under open-loop load precedes the
        submit — the gap is the driver-side queueing delay the
        ``serve_request`` record stamps as ``queue_delay_ms``."""
        if self._finalized:
            raise RuntimeError("session already finalized")
        rid = len(self.requests)
        self.requests.append(list(tokens))
        self.attn_masks.append(
            list(attention_mask) if attention_mask is not None else None
        )
        self.budgets.append(
            min(int(max_new), self.eng.L) if max_new is not None else self.eng.L
        )
        self.labels.append(rid if label is None else label)
        self.outputs.append([])
        self.ttft.append(None)
        now = time.perf_counter()
        self.submit_t.append(now)
        self.arrival_t.append(float(arrival) if arrival is not None else now)
        self.first_tok_wall.append(None)
        self.admit_t.append(None)
        self.prefill_dt.append(0.0)
        self.pending.append(rid)
        self.stats.sequences += 1
        self._win_arrivals += 1
        return rid

    def take_pending(self) -> list[Any]:
        """Remove every not-yet-admitted request and return their labels
        — the router's drain path (re-dispatch elsewhere; live slots keep
        decoding to completion here).  The removed requests' outputs stay
        empty and they never reach the serve_request stream."""
        labels = [self.labels[rid] for rid in self.pending]
        self.pending.clear()
        return labels

    # ------------------------------------------------------------- gauges
    @property
    def queue_depth(self) -> int:
        return len(self.pending)

    @property
    def active_count(self) -> int:
        return int(self.active.sum())

    def has_work(self) -> bool:
        return bool(self.pending) or bool(self.active.any())

    def output(self, rid: int) -> list[int]:
        return self.outputs[rid]

    def first_token_wall(self, rid: int) -> float | None:
        """Absolute perf_counter instant of the request's first token —
        the router computes its own TTFT from its own submit instant."""
        return self.first_tok_wall[rid]

    def prefix_ref_violations(self) -> list[str]:
        """The refcount invariant, walked from THIS session's live block
        tables: every pool block's refcount must equal its live
        references (slot ownership) + warm-LRU membership.  Empty list =
        invariant holds; tests and the lint contract pin this after
        admit/evict/COW churn."""
        return self.eng.pool.ref_invariant_violations(
            [sb for sb in self.slot_blocks if sb]
        )

    def _bytes_in_use(self) -> int:
        if self.eng.paged:
            return self.eng.pool.blocks_in_use * self._per_block
        return self.stats.cache_bytes_resident

    def _live_tokens(self) -> int:
        # tokens the serving state holds for live requests: true prompt
        # + generated so far, per active slot
        return int((self.lengths[self.active] + self.emitted[self.active]).sum())

    # ---------------------------------------------------------- lifecycle
    def _finish_request(self, rid: int, slot: int, now: float) -> None:
        """Evict-time lifecycle record — the trace exporter's feed and
        the post-hoc 'why was THIS request's TTFT fat' answer."""
        if not self.eng.serve.request_spans:
            return
        t_sub = self.submit_t[rid]
        t_arr = self.arrival_t[rid]
        t_admit = self.admit_t[rid] if self.admit_t[rid] is not None else t_sub
        queue_wait = t_admit - t_sub
        t = self.ttft[rid]
        record = {
            "event": "serve_request",
            "request": self.labels[rid],
            "slot": int(slot),
            # arrival→submit: the open-loop driver-side wait (0 under
            # closed-loop driving, where arrival is stamped == submit);
            # queue_wait_ms below is the submit→admit stage — total
            # queueing delay = queue_delay_ms + queue_wait_ms, readable
            # off this one record
            "t_arrival_s": round(t_arr - self.t_open, 6),
            "queue_delay_ms": round((t_sub - t_arr) * 1e3, 3),
            "queue_wait_ms": round(queue_wait * 1e3, 3),
            "prefill_ms": round(self.prefill_dt[rid] * 1e3, 3),
            "ttft_ms": round(t * 1e3, 3) if t is not None else None,
            "decode_ms": round(
                (now - t_sub - (t if t is not None else queue_wait)) * 1e3, 3
            ),
            "tokens": len(self.outputs[rid]),
            "t_admit_s": round(t_admit - self.t_open, 6),
            "t_done_s": round(now - self.t_open, 6),
            "finished_at_step": int(self.stats.decode_steps),
        }
        if self.replica is not None:
            record["replica"] = int(self.replica)
        log_json(record)

    def _evict_slot(self, slot: int) -> None:
        """Free the slot NOW — and, paged, drop one reference per block it
        held (the evict-returns-all-blocks contract; under prefix_cache a
        shared block survives until its LAST holder evicts).  The
        registered chain releases tail-first so warm retention ages the
        DEEP end of a prefix out before its root — a partially-evicted
        chain still matches at shorter prefixes."""
        self.active[slot] = False
        self.slot_req[slot] = -1
        self._spec_fed[slot] = None
        self._win_done += 1
        if self.eng.paged and self.slot_blocks[slot]:
            chain = self.slot_chain[slot]
            if chain:
                in_chain = set(chain)
                rest = [b for b in self.slot_blocks[slot] if b not in in_chain]
                self.eng.pool.free(rest + list(reversed(chain)))
            else:
                self.eng.pool.free(self.slot_blocks[slot])
            self.slot_blocks[slot] = []
            self.slot_chain[slot] = []
            self.slot_bt[slot, :] = self.eng.pool.num_blocks

    def _admit_now(self, finished: list) -> None:
        eng = self.eng
        if eng.paged and eng.prefix:
            return self._admit_now_prefix(finished)
        S, W, C = eng.S, eng.W, eng.prefill_batch
        free = [i for i in range(S) if not self.active[i]]
        n = min(len(free), C, len(self.pending))
        if n == 0:
            return
        plen = lambda rid: min(len(self.requests[rid]), W)  # noqa: E731
        if eng.paged:
            # shrink the chunk until the free list funds it: admission
            # DEFERS on a short pool instead of over-committing — every
            # eviction frees blocks, so deferred requests admit later
            while n > 0:
                needed = sum(
                    cache_pool.blocks_needed(
                        plen(self.pending[i]), self.budgets[self.pending[i]],
                        eng.block_size,
                    )
                    for i in range(n)
                )
                if eng.pool.can_alloc(needed):
                    break
                n -= 1
            if n == 0:
                self.stats.admit_deferrals += 1
                return
        reqs = [self.pending.popleft() for _ in range(n)]
        # the smallest compiled admission width covering this chunk —
        # short prompts stop paying the max_source_length program
        bucket = next(
            b for b in eng.buckets if b >= max(plen(rid) for rid in reqs)
        )
        ids = np.full((C, bucket), eng.pad, np.int32)
        mask = np.zeros((C, bucket), np.int32)
        for r, rid in enumerate(reqs):
            toks = self.requests[rid][:bucket]
            ids[r, : len(toks)] = toks
            mask[r, : len(toks)] = 1
            if self.attn_masks[rid] is not None:
                m = self.attn_masks[rid][:bucket]
                mask[r, : len(m)] = m
        slot_idx = np.full(C, S, np.int32)  # padding rows drop
        slot_idx[:n] = free[:n]
        admit_rows = None
        if eng.paged:
            # fund + map each row's blocks BEFORE the program runs: the
            # flat (chunk × chunk-tiles) assignment carries sentinels for
            # tiles that must not copy (padding rows, prompt gap)
            ntc = (bucket + eng.L) // eng.block_size
            admit_rows = np.full((C, ntc), eng.pool.num_blocks, np.int32)
            for r, rid in enumerate(reqs):
                blocks = eng.pool.alloc(
                    cache_pool.blocks_needed(
                        plen(rid), self.budgets[rid], eng.block_size
                    )
                )
                assert blocks is not None  # funded above
                slot = free[r]
                self.slot_blocks[slot] = blocks
                row = cache_pool.build_block_row(
                    eng.n_tiles, blocks,
                    prompt_len=plen(rid), bucket_width=bucket,
                    budget=self.budgets[rid], block_size=eng.block_size,
                    sentinel=eng.pool.num_blocks,
                )
                self.slot_bt[slot, :] = row
                admit_rows[r, :] = row[:ntc]
        t0 = time.perf_counter()
        pre = eng._prefill(self.params, jnp.asarray(ids), jnp.asarray(mask))
        if eng.is_seq2seq:
            enc, pmask, ckv = pre
            self.state = eng._admit(
                self.state, enc, pmask, ckv, jnp.asarray(slot_idx)
            )
        else:
            cache, full_mask, plens, first = pre
            if eng.paged:
                self.state = eng._admit(
                    self.state, cache, full_mask, first, jnp.asarray(slot_idx),
                    jnp.asarray(admit_rows.reshape(-1)),
                )
            else:
                self.state = eng._admit(
                    self.state, cache, full_mask, first, jnp.asarray(slot_idx)
                )
            plens_h = np.asarray(jax.device_get(plens))
            first_h = np.asarray(jax.device_get(first))
        dt = time.perf_counter() - t0
        self.stats.prefill_seconds += dt
        self._win_prefill += dt
        self.progress += 1
        now = time.perf_counter()
        for r, rid in enumerate(reqs):
            slot = free[r]
            self.slot_req[slot] = rid
            self.emitted[slot] = 0
            self.lengths[slot] = plen(rid)
            self.base[slot] = bucket
            self.active[slot] = True
            self.admit_t[rid] = t0
            self.prefill_dt[rid] = dt
            if not eng.is_seq2seq:
                self.lengths[slot] = int(plens_h[r])
                # the causal prefill already produced token #1
                self.outputs[rid].append(int(first_h[r]))
                self.emitted[slot] = 1
                self.ttft[rid] = now - self.submit_t[rid]
                self.first_tok_wall[rid] = now
                if (
                    int(first_h[r]) == eng.eos
                    or self.emitted[slot] >= self.budgets[rid]
                ):
                    self._evict_slot(slot)
                    self._finish_request(rid, slot, now)
                    finished.append(rid)
        self.stats.peak_cache_bytes_in_use = max(
            self.stats.peak_cache_bytes_in_use, self._bytes_in_use()
        )

    def _admit_now_prefix(self, finished: list) -> None:
        """Prefix-cache admission: per-row transactional packing (match
        the longest cached chain → acquire it → alloc only the tail,
        rolling the acquire back when the pool comes up short), then at
        most TWO dispatches — the plain cold-prefill chunk for rows with
        no cached prefix, followed by one warm-admit chunk that gathers
        the matched chains from the pool and prefills only the tails.

        Cold dispatches FIRST so a warm row may match a chain registered
        by a cold row of the SAME wave (the warm gather reads the cold
        scatter's pool state); a warm row must NOT match another warm
        row's fresh tail blocks — those land in the same program call it
        would gather from — so matches truncate before any block first
        written by this wave's warm chunk."""
        eng = self.eng
        S, W, C = eng.S, eng.W, eng.prefill_batch
        bs, N = eng.block_size, eng.pool.num_blocks
        free = [i for i in range(S) if not self.active[i]]
        n = min(len(free), C, len(self.pending))
        if n == 0:
            return
        plen = lambda rid: min(len(self.requests[rid]), W)  # noqa: E731
        cold: list[tuple[int, int, int, list[str]]] = []  # rid, slot, p, hashes
        warm: list[dict] = []
        warm_written: set[int] = set()
        taken = 0
        while taken < n:
            rid = self.pending[0]
            p = plen(rid)
            budget = self.budgets[rid]
            toks = self.requests[rid][:p]
            # custom-masked prompts have no token-only identity: their KV
            # depends on the mask too, so they neither match nor register
            eligible = self.attn_masks[rid] is None
            hashes = cache_pool.chain_hashes(toks, bs) if eligible else []
            # keep >= 1 prompt token in the tail — the first output token
            # is computed from the LAST prompt position's logits, so a
            # fully-cached prompt still re-prefills its final block
            chain = (
                eng.pool.match_chain(hashes[: (p - 1) // bs])
                if eligible else []
            )
            for i, b in enumerate(chain):
                if b in warm_written:
                    chain = chain[:i]
                    break
            k = len(chain)
            need = (
                max(1, math.ceil(p / bs)) - k
                + math.ceil(max(budget, 1) / bs)
            )
            if k:
                eng.pool.acquire(chain)
            fresh = eng.pool.alloc(need)
            if fresh is None:
                if k:
                    eng.pool.free(list(reversed(chain)))  # roll back
                break
            self.pending.popleft()
            slot = free[taken]
            taken += 1
            blocks = chain + fresh
            self.slot_blocks[slot] = blocks
            full_tiles = p // bs
            if eligible and full_tiles:
                eng.pool.register(blocks[:full_tiles], hashes[:full_tiles])
                self.slot_chain[slot] = list(blocks[:full_tiles])
            else:
                self.slot_chain[slot] = []
            if eligible:
                self.stats.prefix_lookups += 1
            self.stats.prefill_tokens_total += p
            if k:
                self.stats.prefix_hits += 1
                self.stats.prefill_tokens_saved += k * bs
                warm_written.update(blocks[k:full_tiles])
                warm.append({
                    "rid": rid, "slot": slot, "p": p,
                    "bucket": next(b for b in eng.buckets if b >= p),
                    "start": k * bs, "tail": toks[k * bs:],
                })
            else:
                cold.append((rid, slot, p, hashes))
        if taken == 0:
            self.stats.admit_deferrals += 1
            return
        now = time.perf_counter()
        # ---- cold chunk: the plain prefill+admit path over cold rows
        if cold:
            bucket = next(
                b for b in eng.buckets if b >= max(p for _, _, p, _ in cold)
            )
            ids = np.full((C, bucket), eng.pad, np.int32)
            mask = np.zeros((C, bucket), np.int32)
            slot_idx = np.full(C, S, np.int32)
            ntc = (bucket + eng.L) // bs
            admit_rows = np.full((C, ntc), N, np.int32)
            for r, (rid, slot, p, _h) in enumerate(cold):
                toks = self.requests[rid][:bucket]
                ids[r, : len(toks)] = toks
                mask[r, : len(toks)] = 1
                if self.attn_masks[rid] is not None:
                    m = self.attn_masks[rid][:bucket]
                    mask[r, : len(m)] = m
                slot_idx[r] = slot
                row = cache_pool.build_block_row(
                    eng.n_tiles, self.slot_blocks[slot],
                    prompt_len=p, bucket_width=bucket,
                    budget=self.budgets[rid], block_size=bs, sentinel=N,
                )
                self.slot_bt[slot, :] = row
                admit_rows[r, :] = row[:ntc]
            t0 = time.perf_counter()
            cache, full_mask, plens, first = eng._prefill(
                self.params, jnp.asarray(ids), jnp.asarray(mask)
            )
            self.state = eng._admit(
                self.state, cache, full_mask, first, jnp.asarray(slot_idx),
                jnp.asarray(admit_rows.reshape(-1)),
            )
            plens_h = np.asarray(jax.device_get(plens))
            first_h = np.asarray(jax.device_get(first))
            dt = time.perf_counter() - t0
            self.stats.prefill_seconds += dt
            self._win_prefill += dt
            self.progress += 1
            now = time.perf_counter()
            for r, (rid, slot, p, _h) in enumerate(cold):
                self._admit_bookkeep(
                    rid, slot, int(plens_h[r]), bucket, int(first_h[r]),
                    t0, dt, now, finished,
                )
        # ---- warm chunk: gather matched chains, prefill only the tails
        if warm:
            width_full = W + eng.L
            tail_bucket = next(
                b for b in eng.buckets if b >= max(len(w["tail"]) for w in warm)
            )
            ids_t = np.full((C, tail_bucket), eng.pad, np.int32)
            mask_f = np.zeros((C, width_full), np.int32)
            start = np.full(C, width_full, np.int32)  # park rows write nowhere
            tail_last = np.zeros(C, np.int32)
            slot_idx = np.full(C, S, np.int32)
            bt = np.full((C, eng.n_tiles), N, np.int32)
            admit_rows = np.full((C, eng.n_tiles), N, np.int32)
            for r, wr in enumerate(warm):
                slot = wr["slot"]
                tail = wr["tail"]
                ids_t[r, : len(tail)] = tail
                mask_f[r, : wr["p"]] = 1
                start[r] = wr["start"]
                tail_last[r] = len(tail) - 1
                slot_idx[r] = slot
                row = cache_pool.build_block_row(
                    eng.n_tiles, self.slot_blocks[slot],
                    prompt_len=wr["p"], bucket_width=wr["bucket"],
                    budget=self.budgets[wr["rid"]], block_size=bs, sentinel=N,
                )
                self.slot_bt[slot, :] = row
                bt[r, :] = row
                # scatter ONLY the fresh tail prompt tiles back: the
                # matched chain is immutable (shared), and decode tiles
                # keep pool garbage until decode writes them (the
                # poisoned-pool invariant — masked until valid)
                k_tiles = wr["start"] // bs
                full_tiles = max(1, math.ceil(wr["p"] / bs))
                admit_rows[r, k_tiles:full_tiles] = row[k_tiles:full_tiles]
            t0 = time.perf_counter()
            first_w, self.state = eng._warm_admit(
                self.params, self.state,
                jnp.asarray(ids_t), jnp.asarray(mask_f), jnp.asarray(start),
                jnp.asarray(tail_last), jnp.asarray(slot_idx),
                jnp.asarray(bt), jnp.asarray(admit_rows.reshape(-1)),
            )
            first_wh = np.asarray(jax.device_get(first_w))
            dt = time.perf_counter() - t0
            self.stats.prefill_seconds += dt
            self._win_prefill += dt
            self.progress += 1
            now = time.perf_counter()
            for r, wr in enumerate(warm):
                self._admit_bookkeep(
                    wr["rid"], wr["slot"], wr["p"], wr["bucket"],
                    int(first_wh[r]), t0, dt, now, finished,
                )
        self.stats.peak_cache_bytes_in_use = max(
            self.stats.peak_cache_bytes_in_use, self._bytes_in_use()
        )

    def _admit_bookkeep(
        self, rid: int, slot: int, length: int, base: int, first: int,
        t0: float, dt: float, now: float, finished: list,
    ) -> None:
        """Per-row post-admit bookkeeping shared by the prefix path's
        cold and warm chunks — byte-for-byte the causal branch of
        ``_admit_now``'s trailing loop."""
        eng = self.eng
        self.slot_req[slot] = rid
        self.lengths[slot] = length
        self.base[slot] = base
        self.active[slot] = True
        self.admit_t[rid] = t0
        self.prefill_dt[rid] = dt
        self.outputs[rid].append(first)
        self.emitted[slot] = 1
        self.ttft[rid] = now - self.submit_t[rid]
        self.first_tok_wall[rid] = now
        if first == eng.eos or self.emitted[slot] >= self.budgets[rid]:
            self._evict_slot(slot)
            self._finish_request(rid, slot, now)
            finished.append(rid)

    def step(self) -> list[int]:
        """One scheduler round: admit into free slots, then — if any slot
        is live — one decode step.  Returns the session-local rids of
        requests that finished during this call (finish-at-prefill
        included).  The batch ``generate`` loop is
        ``while has_work(): step()``.  A RESOURCE_EXHAUSTED escaping the
        round trips the OOM forensics (obs/memprof.py): the postmortem
        bundle lands atomically, then the error re-raises — the session
        never swallows it."""
        try:
            return self._step_round()
        except Exception as e:
            self._oom_tripwire(e)
            raise

    def _memory_account(self) -> dict:
        """The serving tier's bucketed HBM account over the shared
        taxonomy: loaded weights in ``params``, the live cache/pool bytes
        (the capacity gauges' arithmetic) in ``kv_cache``."""
        from distributed_llms_example_tpu.obs import memprof

        return memprof.serving_account(
            params_bytes=self.params_bytes,
            kv_cache_bytes=self._bytes_in_use(),
            hbm_budget_gib=self.eng.serve.hbm_budget_gib,
        )

    def _oom_tripwire(self, e: BaseException) -> None:
        """Dump the memory postmortem when ``e`` is an OOM and a dump dir
        is configured; the caller re-raises either way."""
        out_dir = self.eng.serve.postmortem_dir
        if not out_dir:
            return
        from distributed_llms_example_tpu.obs import memprof

        if not memprof.is_resource_exhausted(e):
            return
        memprof.dump_postmortem(
            out_dir,
            reason=f"{type(e).__name__}: {str(e)[:300]}",
            step=self.stats.decode_steps,
            account=self._memory_account(),
        )

    def _spec_dispatch(self, offsets):
        """Assemble one draft-then-verify round.  Drafts come from the
        n-gram self-drafter or the shrunk draft model; serving/spec.py
        owns BOTH drafters and all acceptance/rollback math (repo_lint
        rule 17) — this method only packs inputs and runs the compiled
        programs.  Returns host arrays ``(target_tokens (S, k+1),
        n_emit (S,))``."""
        eng = self.eng
        K, S = eng.spec, eng.S
        x = np.full((S, K + 1), eng.pad, np.int32)
        room = np.zeros((S,), np.int32)
        live = np.nonzero(self.active)[0]
        for s in live:
            rid = int(self.slot_req[s])
            x[s, 0] = self.outputs[rid][-1]
            # remaining budget minus the always-emitted bonus token: the
            # verify clamp that keeps a round from decoding past
            # max_new_tokens (clamping truncates, never alters, output)
            room[s] = max(int(self.budgets[rid]) - int(self.emitted[s]) - 1, 0)
        if eng.drafter is not None:
            self._draft_admissions()
            fed = np.full((S, K + 1), eng.pad, np.int32)
            n_fed = np.zeros((S,), np.int32)
            pos0 = np.zeros((S,), np.int32)
            rope0 = np.zeros((S,), np.int32)
            for s in live:
                f = self._spec_fed[s]
                fed[s, : len(f)] = f
                n_fed[s] = len(f)
                pos0[s] = int(self.base[s]) + int(self.emitted[s]) - len(f)
                rope0[s] = int(self.lengths[s]) + int(self.emitted[s]) - len(f)
            drafts, self.draft_state = eng.drafter.round(
                self.draft_state, jnp.asarray(fed), jnp.asarray(n_fed),
                jnp.asarray(pos0), jnp.asarray(rope0),
                jnp.asarray(self.active),
            )
            dr = np.asarray(jax.device_get(drafts))
            x[live, 1:] = dr[live]
        else:
            hist = [
                self.requests[int(self.slot_req[s])]
                + self.outputs[int(self.slot_req[s])]
                if self.active[s]
                else None
                for s in range(S)
            ]
            x[:, 1:] = spec_decode.ngram_drafts(hist, K, eng.pad)
        rope = self.lengths + self.emitted - 1
        if eng.paged:
            target, n_emit, self.state = eng._verify(
                self.params, self.state, jnp.asarray(x),
                jnp.asarray(self.slot_bt),
                jnp.asarray(offsets.astype(np.int32)),
                jnp.asarray(rope.astype(np.int32)),
                jnp.asarray(self.active), jnp.asarray(room),
            )
        else:
            target, n_emit, self.state = eng._verify(
                self.params, self.state, jnp.asarray(x),
                jnp.asarray(offsets.astype(np.int32)),
                jnp.asarray(rope.astype(np.int32)),
                jnp.asarray(self.active), jnp.asarray(room),
            )
        return (
            np.asarray(jax.device_get(target)),
            np.asarray(jax.device_get(n_emit)),
        )

    def _draft_admissions(self) -> None:
        """Bring slots admitted this round into the draft model's cache:
        the target prefilled their prompts during admission, so the draft
        prefills the SAME prompts at the same bucket width into its own
        flat cache (full prompts even under warm prefix hits — the draft
        cache shares nothing) and the catch-up feed starts from the
        admission's first emitted token."""
        eng = self.eng
        need = [
            s for s in np.nonzero(self.active)[0] if self._spec_fed[s] is None
        ]
        if not need:
            return
        for s in need:
            self._spec_fed[s] = [self.outputs[int(self.slot_req[s])][-1]]
        import collections

        by_bucket = collections.defaultdict(list)
        for s in need:
            by_bucket[int(self.base[s])].append(s)
        C = eng.prefill_batch
        for bucket, slots_ in sorted(by_bucket.items()):
            for i in range(0, len(slots_), C):
                chunk = slots_[i : i + C]
                ids = np.full((C, bucket), eng.pad, np.int32)
                mask = np.zeros((C, bucket), np.int32)
                slot_idx = np.full((C,), eng.S, np.int32)
                for r, s in enumerate(chunk):
                    rid = int(self.slot_req[s])
                    toks = self.requests[rid][:bucket]
                    ids[r, : len(toks)] = toks
                    mask[r, : len(toks)] = 1
                    if self.attn_masks[rid] is not None:
                        m = list(self.attn_masks[rid][:bucket])
                        mask[r, : len(m)] = m
                    slot_idx[r] = s
                self.draft_state = eng.drafter.admit_prompt(
                    self.draft_state, jnp.asarray(ids), jnp.asarray(mask),
                    jnp.asarray(slot_idx),
                )

    def _spec_append(self, toks, n_emit, now, finished) -> int:
        """Append one verify round's accepted-prefix + bonus tokens per
        live slot, with the SAME eos/budget eviction as the plain loop —
        a round whose accepted prefix crosses eos stops emitting there
        (trailing accepted tokens are discarded with the slot; greedy
        would never have decoded past eos either).  Returns the number of
        tokens actually appended."""
        eng, stats = self.eng, self.stats
        appended = 0
        slot_rounds = 0
        for slot in np.nonzero(self.active)[0]:
            rid = int(self.slot_req[slot])
            n = int(n_emit[slot])
            slot_rounds += 1
            stats.spec_drafted += eng.spec
            stats.spec_accepted += n - 1
            fed: list[int] = []
            evicted = False
            for j in range(n):
                tok = int(toks[slot, j])
                self.outputs[rid].append(tok)
                fed.append(tok)
                appended += 1
                if self.ttft[rid] is None:
                    self.ttft[rid] = now - self.submit_t[rid]
                    self.first_tok_wall[rid] = now
                self.emitted[slot] += 1
                if tok == eng.eos or self.emitted[slot] >= self.budgets[rid]:
                    self._evict_slot(slot)
                    self._finish_request(rid, slot, now)
                    finished.append(rid)
                    evicted = True
                    break
            if not evicted:
                self._spec_fed[slot] = fed
        stats.spec_steps += 1
        stats.spec_slot_rounds += slot_rounds
        stats.spec_emitted += appended
        self._win_spec_steps += slot_rounds
        self._win_spec_emitted += appended
        return appended

    def _step_round(self) -> list[int]:
        if self._finalized:
            raise RuntimeError("session already finalized")
        eng = self.eng
        finished: list[int] = []
        self._admit_now(finished)
        if not self.active.any():
            return finished  # every admitted sequence finished at prefill
        offsets = (
            self.emitted if eng.is_seq2seq else (self.base + self.emitted - 1)
        )
        t0 = time.perf_counter()
        if eng.spec:
            spec_toks, spec_emit = self._spec_dispatch(offsets)
        elif eng.is_seq2seq:
            tokens, self.state = eng._step(
                self.params, self.state,
                jnp.asarray(offsets.astype(np.int32)),
                jnp.asarray(self.active),
            )
        elif eng.paged:
            rope = self.lengths + self.emitted - 1
            tokens, self.state = eng._step(
                self.params, self.state,
                jnp.asarray(self.slot_bt),
                jnp.asarray(offsets.astype(np.int32)),
                jnp.asarray(rope.astype(np.int32)),
                jnp.asarray(self.active),
            )
        else:
            rope = self.lengths + self.emitted - 1
            tokens, self.state = eng._step(
                self.params, self.state,
                jnp.asarray(offsets.astype(np.int32)),
                jnp.asarray(rope.astype(np.int32)),
                jnp.asarray(self.active),
            )
        if not eng.spec:
            toks = np.asarray(jax.device_get(tokens))
        dt = time.perf_counter() - t0
        self.stats.decode_seconds += dt
        self.stats.decode_steps += 1
        self.progress += 1
        self._win_decode += dt
        n_active = self.active_count
        self.stats.slot_occupancy += n_active / eng.S
        self._win_occ += n_active / eng.S
        self._bpt_samples.append(
            self._bytes_in_use() / max(self._live_tokens(), 1)
        )
        now = time.perf_counter()
        if eng.spec:
            # a verify round appends 1..k+1 tokens per slot — the
            # accounting counts tokens actually emitted, so tok/s stays
            # an honest cross-mode comparison
            appended = self._spec_append(spec_toks, spec_emit, now, finished)
        else:
            appended = n_active
            for slot in np.nonzero(self.active)[0]:
                rid = int(self.slot_req[slot])
                tok = int(toks[slot])
                self.outputs[rid].append(tok)
                if self.ttft[rid] is None:
                    self.ttft[rid] = now - self.submit_t[rid]
                    self.first_tok_wall[rid] = now
                self.emitted[slot] += 1
                if tok == eng.eos or self.emitted[slot] >= self.budgets[rid]:
                    self._evict_slot(slot)  # slot (and its blocks) free NOW
                    self._finish_request(rid, slot, now)
                    finished.append(rid)
        self.stats.decode_tokens += appended
        self._win_tokens += appended
        every = eng.serve.log_every_steps
        if every and self.stats.decode_steps % every == 0:
            w_dt = max(now - self._win_t0, 1e-9)
            window = {
                "event": "serve_window",
                "step": self.stats.decode_steps,
                "decode_tokens_per_sec": round(self._win_tokens / w_dt, 1),
                "decode_tokens_per_sec_chip": round(
                    self._win_tokens / w_dt / self.n_chips, 1
                ),
                "slot_occupancy": round(self._win_occ / every, 4),
                "queue_depth": len(self.pending),
                # queueing telemetry: the window's offered vs served rate
                # and their imbalance — a sustained positive queue_growth
                # is the open-loop collapse signal (arrivals outpacing
                # service), visible live instead of post-hoc
                "arrival_rate_per_sec": round(self._win_arrivals / w_dt, 2),
                "service_rate_per_sec": round(self._win_done / w_dt, 2),
                "queue_growth": int(self._win_arrivals - self._win_done),
                # the window's wall split: admission prefill vs decode
                # steps — a window whose prefill share balloons is paying
                # admission on the decode critical path
                "prefill_ms": round(self._win_prefill * 1e3, 1),
                "decode_ms": round(self._win_decode * 1e3, 1),
                # capacity gauges: what the cache state holds RIGHT NOW
                # per live token — the number the paged pool shrinks
                "cache_bytes_in_use": self._bytes_in_use(),
                "cache_bytes_per_token": round(
                    self._bytes_in_use() / max(self._live_tokens(), 1), 1
                ),
            }
            if eng.paged:
                window["pool_blocks_in_use"] = eng.pool.blocks_in_use
                window["pool_blocks_free"] = eng.pool.blocks_free
                if eng.prefix:
                    # cumulative-to-date prefix-cache gauges: hit rate over
                    # eligible admissions, prefill tokens served from the
                    # pool instead of recomputed, and the warm set's bytes
                    window["prefix_hit_rate"] = round(
                        self.stats.prefix_hits
                        / max(self.stats.prefix_lookups, 1), 4
                    )
                    window["prefill_tokens_saved_frac"] = round(
                        self.stats.prefill_tokens_saved
                        / max(self.stats.prefill_tokens_total, 1), 4
                    )
                    window["pool_blocks_warm"] = eng.pool.blocks_warm
                    window["warm_bytes"] = (
                        eng.pool.blocks_warm * self._per_block
                    )
            if eng.spec:
                # the speculative ledger live: window-local multi-token
                # yield + the cumulative draft acceptance rate
                window["accepted_tokens_per_step"] = round(
                    self._win_spec_emitted / max(self._win_spec_steps, 1), 4
                )
                window["acceptance_rate"] = round(
                    self.stats.spec_accepted
                    / max(self.stats.spec_drafted, 1), 4
                )
            if self.replica is not None:
                window["replica"] = int(self.replica)
            log_json(window)
            self._win_tokens, self._win_t0, self._win_occ = 0, now, 0.0
            self._win_prefill, self._win_decode = 0.0, 0.0
            self._win_arrivals, self._win_done = 0, 0
            self._win_spec_steps, self._win_spec_emitted = 0, 0
        return finished

    # ------------------------------------------------------------ closing
    def finalize(self) -> ServeStats:
        """Close the books: TTFT decomposition, goodput, the
        serve_summary event; sets ``engine.last_stats``.  Safe to call
        once per session; requests still pending (a drained replica) stay
        unfinished and count against goodput, never silently vanish."""
        if self._finalized:
            return self.stats
        self._finalized = True
        eng, stats = self.eng, self.stats
        stats.ttft_s = [t for t in self.ttft if t is not None]
        # TTFT decomposition rows, kept in ttft_s order (finished requests)
        for rid, t in enumerate(self.ttft):
            if t is None:
                continue
            t_admit = (
                self.admit_t[rid]
                if self.admit_t[rid] is not None
                else self.submit_t[rid]
            )
            stats.queue_wait_s.append(t_admit - self.submit_t[rid])
            stats.prefill_share_s.append(self.prefill_dt[rid])
        stats.slot_occupancy = (
            stats.slot_occupancy / stats.decode_steps if stats.decode_steps else 0.0
        )
        stats.goodput = compute_goodput(
            self.ttft,
            [len(o) for o in self.outputs],
            wall_s=time.perf_counter() - self.t_open,
            ttft_slo_ms=eng.serve.ttft_slo_ms,
            n_chips=self.n_chips,
        )
        stats.bytes_per_live_token = (
            sum(self._bpt_samples) / len(self._bpt_samples)
            if self._bpt_samples
            else 0.0
        )
        p50, p95 = stats.ttft_percentiles()
        # arrival→submit delay percentiles over every request (0s under
        # closed-loop driving; the open-loop driver's queueing signature)
        from distributed_llms_example_tpu.obs.spans import percentiles

        qd50, qd95, qd99 = percentiles(
            [s - a for s, a in zip(self.submit_t, self.arrival_t)],
            (0.50, 0.95, 0.99),
        )
        summary = {
            "event": "serve_summary",
            "sequences": stats.sequences,
            "decode_steps": stats.decode_steps,
            "decode_tokens": stats.decode_tokens,
            "decode_tokens_per_sec": round(stats.tokens_per_sec(), 1),
            "decode_tokens_per_sec_chip": round(
                stats.tokens_per_sec() / self.n_chips, 1
            ),
            "ttft_p50_ms": round(p50 * 1e3, 1),
            "ttft_p95_ms": round(p95 * 1e3, 1),
            "queue_delay_p50_ms": round(qd50 * 1e3, 3),
            "queue_delay_p95_ms": round(qd95 * 1e3, 3),
            "queue_delay_p99_ms": round(qd99 * 1e3, 3),
            **stats.ttft_decomposition(),
            **stats.goodput,
            "slot_occupancy": round(stats.slot_occupancy, 4),
            "prefill_seconds": round(stats.prefill_seconds, 3),
            "slots": eng.S,
            "chips": self.n_chips,
            # capacity block: config knobs + the measured static account —
            # so capacity claims are read off the log, not inferred
            "kv_cache_dtype": eng.serve.kv_cache_dtype,
            "paged_kv": eng.paged,
            "prefill_buckets": list(eng.buckets),
            "cache_bytes_resident": stats.cache_bytes_resident,
            "peak_cache_bytes_in_use": stats.peak_cache_bytes_in_use,
            "cache_bytes_per_token": round(stats.bytes_per_live_token, 1),
        }
        if eng.paged:
            summary["pool_blocks"] = eng.pool.num_blocks
            summary["kv_block_size"] = eng.block_size
            summary["admit_deferrals"] = stats.admit_deferrals
            if eng.prefix:
                # the prefix-cache ledger: how often admission matched a
                # cached chain, how much prefill it skipped, and what the
                # warm retention holds at close — the bench's hit_rate /
                # prefill_tokens_saved_frac read straight off this block
                summary["prefix_cache"] = True
                summary["prefix_cache_budget_gib"] = (
                    eng.serve.prefix_cache_budget_gib
                )
                summary["prefix_lookups"] = stats.prefix_lookups
                summary["prefix_hits"] = stats.prefix_hits
                summary["prefix_hit_rate"] = round(
                    stats.prefix_hits / max(stats.prefix_lookups, 1), 4
                )
                summary["prefill_tokens_total"] = stats.prefill_tokens_total
                summary["prefill_tokens_saved"] = stats.prefill_tokens_saved
                summary["prefill_tokens_saved_frac"] = round(
                    stats.prefill_tokens_saved
                    / max(stats.prefill_tokens_total, 1), 4
                )
                summary["pool_blocks_warm"] = eng.pool.blocks_warm
                summary["warm_bytes"] = (
                    eng.pool.blocks_warm * self._per_block
                )
        if eng.spec:
            # the speculative-decode ledger: how many target tokens each
            # verify round yielded (accepted_tokens_per_step > 1.0 is the
            # win) and how often drafts survived the target's argmax —
            # the serve-spec bench and the --min-acceptance-rate strict
            # gate read straight off this block
            summary["spec_decode"] = True
            summary["spec_tokens"] = eng.spec
            summary["spec_draft_model"] = eng.serve.spec_draft_model or "ngram"
            summary["spec_steps"] = stats.spec_steps
            summary["spec_drafted_tokens"] = stats.spec_drafted
            summary["spec_accepted_tokens"] = stats.spec_accepted
            summary["accepted_tokens_per_step"] = round(
                stats.spec_emitted / max(stats.spec_slot_rounds, 1), 4
            )
            summary["acceptance_rate"] = round(
                stats.spec_accepted / max(stats.spec_drafted, 1), 4
            )
        if self.replica is not None:
            summary["replica"] = int(self.replica)
        # the shared bucketed account (params + kv_cache over the one
        # taxonomy) with its fit verdict — the capacity gauges' bytes,
        # re-pointed through obs/memprof.py
        acct = self._memory_account()
        summary["memory_account"] = acct
        summary["hbm_headroom_gib"] = acct["hbm_headroom_gib"]
        peak_hbm = device_peak_bytes()
        if peak_hbm is not None:
            # live allocator peak where the backend supports memory_stats
            # (TPU); the static account above is the portable fallback
            summary["peak_hbm_bytes"] = peak_hbm
        log_json(summary)
        eng.last_stats = stats
        return stats


def make_static_runner(
    model: Any, config: Any, mesh: Any, *,
    max_new_tokens: int, width: int, batch: int, is_seq2seq: bool = True,
    kv_cache_dtype: str = "f32",
):
    """The pre-engine contract as ONE compiled runner: pad every request
    chunk to a static batch and decode EVERY row to ``max_new_tokens``
    regardless of when it finishes.  Returns ``run_all(params, requests)
    -> list of generated-id rows``; the jit lives in the closure, so a
    warm-up call and a timed call share the compile (bench) and the
    determinism test compares against exactly this contract.
    ``kv_cache_dtype`` matches the engine flag, so the engine-vs-static
    determinism pins hold under int8 too (same quantized cache on both
    sides)."""
    from distributed_llms_example_tpu.evaluation.generation import (
        CausalGenerator,
        Seq2SeqGenerator,
    )

    cls = Seq2SeqGenerator if is_seq2seq else CausalGenerator
    run = jax.jit(cls(model, config, max_new_tokens, num_beams=1).run)

    def run_all(params: Any, requests: Sequence[Sequence[int]]) -> list[list[int]]:
        outs: list[list[int]] = []
        for lo in range(0, len(requests), batch):
            chunk = list(requests[lo : lo + batch])
            ids = np.full((batch, width), config.pad_token_id, np.int32)
            mask = np.zeros((batch, width), np.int32)
            for r, req in enumerate(chunk):
                toks = list(req)[:width]
                ids[r, : len(toks)] = toks
                mask[r, : len(toks)] = 1
            with activation_mesh(mesh), kv_cache_context(kv_cache_dtype):
                got = np.asarray(run(params, jnp.asarray(ids), jnp.asarray(mask)))
            outs.extend(got[r].tolist() for r in range(len(chunk)))
        return outs

    return run_all


def static_batch_generate(
    model: Any, config: Any, mesh: Any, params: Any,
    requests: Sequence[Sequence[int]], *,
    max_new_tokens: int, width: int, batch: int | None = None,
    is_seq2seq: bool = True, kv_cache_dtype: str = "f32",
) -> list[list[int]]:
    """One-shot form of ``make_static_runner`` (the determinism tests'
    entry point)."""
    return make_static_runner(
        model, config, mesh,
        max_new_tokens=max_new_tokens, width=width,
        batch=batch or len(requests), is_seq2seq=is_seq2seq,
        kv_cache_dtype=kv_cache_dtype,
    )(params, requests)


def trim_eos(ids: Sequence[int], eos: int, pad: int) -> list[int]:
    """Generated ids up to and including the first EOS, pads stripped —
    the canonical form both decode paths agree on."""
    out: list[int] = []
    for t in ids:
        t = int(t)
        if t == pad:
            continue
        out.append(t)
        if t == eos:
            break
    return out
