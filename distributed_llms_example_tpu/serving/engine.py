"""Continuous-batching serving engine: padded decode slots, admit/evict per step.

The static eval path (evaluation/generation.py) decodes one padded batch to
completion — every finished row keeps "decoding" pads until the SLOWEST row
is done, so utilization decays over the batch's lifetime.  This engine is
the Orca-style iteration-level alternative (arXiv:2412.14374's serving
discussion): a fixed set of ``max_slots`` decode slots, each holding ONE
in-flight sequence at its own offset, with finished sequences EVICTED and
new ones ADMITTED between per-token steps.  The compiled programs stay
fixed-shape (slot count never changes); only the host-side slot bookkeeping
moves.

Three compiled programs per model, all traced under the ambient mesh so
cache/activation sharding constraints bake in (batch rows over
data×fsdp×expert, heads over tensor — ``CACHE_RULES``):

- **prefill** (once per admitted chunk): the encoder + cross-KV projection
  (seq2seq) or the prompt pass into a chunk-sized cache (causal).
- **admit** (scatter): chunk rows land in their slots via ``.at[idx].set``
  with ``mode="drop"`` — an out-of-range index is a no-op, which is how
  partially-filled chunks park their padding rows.  Slot caches are NOT
  zeroed on reuse: every read is masked to ``k_pos <= offset``, so stale
  K/V from the previous occupant is unreachable by construction (the
  determinism test pins engine output == static-batch output through slot
  reuse).
- **decode step** (every token): one token per slot, per-slot offsets
  (``cache_positions`` per-row cache writes), idle slots parked at an
  out-of-range offset so their writes drop.

Host loop per step: admit into free slots (if any), run the step, read the
(slots,) token vector back, append/evict.  Greedy only — beam search keeps
the static split path (the per-step beam reorder has no per-slot form).
Single-controller: multi-process serving is a queueing layer above this,
not a collective program.

Obs events (utils/jsonlog → obs sink): ``serve_window`` at the log cadence
(decode tokens/sec[/chip], slot occupancy, queue depth, the window's
prefill-vs-decode time split), a ``serve_request`` lifecycle record per
finished request (queue-wait → prefill → first-token → decode → evict,
with times relative to the batch's submit instant so ``obs.report
--trace`` can draw each request as a slot-track slice), and a final
``serve_summary`` (tokens/sec/chip, TTFT p50/p95 **with its queue-vs-
prefill decomposition**, occupancy, evictions, and the **goodput
fields** — useful tokens/sec and the SLO-attainment fraction at the
configured ``ttft_slo_ms``, the router tier's dispatch inputs) — TTFT
p95 stops being one opaque aggregate and becomes "the tail waited in
queue" vs "prefill is slow".
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llms_example_tpu.evaluation.generation import (
    _causal_prefill,
    _init_cache,
)
from distributed_llms_example_tpu.parallel.activation import (
    BATCH_AXES,
    activation_mesh,
    constrain_cache,
    kv_cache_context,
)
from distributed_llms_example_tpu.serving import cache_pool
from distributed_llms_example_tpu.utils.jsonlog import log_json


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape/behavior knobs (all compiled shapes derive from these).

    ``max_slots``: concurrent in-flight sequences — the decode batch.
    ``prefill_batch``: sequences prefilled per admission chunk (one compile
    at this batch; fewer pending sequences ride the same program with
    dropped padding rows); 0 = auto (``max_slots`` — always divides the
    mesh's batch shards when the slot count does, so the defaults work on
    any mesh).  ``max_source_length``: fixed prompt width (prompts are
    padded to it; the serving twin of the trainer's bucketed max).
    ``max_new_tokens``: decode budget per sequence = the KV-cache length
    (seq2seq) or its decode tail (causal).  ``request_spans``: emit one
    ``serve_request`` lifecycle event per finished request (queue-wait /
    prefill / ttft / decode breakdown — the trace exporter's feed).
    ``ttft_slo_ms``: the first-token SLO the goodput fields are judged
    against (0 = no SLO: every finished request's tokens are useful) —
    the router tier's dispatch inputs (``serve_summary``:
    ``goodput_tokens_per_sec`` + ``slo_attainment``).

    Decode-capacity knobs (README "Serving capacity"):

    ``kv_cache_dtype``: "f32" (store K/V at compute dtype) or "int8"
    (quantize on cache write, per-head per-position scales; ~4× less
    cache HBM and decode traffic at a token-match-rate tolerance — the
    paged/bucketed knobs below stay BIT-exact instead).
    ``prefill_buckets``: ascending compiled admission widths (e.g.
    ``(128, 256, 512)``); each admission chunk pads to the smallest
    bucket covering it instead of always paying ``max_source_length``,
    and every bucket's programs are AOT-warmed before the first request
    so no request ever hits a compile.  ``max_source_length`` is always
    an implicit last bucket.
    ``paged_kv`` (causal families only): slots hold block lists over a
    shared pool (serving/cache_pool.py) instead of worst-case-width
    rows; ``pool_blocks`` (0 = worst case: every slot at full width) and
    ``kv_block_size`` (0 = auto kv tile size) shape the pool.  Admission
    defers while the free list is short; eviction returns all blocks."""

    max_slots: int = 8
    prefill_batch: int = 0  # 0 = max_slots
    max_new_tokens: int = 128
    max_source_length: int = 1024
    log_every_steps: int = 50
    request_spans: bool = True
    ttft_slo_ms: float = 0.0
    kv_cache_dtype: str = "f32"
    prefill_buckets: tuple = ()
    paged_kv: bool = False
    pool_blocks: int = 0  # 0 = worst case (max_slots x tiles per slot)
    kv_block_size: int = 0  # 0 = auto (the kv tile size for the cache width)


@dataclasses.dataclass
class ServeStats:
    """Filled by ``ServingEngine.generate`` — the bench/obs read surface."""

    sequences: int = 0
    decode_steps: int = 0
    decode_tokens: int = 0
    decode_seconds: float = 0.0
    prefill_seconds: float = 0.0
    slot_occupancy: float = 0.0
    # capacity gauges (static byte accounting — measured, not inferred):
    # resident = the serving state's fixed allocation; in_use = what live
    # requests actually hold (= resident on the flat path; blocks×block
    # bytes on the paged path); bytes_per_live_token averages in_use over
    # the live tokens at each decode step
    cache_bytes_resident: int = 0
    peak_cache_bytes_in_use: int = 0
    bytes_per_live_token: float = 0.0
    admit_deferrals: int = 0  # paged: admissions deferred on a short free list
    ttft_s: list[float] = dataclasses.field(default_factory=list)
    # per-request TTFT decomposition (same order as ttft_s): time spent
    # waiting for a slot vs inside the request's prefill call
    queue_wait_s: list[float] = dataclasses.field(default_factory=list)
    prefill_share_s: list[float] = dataclasses.field(default_factory=list)
    # goodput fields (filled by generate): useful tokens/sec at the
    # configured TTFT SLO + the attainment fraction — the router tier's
    # dispatch inputs
    goodput: dict = dataclasses.field(default_factory=dict)

    def tokens_per_sec(self) -> float:
        return self.decode_tokens / max(self.decode_seconds, 1e-9)

    def ttft_percentiles(self) -> tuple[float, float]:
        from distributed_llms_example_tpu.obs.spans import percentiles

        if not self.ttft_s:
            return 0.0, 0.0
        p50, p95 = percentiles(self.ttft_s, (0.50, 0.95))
        return p50, p95

    def ttft_decomposition(self) -> dict:
        """Queue-wait vs prefill share of TTFT over finished requests —
        the serve_summary fields that make a fat TTFT p95 actionable
        (admit more slots vs speed up prefill)."""
        from distributed_llms_example_tpu.obs.spans import percentiles

        q50, q95 = percentiles(self.queue_wait_s, (0.50, 0.95))
        p50, p95 = percentiles(self.prefill_share_s, (0.50, 0.95))
        total = sum(self.ttft_s)
        return {
            "ttft_queue_p50_ms": round(q50 * 1e3, 1),
            "ttft_queue_p95_ms": round(q95 * 1e3, 1),
            "ttft_prefill_p50_ms": round(p50 * 1e3, 1),
            "ttft_prefill_p95_ms": round(p95 * 1e3, 1),
            "ttft_queue_share": round(sum(self.queue_wait_s) / total, 4) if total else 0.0,
            "ttft_prefill_share": round(sum(self.prefill_share_s) / total, 4) if total else 0.0,
        }


def compute_goodput(
    ttft_s: Sequence[float | None],
    tokens_out: Sequence[int],
    *,
    wall_s: float,
    ttft_slo_ms: float,
    n_chips: int,
) -> dict:
    """Goodput: USEFUL tokens per wall second, + SLO attainment.

    Useful = tokens of requests whose first token met the TTFT SLO (all
    FINISHED requests when no SLO is set — ``ttft_s[i] is None`` marks an
    unfinished request); wall = submit → batch done, so queue-wait and
    prefill stalls cost goodput the way they cost a user.
    ``slo_attainment`` is the fraction of finished requests served within
    the SLO — the router tier's per-replica health signal.  Pure
    host-float arithmetic; shared by the engine summary and tests so the
    numbers are pinnable."""
    wall_s = max(float(wall_s), 1e-9)
    slo_s = float(ttft_slo_ms) / 1e3
    finished = [
        (i, t) for i, t in enumerate(ttft_s) if t is not None
    ]
    met = [i for i, t in finished if slo_s <= 0 or t <= slo_s]
    useful = sum(int(tokens_out[i]) for i in met)
    out = {
        "goodput_tokens_per_sec": round(useful / wall_s, 1),
        "goodput_tokens_per_sec_chip": round(useful / wall_s / max(n_chips, 1), 1),
    }
    if slo_s > 0:
        out["ttft_slo_ms"] = round(float(ttft_slo_ms), 1)
        out["slo_attainment"] = (
            round(len(met) / len(finished), 4) if finished else 0.0
        )
    return out


def device_peak_bytes() -> int | None:
    """Peak allocator bytes from ``memory_stats`` where the backend
    supports it (TPU/GPU); None on CPU — callers fall back to the static
    account, which is why the capacity gauges never claim a live number
    they didn't measure."""
    try:
        ms = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not ms:
        return None
    peak = ms.get("peak_bytes_in_use")
    return int(peak) if peak is not None else None


class ServingEngine:
    """Greedy continuous-batching decode over a fixed slot set.

    ``model``/``config`` as in the Evaluator; ``mesh`` (or None) is the
    ambient mesh every program traces under.  ``is_seq2seq`` picks the
    adapter: encoder+cross-KV slots (BART/T5) or prompt-cache slots
    (LLaMA-family)."""

    def __init__(self, model: Any, config: Any, mesh: Any,
                 serve: ServeConfig | None = None, *, is_seq2seq: bool = True):
        self.model, self.config, self.mesh = model, config, mesh
        self.serve = serve or ServeConfig()
        self.is_seq2seq = is_seq2seq
        self.eos = config.eos_token_id
        self.pad = config.pad_token_id
        self.start = getattr(config, "decoder_start_token_id", None)
        self.forced_bos = getattr(config, "forced_bos_token_id", None)
        self.forced_eos = getattr(config, "forced_eos_token_id", None)
        self.L = self.serve.max_new_tokens
        self.S = self.serve.max_slots
        self.W = self.serve.max_source_length
        self.prefill_batch = self.serve.prefill_batch or self.S  # 0 = auto
        if self.prefill_batch < 1 or self.prefill_batch > self.S:
            raise ValueError(
                f"prefill_batch {self.prefill_batch} must be in "
                f"[1, max_slots={self.S}]"
            )
        if self.serve.kv_cache_dtype not in ("f32", "int8"):
            raise ValueError(
                f"kv_cache_dtype={self.serve.kv_cache_dtype!r}: "
                "must be 'f32' or 'int8'"
            )
        # admission buckets: ascending widths, max_source_length always the
        # implicit last bucket (every prompt fits somewhere)
        self.buckets = tuple(
            sorted({int(b) for b in self.serve.prefill_buckets if 0 < int(b) < self.W})
        ) + (self.W,)
        self.paged = bool(self.serve.paged_kv)
        self.pool: cache_pool.CachePool | None = None
        if self.paged:
            if self.is_seq2seq:
                raise ValueError(
                    "paged_kv applies to the causal KV cache (prompt + "
                    "decode tail in one buffer); the seq2seq slot state is "
                    "encoder output + cross-KV, which pages nothing — run "
                    "the flat cache for seq2seq families"
                )
            from distributed_llms_example_tpu.ops.flash_attention import auto_block

            width = self.W + self.L
            bs = self.serve.kv_block_size
            if not bs:
                # the block size must tile the cache width AND every
                # admission bucket (decode tiles start on tile boundaries),
                # so the auto default divides their gcd — kernel-preferred
                # tile when the gcd allows, else the gcd itself (8-aligned)
                g = math.gcd(width, *self.buckets)
                bs = auto_block(g) or (g if g >= 8 and g % 8 == 0 else 0)
            if not bs or width % bs:
                raise ValueError(
                    f"kv_block_size={self.serve.kv_block_size} does not tile "
                    f"the cache width {width} (prompt {self.W} + decode "
                    f"{self.L}); pass an explicit 8-aligned divisor of "
                    f"gcd(width, buckets) = "
                    f"{math.gcd(width, *self.buckets)}"
                )
            for b in self.buckets:
                if b % bs:
                    raise ValueError(
                        f"prefill bucket {b} is not a multiple of the kv "
                        f"block size {bs} — decode tiles must start on a "
                        "tile boundary"
                    )
            self.block_size = int(bs)
            self.n_tiles = width // self.block_size
            n_blocks = self.serve.pool_blocks or self.S * self.n_tiles
            worst = cache_pool.blocks_needed(self.W, self.L, self.block_size)
            if n_blocks < worst:
                raise ValueError(
                    f"pool_blocks={n_blocks} cannot hold even one "
                    f"worst-case request ({worst} blocks at block size "
                    f"{self.block_size}) — admission would livelock"
                )
            self.pool = cache_pool.CachePool(n_blocks, self.block_size)
        mesh_axes = dict(mesh.shape) if mesh is not None else {}
        # known-bad serving compositions are matrix rows, not scattered
        # raises — same table the trainer/lint consult
        from distributed_llms_example_tpu.analysis.composition import (
            validate_composition,
        )

        validate_composition(
            family=None, schedule=None, mesh_axes=mesh_axes,
            flags=("decode", "seq2seq" if is_seq2seq else "causal"),
        )
        batch_shards = 1
        for a in BATCH_AXES:
            batch_shards *= mesh_axes.get(a, 1)
        for what, n in (("max_slots", self.S), ("prefill_batch", self.prefill_batch)):
            if n % max(batch_shards, 1):
                raise ValueError(
                    f"{what}={n} must divide evenly over the mesh's "
                    f"{batch_shards} batch shards (data×fsdp×expert) — "
                    "uneven slot rows cannot shard"
                )
        # per-program Python trace counts: a retrace IS a recompile, so the
        # zero-recompile contract (AOT-warmed buckets, fixed-shape churn)
        # is pinnable by comparing these before/after serving traffic
        self.trace_counts: dict[str, int] = {}
        self._warmed = False
        self._build_programs()
        self.last_stats: ServeStats | None = None

    # ------------------------------------------------------------ programs
    def _wrap(self, fn, donate: tuple[int, ...] = (), name: str = ""):
        # donate the slot-state buffers where the backend supports it: the
        # engine holds the only reference and rebinds the result, so the
        # per-step cache update happens in place instead of copying the
        # whole serving state every token (CPU lacks donation — keep the
        # test backend quiet)
        if jax.default_backend() == "cpu":
            donate = ()
        name = name or getattr(fn, "__name__", "program")

        def counted(*args):
            # runs at TRACE time only: one bump per compiled specialization
            self.trace_counts[name] = self.trace_counts.get(name, 0) + 1
            return fn(*args)

        jitted = jax.jit(counted, donate_argnums=donate)

        def run(*args):
            with activation_mesh(self.mesh), kv_cache_context(
                self.serve.kv_cache_dtype
            ):
                return jitted(*args)

        return run

    @staticmethod
    def _pad_axis(x, axis: int, width: int):
        """Right-pad one axis to ``width`` with zeros — how a bucket-width
        admission chunk lands in full-width slot state.  The padding is
        mask-invisible: enc_mask/full_mask stay 0 there, so padded
        positions contribute exactly nothing (the bucketed == unbucketed
        bit-identity argument)."""
        if x.shape[axis] == width:
            return x
        pads = [(0, 0)] * x.ndim
        pads[axis] = (0, width - x.shape[axis])
        return jnp.pad(x, pads)

    def _build_programs(self) -> None:
        model, L, S = self.model, self.L, self.S

        if self.is_seq2seq:
            def prefill(params, ids, mask):
                enc = model.apply({"params": params}, ids, mask, method="encode")
                ckv = constrain_cache(model.apply({"params": params}, enc, method="cross_kv"))
                return enc, mask, ckv

            def admit(state, enc, mask, ckv, slot_idx):
                put = lambda dst, src: dst.at[slot_idx].set(src, mode="drop")  # noqa: E731
                # bucket-width chunks pad to the slot width here, inside
                # the (per-bucket-compiled) admit program
                enc = self._pad_axis(enc, 1, self.W)
                mask = self._pad_axis(mask, 1, self.W)
                ckv = jax.tree.map(
                    lambda x: self._pad_axis(x, 2, self.W) if x.ndim == 4 else x,
                    ckv,
                )
                return {
                    **state,
                    "enc": put(state["enc"], enc),
                    "enc_mask": put(state["enc_mask"], mask),
                    "ckv": jax.tree.map(put, state["ckv"], ckv),
                    "last": state["last"].at[slot_idx].set(
                        jnp.full((slot_idx.shape[0], 1), self.start, jnp.int32),
                        mode="drop",
                    ),
                }

            def step(params, state, offsets, active):
                # idle slots park at L: their cache writes drop
                # (mode="drop") and their tokens are masked to pad below
                offs = jnp.where(active, offsets, L)
                logits, mut = model.apply(
                    {"params": params, "cache": state["cache"]},
                    state["last"],
                    state["enc"],
                    state["enc_mask"],
                    use_cache=True,
                    cache_offset=offs,
                    max_kv_len=L,
                    cross_kv=state["ckv"],
                    method="decode",
                    mutable=["cache"],
                )
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                if self.forced_bos is not None:
                    nxt = jnp.where(offs == 0, self.forced_bos, nxt)
                if self.forced_eos is not None:
                    nxt = jnp.where(offs == L - 1, self.forced_eos, nxt)
                nxt = jnp.where(active, nxt, self.pad)
                return nxt, {
                    **state,
                    "cache": constrain_cache(mut["cache"]),
                    "last": nxt[:, None],
                }
        else:
            def prefill(params, ids, mask):
                cache, full_mask, lengths, first = _causal_prefill(
                    model, params, ids, mask, L
                )
                return cache, full_mask, lengths, jnp.argmax(first, axis=-1).astype(jnp.int32)

            width_full = self.W + L

            def _pad_cache_tree(cache):
                # bucket-width chunk cache → slot width; K/V on axis 2,
                # int8 scale leaves on axis 2 too, scalars untouched
                def pad(x):
                    if x.ndim >= 3:
                        return self._pad_axis(x, 2, width_full)
                    return x

                return jax.tree.map(pad, cache)

            if self.paged:
                n_blocks, bs = self.pool.num_blocks, self.block_size

                def admit(state, cache, full_mask, first_tok, slot_idx,
                          admit_blocks):
                    put = lambda dst, src: (  # noqa: E731
                        dst.at[slot_idx].set(src, mode="drop") if dst.ndim > 0 else dst
                    )
                    return {
                        **state,
                        "pool": cache_pool.scatter_admit(
                            state["pool"], cache, admit_blocks, bs
                        ),
                        "mask": put(state["mask"], self._pad_axis(full_mask, 1, width_full)),
                        "last": put(state["last"], first_tok),
                    }

                def step(params, state, block_tables, write_pos, rope_pos, active):
                    width = state["mask"].shape[1]
                    offs = jnp.where(active, write_pos, width)
                    mask = state["mask"].at[jnp.arange(S), offs].set(1, mode="drop")
                    # the slot view is a step-transient: only the pool is
                    # resident between steps (serving/cache_pool.py)
                    cache = constrain_cache(
                        cache_pool.gather_cache(state["pool"], block_tables)
                    )
                    logits, mut = model.apply(
                        {"params": params, "cache": cache},
                        state["last"][:, None],
                        mask,
                        use_cache=True,
                        positions=rope_pos[:, None],
                        cache_positions=offs,
                        mutable=["cache"],
                    )
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                    nxt = jnp.where(active, nxt, self.pad)
                    pool = cache_pool.scatter_step(
                        state["pool"], mut["cache"], block_tables, offs,
                        num_blocks=n_blocks, block_size=bs,
                    )
                    return nxt, {
                        **state,
                        "pool": pool,
                        "mask": mask,
                        "last": nxt,
                    }
            else:
                def admit(state, cache, full_mask, first_tok, slot_idx):
                    put = lambda dst, src: (  # noqa: E731
                        dst.at[slot_idx].set(src, mode="drop") if dst.ndim > 0 else dst
                    )
                    return {
                        **state,
                        "cache": jax.tree.map(put, state["cache"], _pad_cache_tree(cache)),
                        "mask": put(state["mask"], self._pad_axis(full_mask, 1, width_full)),
                        "last": put(state["last"], first_tok),
                    }

                def step(params, state, write_pos, rope_pos, active):
                    width = state["mask"].shape[1]
                    offs = jnp.where(active, write_pos, width)
                    mask = state["mask"].at[jnp.arange(S), offs].set(1, mode="drop")
                    logits, mut = model.apply(
                        {"params": params, "cache": state["cache"]},
                        state["last"][:, None],
                        mask,
                        use_cache=True,
                        positions=rope_pos[:, None],
                        cache_positions=offs,
                        mutable=["cache"],
                    )
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                    nxt = jnp.where(active, nxt, self.pad)
                    return nxt, {
                        **state,
                        "cache": constrain_cache(mut["cache"]),
                        "mask": mask,
                        "last": nxt,
                    }

        self._prefill_core = prefill
        self._prefill = self._wrap(prefill, name="prefill")
        self._admit = self._wrap(admit, donate=(0,), name="admit")
        self._step = self._wrap(step, donate=(1,), name="decode_step")

    # --------------------------------------------------------------- state
    def _leaf_spec(self, path: str, x):
        from jax.sharding import PartitionSpec as P

        from distributed_llms_example_tpu.parallel.sharding import (
            kv_leaf_spec,
            kv_scale_spec,
            pool_rules,
        )

        mesh_axes = dict(self.mesh.shape)
        batch_shards = 1
        for a in BATCH_AXES:
            batch_shards *= mesh_axes.get(a, 1)
        nd = getattr(x, "ndim", 0)
        if nd == 0:
            return P()
        if path.startswith("pool"):
            # shared block pool: blocks belong to single slots, so the
            # block dim never shards over the batch axes — POOL_RULES
            leaf = path.rsplit("/", 1)[-1]
            return pool_rules().spec_for(leaf, nd)
        if nd == 4:  # cached/cross K/V: the ONE shared layout definition
            return kv_leaf_spec(x.shape, mesh_axes)
        if nd == 3 and path.endswith("_scale"):  # int8 KV scales
            return kv_scale_spec(x.shape, mesh_axes)
        batch = BATCH_AXES if x.shape[0] % max(batch_shards, 1) == 0 else None
        return P(batch, *([None] * (nd - 1)))

    def _place(self, tree):
        if self.mesh is None:
            return tree
        import jax.tree_util as jtu
        from jax.sharding import NamedSharding

        from distributed_llms_example_tpu.parallel.sharding import _path_str

        return jtu.tree_map_with_path(
            lambda p, x: jax.device_put(
                x, NamedSharding(self.mesh, self._leaf_spec(_path_str(p), x))
            ),
            tree,
        )

    def _init_state(self, params) -> dict:
        S, W, L = self.S, self.W, self.L
        zeros = lambda s: jax.tree.map(  # noqa: E731
            lambda a: jnp.zeros(a.shape, a.dtype), s
        )
        with kv_cache_context(self.serve.kv_cache_dtype):
            if self.is_seq2seq:
                ids = jnp.zeros((S, W), jnp.int32)
                mask = jnp.zeros((S, W), jnp.int32)
                a_enc, _, a_ckv = jax.eval_shape(
                    lambda p: self._prefill_core(p, ids, mask), params
                )
                enc0 = zeros(a_enc)
                state = {
                    "cache": _init_cache(self.model, params, S, L, enc0, mask),
                    "enc": enc0,
                    "enc_mask": mask,
                    "ckv": zeros(a_ckv),
                    "last": jnp.full((S, 1), self.pad, jnp.int32),
                }
            else:
                ids = jnp.zeros((S, W), jnp.int32)
                mask = jnp.zeros((S, W), jnp.int32)
                a_cache, a_mask, _, _ = jax.eval_shape(
                    lambda p: self._prefill_core(p, ids, mask), params
                )
                if self.paged:
                    state = {
                        "pool": cache_pool.pool_cache_tree(
                            a_cache, self.pool.num_blocks, self.block_size
                        ),
                        "mask": zeros(a_mask),
                        "last": jnp.full((S,), self.pad, jnp.int32),
                    }
                else:
                    state = {
                        "cache": zeros(a_cache),
                        "mask": zeros(a_mask),
                        "last": jnp.full((S,), self.pad, jnp.int32),
                    }
        return self._place(state)

    # ------------------------------------------------------------ capacity
    def _state_byte_account(self, state) -> tuple[int, int]:
        """(resident bytes, per-block bytes) of the serving K/V state —
        static accounting over the cache/pool/enc/ckv leaves (masks and
        token vectors are noise).  per-block is 0 on the flat path."""
        if self.paged:
            kv = state["pool"]
            resident = cache_pool.tree_bytes(kv)
            per_block = cache_pool.block_bytes(kv, self.pool.num_blocks)
            return resident, per_block
        keys = ("cache", "enc", "ckv") if self.is_seq2seq else ("cache",)
        resident = sum(cache_pool.tree_bytes(state[k]) for k in keys if k in state)
        return resident, 0

    def warm(self, params, state) -> Any:
        """AOT-warm every compiled program before the first real request:
        one prefill+admit trace per bucket (zeros, all writes dropped via
        out-of-range slot indices) and one all-slots-idle decode step —
        so no request ever pays a compile, and the trace counts are
        pinned BEFORE traffic (``trace_counts``).  Returns the (possibly
        donated-and-rebound) state."""
        if self._warmed:
            return state
        C, S = self.prefill_batch, self.S
        park = jnp.full((C,), S, jnp.int32)  # out of range: every write drops
        for bucket in self.buckets:
            ids = jnp.zeros((C, bucket), jnp.int32)
            mask = jnp.zeros((C, bucket), jnp.int32)
            pre = self._prefill(params, ids, mask)
            if self.is_seq2seq:
                enc, pmask, ckv = pre
                state = self._admit(state, enc, pmask, ckv, park)
            elif self.paged:
                cache, full_mask, _, first = pre
                ntc = (bucket + self.L) // self.block_size
                sentinel = jnp.full((C * ntc,), self.pool.num_blocks, jnp.int32)
                state = self._admit(state, cache, full_mask, first, park, sentinel)
            else:
                cache, full_mask, _, first = pre
                state = self._admit(state, cache, full_mask, first, park)
        idle = jnp.zeros((S,), bool)
        pos = jnp.zeros((S,), jnp.int32)
        if self.is_seq2seq:
            _, state = self._step(params, state, pos, idle)
        elif self.paged:
            bt = jnp.full((S, self.n_tiles), self.pool.num_blocks, jnp.int32)
            _, state = self._step(params, state, bt, pos, pos, idle)
        else:
            _, state = self._step(params, state, pos, pos, idle)
        self._warmed = True
        return state

    # ---------------------------------------------------------------- loop
    def generate(
        self,
        params: Any,
        requests: Sequence[Sequence[int]],
        *,
        attention_masks: Sequence[Sequence[int]] | None = None,
        max_new: Sequence[int] | None = None,
    ) -> list[list[int]]:
        """Serve ``requests`` (token-id prompts, request order preserved)
        to completion; returns per-request generated ids (eos included when
        emitted).  ``max_new`` optionally caps each request below the
        engine-wide ``max_new_tokens`` (the per-request ``max_tokens`` of a
        real serving API — and the lever continuous batching exists for:
        a short request frees its slot the step it finishes).  Fills
        ``self.last_stats`` and emits serve_window / serve_summary obs
        events."""
        S, L, W, C = self.S, self.L, self.W, self.prefill_batch
        budgets = (
            [min(int(m), L) for m in max_new]
            if max_new is not None
            else [L] * len(requests)
        )
        if len(budgets) != len(requests):
            raise ValueError(
                f"max_new has {len(budgets)} entries for {len(requests)} requests"
            )
        n_chips = max(jax.device_count(), 1)
        stats = ServeStats(sequences=len(requests))
        outputs: list[list[int]] = [[] for _ in requests]
        ttft: list[float | None] = [None] * len(requests)
        # per-request lifecycle (queue-wait → prefill → first-token →
        # decode → evict): admit instant + this request's prefill-call
        # duration, all relative to the batch's submit instant so the
        # serve_request records line up on one timeline
        admit_t: list[float | None] = [None] * len(requests)
        prefill_dt = [0.0] * len(requests)
        pending = list(range(len(requests)))[::-1]  # pop() preserves order
        slot_req = np.full(S, -1, np.int64)  # request index per slot
        emitted = np.zeros(S, np.int64)
        lengths = np.zeros(S, np.int64)  # true prompt lengths (both families)
        base = np.full(S, W, np.int64)  # causal: decode tail start (= the
        #                                 slot's admission-bucket width)
        active = np.zeros(S, bool)
        # paged bookkeeping: block ownership per slot + the block table the
        # step program reads (sentinel = num_blocks → reads fill zeros,
        # writes drop)
        slot_blocks: list[list[int]] = [[] for _ in range(S)]
        slot_bt = (
            np.full((S, self.n_tiles), self.pool.num_blocks, np.int32)
            if self.paged
            else None
        )
        state = self._init_state(params)
        state = self.warm(params, state)
        t_submit = time.perf_counter()
        stats.cache_bytes_resident, per_block = self._state_byte_account(state)
        bpt_samples: list[float] = []
        win_tokens, win_t0, win_occ = 0, time.perf_counter(), 0.0
        win_prefill, win_decode = 0.0, 0.0

        def bytes_in_use() -> int:
            if self.paged:
                return self.pool.blocks_in_use * per_block
            return stats.cache_bytes_resident

        def live_tokens() -> int:
            # tokens the serving state holds for live requests: true
            # prompt + generated so far, per active slot
            return int((lengths[active] + emitted[active]).sum())

        def finish_request(req: int, slot: int, now: float) -> None:
            """Evict-time lifecycle record — the trace exporter's feed and
            the post-hoc 'why was THIS request's TTFT fat' answer."""
            if not self.serve.request_spans:
                return
            t_admit = admit_t[req] if admit_t[req] is not None else t_submit
            queue_wait = t_admit - t_submit
            t = ttft[req]
            log_json({
                "event": "serve_request",
                "request": int(req),
                "slot": int(slot),
                "queue_wait_ms": round(queue_wait * 1e3, 3),
                "prefill_ms": round(prefill_dt[req] * 1e3, 3),
                "ttft_ms": round(t * 1e3, 3) if t is not None else None,
                "decode_ms": round((now - t_submit - (t or queue_wait)) * 1e3, 3),
                "tokens": len(outputs[req]),
                "t_admit_s": round(t_admit - t_submit, 6),
                "t_done_s": round(now - t_submit, 6),
                "finished_at_step": int(stats.decode_steps),
            })

        def evict_slot(slot: int) -> None:
            """Free the slot NOW — and, paged, return every block it held
            to the pool (the evict-returns-all-blocks contract)."""
            active[slot] = False
            slot_req[slot] = -1
            if self.paged and slot_blocks[slot]:
                self.pool.free(slot_blocks[slot])
                slot_blocks[slot] = []
                slot_bt[slot, :] = self.pool.num_blocks

        def admit_now() -> None:
            nonlocal state
            free = [i for i in range(S) if not active[i]]
            n = min(len(free), C, len(pending))
            if n == 0:
                return
            plen = lambda req: min(len(requests[req]), W)  # noqa: E731
            if self.paged:
                # shrink the chunk until the free list funds it: admission
                # DEFERS on a short pool instead of over-committing — every
                # eviction frees blocks, so deferred requests admit later
                while n > 0:
                    needed = sum(
                        cache_pool.blocks_needed(
                            plen(pending[-1 - i]), budgets[pending[-1 - i]],
                            self.block_size,
                        )
                        for i in range(n)
                    )
                    if self.pool.can_alloc(needed):
                        break
                    n -= 1
                if n == 0:
                    stats.admit_deferrals += 1
                    return
            reqs = [pending.pop() for _ in range(n)]
            # the smallest compiled admission width covering this chunk —
            # short prompts stop paying the max_source_length program
            bucket = next(
                b for b in self.buckets if b >= max(plen(req) for req in reqs)
            )
            ids = np.full((C, bucket), self.pad, np.int32)
            mask = np.zeros((C, bucket), np.int32)
            for r, req in enumerate(reqs):
                toks = list(requests[req])[:bucket]
                ids[r, : len(toks)] = toks
                mask[r, : len(toks)] = 1
                if attention_masks is not None:
                    m = list(attention_masks[req])[:bucket]
                    mask[r, : len(m)] = m
            slot_idx = np.full(C, S, np.int32)  # padding rows drop
            slot_idx[:n] = free[:n]
            admit_rows = None
            if self.paged:
                # fund + map each row's blocks BEFORE the program runs: the
                # flat (chunk × chunk-tiles) assignment carries sentinels
                # for tiles that must not copy (padding rows, prompt gap)
                ntc = (bucket + self.L) // self.block_size
                admit_rows = np.full((C, ntc), self.pool.num_blocks, np.int32)
                for r, req in enumerate(reqs):
                    blocks = self.pool.alloc(
                        cache_pool.blocks_needed(
                            plen(req), budgets[req], self.block_size
                        )
                    )
                    assert blocks is not None  # funded above
                    slot = free[r]
                    slot_blocks[slot] = blocks
                    row = cache_pool.build_block_row(
                        self.n_tiles, blocks,
                        prompt_len=plen(req), bucket_width=bucket,
                        budget=budgets[req], block_size=self.block_size,
                        sentinel=self.pool.num_blocks,
                    )
                    slot_bt[slot, :] = row
                    admit_rows[r, :] = row[:ntc]
            t0 = time.perf_counter()
            pre = self._prefill(params, jnp.asarray(ids), jnp.asarray(mask))
            if self.is_seq2seq:
                enc, pmask, ckv = pre
                state = self._admit(state, enc, pmask, ckv, jnp.asarray(slot_idx))
            else:
                cache, full_mask, plens, first = pre
                if self.paged:
                    state = self._admit(
                        state, cache, full_mask, first, jnp.asarray(slot_idx),
                        jnp.asarray(admit_rows.reshape(-1)),
                    )
                else:
                    state = self._admit(
                        state, cache, full_mask, first, jnp.asarray(slot_idx)
                    )
                plens_h = np.asarray(jax.device_get(plens))
                first_h = np.asarray(jax.device_get(first))
            dt = time.perf_counter() - t0
            stats.prefill_seconds += dt
            nonlocal win_prefill
            win_prefill += dt
            now = time.perf_counter()
            for r, req in enumerate(reqs):
                slot = free[r]
                slot_req[slot] = req
                emitted[slot] = 0
                lengths[slot] = plen(req)
                base[slot] = bucket
                active[slot] = True
                admit_t[req] = t0
                prefill_dt[req] = dt
                if not self.is_seq2seq:
                    lengths[slot] = int(plens_h[r])
                    # the causal prefill already produced token #1
                    outputs[req].append(int(first_h[r]))
                    emitted[slot] = 1
                    ttft[req] = now - t_submit
                    if int(first_h[r]) == self.eos or emitted[slot] >= budgets[req]:
                        evict_slot(slot)
                        finish_request(req, slot, now)
            stats.peak_cache_bytes_in_use = max(
                stats.peak_cache_bytes_in_use, bytes_in_use()
            )

        while pending or active.any():
            admit_now()
            if not active.any():
                continue  # every admitted sequence finished at prefill
            offsets = emitted if self.is_seq2seq else (base + emitted - 1)
            t0 = time.perf_counter()
            if self.is_seq2seq:
                tokens, state = self._step(
                    params, state,
                    jnp.asarray(offsets.astype(np.int32)),
                    jnp.asarray(active),
                )
            elif self.paged:
                rope = lengths + emitted - 1
                tokens, state = self._step(
                    params, state,
                    jnp.asarray(slot_bt),
                    jnp.asarray(offsets.astype(np.int32)),
                    jnp.asarray(rope.astype(np.int32)),
                    jnp.asarray(active),
                )
            else:
                rope = lengths + emitted - 1
                tokens, state = self._step(
                    params, state,
                    jnp.asarray(offsets.astype(np.int32)),
                    jnp.asarray(rope.astype(np.int32)),
                    jnp.asarray(active),
                )
            toks = np.asarray(jax.device_get(tokens))
            dt = time.perf_counter() - t0
            stats.decode_seconds += dt
            stats.decode_steps += 1
            win_decode += dt
            n_active = int(active.sum())
            stats.decode_tokens += n_active
            stats.slot_occupancy += n_active / S
            win_tokens += n_active
            win_occ += n_active / S
            bpt_samples.append(bytes_in_use() / max(live_tokens(), 1))
            now = time.perf_counter()
            for slot in np.nonzero(active)[0]:
                req = int(slot_req[slot])
                tok = int(toks[slot])
                outputs[req].append(tok)
                if ttft[req] is None:
                    ttft[req] = now - t_submit
                emitted[slot] += 1
                if tok == self.eos or emitted[slot] >= budgets[req]:
                    evict_slot(slot)  # the slot (and its blocks) free NOW
                    finish_request(req, slot, now)
            if (
                self.serve.log_every_steps
                and stats.decode_steps % self.serve.log_every_steps == 0
            ):
                w_dt = max(now - win_t0, 1e-9)
                window = {
                    "event": "serve_window",
                    "step": stats.decode_steps,
                    "decode_tokens_per_sec": round(win_tokens / w_dt, 1),
                    "decode_tokens_per_sec_chip": round(win_tokens / w_dt / n_chips, 1),
                    "slot_occupancy": round(
                        win_occ / self.serve.log_every_steps, 4
                    ),
                    "queue_depth": len(pending),
                    # the window's wall split: admission prefill vs decode
                    # steps — a window whose prefill share balloons is
                    # paying admission on the decode critical path
                    "prefill_ms": round(win_prefill * 1e3, 1),
                    "decode_ms": round(win_decode * 1e3, 1),
                    # capacity gauges: what the cache state holds RIGHT NOW
                    # per live token — the number the paged pool shrinks
                    "cache_bytes_in_use": bytes_in_use(),
                    "cache_bytes_per_token": round(
                        bytes_in_use() / max(live_tokens(), 1), 1
                    ),
                }
                if self.paged:
                    window["pool_blocks_in_use"] = self.pool.blocks_in_use
                    window["pool_blocks_free"] = self.pool.blocks_free
                log_json(window)
                win_tokens, win_t0, win_occ = 0, now, 0.0
                win_prefill, win_decode = 0.0, 0.0

        stats.ttft_s = [t for t in ttft if t is not None]
        # TTFT decomposition rows, kept in ttft_s order (finished requests)
        for req, t in enumerate(ttft):
            if t is None:
                continue
            t_admit = admit_t[req] if admit_t[req] is not None else t_submit
            stats.queue_wait_s.append(t_admit - t_submit)
            stats.prefill_share_s.append(prefill_dt[req])
        stats.slot_occupancy = (
            stats.slot_occupancy / stats.decode_steps if stats.decode_steps else 0.0
        )
        stats.goodput = compute_goodput(
            ttft,
            [len(o) for o in outputs],
            wall_s=time.perf_counter() - t_submit,
            ttft_slo_ms=self.serve.ttft_slo_ms,
            n_chips=n_chips,
        )
        stats.bytes_per_live_token = (
            sum(bpt_samples) / len(bpt_samples) if bpt_samples else 0.0
        )
        p50, p95 = stats.ttft_percentiles()
        summary = {
            "event": "serve_summary",
            "sequences": stats.sequences,
            "decode_steps": stats.decode_steps,
            "decode_tokens": stats.decode_tokens,
            "decode_tokens_per_sec": round(stats.tokens_per_sec(), 1),
            "decode_tokens_per_sec_chip": round(stats.tokens_per_sec() / n_chips, 1),
            "ttft_p50_ms": round(p50 * 1e3, 1),
            "ttft_p95_ms": round(p95 * 1e3, 1),
            **stats.ttft_decomposition(),
            **stats.goodput,
            "slot_occupancy": round(stats.slot_occupancy, 4),
            "prefill_seconds": round(stats.prefill_seconds, 3),
            "slots": S,
            "chips": n_chips,
            # capacity block: config knobs + the measured static account —
            # so capacity claims are read off the log, not inferred
            "kv_cache_dtype": self.serve.kv_cache_dtype,
            "paged_kv": self.paged,
            "prefill_buckets": list(self.buckets),
            "cache_bytes_resident": stats.cache_bytes_resident,
            "peak_cache_bytes_in_use": stats.peak_cache_bytes_in_use,
            "cache_bytes_per_token": round(stats.bytes_per_live_token, 1),
        }
        if self.paged:
            summary["pool_blocks"] = self.pool.num_blocks
            summary["kv_block_size"] = self.block_size
            summary["admit_deferrals"] = stats.admit_deferrals
        peak_hbm = device_peak_bytes()
        if peak_hbm is not None:
            # live allocator peak where the backend supports memory_stats
            # (TPU); the static account above is the portable fallback
            summary["peak_hbm_bytes"] = peak_hbm
        log_json(summary)
        self.last_stats = stats
        return outputs


def make_static_runner(
    model: Any, config: Any, mesh: Any, *,
    max_new_tokens: int, width: int, batch: int, is_seq2seq: bool = True,
    kv_cache_dtype: str = "f32",
):
    """The pre-engine contract as ONE compiled runner: pad every request
    chunk to a static batch and decode EVERY row to ``max_new_tokens``
    regardless of when it finishes.  Returns ``run_all(params, requests)
    -> list of generated-id rows``; the jit lives in the closure, so a
    warm-up call and a timed call share the compile (bench) and the
    determinism test compares against exactly this contract.
    ``kv_cache_dtype`` matches the engine flag, so the engine-vs-static
    determinism pins hold under int8 too (same quantized cache on both
    sides)."""
    from distributed_llms_example_tpu.evaluation.generation import (
        CausalGenerator,
        Seq2SeqGenerator,
    )

    cls = Seq2SeqGenerator if is_seq2seq else CausalGenerator
    run = jax.jit(cls(model, config, max_new_tokens, num_beams=1).run)

    def run_all(params: Any, requests: Sequence[Sequence[int]]) -> list[list[int]]:
        outs: list[list[int]] = []
        for lo in range(0, len(requests), batch):
            chunk = list(requests[lo : lo + batch])
            ids = np.full((batch, width), config.pad_token_id, np.int32)
            mask = np.zeros((batch, width), np.int32)
            for r, req in enumerate(chunk):
                toks = list(req)[:width]
                ids[r, : len(toks)] = toks
                mask[r, : len(toks)] = 1
            with activation_mesh(mesh), kv_cache_context(kv_cache_dtype):
                got = np.asarray(run(params, jnp.asarray(ids), jnp.asarray(mask)))
            outs.extend(got[r].tolist() for r in range(len(chunk)))
        return outs

    return run_all


def static_batch_generate(
    model: Any, config: Any, mesh: Any, params: Any,
    requests: Sequence[Sequence[int]], *,
    max_new_tokens: int, width: int, batch: int | None = None,
    is_seq2seq: bool = True, kv_cache_dtype: str = "f32",
) -> list[list[int]]:
    """One-shot form of ``make_static_runner`` (the determinism tests'
    entry point)."""
    return make_static_runner(
        model, config, mesh,
        max_new_tokens=max_new_tokens, width=width,
        batch=batch or len(requests), is_seq2seq=is_seq2seq,
        kv_cache_dtype=kv_cache_dtype,
    )(params, requests)


def trim_eos(ids: Sequence[int], eos: int, pad: int) -> list[int]:
    """Generated ids up to and including the first EOS, pads stripped —
    the canonical form both decode paths agree on."""
    out: list[int] = []
    for t in ids:
        t = int(t)
        if t == pad:
            continue
        out.append(t)
        if t == eos:
            break
    return out
