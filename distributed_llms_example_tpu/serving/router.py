"""Fault-tolerant serving tier: a replica router over N engine replicas.

The PR 7 engine is a single controller: one crash silently loses every
in-flight request.  This module is the tier above it — a host-side
router spreading requests over N ``ServingEngine`` replicas (in-process
on this container; each replica owns its own compiled programs and slot
state, so the replica boundary is exactly the seam a multi-host router
needs later), with the robustness core the training stack already has
for checkpoints (PRs 6/14) applied to serving:

- **dispatch**: session→replica affinity (a ``session`` key maps to one
  replica while that replica lives, so a conversation's KV locality is
  preservable later) with queue-depth-aware placement otherwise — the
  same ``queue_depth``/occupancy numbers the engine's ``serve_window``
  stream stamps, read live off each replica's session;
- **health machine** per replica: ``live → suspect → dead`` driven by
  heartbeat-miss / step-stall detection (a replica with work whose
  session ``progress`` counter stops moving misses beats), plus
  ``draining → drained`` for graceful retirement.  A step that RAISES is
  an immediate crash → dead;
- **retry / re-prefill**: every request a dead replica held (queued or
  mid-decode) is re-dispatched to a surviving replica with its original
  prompt, budget, and sampling state (greedy — the sampling state IS the
  prompt), bounded by ``max_retries`` with deterministic tick-unit
  exponential backoff (utils/backoff.py ``backoff_ticks``).  Serving is
  stateless by construction, and greedy decode is schedule-independent
  (the PR 7 engine-vs-static pins), so the re-prefilled output is
  BIT-IDENTICAL to an unfailed run — partial tokens from the dead
  replica are discarded, never surfaced;
- **admission control / backpressure**: a bounded router queue
  (``max_queue``); over-pressure submissions are SHED (counted,
  reported) or DEFERRED to a client-side buffer per ``shed_policy``
  instead of queueing unboundedly — the router-level twin of PR 13's
  pool-pressure admit-deferral, which keeps operating underneath (a
  replica whose paged pool is short defers its own admissions);
- **deadlines**: per-request wall/tick deadlines checked while a request
  waits (queued, deferred, or backing off) — a request that can no
  longer be served in time is shed with a reason, not silently late;
- **graceful drain**: ``drain_replica(i)`` stops admitting to a replica,
  re-dispatches its queued requests, lets live slots finish, then
  retires it — zero requests lost, nothing checkpointed, because there
  is nothing to checkpoint.

Chaos (obs/chaos.py serving kinds, ticks = router scheduler ticks):
``replica_crash@K`` raises from the busiest replica's step at tick K;
``replica_stall@K`` wedges it (no progress, no exception — only the
heartbeat-miss detector can catch it); ``request_storm@K`` injects a
synthetic burst through admission control.  Every failure path in this
module is reachable from the grammar, and ``obs.report --strict`` stays
green exactly when every observed serving fault is one the harness
injected.

Honest scope notes: replicas here are in-process, so an ORGANIC wedged
step would block the single scheduler thread — the stall detector's
organic trigger is a replica that stops progressing across ticks (e.g.
a paged pool livelock), while a truly hung device call needs the
multi-host router this seam is built for.  Organic crashes (any
exception out of a replica's step) take the full detect→retry path.

Obs events: ``router_window`` (cadence), ``replica_health``
(transitions, ``local``), ``serve_retry`` / ``serve_shed`` per
occurrence, and a final ``router_summary`` carrying request-level MTTR,
retry rate, shed counts and the goodput fields — what
``scripts/obs_gate.py --max-request-retry-rate /
--min-serve-goodput-frac`` gates on.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Sequence

from distributed_llms_example_tpu.obs import sink as sink_mod
from distributed_llms_example_tpu.obs.chaos import ChaosSchedule
from distributed_llms_example_tpu.serving.engine import (
    ServingEngine,
    compute_goodput,
)
from distributed_llms_example_tpu.utils.backoff import backoff_ticks
from distributed_llms_example_tpu.utils.jsonlog import log_json

HEALTH_STATES = ("live", "suspect", "dead", "draining", "drained")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router policy knobs.  Tick-unit fields are deterministic by
    design: the failure tests replay bit-for-bit.

    ``max_retries``: re-dispatch budget per request after replica
    failures (exceeding it sheds the request — bounded retry, never a
    hot loop).  ``retry_backoff_ticks``/``retry_backoff_cap_ticks``: the
    capped exponential re-dispatch delay (utils/backoff.py).
    ``suspect_after_ticks``/``dead_after_ticks``: missed heartbeats
    (ticks without session progress while holding work) before live →
    suspect → dead.  ``max_queue``: router queue bound (0 = unbounded —
    admission control off).  ``shed_policy``: what happens to a
    submission over ``max_queue`` — "shed" rejects it now, "defer" parks
    it client-side and admits when the queue drains.
    ``replica_queue_depth``: per-replica dispatch cap (0 = the engine's
    prefill chunk).  ``deadline_s``: default per-request wall deadline
    (0 = none).  ``storm_size``/``storm_deadline_ticks``: the
    ``request_storm`` chaos burst's size (0 = auto) and the synthetic
    requests' tick deadline (storms must shed, not starve real work).
    """

    max_retries: int = 2
    retry_backoff_ticks: int = 2
    retry_backoff_cap_ticks: int = 16
    suspect_after_ticks: int = 3
    dead_after_ticks: int = 6
    max_queue: int = 0
    shed_policy: str = "defer"  # "defer" | "shed"
    replica_queue_depth: int = 0
    deadline_s: float = 0.0
    log_every_ticks: int = 50
    storm_size: int = 0
    storm_deadline_ticks: int = 64
    chaos: ChaosSchedule | None = None

    def __post_init__(self):
        if self.shed_policy not in ("defer", "shed"):
            raise ValueError(
                f"shed_policy={self.shed_policy!r}: must be 'defer' or 'shed'"
            )
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.dead_after_ticks <= self.suspect_after_ticks:
            raise ValueError(
                "dead_after_ticks must exceed suspect_after_ticks "
                "(suspect is the earlier rung of the same detector)"
            )


@dataclasses.dataclass
class _Request:
    rid: int
    tokens: list
    mask: Any
    budget: int | None
    session_key: Any
    synthetic: bool
    arrival_wall: float  # scheduled arrival (== submit_wall closed-loop)
    submit_wall: float
    submit_tick: int
    deadline_wall: float | None  # absolute perf_counter instant
    deadline_tick: int | None
    retries: int = 0
    ready_tick: int = 0
    replica: int | None = None  # current assignment
    local: int | None = None  # session-local rid on that replica
    done: bool = False
    shed: bool = False
    shed_reason: str = ""
    out: list = dataclasses.field(default_factory=list)
    ttft_s: float | None = None
    done_wall: float | None = None
    first_fail_wall: float | None = None


@dataclasses.dataclass
class _Replica:
    idx: int
    engine: ServingEngine
    session: Any
    state: str = "live"
    last_beat: int = 0
    crashes: int = 0


class ReplicaRouter:
    """The scheduler: one ``tick()`` = chaos → deadlines → dispatch →
    step every serving replica → health update → cadence window.
    ``serve()`` is the batch driver (submit everything, tick until every
    request is done or shed, finalize)."""

    def __init__(
        self,
        engines: Sequence[ServingEngine],
        params: Any,
        cfg: RouterConfig | None = None,
    ):
        if not engines:
            raise ValueError("the replica pool needs at least one engine")
        self.cfg = cfg or RouterConfig()
        self.params = params
        self.replicas = [
            _Replica(idx=i, engine=e, session=e.open(params, replica=i))
            for i, e in enumerate(engines)
        ]
        self._depth_cap = self.cfg.replica_queue_depth or max(
            e.prefill_batch for e in engines
        )
        self.requests: list[_Request] = []
        self.queue: "collections.deque[_Request]" = collections.deque()
        self.deferred: "collections.deque[_Request]" = collections.deque()
        self.affinity: dict[Any, int] = {}
        self.ticks = 0
        self.t_open = time.perf_counter()
        self.admitting = True  # drain() flips it
        # counters / degraded-phase stamps
        self.retries_total = 0
        self.shed_by_reason: dict[str, int] = {}
        self._chaos_stalled: set[int] = set()
        self._requeued_outstanding: set[int] = set()
        self.t_fail: float | None = None  # first replica failure (wall)
        self.t_recovered: float | None = None  # last failure-requeue re-dispatched
        self.last_stats: dict | None = None
        self._finalized = False

    # ------------------------------------------------------------- intake
    def submit(
        self,
        tokens: Sequence[int],
        *,
        max_new: int | None = None,
        attention_mask: Sequence[int] | None = None,
        session: Any = None,
        deadline_s: float | None = None,
        deadline_ticks: int | None = None,
        synthetic: bool = False,
        arrival: float | None = None,
    ) -> int:
        """Offer one request to the router.  Admission control applies
        HERE: a full queue sheds (policy "shed") or defers (policy
        "defer" — parked client-side, admitted as the queue drains)
        instead of growing without bound.  Returns the router-global
        request id either way; a shed request's output stays empty and
        its reason rides the summary.  ``arrival`` (absolute
        perf_counter instant, default: now) is the open-loop scheduled
        arrival — it threads through dispatch to the replica session so
        the ``serve_request`` stream's arrival→submit queue-delay stage
        covers router-held time too."""
        now = time.perf_counter()
        ddl_s = self.cfg.deadline_s if deadline_s is None else deadline_s
        req = _Request(
            rid=len(self.requests),
            tokens=list(tokens),
            mask=list(attention_mask) if attention_mask is not None else None,
            budget=max_new,
            session_key=session,
            synthetic=synthetic,
            arrival_wall=float(arrival) if arrival is not None else now,
            submit_wall=now,
            submit_tick=self.ticks,
            deadline_wall=(now + ddl_s) if ddl_s and ddl_s > 0 else None,
            deadline_tick=(
                self.ticks + int(deadline_ticks)
                if deadline_ticks is not None
                else None
            ),
        )
        self.requests.append(req)
        if not self.admitting:
            self._shed(req, "draining")
            return req.rid
        if self.cfg.max_queue and len(self.queue) >= self.cfg.max_queue:
            if self.cfg.shed_policy == "shed":
                self._shed(req, "queue_full")
            else:
                self.deferred.append(req)
        else:
            self.queue.append(req)
        return req.rid

    # ------------------------------------------------------------ helpers
    def _shed(self, req: _Request, reason: str) -> None:
        req.shed, req.shed_reason = True, reason
        self._requeued_outstanding.discard(req.rid)
        self.shed_by_reason[reason] = self.shed_by_reason.get(reason, 0) + 1
        log_json({
            "event": "serve_shed",
            "request": req.rid,
            "reason": reason,
            "tick": self.ticks,
            "synthetic": req.synthetic,
        })

    def _emit_health(self, r: _Replica, old: str, new: str, *,
                     reason: str, **extra: Any) -> None:
        r.state = new
        # local: single-process today, but the event is per-replica
        # telemetry by nature — the multi-host router will fan it out
        sink_mod.emit({
            "event": "replica_health",
            "replica": r.idx,
            "from": old,
            "to": new,
            "tick": self.ticks,
            "reason": reason,
            **extra,
        }, local=True)

    def _live(self) -> list[_Replica]:
        return [r for r in self.replicas if r.state == "live"]

    def _serving(self) -> list[_Replica]:
        # replicas still worth stepping: live, suspect (maybe just slow),
        # draining (finishing their slots)
        return [
            r for r in self.replicas
            if r.state in ("live", "suspect", "draining")
        ]

    def _pick_victim(self) -> _Replica | None:
        """The chaos target: the busiest steppable replica (most active
        decode slots, ties to the lowest id) — deterministic, and the
        most impactful kill."""
        cands = [r for r in self.replicas if r.state in ("live", "suspect")]
        if not cands:
            return None
        return max(cands, key=lambda r: (r.session.active_count, -r.idx))

    # ------------------------------------------------------------ failure
    def _fail_replica(self, r: _Replica, *, cause: str, reason: str) -> None:
        """A replica is gone (crash raised, or the stall detector gave
        up): mark it dead and re-dispatch every request it held — queued
        OR mid-decode — onto the surviving pool.  Partial tokens are
        discarded; the re-prefill regenerates from the original prompt,
        so greedy output stays bit-identical to an unfailed run."""
        now = time.perf_counter()
        if self.t_fail is None:
            self.t_fail = now
        r.crashes += 1
        self._emit_health(
            r, r.state, "dead", reason=reason, cause=cause,
            since_tick=r.last_beat,
        )
        self._chaos_stalled.discard(r.idx)
        held = [
            q for q in self.requests
            if q.replica == r.idx and not q.done and not q.shed
        ]
        for q in held:
            had_tokens = bool(q.local is not None
                              and r.session.outputs[q.local])
            q.replica, q.local = None, None
            q.retries += 1
            self.retries_total += 1
            if q.first_fail_wall is None:
                q.first_fail_wall = now
            if q.retries > self.cfg.max_retries:
                self._shed(q, "retries_exhausted")
                continue
            q.ready_tick = self.ticks + backoff_ticks(
                q.retries,
                base=self.cfg.retry_backoff_ticks,
                cap=self.cfg.retry_backoff_cap_ticks,
            )
            self._requeued_outstanding.add(q.rid)
            self.queue.appendleft(q)  # failed work re-queues at the front
            log_json({
                "event": "serve_retry",
                "request": q.rid,
                "replica": r.idx,
                "retries": q.retries,
                "ready_tick": q.ready_tick,
                "tick": self.ticks,
                "had_tokens": had_tokens,
                "synthetic": q.synthetic,
                "reason": cause,
            })
        # the session (and its device state) is gone with the replica —
        # but the paged pool's free list is HOST state on the engine: if
        # the engine object is ever reused (tests, bench reruns), the
        # dead session's blocks must return or they leak forever
        if r.engine.paged and r.session is not None:
            for blocks in r.session.slot_blocks:
                if blocks:
                    r.engine.pool.free(blocks)
            if r.engine.prefix:
                # the dead replica's warm set dies with it: its pool
                # content is device state that no re-prefilled survivor
                # may match against — a follow-up turn re-prefills cold
                # on whichever replica inherits the session
                r.engine.pool.drop_warm()
        r.session = None

    # ------------------------------------------------------------- drain
    def drain_replica(self, idx: int) -> None:
        """Graceful retirement: stop admitting to the replica, re-route
        its queued requests, let its live slots decode to completion —
        then it parks as ``drained``.  Nothing is checkpointed: serving
        state is derived entirely from the request stream."""
        r = self.replicas[idx]
        if r.state not in ("live", "suspect"):
            return
        self._emit_health(r, r.state, "draining", reason="operator drain")
        taken = set(r.session.take_pending())
        for q in self.requests:
            if q.rid in taken:
                q.replica, q.local = None, None
                q.ready_tick = self.ticks  # no lost work: no backoff
                self.queue.appendleft(q)
                log_json({
                    "event": "serve_retry",
                    "request": q.rid,
                    "replica": r.idx,
                    "retries": q.retries,  # drain re-dispatch is not a retry
                    "ready_tick": q.ready_tick,
                    "tick": self.ticks,
                    "had_tokens": False,
                    "synthetic": q.synthetic,
                    "reason": "drain",
                })

    def drain(self) -> None:
        """Router-wide graceful drain: stop admitting NEW submissions
        (they shed with reason "draining"); everything already accepted
        finishes."""
        self.admitting = False

    # ------------------------------------------------------------ routing
    def _route(self, req: _Request) -> _Replica | None:
        """Pick the replica for a request: session affinity while the
        mapped replica is live and has room, else the live replica with
        the smallest (queued + active) load — the dispatch signal the
        engine's serve_window stamps as queue_depth/occupancy, read live
        off each session."""
        def depth(r: _Replica) -> int:
            return r.session.queue_depth + r.session.active_count

        live = self._live()
        if not live:
            return None
        if req.session_key is not None:
            mapped = self.affinity.get(req.session_key)
            if mapped is not None:
                r = self.replicas[mapped]
                if r.state == "live" and depth(r) < self._depth_cap:
                    return r
        best = min(live, key=lambda r: (depth(r), r.idx))
        if depth(best) >= self._depth_cap:
            return None
        if req.session_key is not None:
            self.affinity[req.session_key] = best.idx
        return best

    def _dispatch(self) -> None:
        # FIFO over READY requests (backoff holds a request out without
        # blocking the ones behind it)
        held: list[_Request] = []
        while self.queue:
            req = self.queue.popleft()
            if req.shed or req.done:
                continue
            if req.ready_tick > self.ticks:
                held.append(req)
                continue
            target = self._route(req)
            if target is None:
                held.append(req)
                break  # no capacity anywhere this tick
            req.local = target.session.submit(
                req.tokens,
                max_new=req.budget,
                attention_mask=req.mask,
                label=req.rid,
                arrival=req.arrival_wall,
            )
            req.replica = target.idx
            if req.rid in self._requeued_outstanding:
                self._requeued_outstanding.discard(req.rid)
                if not self._requeued_outstanding and self.t_fail is not None:
                    # every failure-displaced request is re-admitted: the
                    # degraded phase ends here (bench's before/during/after)
                    self.t_recovered = time.perf_counter()
        for req in reversed(held):
            self.queue.appendleft(req)

    # ----------------------------------------------------------- deadline
    def _sweep_deadlines(self) -> None:
        now = time.perf_counter()

        def expired(q: _Request) -> bool:
            if q.deadline_wall is not None and now > q.deadline_wall:
                return True
            return q.deadline_tick is not None and self.ticks > q.deadline_tick

        for buf in (self.queue, self.deferred):
            for q in list(buf):
                if expired(q):
                    buf.remove(q)
                    self._shed(q, "deadline")

    def _promote_deferred(self) -> None:
        while self.deferred and (
            not self.cfg.max_queue or len(self.queue) < self.cfg.max_queue
        ):
            self.queue.append(self.deferred.popleft())

    # -------------------------------------------------------------- chaos
    def _take_chaos(self) -> None:
        chaos = self.cfg.chaos
        if not chaos:
            return
        if chaos.take("replica_crash", self.ticks):
            victim = self._pick_victim()
            if victim is not None:
                # the injected crash IS an exception out of the replica's
                # step path: route it through the one failure handler
                self._fail_replica(
                    victim, cause="crash",
                    reason="chaos: injected replica crash",
                )
        if chaos.take("replica_stall", self.ticks):
            victim = self._pick_victim()
            if victim is not None:
                # wedge, don't kill: the replica stops progressing and
                # only the heartbeat-miss detector can notice
                self._chaos_stalled.add(victim.idx)
        if chaos.take("request_storm", self.ticks):
            real = [q for q in self.requests if not q.synthetic]
            if real:
                size = self.cfg.storm_size or 2 * (
                    self.cfg.max_queue or 2 * self._depth_cap
                )
                for i in range(size):
                    src = real[i % len(real)]
                    self.submit(
                        src.tokens,
                        max_new=src.budget,
                        attention_mask=src.mask,
                        deadline_ticks=self.cfg.storm_deadline_ticks,
                        synthetic=True,
                    )

    # ------------------------------------------------------------ the tick
    def tick(self) -> None:
        self.ticks += 1
        self._take_chaos()
        self._sweep_deadlines()
        self._promote_deferred()
        self._dispatch()
        now = time.perf_counter()
        for r in self._serving():
            if not r.session.has_work():
                if r.state == "draining":
                    self._emit_health(
                        r, "draining", "drained", reason="slots empty"
                    )
                else:
                    r.last_beat = self.ticks  # idle is not a missed beat
                continue
            if r.idx in self._chaos_stalled:
                continue  # wedged: no step, no progress, no beat
            before = r.session.progress
            try:
                finished = r.session.step()
            except Exception as e:  # noqa: BLE001 — a replica crash is any escape
                self._fail_replica(
                    r, cause="crash", reason=f"step raised: {str(e)[:200]}"
                )
                continue
            if r.session.progress > before:
                r.last_beat = self.ticks
                if r.state == "suspect":
                    self._emit_health(
                        r, "suspect", "live", reason="progress resumed"
                    )
            for local in finished:
                self._complete(r, local, now)
        self._update_health()
        if (
            self.cfg.log_every_ticks
            and self.ticks % self.cfg.log_every_ticks == 0
        ):
            self._emit_window()

    def _complete(self, r: _Replica, local: int, now: float) -> None:
        rid = r.session.labels[local]
        req = self.requests[rid]
        req.done = True
        req.out = list(r.session.output(local))
        req.done_wall = now
        ft = r.session.first_token_wall(local)
        if ft is not None:
            # TTFT from the ORIGINAL submit: a retried request's first
            # token is the one the client actually received — failure +
            # re-prefill time lands in the tail, where the degraded-mode
            # bench must see it
            req.ttft_s = ft - req.submit_wall
        if req.session_key is not None:
            self.affinity[req.session_key] = r.idx

    def _update_health(self) -> None:
        # draining replicas stay under the stall detector too: a wedged
        # replica mid-drain must still be declared dead (and its slot
        # work re-prefilled) or the drain would hang forever
        for r in self.replicas:
            if r.state not in ("live", "suspect", "draining"):
                continue
            if not (r.session.has_work() or r.idx in self._chaos_stalled):
                continue
            missed = self.ticks - r.last_beat
            if missed > self.cfg.dead_after_ticks:
                self._fail_replica(
                    r, cause="stall",
                    reason=(
                        f"no progress for {missed} ticks with work queued "
                        "(heartbeat-miss / step-stall detector)"
                    ),
                )
            elif missed > self.cfg.suspect_after_ticks and r.state == "live":
                self._emit_health(
                    r, "live", "suspect",
                    reason=f"no progress for {missed} ticks",
                )

    def _emit_window(self) -> None:
        log_json({
            "event": "router_window",
            "tick": self.ticks,
            "queue_depth": len(self.queue),
            "deferred": len(self.deferred),
            "retries": self.retries_total,
            "shed": sum(self.shed_by_reason.values()),
            "completed": sum(1 for q in self.requests if q.done),
            "replicas": [
                {
                    "replica": r.idx,
                    "state": r.state,
                    "queue_depth": (
                        r.session.queue_depth if r.session is not None else 0
                    ),
                    "active": (
                        r.session.active_count if r.session is not None else 0
                    ),
                }
                for r in self.replicas
            ],
        })

    # ------------------------------------------------------------- driver
    def _outstanding(self) -> bool:
        return any(not (q.done or q.shed) for q in self.requests)

    def run_until_drained(self) -> None:
        """Tick until every accepted request is done or shed.  If the
        pool empties (every replica dead), the remainder sheds loudly —
        a router with no replicas is an outage, not a hang."""
        while self._outstanding():
            if not self._serving():
                for q in self.requests:
                    if not (q.done or q.shed):
                        self._shed(q, "no_replicas")
                break
            self.tick()

    def serve(
        self,
        requests: Sequence[Sequence[int]],
        *,
        max_new: Sequence[int] | None = None,
        attention_masks: Sequence[Sequence[int]] | None = None,
        sessions: Sequence[Any] | None = None,
    ) -> list[list[int]]:
        """The batch entry point (the serve-router CLI's driver): submit
        everything, run to drained, finalize.  Returns per-request
        generated ids in request order (shed requests: empty list)."""
        if max_new is not None and len(max_new) != len(requests):
            raise ValueError(
                f"max_new has {len(max_new)} entries for {len(requests)} requests"
            )
        rids = [
            self.submit(
                req,
                max_new=(max_new[i] if max_new is not None else None),
                attention_mask=(
                    attention_masks[i] if attention_masks is not None else None
                ),
                session=(sessions[i] if sessions is not None else None),
            )
            for i, req in enumerate(requests)
        ]
        self.run_until_drained()
        self.finalize()
        return [list(self.requests[rid].out) for rid in rids]

    # ------------------------------------------------------------ summary
    def finalize(self) -> dict:
        """Close every surviving session (their serve_summary events) and
        emit the ``router_summary`` the report/gates consume.  Idempotent."""
        if self._finalized:
            return self.last_stats
        self._finalized = True
        for r in self.replicas:
            if r.session is not None:
                r.session.finalize()
        now = time.perf_counter()
        wall = max(now - self.t_open, 1e-9)
        real = [q for q in self.requests if not q.synthetic]
        completed = [q for q in real if q.done]
        mttr_vals = [
            q.done_wall - q.first_fail_wall
            for q in real
            if q.done and q.first_fail_wall is not None
        ]
        from distributed_llms_example_tpu.obs.spans import percentiles

        ttfts = [q.ttft_s for q in completed if q.ttft_s is not None]
        p50, p95, p99 = percentiles(ttfts, (0.50, 0.95, 0.99))
        slo_ms = max(
            (e.serve.ttft_slo_ms for e in (r.engine for r in self.replicas)),
            default=0.0,
        )
        slo_s = slo_ms / 1e3
        useful = [
            q for q in completed
            if q.ttft_s is not None and (slo_s <= 0 or q.ttft_s <= slo_s)
        ]
        import jax

        goodput = compute_goodput(
            [q.ttft_s for q in real],
            [len(q.out) for q in real],
            wall_s=wall,
            ttft_slo_ms=slo_ms,
            n_chips=max(jax.device_count(), 1),
        )
        # the gated rate is REAL traffic's failure retries: synthetic
        # storm requests are injected load, and counting their retries
        # against a real-request denominator would inflate the rate past
        # 1.0 under storm+crash chaos
        real_retries = sum(q.retries for q in real)
        summary = {
            "event": "router_summary",
            "replicas": len(self.replicas),
            "replica_states": {
                str(r.idx): r.state for r in self.replicas
            },
            "ticks": self.ticks,
            "wall_s": round(wall, 3),
            "requests": len(real),
            "synthetic_requests": len(self.requests) - len(real),
            "completed": len(completed),
            "shed": sum(
                1 for q in real if q.shed
            ),
            "shed_by_reason": dict(sorted(self.shed_by_reason.items())),
            "retries": real_retries,
            "retries_total": self.retries_total,  # synthetic included
            # the gate inputs: bounded-retry health and request-level
            # usefulness of the whole tier, not one replica
            "request_retry_rate": round(
                real_retries / max(len(real), 1), 4
            ),
            "goodput_frac": round(len(useful) / max(len(real), 1), 4),
            "request_mttr_s": (
                round(sum(mttr_vals) / len(mttr_vals), 4) if mttr_vals else None
            ),
            "ttft_p50_ms": round(p50 * 1e3, 1),
            "ttft_p95_ms": round(p95 * 1e3, 1),
            "ttft_p99_ms": round(p99 * 1e3, 1),
            **goodput,
        }
        if any(r.engine.paged and r.engine.prefix for r in self.replicas):
            # tier-wide prefix-cache ledger, summed over surviving
            # replicas' closed sessions (a dead replica's stats die with
            # its session — the drop is part of the failure's cost)
            lookups = hits = saved = total = 0
            for r in self.replicas:
                st = r.engine.last_stats
                if st is None:
                    continue
                lookups += st.prefix_lookups
                hits += st.prefix_hits
                saved += st.prefill_tokens_saved
                total += st.prefill_tokens_total
            summary["prefix_lookups"] = lookups
            summary["prefix_hits"] = hits
            summary["prefix_hit_rate"] = round(hits / max(lookups, 1), 4)
            summary["prefill_tokens_saved"] = saved
            summary["prefill_tokens_total"] = total
            summary["prefill_tokens_saved_frac"] = round(
                saved / max(total, 1), 4
            )
        if any(getattr(r.engine, "spec", 0) for r in self.replicas):
            # tier-wide speculative-decode ledger over surviving replicas
            # — the degraded-mode leg reads its multi-token yield off the
            # SAME fields, so "the speedup survives a replica kill" is a
            # router_summary claim, not a per-replica one
            drafted = accepted = emitted = rounds = 0
            for r in self.replicas:
                st = r.engine.last_stats
                if st is None:
                    continue
                drafted += st.spec_drafted
                accepted += st.spec_accepted
                emitted += st.spec_emitted
                rounds += st.spec_slot_rounds
            summary["spec_tokens"] = max(
                getattr(r.engine, "spec", 0) for r in self.replicas
            )
            summary["spec_drafted_tokens"] = drafted
            summary["spec_accepted_tokens"] = accepted
            summary["acceptance_rate"] = round(
                accepted / max(drafted, 1), 4
            )
            summary["accepted_tokens_per_step"] = round(
                emitted / max(rounds, 1), 4
            )
        if self.t_fail is not None:
            summary["t_fail_s"] = round(self.t_fail - self.t_open, 4)
            if self.t_recovered is not None:
                summary["t_recovered_s"] = round(
                    self.t_recovered - self.t_open, 4
                )
        log_json(summary)
        self.last_stats = summary
        return summary

    def request_rows(self) -> list[dict]:
        """Per-request completion rows (bench's degraded-phase input):
        submit/done instants relative to router open, TTFT, tokens,
        retries, shed."""
        return [
            {
                "rid": q.rid,
                "synthetic": q.synthetic,
                "arrival_s": round(q.arrival_wall - self.t_open, 6),
                "queue_delay_ms": round(
                    (q.submit_wall - q.arrival_wall) * 1e3, 3
                ),
                "submit_s": round(q.submit_wall - self.t_open, 6),
                "done_s": (
                    round(q.done_wall - self.t_open, 6)
                    if q.done_wall is not None
                    else None
                ),
                "ttft_s": q.ttft_s,
                "tokens": len(q.out),
                "retries": q.retries,
                "shed": q.shed,
                "shed_reason": q.shed_reason,
            }
            for q in self.requests
        ]
