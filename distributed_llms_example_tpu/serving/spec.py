"""Speculative multi-token decode: draft-then-verify, bit-identical to greedy.

Plain continuous batching pays one full decode-step dispatch per generated
token per slot.  ``flash_decode`` already scores q blocks of up to 8 rows in
one kernel call (the per-row length masks express staggered offsets), so
*verifying* k draft tokens costs about one decode step — the classic
draft-then-verify win.  This module owns ALL of the speculative math; the
engine only assembles inputs and appends accepted tokens (repo_lint rule 17
fences acceptance/rollback to this file + serving/cache_pool.py).

Two draft sources, both proposing ``k`` tokens per slot per round:

- **n-gram self-drafting** (default, zero extra model): the longest-suffix
  n-gram match over the slot's prompt + already-generated tokens proposes
  the tokens that followed the last occurrence — free lookahead that pays
  off exactly when decode output is locally repetitive (code, templated
  prose, greedy loops).
- **a shrunk draft model** resolved through the model registry
  (``--spec-draft-model``): a causal model sharing the target's vocab,
  decoded greedily ``k`` steps per round on its own flat cache
  (``DraftRunner``).

The acceptance rule is the whole contract: run the target model ONCE over
``x = [last_emitted, d_1 .. d_k]`` (a q block of k+1 rows), take the
target's greedy argmax at every position, accept the longest prefix where
``draft == target argmax``, then emit the target's OWN next token after the
accepted prefix.  Every emitted token is therefore a token greedy decoding
would have produced — speculative output is **bit-identical to plain
greedy**, only cheaper per token.  (That is the engine-vs-static
determinism pattern: same argmax expression, same kernel path — int8 KV
dequant included — so the tests pin equality, not closeness.)

Rollback is mask discipline, not data movement: the verify program opens
the k+1 mask span up front, and after acceptance rebuilds the span to
``accepted + 1`` bits.  Rejected positions hold garbage K/V but are
mask-invisible (the poisoned-pool invariant), and the NEXT round's span
write covers exactly those positions before any read — write-before-attend
makes the stale tail unreachable by construction.  On the paged path the
span write scatters through ``cache_pool.scatter_span`` (per-row block
tables, sentinel drops), so speculative writes only ever land in blocks the
slot already owns: rejection returns nothing to the free-list because
nothing was ever taken, and the prefix-cache hash index never sees a
speculative block (registration happens only at admission).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llms_example_tpu.evaluation.generation import _causal_prefill
from distributed_llms_example_tpu.parallel.activation import kv_cache_context
from distributed_llms_example_tpu.serving import cache_pool

__all__ = [
    "ngram_draft",
    "ngram_drafts",
    "acceptance_lengths",
    "build_verify",
    "DraftRunner",
]


# ----------------------------------------------------------------- drafting
def ngram_draft(history: Sequence[int], k: int, *, max_n: int = 3) -> list[int]:
    """Self-drafting lookahead: find the most recent earlier occurrence of
    the longest suffix n-gram (n = max_n .. 1) of ``history`` and propose
    the ``k`` tokens that followed it, extending from ``history`` itself
    when the match runs off the end.  Falls back to repeating the last
    token, so the result always has exactly ``k`` entries — the verify
    step prices a wrong draft at zero emitted tokens, never at
    correctness."""
    h = list(history)
    if not h:
        return [0] * k
    for n in range(min(max_n, len(h) - 1), 0, -1):
        suffix = h[-n:]
        # scan right-to-left for the most recent PRIOR occurrence
        for i in range(len(h) - n - 1, -1, -1):
            if h[i : i + n] == suffix:
                out = h[i + n : i + n + k]
                comb = suffix + out
                while len(out) < k:
                    # the match ran off the end: continue period-n
                    # repetition over the proposed stream itself
                    nxt = comb[-n]
                    out.append(nxt)
                    comb.append(nxt)
                return out[:k]
    return [h[-1]] * k


def ngram_drafts(
    histories: Sequence[Sequence[int] | None], k: int, pad: int,
) -> np.ndarray:
    """Batch ``ngram_draft`` over per-slot histories (None = idle slot →
    pad row).  Returns an (slots, k) int32 array — the verify program's
    draft columns."""
    out = np.full((len(histories), k), pad, np.int32)
    for s, h in enumerate(histories):
        if h:
            out[s] = ngram_draft(h, k)
    return out


# --------------------------------------------------------------- acceptance
def acceptance_lengths(
    x: jnp.ndarray, target: jnp.ndarray, room: jnp.ndarray,
) -> jnp.ndarray:
    """The acceptance rule: longest prefix where draft == target argmax.

    ``x`` is (S, k+1) = [last_emitted, d_1..d_k]; ``target`` is (S, k+1),
    the target model's greedy argmax at each of those positions (so
    ``target[:, j]`` is what greedy decoding emits after seeing
    ``x[:, :j+1]``).  Draft ``d_{j+1}`` is accepted iff it EQUALS
    ``target[:, j]`` and every earlier draft was accepted — the cumprod
    over matches.  ``room`` (S,) clamps acceptance to the slot's remaining
    budget minus one (the bonus token always lands), so a round never
    emits past ``max_new_tokens``; clamping only truncates the prefix, it
    never changes a token, so emitted output stays exactly the greedy
    string.  Returns (S,) int32 accepted-draft counts in [0, k]."""
    k = x.shape[1] - 1
    j = jnp.arange(k)
    matches = (x[:, 1:] == target[:, :-1]) & (j[None, :] < room[:, None])
    return jnp.cumprod(matches.astype(jnp.int32), axis=1).sum(axis=1)


# ------------------------------------------------------------ verify program
def build_verify(
    model: Any, *, slots: int, k: int, pad: int,
    paged: bool = False, num_blocks: int = 0, block_size: int = 0,
):
    """Build the engine's spec-verify program: ONE target-model call over a
    q block of k+1 rows per slot, acceptance, and the mask-rebuild
    rollback.  Flat signature ``(params, state, x, write_pos, rope_pos,
    active, room)``; paged inserts ``block_tables`` after ``x``.  Returns
    ``(target, n_emit, state)`` where ``target`` (S, k+1) holds the greedy
    tokens (pad on idle rows) and ``n_emit = accepted + 1`` counts how
    many of ``target``'s leading entries the host appends.

    Position contract: cache position ``write_pos + j`` receives the K/V
    of ``x[:, j]``.  An accepted prefix of length m means positions
    ``write_pos .. write_pos + m`` hold [last, target_0..target_{m-1}] —
    all tokens greedy decode would have cached there.  The bonus token
    ``target[:, m]`` becomes the next round's ``x[:, 0]``, written at the
    next round's ``write_pos' = write_pos + m + 1`` — exactly where the
    rejected tail starts, so stale K/V is overwritten before its mask bit
    can ever be re-set (write-before-attend)."""
    S, K = slots, k
    span = jnp.arange(K + 1)
    rows = jnp.arange(S)

    def _verify_core(params, state, x, block_tables, write_pos, rope_pos,
                     active, room):
        width = state["mask"].shape[1]
        offs = jnp.where(active, write_pos, width)
        # open the whole candidate span; per-row causality within the span
        # rides the decode-step bias (q_pos = offset + row index)
        mask = state["mask"].at[
            rows[:, None], offs[:, None] + span[None, :]
        ].set(1, mode="drop")
        if paged:
            from distributed_llms_example_tpu.parallel.activation import (
                constrain_cache,
            )

            cache = constrain_cache(
                cache_pool.gather_cache(state["pool"], block_tables)
            )
        else:
            cache = state["cache"]
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            x,
            mask,
            use_cache=True,
            positions=rope_pos[:, None] + span[None, :],
            cache_positions=offs,
            mutable=["cache"],
        )
        target = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S, K+1)
        accept = acceptance_lengths(x, target, room)
        n_emit = jnp.where(active, accept + 1, 0).astype(jnp.int32)
        # rollback = mask rebuild: only the accepted prefix (+ the row for
        # x[:,0], always valid) keeps its bits; rejected positions go dark
        keep = (span[None, :] <= accept[:, None]).astype(state["mask"].dtype)
        mask = state["mask"].at[
            rows[:, None], offs[:, None] + span[None, :]
        ].set(keep, mode="drop")
        last = jnp.take_along_axis(target, accept[:, None], axis=1)[:, 0]
        last = jnp.where(active, last, pad)
        target = jnp.where(active[:, None], target, pad)
        out = {**state, "mask": mask, "last": last}
        if paged:
            out["pool"] = cache_pool.scatter_span(
                state["pool"], mut["cache"], block_tables, offs, K + 1,
                num_blocks=num_blocks, block_size=block_size,
            )
        else:
            from distributed_llms_example_tpu.parallel.activation import (
                constrain_cache,
            )

            out["cache"] = constrain_cache(mut["cache"])
        return target, n_emit, out

    if paged:
        def verify(params, state, x, block_tables, write_pos, rope_pos,
                   active, room):
            return _verify_core(params, state, x, block_tables, write_pos,
                                rope_pos, active, room)
    else:
        def verify(params, state, x, write_pos, rope_pos, active, room):
            return _verify_core(params, state, x, None, write_pos,
                                rope_pos, active, room)

    return verify


# ------------------------------------------------------------- draft runner
class DraftRunner:
    """The shrunk-draft-model path: a second causal model (same vocab,
    resolved through the registry) greedily proposes ``k`` tokens per slot
    per round on its own FLAT cache, mirroring the target's slot layout
    (prompt at positions 0..len-1 inside the admission bucket, decode tail
    at ``base = bucket``).

    The per-round program is catch-up-then-draft: the draft cache always
    trails the target by exactly the tokens the engine appended last round
    (``fed``, between 1 and k+1 of them), so each round first writes that
    span in one multi-token call — whose logits at the last fed position
    already yield draft token 1 — then single-steps k-1 more.  The final
    mask rebuild keeps only the fed positions: the draft's own speculative
    writes roll back by the same mask discipline as the verify program,
    and the next round's catch-up span overwrites them before any read."""

    def __init__(self, loaded: Any, *, slots: int, src_width: int,
                 max_new: int, buckets: Sequence[int], prefill_batch: int,
                 k: int, pad: int, kv_cache_dtype: str, wrap: Any):
        self.model = loaded.module
        self.config = loaded.config
        params = loaded.params
        if params is None:
            params = jax.device_get(loaded.init_params(0))
        self.params = params
        self.S, self.W, self.L, self.K = slots, src_width, max_new, k
        self.C = prefill_batch
        self.pad = pad
        self.width = src_width + max_new
        self.buckets = tuple(buckets)
        self.kv_cache_dtype = kv_cache_dtype
        self._warmed = False
        self._build(wrap)

    # ------------------------------------------------------------ programs
    def _build(self, wrap) -> None:
        model, S, K, L = self.model, self.S, self.K, self.L
        width = self.width
        # the round touches the catch-up span (n_fed ≤ K+1 rows from pos0)
        # AND the draft tail (K-1 single steps from pos0+n_fed-1): open
        # every position either can reach up front, rebuild at the end
        open_w = max(K + 1, 2 * K)
        ospan = jnp.arange(open_w)
        kspan = jnp.arange(K + 1)
        rows = jnp.arange(S)

        def prefill(params, ids, mask):
            cache, full_mask, _lengths, _first = _causal_prefill(
                model, params, ids, mask, L
            )
            return cache, full_mask

        def admit(state, cache, full_mask, slot_idx):
            def pad_axis(x):
                if getattr(x, "ndim", 0) >= 3 and x.shape[2] != width:
                    pads = [(0, 0)] * x.ndim
                    pads[2] = (0, width - x.shape[2])
                    return jnp.pad(x, pads)
                return x

            put = lambda dst, src: (  # noqa: E731
                dst.at[slot_idx].set(src, mode="drop") if dst.ndim > 0 else dst
            )
            fm = full_mask
            if fm.shape[1] != width:
                fm = jnp.pad(fm, ((0, 0), (0, width - fm.shape[1])))
            return {
                "cache": jax.tree.map(
                    put, state["cache"], jax.tree.map(pad_axis, cache)
                ),
                "mask": put(state["mask"], fm),
            }

        def round_(params, state, fed, n_fed, pos0, rope0, active):
            pos = jnp.where(active, pos0, width)
            mask = state["mask"].at[
                rows[:, None], pos[:, None] + ospan[None, :]
            ].set(1, mode="drop")
            # catch-up: write the fed span (garbage pad-K/V lands at
            # positions >= n_fed but is overwritten by the draft steps
            # below before any read — write-before-attend); the logits at
            # the last fed row are the first draft token
            logits, mut = model.apply(
                {"params": params, "cache": state["cache"]},
                fed,
                mask,
                use_cache=True,
                positions=rope0[:, None] + kspan[None, :],
                cache_positions=pos,
                mutable=["cache"],
            )
            cache = mut["cache"]
            toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            idx = jnp.clip(n_fed - 1, 0, K)  # idle rows have n_fed = 0
            cur = jnp.take_along_axis(toks, idx[:, None], axis=1)[:, 0]
            drafts = [cur]
            q = pos0 + n_fed - 1  # the last fed position
            rq = rope0 + n_fed - 1
            for t in range(1, K):
                cp = jnp.where(active, q + t, width)
                lg, mut = model.apply(
                    {"params": params, "cache": cache},
                    cur[:, None],
                    mask,
                    use_cache=True,
                    positions=(rq + t)[:, None],
                    cache_positions=cp,
                    mutable=["cache"],
                )
                cache = mut["cache"]
                cur = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                drafts.append(cur)
            # rollback: only the fed tokens stay visible — every
            # speculative draft position goes dark until the next round's
            # catch-up span rewrites it
            keep = (ospan[None, :] < n_fed[:, None]).astype(state["mask"].dtype)
            final_mask = state["mask"].at[
                rows[:, None], pos[:, None] + ospan[None, :]
            ].set(keep, mode="drop")
            return jnp.stack(drafts, axis=1), {
                "cache": cache, "mask": final_mask,
            }

        self._prefill_core = prefill
        self._prefill = wrap(prefill, name="draft_prefill")
        self._admit = wrap(admit, donate=(0,), name="draft_admit")
        self._round = wrap(round_, donate=(0,), name="draft_round")

    # --------------------------------------------------------------- state
    def init_state(self) -> dict:
        ids = jnp.zeros((self.S, self.W), jnp.int32)
        mask = jnp.zeros((self.S, self.W), jnp.int32)
        with kv_cache_context(self.kv_cache_dtype):
            a_cache, a_mask = jax.eval_shape(
                lambda p: self._prefill_core(p, ids, mask), self.params
            )
        zeros = lambda t: jax.tree.map(  # noqa: E731
            lambda a: jnp.zeros(a.shape, a.dtype), t
        )
        return {"cache": zeros(a_cache), "mask": zeros(a_mask)}

    def warm(self, state) -> Any:
        """One prefill+admit trace per bucket (parked writes) plus one
        all-idle round — the draft programs join the engine's
        zero-recompile contract."""
        if self._warmed:
            return state
        C, S, K = self.C, self.S, self.K
        park = jnp.full((C,), S, jnp.int32)
        for bucket in self.buckets:
            cache, fm = self._prefill(
                self.params, jnp.zeros((C, bucket), jnp.int32),
                jnp.zeros((C, bucket), jnp.int32),
            )
            state = self._admit(state, cache, fm, park)
        idle = jnp.zeros((S,), bool)
        z = jnp.zeros((S,), jnp.int32)
        _, state = self._round(
            self.params, state, jnp.full((S, K + 1), self.pad, jnp.int32),
            z, z, z, idle,
        )
        self._warmed = True
        return state

    def admit_prompt(self, state, ids, mask, slot_idx) -> Any:
        """Prefill + admit one bucket-width chunk of prompts into the
        draft cache (host passes rows padded to ``prefill_batch``, parked
        rows at slot index S)."""
        cache, fm = self._prefill(self.params, ids, mask)
        return self._admit(state, cache, fm, slot_idx)

    def round(self, state, fed, n_fed, pos0, rope0, active):
        """One draft round; returns ((S, k) proposed tokens, new state)."""
        return self._round(self.params, state, fed, n_fed, pos0, rope0, active)
