from distributed_llms_example_tpu.serving.engine import (  # noqa: F401
    ServeConfig,
    ServingEngine,
)
