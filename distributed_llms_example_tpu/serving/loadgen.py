"""Open-loop load generation: seeded arrival schedules, QPS sweeps, knees.

Every serving number the repo produced before this module — TTFT
decomposition, goodput/SLO attainment, the router's degraded-mode
block — was measured under CLOSED-LOOP batch driving: submit a batch,
step until drained.  A closed-loop driver's offered rate is capped by
the service rate by construction, so it can never expose queueing
collapse — the regime where arrivals outpace service and delay grows
without bound, which is exactly what production traffic does to a
saturated tier.  This module is the open-loop alternative (the
Gemma-on-TPU serving comparison's methodology, arXiv:2605.25645):

- **arrival schedules** (``arrival_schedule``): seeded, deterministic
  offset arrays for three processes — ``poisson`` (exponential
  inter-arrivals at the offered rate), ``bursty`` (Poisson bursts of
  ``burst_size`` simultaneous arrivals, same average rate), ``ramp``
  (instantaneous rate climbing linearly from ``ramp_start_frac``×rate
  to rate across the run).  Same seed + config → bit-identical
  float64 schedule; nothing about the schedule reads a wall clock.
- **the open-loop driver** (``drive_open_loop``): submits each request
  the instant its scheduled arrival passes — arrivals NEVER wait for
  completions, so queues genuinely build — and otherwise steps the
  target continuously.  The clock is injectable: real runs use
  ``time.perf_counter``; deterministic tests share a ``VirtualClock``
  with a fake session whose ``step`` advances it.
- **targets**: ``EngineTarget`` (a ``ServeSession`` — or any
  session-shaped fake) and ``RouterTarget`` (a ``ReplicaRouter``, so
  the sweep composes with replica chaos: degraded-mode numbers exist
  AT a stated offered load, not just for a batch).
- **the sweep** (``sweep_qps``): one fresh target per offered-QPS grid
  point, same request set and same arrival seed throughout, producing
  the offered-vs-goodput and p50/p95/p99-TTFT-vs-QPS curves with a
  detected **saturation knee** (``detect_knee``): the first offered
  rate where measured throughput stops tracking the offered rate
  (``achieved < track_tol × offered``), requests shed, or queue delay
  grows without bound (``queue_growing``).

TTFT here is measured from the scheduled ARRIVAL, not the submit
instant — under open-loop load the driver-side wait (arrival→submit)
is real user-visible latency, the stage the engine's ``serve_request``
records now stamp as ``queue_delay_ms``.

Obs events: one ``loadgen_point`` per grid point and a final
``loadgen_summary`` carrying the whole curve + knee — what
``obs.report``'s "Open-loop load sweep" section and the
``--min-slo-attainment`` / ``--max-p99-ttft-ms`` strict gates consume.

Determinism contract (the acceptance pin): greedy decode is
schedule-independent (the engine-vs-static pins), so the SAME requests
driven open-loop at ANY offered rate produce per-request outputs
identical to the closed-loop oracle — arrival timing moves latency,
never tokens.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Sequence

import numpy as np

from distributed_llms_example_tpu.obs.spans import percentiles
from distributed_llms_example_tpu.utils.jsonlog import log_json

ARRIVAL_PROCESSES = ("poisson", "bursty", "ramp")

WORKLOADS = ("random", "chatbot")


def chatbot_requests(
    *,
    sessions: int,
    turns: int,
    seed: int = 0,
    vocab: int = 120,
    system_len: int = 12,
    user_len: tuple[int, int] = (2, 6),
    reply_len: tuple[int, int] = (2, 6),
    shared_frac: float = 0.9,
    max_len: int = 0,
    with_budgets: bool = False,
) -> tuple:
    """The shared-prefix chat mix: (requests, session_keys) in arrival
    order — the workload the prefix cache exists for.

    ``sessions`` conversations × ``turns`` turns each, interleaved
    turn-major (every session's turn 1, then every session's turn 2, …)
    so follow-up turns arrive with OTHER traffic in between — warm
    retention, not just same-wave sharing, is what makes them hit.
    ``shared_frac`` of the sessions open with one COMMON system prompt
    (``system_len`` tokens); the rest draw private system prompts (the
    minority custom-prompt traffic).  Each turn appends a seeded user
    message to the session's history, the prompt is the WHOLE history so
    far (the chat API shape: clients re-send everything), and a seeded
    synthetic assistant reply is appended after — so turn t+1's prompt
    extends turn t's prompt exactly, and every session chain shares the
    system-prompt root.  ``max_len`` (0 = off) right-truncates prompts,
    matching the engine's own ``max_source_length`` truncation.

    Pure function of its arguments (one ``RandomState(seed)`` drives
    every draw in a fixed order): same seed + config → bit-identical
    requests AND keys, the same replay contract as
    ``arrival_schedule``.  ``session_keys`` feed the router's session
    affinity so a conversation's turns land on the replica whose pool
    holds its blocks.

    ``with_budgets=True`` returns ``(requests, session_keys,
    decode_budgets)`` instead, where each budget is the length of the
    turn's synthetic assistant reply — the number of tokens the engine
    would decode to reproduce the scripted conversation.  The budgets
    come from the SAME ``reply_len`` draws that extend the histories
    (no extra rng consumption), so the 2-tuple and 3-tuple forms of one
    seed describe the identical conversation; spec-decode A/B runs use
    them as per-request ``max_new_tokens`` so both legs decode the same
    token counts."""
    if sessions < 1 or turns < 1:
        raise ValueError("sessions and turns must be >= 1")
    if not 0.0 <= shared_frac <= 1.0:
        raise ValueError("shared_frac must be in [0, 1]")
    rng = np.random.RandomState(seed)
    draw = lambda k: rng.randint(4, vocab, int(k)).tolist()  # noqa: E731
    span = lambda lo_hi: rng.randint(lo_hi[0], lo_hi[1] + 1)  # noqa: E731
    shared_system = draw(system_len)
    n_shared = int(round(shared_frac * sessions))
    hist = [
        list(shared_system) if s < n_shared else draw(system_len)
        for s in range(sessions)
    ]
    reqs: list[list[int]] = []
    keys: list[str] = []
    budgets: list[int] = []
    for _t in range(turns):
        for s in range(sessions):
            hist[s] = hist[s] + draw(span(user_len))
            prompt = hist[s][:max_len] if max_len else list(hist[s])
            reqs.append(prompt)
            keys.append(f"session-{s}")
            reply = draw(span(reply_len))
            budgets.append(len(reply))
            hist[s] = hist[s] + reply
    if with_budgets:
        return reqs, keys, budgets
    return reqs, keys


@dataclasses.dataclass(frozen=True)
class LoadgenConfig:
    """Sweep knobs.  Everything the arrival schedule depends on lives
    here, which is why same-config-same-seed runs replay bit-for-bit.

    ``process``: arrival process kind (``ARRIVAL_PROCESSES``).
    ``seed``: the schedule RNG seed.  ``burst_size``: arrivals per
    burst (bursty only).  ``ramp_start_frac``: the ramp's starting
    rate as a fraction of the point's offered rate (ramp only).
    ``qps_grid``: ascending offered-QPS points to sweep.
    ``ttft_slo_ms``: the first-token SLO attainment/goodput are judged
    against (from ARRIVAL, not submit).  ``max_wall_s``: per-point
    wall cap (0 = none) — a point far past saturation stops here and
    reports its unfinished tail instead of running unboundedly.
    ``track_tol``: knee sensitivity — a point whose achieved QPS falls
    below ``track_tol × offered`` has stopped tracking the offer."""

    process: str = "poisson"
    seed: int = 0
    burst_size: int = 4
    ramp_start_frac: float = 0.25
    qps_grid: tuple = (1.0, 2.0, 4.0, 8.0)
    ttft_slo_ms: float = 500.0
    max_wall_s: float = 0.0
    track_tol: float = 0.9

    def __post_init__(self):
        if self.process not in ARRIVAL_PROCESSES:
            raise ValueError(
                f"process={self.process!r}: must be one of {ARRIVAL_PROCESSES}"
            )
        if self.burst_size < 1:
            raise ValueError("burst_size must be >= 1")
        if not 0.0 < self.ramp_start_frac <= 1.0:
            raise ValueError("ramp_start_frac must be in (0, 1]")
        if not self.qps_grid:
            raise ValueError("qps_grid must name at least one offered rate")
        grid = tuple(float(q) for q in self.qps_grid)
        if any(q <= 0 for q in grid):
            raise ValueError("qps_grid rates must be positive")
        if list(grid) != sorted(grid):
            raise ValueError("qps_grid must ascend (the knee is a first-X)")


def arrival_schedule(
    process: str,
    *,
    qps: float,
    n: int,
    seed: int,
    burst_size: int = 4,
    ramp_start_frac: float = 0.25,
) -> np.ndarray:
    """Deterministic arrival offsets (seconds from run start, ascending
    float64, length ``n``) at average offered rate ``qps``.  Pure
    function of its arguments — the determinism acceptance pin is
    ``arrival_schedule(...) == arrival_schedule(...)`` bit-for-bit."""
    if n <= 0:
        raise ValueError("n must be positive")
    if qps <= 0:
        raise ValueError("qps must be positive")
    rng = np.random.RandomState(seed)
    if process == "poisson":
        gaps = rng.exponential(1.0 / qps, size=n)
        return np.cumsum(gaps)
    if process == "bursty":
        k = int(burst_size)
        n_bursts = (n + k - 1) // k
        # burst instants are themselves Poisson at qps/k, so the
        # AVERAGE rate stays the offered qps — the process only moves
        # variance (every burst lands k arrivals on one instant)
        starts = np.cumsum(rng.exponential(k / qps, size=n_bursts))
        return np.repeat(starts, k)[:n].astype(np.float64)
    if process == "ramp":
        u = rng.exponential(1.0, size=n)
        frac = np.arange(n, dtype=np.float64) / max(n - 1, 1)
        rates = qps * (ramp_start_frac + (1.0 - ramp_start_frac) * frac)
        return np.cumsum(u / rates)
    raise ValueError(
        f"process={process!r}: must be one of {ARRIVAL_PROCESSES}"
    )


class VirtualClock:
    """The test clock: ``now()`` in seconds, advanced explicitly.  A
    deterministic fake session advances it from ``step()`` (one step =
    its modeled service time) and stamps its timestamps from it, so a
    whole open-loop run — schedule, queueing, verdicts — replays
    bit-for-bit with no wall clock anywhere."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError("the clock only runs forward")
        self.t += dt


class EngineTarget:
    """Driver surface over a ``ServeSession`` (or any session-shaped
    fake: ``submit``/``step``/``has_work``/``submit_t``/
    ``first_token_wall``/``output``/``finalize``).  The engine never
    sheds — over-offer shows up as unfinished tail + growing delay."""

    def __init__(self, session: Any):
        self.session = session

    def submit(self, tokens, *, budget=None, mask=None, arrival=None,
               session=None) -> int:
        # a bare engine has no affinity tier: the session key is accepted
        # (the driver passes it uniformly) and dropped
        del session
        return self.session.submit(
            tokens, max_new=budget, attention_mask=mask, arrival=arrival
        )

    def advance(self) -> list[int]:
        return list(self.session.step())

    def has_work(self) -> bool:
        return bool(self.session.has_work())

    def close(self) -> None:
        self.session.finalize()

    def row(self, rid: int) -> dict:
        s = self.session
        return {
            "submit": s.submit_t[rid],
            "first_tok": s.first_token_wall(rid),
            "tokens": len(s.output(rid)),
            "shed": False,
        }


class RouterTarget:
    """Driver surface over a ``ReplicaRouter`` — the composition point
    with replica chaos (crash/stall/storm fire at router ticks while
    the open-loop schedule keeps offering load).  Synthetic storm
    requests are injected load, not offered traffic: they never appear
    in the driver's rows."""

    def __init__(self, router: Any):
        self.router = router
        self._reported: set[int] = set()

    def submit(self, tokens, *, budget=None, mask=None, arrival=None,
               session=None) -> int:
        return self.router.submit(
            tokens, max_new=budget, attention_mask=mask, arrival=arrival,
            session=session,
        )

    def advance(self) -> list[int]:
        r = self.router
        if not r._serving():
            # no steppable replica left: the remainder sheds loudly, the
            # same outage contract as run_until_drained
            for q in r.requests:
                if not (q.done or q.shed):
                    r._shed(q, "no_replicas")
        else:
            r.tick()
        fresh = [
            q.rid
            for q in r.requests
            if (q.done or q.shed)
            and not q.synthetic
            and q.rid not in self._reported
        ]
        self._reported.update(fresh)
        return fresh

    def has_work(self) -> bool:
        return self.router._outstanding()

    def close(self) -> None:
        self.router.finalize()

    def row(self, rid: int) -> dict:
        q = self.router.requests[rid]
        first = (
            q.submit_wall + q.ttft_s if q.ttft_s is not None else None
        )
        return {
            "submit": q.submit_wall,
            "first_tok": first,
            "tokens": len(q.out),
            "shed": bool(q.shed),
        }


def drive_open_loop(
    target: Any,
    requests: Sequence[Sequence[int]],
    schedule: Sequence[float],
    *,
    budgets: Sequence[int] | None = None,
    masks: Sequence[Sequence[int] | None] | None = None,
    sessions: Sequence[Any] | None = None,
    clock: Callable[[], float] | None = None,
    wait: Callable[[float], None] | None = None,
    max_wall_s: float = 0.0,
    idle_wait_s: float = 0.0005,
) -> tuple[list[dict], float]:
    """One open-loop run: submit request ``i`` the instant
    ``schedule[i]`` passes (never waiting on completions), otherwise
    step the target; returns (per-request rows in arrival order, run
    wall seconds).  ``clock``/``wait`` default to the real
    ``time.perf_counter``/``time.sleep``; tests inject a
    ``VirtualClock``'s ``now``/``advance``.  ``max_wall_s`` (0 = none)
    caps a run past saturation — whatever hasn't finished reports as
    the unfinished tail, which is data, not an error."""
    n = len(requests)
    if len(schedule) != n:
        raise ValueError(
            f"schedule has {len(schedule)} arrivals for {n} requests"
        )
    if budgets is not None and len(budgets) != n:
        raise ValueError(f"budgets has {len(budgets)} entries for {n} requests")
    if sessions is not None and len(sessions) != n:
        raise ValueError(
            f"sessions has {len(sessions)} keys for {n} requests"
        )
    clock = clock or time.perf_counter
    wait = wait or time.sleep
    t0 = clock()
    submit_at = [t0 + float(s) for s in schedule]
    idx_of: dict[int, int] = {}
    rids: list[int | None] = [None] * n
    done_at: list[float | None] = [None] * n
    i = 0
    while True:
        now = clock()
        while i < n and submit_at[i] <= now:
            rid = target.submit(
                requests[i],
                budget=budgets[i] if budgets is not None else None,
                mask=masks[i] if masks is not None else None,
                arrival=submit_at[i],
                session=sessions[i] if sessions is not None else None,
            )
            rids[i], idx_of[rid] = rid, i
            i += 1
        if i >= n and not target.has_work():
            break
        if max_wall_s and (now - t0) > max_wall_s:
            break
        if target.has_work():
            finished = target.advance()
            t_done = clock()
            for rid in finished:
                idx = idx_of.get(rid)
                if idx is not None:
                    done_at[idx] = t_done
        else:
            wait(max(submit_at[i] - clock(), 0.0) or idle_wait_s)
    wall_s = max(clock() - t0, 1e-9)
    target.close()
    rows: list[dict] = []
    for idx in range(n):
        rid = rids[idx]
        arrival = float(schedule[idx])
        if rid is None:  # wall cap hit before this arrival was even due
            rows.append({
                "index": idx, "arrival_s": arrival, "submitted": False,
                "queue_delay_s": None, "ttft_s": None, "done_s": None,
                "tokens": 0, "finished": False, "shed": False,
            })
            continue
        info = target.row(rid)
        first = info["first_tok"]
        done = done_at[idx]
        rows.append({
            "index": idx,
            "arrival_s": arrival,
            "submitted": True,
            "queue_delay_s": info["submit"] - submit_at[idx],
            # TTFT from the scheduled ARRIVAL: the driver-side wait is
            # user-visible latency under open-loop load
            "ttft_s": (first - submit_at[idx]) if first is not None else None,
            "done_s": (done - t0) if done is not None else None,
            "tokens": int(info["tokens"]),
            "finished": done is not None and not info["shed"],
            "shed": bool(info["shed"]),
        })
    return rows, wall_s


def _wait_s(row: dict, wall_s: float) -> float:
    """A request's observed queueing wait: TTFT from arrival when it
    got a first token, else how long it has ALREADY waited by run end —
    a lower bound that keeps growing, which is what makes the
    unbounded-growth signal detectable on a capped run."""
    if row["ttft_s"] is not None:
        return float(row["ttft_s"])
    return max(wall_s - row["arrival_s"], 0.0)


def queue_growing(rows: Sequence[dict], wall_s: float, *,
                  growth_x: float = 2.0, min_wait_s: float = 5e-3) -> bool:
    """Unbounded-queue verdict for one run: an unfinished tail at run
    end, or the last-quarter arrivals waiting ``growth_x``× the
    first-quarter ones (and at least ``min_wait_s`` in absolute terms —
    noise on an idle engine is not growth).  Under a stable queue the
    wait distribution is stationary; under over-offer it grows with
    arrival index, which this detects without modeling the queue."""
    if any(not r["finished"] and not r["shed"] for r in rows):
        return True
    n = len(rows)
    if n < 4:
        return False
    k = max(n // 4, 1)
    head = sum(_wait_s(r, wall_s) for r in rows[:k]) / k
    tail = sum(_wait_s(r, wall_s) for r in rows[-k:]) / k
    return tail > growth_x * max(head, 1e-9) and tail > min_wait_s


def summarize_point(
    rows: Sequence[dict],
    *,
    offered_qps: float,
    ttft_slo_ms: float,
    wall_s: float,
    growth_x: float = 2.0,
) -> dict:
    """One sweep point's measured record.  SLO attainment is judged
    over every OFFERED request — unfinished and shed requests are
    misses, never silently dropped from the denominator — and TTFT is
    from arrival.  TTFT percentiles are ``None`` when nothing finished
    (a missing measurement must never read as a pass).

    ``offered_qps`` is the nominal grid label; ``offered_qps_realized``
    is what this finite seeded sample actually offered (n over the
    arrival span).  At small n a Poisson draw can realize well under
    the nominal rate, so throughput tracking must be judged against the
    realized rate or sampling variance reads as saturation."""
    offered = len(rows)
    completed = sum(1 for r in rows if r["finished"])
    shed = sum(1 for r in rows if r["shed"])
    unfinished = offered - completed - shed
    ttfts = [r["ttft_s"] for r in rows if r["finished"] and r["ttft_s"] is not None]
    delays = [r["queue_delay_s"] for r in rows if r["queue_delay_s"] is not None]
    slo_s = float(ttft_slo_ms) / 1e3
    met = [
        r for r in rows
        if r["finished"] and r["ttft_s"] is not None
        and (slo_s <= 0 or r["ttft_s"] <= slo_s)
    ]
    span = max((r.get("arrival_s") or 0.0 for r in rows), default=0.0)
    point = {
        "offered_qps": round(float(offered_qps), 4),
        "offered_qps_realized": round(
            offered / span if span > 0 else float(offered_qps), 4
        ),
        "achieved_qps": round(completed / wall_s, 4),
        "offered": offered,
        "completed": completed,
        "shed": shed,
        "unfinished": unfinished,
        "wall_s": round(wall_s, 4),
        "ttft_slo_ms": round(float(ttft_slo_ms), 1),
        "slo_attainment": round(len(met) / max(offered, 1), 4),
        "goodput_qps": round(len(met) / wall_s, 4),
        "queue_growing": queue_growing(rows, wall_s, growth_x=growth_x),
    }
    if ttfts:
        p50, p95, p99 = percentiles(ttfts, (0.50, 0.95, 0.99))
        point["ttft_p50_ms"] = round(p50 * 1e3, 3)
        point["ttft_p95_ms"] = round(p95 * 1e3, 3)
        point["ttft_p99_ms"] = round(p99 * 1e3, 3)
    else:
        point["ttft_p50_ms"] = None
        point["ttft_p95_ms"] = None
        point["ttft_p99_ms"] = None
    if delays:
        d50, d99 = percentiles(delays, (0.50, 0.99))
        point["queue_delay_p50_ms"] = round(d50 * 1e3, 3)
        point["queue_delay_p99_ms"] = round(d99 * 1e3, 3)
    else:
        point["queue_delay_p50_ms"] = None
        point["queue_delay_p99_ms"] = None
    return point


def detect_knee(points: Sequence[dict], *, track_tol: float = 0.9) -> float | None:
    """The saturation knee: the FIRST offered rate (grid order) whose
    point stopped tracking the offer — achieved QPS below ``track_tol ×``
    the REALIZED offered rate (the nominal grid rate when no realized
    rate was recorded), any request shed, or the unbounded-queue
    verdict.  None when every measured point tracks (the grid never
    reached saturation).  Pure function of the curve, pinnable on
    hand-built points."""
    for p in points:
        offered = float(p["offered_qps"])
        if p.get("queue_growing"):
            return offered
        if int(p.get("shed") or 0) > 0:
            return offered
        achieved = p.get("achieved_qps")
        baseline = float(p.get("offered_qps_realized") or offered)
        if achieved is not None and float(achieved) < track_tol * baseline:
            return offered
    return None


def sweep_qps(
    target_factory: Callable[[], Any],
    requests: Sequence[Sequence[int]],
    cfg: LoadgenConfig,
    *,
    budgets: Sequence[int] | None = None,
    masks: Sequence[Sequence[int] | None] | None = None,
    sessions: Sequence[Any] | None = None,
    clock: Callable[[], float] | None = None,
    wait: Callable[[float], None] | None = None,
    emit: bool = True,
) -> dict:
    """The QPS sweep: one FRESH target per grid point (``target_factory``
    returns an ``EngineTarget``/``RouterTarget`` over a fresh session/
    router), the SAME request set and the SAME arrival seed throughout,
    so points differ only by offered rate.  Emits one ``loadgen_point``
    per grid point and a final ``loadgen_summary`` carrying the whole
    curve + knee; returns the summary dict.

    When ``budgets`` is given (the chatbot mix's per-turn decode
    lengths), every ``loadgen_point`` and the summary carry
    ``decode_budget_tokens``/``decode_budget_mean`` so a later
    spec-vs-plain comparison can confirm both sweeps decoded the same
    scripted token counts — apples-to-apples, stamped in the JSONL
    rather than re-derived."""
    points: list[dict] = []
    budget_stamp: dict = {}
    if budgets is not None and len(budgets) > 0:
        budget_stamp = {
            "decode_budget_tokens": int(sum(int(b) for b in budgets)),
            "decode_budget_mean": round(
                float(sum(int(b) for b in budgets)) / len(budgets), 2
            ),
        }
    for qps in cfg.qps_grid:
        schedule = arrival_schedule(
            cfg.process, qps=float(qps), n=len(requests), seed=cfg.seed,
            burst_size=cfg.burst_size, ramp_start_frac=cfg.ramp_start_frac,
        )
        rows, wall_s = drive_open_loop(
            target_factory(), requests, schedule,
            budgets=budgets, masks=masks, sessions=sessions,
            clock=clock, wait=wait, max_wall_s=cfg.max_wall_s,
        )
        point = summarize_point(
            rows, offered_qps=float(qps), ttft_slo_ms=cfg.ttft_slo_ms,
            wall_s=wall_s,
        )
        point.update(budget_stamp)
        points.append(point)
        if emit:
            log_json({
                "event": "loadgen_point",
                "process": cfg.process,
                "seed": cfg.seed,
                **point,
            })
    knee = detect_knee(points, track_tol=cfg.track_tol)
    summary = {
        "process": cfg.process,
        "seed": cfg.seed,
        "requests_per_point": len(requests),
        "qps_grid": [float(q) for q in cfg.qps_grid],
        "ttft_slo_ms": round(float(cfg.ttft_slo_ms), 1),
        "track_tol": cfg.track_tol,
        "knee_qps": knee,
        **budget_stamp,
        "points": points,
    }
    if emit:
        log_json({"event": "loadgen_summary", **summary})
    return summary
