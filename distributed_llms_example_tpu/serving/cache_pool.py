"""Paged KV-cache: a shared block pool + host-side free-list allocator.

The flat serving state charges every decode slot ``max_source_length``
worth of cache whether its prompt needs it or not — the exact capacity
ceiling the Gemma-on-TPU serving comparison (arXiv:2605.25645) names.
Here slots become BLOCK LISTS over a shared pool (vLLM-style paging,
restated for the fixed-shape SPMD engine):

- the resident serving state is one fixed-shape pool tensor per cache
  leaf — ``(num_blocks, heads, block_size, head_dim)`` — so admitting or
  evicting a request never changes a compiled shape (no recompiles);
- a request holds ``ceil(prompt_len / block_size)`` prompt blocks plus
  ``ceil(budget / block_size)`` decode blocks — bytes scale with the
  ACTUAL prompt, not the worst case;
- allocation/free is pure host bookkeeping (``CachePool``) between
  jitted steps, mirroring how the engine already admits/evicts slots;
  blocks are identityless, so "fragmentation" cannot strand capacity —
  any request whose block count fits the free list is admissible;
- the compiled decode step reads the pool through a per-slot block
  table: on the kernel path ``ops.flash_attention.flash_decode_paged``
  indexes pool blocks directly in its tile loop (block size == kv tile
  size); the XLA path gathers a slot view with ``mode="fill"`` zeros for
  unallocated tiles, which the attention mask makes contribute exactly
  nothing — that fill is what makes paged decode BIT-identical to flat.

Stale blocks are unreachable by the same argument PR 7 made for slot
reuse, restated per block: a freed block re-enters the pool with its old
contents, but every read is masked to ``k_pos <= offset`` (decode tail)
or to the attention mask (prompt region), so a new owner's output cannot
observe the previous owner's K/V.  The ``pool-garbage-invariant`` test
pins this by poisoning the whole pool at init.

Spec lint: ``parallel/sharding.py POOL_RULES`` is the pool's rule set,
validated by ``analysis/spec_lint.py lint_cache_sharding`` exactly like
``CACHE_RULES`` for the flat cache.
"""

from __future__ import annotations

import hashlib
import math
from collections import OrderedDict
from typing import Any, Iterable, Sequence

import jax
import jax.numpy as jnp


# --------------------------------------------------- block content identity
#
# A block's identity is the CHAIN of token ids that produced it: layer-l
# K/V at position p depends on every token <= p, so two blocks holding
# the same block_size tokens are only interchangeable when their whole
# prefixes match.  Chaining the predecessor's hash into each block's
# hash encodes exactly that — equal chain hash ⟺ equal token prefix.
# This module is the ONE owner of both the hash computation and the
# refcount bookkeeping (repo_lint rule: cache identity has one owner).


def block_hash(prev_hash: str | None, tokens: Sequence[int]) -> str:
    """Chain hash of one full block: sha256 over the predecessor's hash
    (empty for the first block) and this block's token ids.  Different
    predecessor → different hash, so a match on block k implies blocks
    0..k-1 matched too — the collision discipline the prefix walk
    relies on."""
    h = hashlib.sha256()
    h.update(b"" if prev_hash is None else prev_hash.encode("ascii"))
    h.update(("|".join(str(int(t)) for t in tokens)).encode("ascii"))
    return h.hexdigest()


def chain_hashes(tokens: Sequence[int], block_size: int) -> list[str]:
    """Chain hashes for every FULL block of ``tokens`` (the partial tail
    block has no stable identity and is never shared)."""
    out: list[str] = []
    prev: str | None = None
    for start in range(0, (len(tokens) // block_size) * block_size, block_size):
        prev = block_hash(prev, tokens[start : start + block_size])
        out.append(prev)
    return out


# ----------------------------------------------------- host-side allocator


class CachePool:
    """Free-list allocator over cache blocks, with refcounted sharing and
    a warm LRU of finished requests' prefix blocks (pure host).

    The engine calls ``alloc`` at admission and ``free`` at eviction —
    between jitted steps, like every other piece of slot bookkeeping.
    ``alloc`` grants a block with refcount 1; ``acquire`` bumps the
    count on a matched prefix chain; ``free`` is a refcount DECREMENT
    with reclaim at zero — reclaimed blocks whose chain hash is
    registered park in a warm LRU (up to ``warm_capacity`` blocks) so a
    follow-up turn can re-acquire them, everything else returns to the
    free list.  Warm blocks count as allocatable: ``alloc`` evicts the
    oldest warm entries under pressure, so retention can never fail an
    admission that would have fit without it.

    Invariants (property-tested and walkable via
    ``ref_invariant_violations``): a block is never handed out twice,
    ``blocks_free + blocks_in_use == num_blocks`` always, every
    refcount equals the number of live references, warm blocks are
    strictly refcount 0, double-free and foreign-free raise."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # pop() from the end → blocks hand out in ascending order, which
        # keeps tests readable; correctness never depends on the order
        self._free: list[int] = list(range(self.num_blocks - 1, -1, -1))
        self._used: set[int] = set()
        # prefix-cache state — inert until the engine registers chains:
        # _ref[b] is b's refcount (every _used block has an entry),
        # _hash_of[b]/_index[h] the two directions of the chain-hash
        # index (live OR warm blocks only — a block on the free list has
        # no identity), _lru the refcount-0 retained blocks in eviction
        # order (oldest first), warm_capacity the retention budget in
        # blocks (0 = retention off, the default: free() then behaves
        # exactly like the pre-prefix-cache pool)
        self._ref: dict[int, int] = {}
        self._hash_of: dict[int, str] = {}
        self._index: dict[str, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.warm_capacity = 0

    @property
    def blocks_free(self) -> int:
        # warm blocks are reclaimable on demand, so they are FREE from
        # the allocator's point of view — retention never costs capacity
        return len(self._free) + len(self._lru)

    @property
    def blocks_in_use(self) -> int:
        return len(self._used)

    @property
    def blocks_warm(self) -> int:
        return len(self._lru)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free) + len(self._lru)

    def alloc(self, n: int) -> list[int] | None:
        """``n`` fresh blocks at refcount 1, or None when the free list
        plus the evictable warm set is short (the caller defers
        admission — never a partial grant)."""
        if n < 0:
            raise ValueError(f"cannot alloc {n} blocks")
        if n > len(self._free) + len(self._lru):
            return None
        while len(self._free) < n:
            self._evict_warm()
        out = [self._free.pop() for _ in range(n)]
        self._used.update(out)
        for b in out:
            self._ref[b] = 1
        return out

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block; a block reclaims at refcount 0 —
        into the warm LRU when its chain hash is registered and the
        budget allows, else back to the free list."""
        for b in blocks:
            if b not in self._used:
                raise ValueError(
                    f"block {b} is not allocated (double-free or foreign id)"
                )
            self._ref[b] -= 1
            if self._ref[b] > 0:
                continue
            self._used.remove(b)
            del self._ref[b]
            if b in self._hash_of and self.warm_capacity > 0:
                self._lru[b] = None
                self._lru.move_to_end(b)
                while len(self._lru) > self.warm_capacity:
                    self._evict_warm()
            else:
                self._unregister(b)
                self._free.append(b)

    # ------------------------------------------------- prefix-chain index

    def acquire(self, blocks: Sequence[int]) -> None:
        """Take one more reference on each block of a matched chain —
        live blocks bump their refcount, warm blocks revive out of the
        LRU at refcount 1."""
        for b in blocks:
            if b in self._used:
                self._ref[b] += 1
            elif b in self._lru:
                del self._lru[b]
                self._used.add(b)
                self._ref[b] = 1
            else:
                raise ValueError(
                    f"block {b} is neither live nor warm (stale chain match)"
                )

    def register(self, blocks: Sequence[int], hashes: Sequence[str]) -> None:
        """Record chain hashes for a request's full prompt blocks so later
        admissions can match them.  First writer wins: a hash already
        indexed keeps its existing block (the duplicate block simply
        stays anonymous and reclaims to the free list)."""
        if len(blocks) != len(hashes):
            raise ValueError(
                f"got {len(blocks)} blocks for {len(hashes)} hashes"
            )
        for b, h in zip(blocks, hashes):
            if b not in self._used:
                raise ValueError(f"block {b} is not allocated (cannot register)")
            if self._hash_of.get(b) == h:
                continue  # re-registration of a shared chain is a no-op
            if b in self._hash_of or h in self._index:
                continue  # first writer wins; never re-key a live block
            self._hash_of[b] = h
            self._index[h] = b

    def lookup(self, h: str) -> int | None:
        return self._index.get(h)

    def match_chain(self, hashes: Sequence[str]) -> list[int]:
        """Blocks for the longest indexed prefix of ``hashes`` — the
        admission walk.  Chained hashing makes any gap impossible, so
        the walk stops at the first miss."""
        out: list[int] = []
        for h in hashes:
            b = self._index.get(h)
            if b is None:
                break
            out.append(b)
        return out

    def drop_warm(self) -> int:
        """Evict the ENTIRE warm set (replica teardown: a dead replica's
        pool is gone, so its retained chains must not be matchable).
        Returns the number of blocks released."""
        n = len(self._lru)
        while self._lru:
            self._evict_warm()
        return n

    def _evict_warm(self) -> None:
        b, _ = self._lru.popitem(last=False)  # strictly oldest first
        self._unregister(b)
        self._free.append(b)

    def _unregister(self, b: int) -> None:
        h = self._hash_of.pop(b, None)
        if h is not None:
            self._index.pop(h, None)

    # ------------------------------------------------- invariant walking

    def ref_invariant_violations(
        self, live_chains: Iterable[Sequence[int]]
    ) -> list[str]:
        """Every block's refcount must equal its live references — walked
        from the engine's block tables (``live_chains``: one sequence of
        block ids per live slot) plus the warm LRU.  Also checks the
        free/used/warm partition and index consistency.  Returns
        human-readable violations; empty means the account is exact."""
        out: list[str] = []
        want: dict[int, int] = {}
        for chain in live_chains:
            for b in chain:
                want[b] = want.get(b, 0) + 1
        for b, n in sorted(want.items()):
            if self._ref.get(b) != n:
                out.append(
                    f"block {b}: refcount {self._ref.get(b)} != {n} live references"
                )
        for b in sorted(self._used):
            if b not in want:
                out.append(f"block {b}: in use with no live reference")
        for b in self._lru:
            if b in want:
                out.append(f"block {b}: warm but referenced by a live slot")
            if b not in self._hash_of:
                out.append(f"block {b}: warm without a registered hash")
        free, used, warm = set(self._free), self._used, set(self._lru)
        if free & used or free & warm or used & warm:
            out.append("free/used/warm sets overlap")
        if len(free) + len(used) + len(warm) != self.num_blocks:
            out.append(
                f"partition covers {len(free) + len(used) + len(warm)} of "
                f"{self.num_blocks} blocks"
            )
        for h, b in self._index.items():
            if b not in used and b not in warm:
                out.append(f"hash {h[:12]}…: indexed block {b} is on the free list")
            if self._hash_of.get(b) != h:
                out.append(f"hash {h[:12]}…: index and hash_of disagree on {b}")
        return out


def blocks_needed(prompt_len: int, budget: int, block_size: int) -> int:
    """Blocks one request holds for its whole lifetime: prompt tiles by
    ACTUAL length + decode tiles by its token budget — allocated once at
    admission, so a slot never stalls mid-decode waiting for a block."""
    return max(
        1, math.ceil(max(prompt_len, 1) / block_size)
    ) + math.ceil(max(budget, 1) / block_size)


def build_block_row(
    n_tiles: int,
    blocks: Sequence[int],
    *,
    prompt_len: int,
    bucket_width: int,
    budget: int,
    block_size: int,
    sentinel: int,
):
    """One slot's block-table row: prompt tiles ``[0, ceil(len/bs))`` and
    decode tiles ``[bucket/bs, bucket/bs + ceil(budget/bs))`` take the
    allocated blocks in order; everything else (the padding gap between
    the true prompt and the bucket width, and the tail past the budget)
    stays at ``sentinel`` — reads of those tiles fill zeros, writes drop."""
    import numpy as np

    if bucket_width % block_size:
        raise ValueError(
            f"bucket width {bucket_width} must be a multiple of the block "
            f"size {block_size} (decode tiles must start on a tile boundary)"
        )
    row = np.full(n_tiles, sentinel, np.int32)
    prompt_tiles = max(1, math.ceil(max(prompt_len, 1) / block_size))
    decode_tile0 = bucket_width // block_size
    decode_tiles = math.ceil(max(budget, 1) / block_size)
    want = prompt_tiles + decode_tiles
    if len(blocks) != want:
        raise ValueError(f"got {len(blocks)} blocks for {want} tiles")
    row[:prompt_tiles] = blocks[:prompt_tiles]
    row[decode_tile0 : decode_tile0 + decode_tiles] = blocks[prompt_tiles:]
    return row


# ------------------------------------------------ in-program pool plumbing
#
# These run INSIDE the engine's jitted admit/step programs.  Leaf
# conventions mirror the flax cache collection: 4-D (slots, heads, len,
# head_dim) K/V buffers, 3-D (slots, heads, len) int8-KV scale leaves,
# scalars (cache_index) pass through untouched.


def pool_cache_tree(abstract_cache: Any, num_blocks: int, block_size: int):
    """Zeroed pool tree with the same structure as a slot-view cache tree:
    every K/V leaf becomes ``(num_blocks, heads, block_size[, head_dim])``,
    scalars stay scalars.  The ONE place slot-view shapes map to pool
    shapes."""

    def to_pool(x):
        nd = len(getattr(x, "shape", ()))
        if nd == 4:
            return jnp.zeros(
                (num_blocks, x.shape[1], block_size, x.shape[3]), x.dtype
            )
        if nd == 3:
            return jnp.zeros((num_blocks, x.shape[1], block_size), x.dtype)
        return jnp.zeros(getattr(x, "shape", ()), x.dtype)

    return jax.tree.map(to_pool, abstract_cache)


def gather_cache(pool_tree: Any, block_tables: jnp.ndarray):
    """Slot-view cache tree from the pool through the block tables —
    ``mode="fill"`` zeros for sentinel (unallocated) tiles, which the
    attention masks make contribute exactly nothing (the paged==flat
    bit-identity argument).  The view is a STEP-TRANSIENT on the XLA
    path — only the pool is resident between steps; the kernel path
    (``flash_decode_paged``) never materializes it at all."""
    n_tiles = block_tables.shape[1]

    def view(x):
        if x.ndim == 4:
            g = jnp.take(x, block_tables, axis=0, mode="fill", fill_value=0)
            g = g.transpose(0, 2, 1, 3, 4)  # (S, H, nt, bs, D)
            return g.reshape(g.shape[0], g.shape[1], n_tiles * x.shape[2], x.shape[3])
        if x.ndim == 3:
            g = jnp.take(x, block_tables, axis=0, mode="fill", fill_value=0)
            g = g.transpose(0, 2, 1, 3)
            return g.reshape(g.shape[0], g.shape[1], n_tiles * x.shape[2])
        return x

    return jax.tree.map(view, pool_tree)


def scatter_step(
    pool_tree: Any,
    new_cache: Any,
    block_tables: jnp.ndarray,
    offsets: jnp.ndarray,
    *,
    num_blocks: int,
    block_size: int,
):
    """Write each slot's just-decoded cache row (position ``offsets[s]``
    of the slot view) back into its pool block.  Parked slots (offset
    past the view width) and sentinel tiles resolve to an out-of-range
    block index, so their writes drop — the paged twin of the flat
    path's ``mode="drop"`` scatter."""
    n_tiles = block_tables.shape[1]
    width = n_tiles * block_size
    rows = jnp.arange(offsets.shape[0])
    tile = jnp.clip(offsets // block_size, 0, n_tiles - 1)
    blocks = jnp.take_along_axis(block_tables, tile[:, None], axis=1)[:, 0]
    blocks = jnp.where(offsets < width, blocks, num_blocks)
    inb = offsets % block_size
    safe = jnp.clip(offsets, 0, width - 1)

    def scat(pool, flat):
        if pool.ndim == 4:
            row = flat[rows, :, safe, :]  # (S, H, D)
            return pool.at[blocks, :, inb, :].set(row, mode="drop")
        if pool.ndim == 3:
            row = flat[rows, :, safe]
            return pool.at[blocks, :, inb].set(row, mode="drop")
        return pool

    return jax.tree.map(scat, pool_tree, new_cache)


def scatter_span(
    pool_tree: Any,
    new_cache: Any,
    block_tables: jnp.ndarray,
    offsets: jnp.ndarray,
    span: int,
    *,
    num_blocks: int,
    block_size: int,
):
    """``scatter_step`` over a contiguous span: write positions
    ``offsets[s] .. offsets[s] + span - 1`` of each slot view back into
    the slot's pool blocks — the speculative-decode verify write (the
    k+1 candidate rows land together; acceptance is mask discipline, so
    rejected rows are written-but-dark until the next span overwrites
    them).  Every position resolves through the SAME sentinel/parked
    drops as the single-step scatter: a speculative write can only land
    in a block the slot already owns, so rejection never touches the
    free-list and the prefix index never sees a speculative block."""
    out = pool_tree
    for j in range(span):
        out = scatter_step(
            out, new_cache, block_tables, offsets + j,
            num_blocks=num_blocks, block_size=block_size,
        )
    return out


def scatter_admit(
    pool_tree: Any, chunk_cache: Any, admit_blocks: jnp.ndarray, block_size: int
):
    """Copy a prefilled admission chunk's allocated tiles into the pool.

    ``chunk_cache`` leaves are (chunk, heads, width, head_dim) at the
    BUCKET width; ``admit_blocks`` is the flat (chunk × tiles,) block
    assignment with sentinel entries for tiles that must not copy
    (padding rows, the prompt-gap region).  Decode tiles DO copy — the
    chunk cache is zeros there, which scrubs whatever a freed block held
    and keeps the paged==flat bit-identity argument airtight."""

    def scat(pool, chunk):
        nd = chunk.ndim
        if nd == 4:
            c, h, lc, d = chunk.shape
            nt = lc // block_size
            tiles = (
                chunk.reshape(c, h, nt, block_size, d)
                .transpose(0, 2, 1, 3, 4)
                .reshape(c * nt, h, block_size, d)
            )
            return pool.at[admit_blocks].set(tiles, mode="drop")
        if nd == 3:
            c, h, lc = chunk.shape
            nt = lc // block_size
            tiles = (
                chunk.reshape(c, h, nt, block_size)
                .transpose(0, 2, 1, 3)
                .reshape(c * nt, h, block_size)
            )
            return pool.at[admit_blocks].set(tiles, mode="drop")
        return pool

    return jax.tree.map(scat, pool_tree, chunk_cache)


# --------------------------------------------------------- byte accounting


def tree_bytes(tree: Any) -> int:
    """Static byte account of a pytree (arrays or ShapeDtypeStructs) —
    the resident-footprint number the capacity gauges and the bench's
    ``cache_bytes_per_token`` report, measured nowhere near a device."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()))
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        total += int(math.prod(shape)) * int(itemsize)
    return total


def block_bytes(pool_tree: Any, num_blocks: int) -> int:
    """Bytes ONE pool block accounts for across every cache leaf."""
    total = 0
    for leaf in jax.tree.leaves(pool_tree):
        if len(getattr(leaf, "shape", ())) >= 3:
            total += int(
                math.prod(leaf.shape) * leaf.dtype.itemsize
            ) // max(num_blocks, 1)
    return total
