"""The SPMD train step — the heart of the framework.

One jitted function replaces all three of the reference's distribution
mechanisms (torchrun-DDP, Accelerate, hand-rolled NCCL loops):

- the global batch arrives sharded over the ``("data","fsdp")`` mesh axes;
- parameters and optimizer state are sharded by the path-regex rules
  (FSDP over ``fsdp``, megatron-style splits over ``tensor``);
- ``jax.value_and_grad`` of a *global-mean* loss makes the XLA SPMD
  partitioner insert the gradient all-reduce — the five hand-written lines
  of ``average_gradients`` (reference train-task.py:65-69, one NCCL call
  per tensor, no bucketing, no overlap) become zero lines here, and XLA
  overlaps the collectives with the backward pass;
- gradient accumulation is a ``lax.scan`` over microbatches (the
  TPU-native form of ``gradient_accumulation_steps=16``,
  reference train-torchrun.py:126), accumulating token-weighted loss and
  gradient sums so the result is exactly the full-batch gradient.

Gradient accumulation invariants (the in-step microbatching contract):

- the fp32 accumulators are sharded EXACTLY like the parameters
  (``accumulator_shardings`` is the one mirror; an explicit
  ``with_sharding_constraint`` pins the scan carry so FSDP keeps its
  reduce-scatter gradient shape and the accumulators never replicate —
  per the weight-update-sharding recipe of arXiv:2004.13336);
- microbatches are cut SHARD-LOCALLY when the microbatch divides the
  batch shards: each device scans over slices of rows it already holds,
  so the (B,) → (N, B/N) regrouping costs zero collectives.  Loss and
  gradient sums are additive over rows, so any partition of the batch
  into microbatches yields the identical optimizer step;
- clip + AdamW + the health numerics run ONCE per optimizer step, after
  the scan (``optimizer_apply_block`` — a named function so the IR lint
  can prove from compiled-HLO metadata that none of it slid into the
  scan body), amortizing the non-layer overhead over N microbatches;
- a global batch is ONE optimizer step regardless of ``accum_steps``:
  the data iterator, the step counter, checkpoints, and the health
  watchdog all count optimizer steps, so O(1) resume lands on an
  optimizer-step boundary by construction.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_llms_example_tpu.data.batching import LABEL_PAD
from distributed_llms_example_tpu.models.t5 import shift_right
from distributed_llms_example_tpu.parallel.activation import activation_mesh
from distributed_llms_example_tpu.parallel.sharding import (
    ShardingRules,
    batch_sharding,
    default_rules,
    resolve_shardings,
)


@flax.struct.dataclass
class TrainState:
    """step / params / opt_state, plus ``ef`` — the error-feedback tree of
    ``--grad-compression int8`` (``ops/quant_collectives.py``): per-leaf
    ``(W, *shape)`` fp32 quantization residuals, worker dim over the
    replica axes, inner dims sharded exactly like the params.  ``None``
    whenever compression is off (the default), which keeps the off path's
    compiled program bit-identical to the pre-compression step.  Carried
    in the state so checkpoints resume it; a checkpoint written without
    it (older run, or compression off) resumes with a zero-filled tree —
    step 0 semantics, no error to feed back yet."""

    step: jnp.ndarray
    params: Any
    opt_state: Any
    ef: Any = None


# ---------------------------------------------------------------------------
# In-graph training-health telemetry (the obs/health.py numerics source).
#
# Everything here is computed INSIDE the pjit'd step — a handful of
# elementwise reductions riding the same program as the loss, so the
# values are device scalars like ``loss``/``grad_norm`` and cost zero
# extra device syncs: the watchdog converts them to host floats only at
# the logging cadence (the same fetch the MetricLogger already pays).
# ---------------------------------------------------------------------------

# Coarse parameter buckets for the per-bucket update ratio.  A uniform
# whole-tree ratio hides the classic failure signatures (an embedding
# whose updates dwarf its weights while the MLPs are healthy, a head
# diverging under a bad label stream), and a per-leaf report would be
# thousands of scalars; four buckets is the resolution operators act on.
HEALTH_BUCKETS = ("embed", "attn", "mlp", "head")

# The per-step scalars a health-enabled step adds to its metrics dict.
HEALTH_METRIC_KEYS: tuple[str, ...] = (
    "param_norm",
    "nonfinite_count",
) + tuple(f"update_ratio_{b}" for b in HEALTH_BUCKETS)


def bucket_of_path(path: tuple) -> str:
    """Coarse bucket for one parameter path (a jax key-path tuple).

    Name matching covers every family in models/: llama (embed_tokens /
    self_attn / mlp / lm_head), t5 (shared / self_attn / cross_attn /
    mlp / lm_head), bart (shared / *_embed_positions / self_attn / mlp),
    and the pipelined stacked trees (same leaf names under
    ``stacked_blocks``).  The matching table itself lives in
    analysis/ir_lint.py (``MODULE_BUCKET_PATTERNS``) and is shared with
    the device-time attribution of HLO ``op_name`` scopes
    (obs/devprof.py) — one definition of what "attn" means.  Unmatched
    leaves (norms, biases) fall to ``mlp`` — a param bucket must be
    total, and misfiling a layernorm scale costs nothing the per-bucket
    ratio is watching for.
    """
    from distributed_llms_example_tpu.analysis.ir_lint import module_bucket_of

    p = "/".join(
        str(getattr(k, "key", getattr(k, "name", getattr(k, "idx", k)))) for k in path
    )
    return module_bucket_of(p) or "mlp"


def _bucket_sumsq(tree: Any) -> dict[str, jnp.ndarray]:
    sums = {b: jnp.zeros((), jnp.float32) for b in HEALTH_BUCKETS}
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        b = bucket_of_path(path)
        sums[b] = sums[b] + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return sums


def health_metrics(params: Any, grads: Any, updates: Any) -> dict[str, jnp.ndarray]:
    """The in-graph numerics bundle: global param norm, non-finite grad
    element count, and per-bucket update ratios ||Δw|| / ||w|| (the
    step-size-relative-to-weights signal; healthy AdamW fine-tuning sits
    around 1e-3, a bucket at 1e-1 is about to diverge)."""
    p_sq = _bucket_sumsq(params)
    u_sq = _bucket_sumsq(updates)
    # integer accumulation per leaf: a float32 ``size - finite_count``
    # rounds 1-4 NaNs in a 1e8-element leaf to exactly 0 (spacing 8 at
    # that magnitude) — the one signal the tripwire must never lose
    nonfinite = jnp.zeros((), jnp.float32)
    for g in jax.tree.leaves(grads):
        nonfinite = nonfinite + jnp.sum(~jnp.isfinite(g)).astype(jnp.float32)
    out: dict[str, jnp.ndarray] = {
        "param_norm": jnp.sqrt(sum(p_sq.values())),
        "nonfinite_count": nonfinite,
    }
    for b in HEALTH_BUCKETS:
        out[f"update_ratio_{b}"] = jnp.sqrt(u_sq[b]) / jnp.maximum(
            jnp.sqrt(p_sq[b]), 1e-12
        )
    return out


def create_train_state(
    params: Any,
    tx: optax.GradientTransformation,
    *,
    grad_compression: str = "off",
    workers: int = 1,
) -> TrainState:
    """``grad_compression="int8"`` additionally allocates the zero
    error-feedback tree (``workers`` = the replica-axis product — see
    ``ops/quant_collectives.py worker_count``)."""
    ef = None
    if grad_compression == "int8":
        from distributed_llms_example_tpu.ops.quant_collectives import (
            zero_error_feedback,
        )

        ef = zero_error_feedback(params, workers)
    return TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=tx.init(params), ef=ef,
    )


def accumulator_shardings(param_shardings: Any) -> Any:
    """Shardings for the in-step fp32 gradient accumulators: EXACTLY the
    param shardings, leaf for leaf.

    This identity is THE accumulator layout contract — the scan carry is
    constrained with it, ``analysis/spec_lint.py`` lints against it, and
    the compiled-carry test pins it — so the three cannot drift.  Anything
    else either replicates a param-sized fp32 tree per device (the memory
    cliff accumulation exists to avoid) or forces GSPMD to reshard every
    microbatch's gradients against the carry."""
    return jax.tree.map(lambda s: s, param_shardings)


def health_metrics_from_stats(stats: Any) -> dict[str, jnp.ndarray]:
    """The health bundle assembled from the fused optimizer kernel's
    per-leaf partial sums (``ops/fused_optim.py`` — param/update
    sum-of-squares and non-finite grad counts produced in the SAME
    kernel pass as the update) instead of a separate reduction pass.
    Same keys and semantics as :func:`health_metrics`; per-bucket sums
    may differ from it in fp reduction order only."""
    from distributed_llms_example_tpu.ops.fused_optim import (
        STAT_NONFINITE,
        STAT_P_SUMSQ,
        STAT_U_SUMSQ,
    )

    p_sq = {b: jnp.zeros((), jnp.float32) for b in HEALTH_BUCKETS}
    u_sq = {b: jnp.zeros((), jnp.float32) for b in HEALTH_BUCKETS}
    nonfinite = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_leaves_with_path(stats):
        b = bucket_of_path(path)
        p_sq[b] = p_sq[b] + leaf[STAT_P_SUMSQ]
        u_sq[b] = u_sq[b] + leaf[STAT_U_SUMSQ]
        nonfinite = nonfinite + leaf[STAT_NONFINITE]
    out: dict[str, jnp.ndarray] = {
        "param_norm": jnp.sqrt(sum(p_sq.values())),
        "nonfinite_count": nonfinite,
    }
    for b in HEALTH_BUCKETS:
        out[f"update_ratio_{b}"] = jnp.sqrt(u_sq[b]) / jnp.maximum(
            jnp.sqrt(p_sq[b]), 1e-12
        )
    return out


def optimizer_apply_block(
    state: TrainState,
    tx: optax.GradientTransformation,
    schedule: optax.Schedule,
    lsum: jnp.ndarray,
    tokens: jnp.ndarray,
    grads: Any,
    *,
    health: bool,
    fused: Any = None,
    ef: Any = None,
) -> tuple[TrainState, dict]:
    """The once-per-optimizer-step tail: normalize the token-weighted
    sums, clip + AdamW, and the health numerics.

    ``fused`` (a ``train.optim.FusedOptimPlan``, or None) selects the
    impl: None runs the optax chain through ``train.optim
    .optimizer_update`` (the ``xla`` impl — the one owner of the raw
    apply, repo-lint rule 8); a plan runs the Pallas fused
    clip+AdamW(+health) apply in place (``--optim-impl fused``), with
    the health numerics sourced from the kernel's partial sums.  The
    impls run the identical op sequence — equal up to XLA float
    contraction (test-pinned), same opt-state pytree.

    A NAMED function on purpose: jax stamps each HLO instruction with the
    first non-library source frame, so everything traced here (including
    optax's clip/adamw internals, attributed to the call lines below)
    carries this function's source span — ``once_per_step_source_spans``
    hands that span to ``analysis/ir_lint.py``, which proves on the
    compiled program that none of it was scheduled inside the
    grad-accumulation scan body, i.e. the optimizer genuinely runs once
    per step regardless of ``accum_steps``."""
    from distributed_llms_example_tpu.train.optim import (
        fused_optimizer_apply,
        optimizer_update,
    )

    tokens = jnp.maximum(tokens, 1.0)
    loss = lsum / tokens
    grads = jax.tree.map(lambda g: (g / tokens).astype(jnp.float32), grads)
    if fused is not None:
        new_params, new_opt, grad_norm, stats = fused_optimizer_apply(
            fused, schedule, state.params, state.opt_state, grads
        )
        health_vals = health_metrics_from_stats(stats) if health else None
    else:
        new_params, new_opt, updates = optimizer_update(
            tx, grads, state.opt_state, state.params
        )
        grad_norm = optax.global_norm(grads)
        health_vals = (
            health_metrics(state.params, grads, updates) if health else None
        )
    new_state = TrainState(
        step=state.step + 1, params=new_params, opt_state=new_opt, ef=ef,
    )
    metrics = {
        "loss": loss,
        "learning_rate": schedule(state.step),
        "grad_norm": grad_norm,
        "target_tokens": tokens,
    }
    if health_vals is not None:
        metrics.update(health_vals)
    return new_state, metrics


def once_per_step_source_spans() -> list[tuple[str, int, int]]:
    """``(source_file, first_line, last_line)`` spans of the code that
    must execute exactly once per optimizer step — ``optimizer_apply_block``
    plus the health-numerics helpers it calls (their bodies are user code,
    so jax attributes their instructions to these lines, not to the apply
    block's call site), plus the fused-apply implementation layer
    (``train/optim.py`` orchestration and the ``ops/fused_optim.py``
    kernel dispatch — under ``--optim-impl fused`` the apply's
    instructions carry THOSE frames).  Computed from the live source so
    the spans track edits; consumed by
    ``ir_lint.once_per_step_placement``."""
    import inspect

    from distributed_llms_example_tpu.ops import fused_optim, quant_collectives
    from distributed_llms_example_tpu.train import optim as optim_mod

    spans = []
    fns = (
        optimizer_apply_block,
        health_metrics,
        _bucket_sumsq,
        health_metrics_from_stats,
        optim_mod.optimizer_update,
        optim_mod.fused_optimizer_apply,
        fused_optim.adamw_tree_apply,
        fused_optim.fused_adamw_leaf,
        fused_optim.adamw_leaf_reference,
        fused_optim._adamw_kernel,
        fused_optim._sharded_leaf,
        # the quantized gradient reduction (--grad-compression int8) runs
        # once per optimizer step, at the boundary AFTER the microbatch
        # scan — covering its frames lets the placement census prove it
        # never slid into the accumulation loop (the grad-compression-accum
        # composition contract)
        quant_collectives.quantized_tree_reduce,
        quant_collectives._reduce_one_leaf,
        quant_collectives.quantize_blocks,
        quant_collectives.dequantize_blocks,
        quant_collectives.stochastic_round,
    )
    for fn in fns:
        lines, first = inspect.getsourcelines(fn)
        spans.append((inspect.getsourcefile(fn), first, first + len(lines) - 1))
    return spans


def cross_entropy_sums(
    logits: jnp.ndarray, labels: jnp.ndarray, label_smoothing: float = 0.0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(sum of token losses, number of unmasked tokens); fp32 accumulation."""
    mask = (labels != LABEL_PAD).astype(jnp.float32)
    targets = jnp.where(labels == LABEL_PAD, 0, labels)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    loss = logz - true_logit
    if label_smoothing > 0.0:
        smooth = -jnp.mean(jax.nn.log_softmax(logits, axis=-1), axis=-1)
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth
    return jnp.sum(loss * mask), jnp.sum(mask)


def make_loss_fn(
    model: Any, config: Any, label_smoothing: float = 0.0, is_seq2seq: bool = True
) -> Callable:
    """Loss over a batch dict (input_ids, attention_mask, labels).

    Seq2seq: teacher-forced decoder on shift-right labels.  Causal LM:
    ``labels`` is input-length-aligned with -100 over prompt/pad positions;
    position t's logits predict ``labels[t+1]`` (next-token convention).
    """

    # MoE models sow a load-balance loss into the "losses" collection; it
    # is token-weighted into the CE sum so the normalized loss comes out
    # as mean-CE + weight·aux (exact under scan-based grad accumulation).
    moe_weight = float(getattr(config, "moe_aux_weight", 0.0) or 0.0)

    # fused (vocab-chunked) CE: consume the pre-head hidden and apply the
    # LM head inside blockwise_cross_entropy_sums' scan, so (tokens, vocab)
    # fp32 logits never materialize.  Causal flax modules only (the
    # pipelined adapters own their loss paths).
    fused_ce = (
        not is_seq2seq
        and bool(getattr(config, "fused_ce", False))
        and hasattr(model, "hidden_states")
    )

    def apply_model(params: Any, *args, **kw):
        if moe_weight > 0.0:
            logits, mutated = model.apply({"params": params}, *args, mutable=["losses"], **kw)
            leaves = jax.tree.leaves(mutated.get("losses", {}))
            # mean over layers (each MoE layer sows one scalar): keeps the
            # configured coefficient comparable to HF Mixtral's single
            # all-layer loss instead of scaling with depth
            aux = sum(leaves, jnp.zeros((), jnp.float32)) / max(len(leaves), 1)
            return logits, aux
        return model.apply({"params": params}, *args, **kw), jnp.zeros((), jnp.float32)

    def loss_sums(params: Any, batch: dict, dropout_rng: jax.Array | None = None) -> tuple:
        labels = batch["labels"]
        rngs = {"dropout": dropout_rng} if dropout_rng is not None else {}
        if is_seq2seq:
            decoder_input_ids = shift_right(labels, config.decoder_start_token_id, config.pad_token_id)
            logits, aux = apply_model(
                params,
                batch["input_ids"],
                batch["attention_mask"],
                decoder_input_ids,
                deterministic=dropout_rng is None,
                rngs=rngs,
            )
            lsum, tokens = cross_entropy_sums(logits, labels, label_smoothing)
        elif fused_ce:
            h, aux = apply_model(
                params,
                batch["input_ids"],
                batch["attention_mask"],
                deterministic=dropout_rng is None,
                rngs=rngs,
                method="hidden_states",
            )
            from distributed_llms_example_tpu.ops.blockwise_ce import (
                blockwise_cross_entropy_sums,
            )

            h2 = h[:, :-1].reshape(-1, h.shape[-1])
            # cast the master-fp32 kernel to the compute dtype first — the
            # unfused lm_head does the same (nn.Dense dtype), and a raw
            # fp32×fp32 chunk matmul would forfeit MXU bf16 throughput
            w = params["lm_head"]["kernel"].astype(h.dtype)
            lsum, tokens = blockwise_cross_entropy_sums(
                h2, w, labels[:, 1:].reshape(-1), label_smoothing
            )
        else:
            logits, aux = apply_model(
                params,
                batch["input_ids"],
                batch["attention_mask"],
                deterministic=dropout_rng is None,
                rngs=rngs,
            )
            lsum, tokens = cross_entropy_sums(logits[:, :-1], labels[:, 1:], label_smoothing)
        return lsum + moe_weight * aux * tokens, tokens

    return loss_sums


def state_shardings(state: Any, mesh: Mesh, rules: ShardingRules | None = None) -> Any:
    """Shardings for a TrainState (or any pytree): param-rule regexes applied
    to every leaf path — optimizer moments mirror the param tree (their
    paths end with the param path, which the regex rules match), scalars
    fall through to replicated.

    The error-feedback tree (``--grad-compression int8``) is the one
    subtree the path rules CANNOT resolve: its leaves carry a leading
    worker dim, so a param rule's spec would land on the wrong ranks.  It
    gets the tiled layout instead — worker dim over the replica axes,
    inner dims exactly the param shardings
    (``ops/quant_collectives.py error_feedback_shardings``)."""
    ef = getattr(state, "ef", None)
    if ef is None or not hasattr(state, "replace"):
        return resolve_shardings(state, mesh, rules)
    # resolve WITHOUT the ef subtree (a param rule matching "ef/<path>"
    # at the tiled rank would log spurious ragged-dim fallbacks), then
    # attach the tiled layout
    from distributed_llms_example_tpu.ops.quant_collectives import (
        error_feedback_shardings,
    )

    sh = resolve_shardings(state.replace(ef=None), mesh, rules)
    return sh.replace(ef=error_feedback_shardings(sh.params, mesh))


def make_train_step(
    model: Any,
    config: Any,
    tx: optax.GradientTransformation,
    schedule: optax.Schedule,
    mesh: Mesh,
    *,
    rules: ShardingRules | None = None,
    grad_accum_steps: int = 1,
    label_smoothing: float = 0.0,
    with_dropout: bool = False,
    donate: bool = True,
    is_seq2seq: bool = True,
    sequence_sharded: bool | None = None,
    health: bool = False,
    optim_spec: Any = None,
    optim_impl: str | None = None,
    grad_compression: str = "off",
) -> Callable[[TrainState, dict], tuple[TrainState, dict]]:
    """Build the jitted train step: (state, batch[, rng]) → (state, metrics).

    ``grad_compression`` (``--grad-compression``): ``"off"`` (default —
    the code path is untouched, the compiled program bit-identical to the
    pre-compression step) or ``"int8"`` — the gradient tree's
    cross-replica reduction runs through ``ops/quant_collectives.py``:
    per-worker partial grads (``value_and_grad`` vmapped over shard-local
    batch groups along the ``data`` axis, the fsdp/tensor legs inside
    each group staying GSPMD's in fp32), block-int8 quantization with
    stochastic rounding off the step RNG, int-safe integer partial sums
    on an s8 wire, and the per-worker error-feedback tree carried in
    ``TrainState.ef`` (callers allocate it via
    ``create_train_state(..., grad_compression="int8", workers=W)``).
    Composes with in-step grad accumulation — the scan accumulates fp32
    TILED partial sums and the quantized reduction runs once at the
    optimizer-step boundary; stage>1 pipelines and sequence parallelism
    are composition-matrix errors.

    ``optim_spec`` (a ``train.optim.OptimizerSpec`` describing ``tx``)
    plus ``optim_impl`` (``--optim-impl``; None follows the process
    default, ``auto`` = fused on TPU) select the optimizer apply: the
    fused Pallas clip+AdamW kernel (in place on the param/accumulator
    shardings, health sourced from its partial sums) or the optax chain.
    Without a spec the step always runs the optax (``xla``) impl.
    Pipelined adapters always run xla (composition row
    ``fused-optim-pipelined`` guards the explicit flag).

    ``health=True`` additionally computes the in-graph numerics bundle
    (``HEALTH_METRIC_KEYS``: param norm, non-finite grad count, per-bucket
    update ratios) inside the compiled step — extra metrics entries, no
    extra device syncs; the obs health watchdog reads them at the logging
    cadence.

    The global batch (leading dim = global batch size) must be divisible by
    ``grad_accum_steps``; each microbatch stays sharded over (data, fsdp).
    ``sequence_sharded``: also split batch lengths over the ``sequence``
    axis (context parallelism).  None = on whenever the mesh has a
    sequence axis > 1; callers whose batch lengths may not divide that
    axis (Trainer checks its bucket widths) must pass False explicitly —
    a sharding over a non-divisible length is a dispatch-time error, not
    a graceful fallback.
    """
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if grad_accum_steps > 1 and hasattr(model, "num_microbatches"):
        # stage>1 pipeline adapters own their microbatching; the table row
        # owns the message (analysis/composition.py — the Trainer checks
        # the same row at startup, this deep guard catches direct callers)
        from distributed_llms_example_tpu.analysis.composition import reason_for

        raise ValueError(reason_for("grad-accum-pipelined"))
    if grad_compression not in ("off", "int8"):
        raise ValueError(
            f"grad_compression must be 'off' or 'int8', got {grad_compression!r}"
        )
    compress = grad_compression == "int8"
    if compress and hasattr(model, "num_microbatches"):
        from distributed_llms_example_tpu.analysis.composition import reason_for

        raise ValueError(reason_for("grad-compression-pipelined"))
    loss_sums = make_loss_fn(model, config, label_smoothing, is_seq2seq=is_seq2seq)
    seq_sharded = (
        sequence_sharded
        if sequence_sharded is not None
        else mesh.shape.get("sequence", 1) > 1
    )
    if compress and seq_sharded:
        from distributed_llms_example_tpu.analysis.composition import reason_for

        raise ValueError(reason_for("grad-compression-sequence"))
    micro_sharding = NamedSharding(
        mesh, P(None, ("data", "fsdp", "expert"), "sequence" if seq_sharded else None)
    )

    if getattr(model, "pipeline_schedule", "gpipe") in ("1f1b", "interleaved"):
        # these pipelines own their backward pass (forward/backward
        # microbatches interleave inside one fused schedule — autodiff
        # cannot reorder its backward, so the adapter computes gradients
        # itself); same (loss_sum, tokens, grads) contract as the
        # jax.value_and_grad path below
        value_and_grad_sums = model.make_value_and_grad(
            label_smoothing, is_seq2seq=is_seq2seq
        )
    else:
        def value_and_grad_sums(params: Any, batch: dict, rng: jax.Array | None) -> tuple:
            def wrapped(p):
                lsum, tokens = loss_sums(p, batch, rng)
                return lsum, tokens

            (lsum, tokens), grads = jax.value_and_grad(wrapped, has_aux=True)(params)
            return lsum, tokens, grads

    workers = 1
    if compress:
        from distributed_llms_example_tpu.ops.quant_collectives import (
            GRAD_WORKER_AXES,
            worker_count,
        )

        base_value_and_grad_sums = value_and_grad_sums
        workers = worker_count(dict(mesh.shape))
        if workers <= 1:
            raise ValueError(
                f"grad_compression='int8' needs a replica axis > 1 (mesh "
                f"axes {GRAD_WORKER_AXES} on {dict(mesh.shape)} give 1 "
                "worker group): with no cross-replica leg there is "
                "nothing to compress — every step would pay quantization "
                "noise and a params-sized fp32 residual for zero wire "
                "savings"
            )
        # each worker group's batch rows keep their (fsdp, expert) spread;
        # the worker dim rides the replica axis.  The (B,) -> (W, B/W)
        # reshape is a zero-collective relabeling: the combined batch
        # sharding orders data-major, so every device's rows stay local.
        tiled_batch_sharding = NamedSharding(
            mesh, P("data", ("fsdp", "expert"), None)
        )

        def tiled_value_and_grad_sums(
            params: Any, batch: dict, rng: jax.Array | None
        ) -> tuple:
            """Per-worker partial gradients: (loss sum, token sum, grads
            tiled ``(W, *shape)``).  The model runs inside ``vmap`` with
            the ambient mesh CLEARED — its internal activation
            constraints name the combined batch axes at the un-tiled
            rank, which would fight the tiled layout; sharding is steered
            by the explicit input/output pins instead (the same
            discipline the pipeline adapters use for nested regions)."""

            def regroup(x):
                if x.shape[0] % workers:
                    raise ValueError(
                        f"microbatch {x.shape[0]} is not divisible by the "
                        f"{workers} grad-compression worker group(s) "
                        f"(mesh axes {GRAD_WORKER_AXES})"
                    )
                return x.reshape(workers, x.shape[0] // workers, *x.shape[1:])

            grouped = jax.tree.map(regroup, batch)
            grouped = jax.lax.with_sharding_constraint(
                grouped, jax.tree.map(lambda _: tiled_batch_sharding, batch)
            )
            if rng is not None:
                keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                    jnp.arange(workers)
                )

                def one(mb, k):
                    with activation_mesh(None):
                        return base_value_and_grad_sums(params, mb, k)

                ls, toks, gt = jax.vmap(one)(grouped, keys)
            else:

                def one(mb):
                    with activation_mesh(None):
                        return base_value_and_grad_sums(params, mb, None)

                ls, toks, gt = jax.vmap(one)(grouped)
            return jnp.sum(ls), jnp.sum(toks), gt

        value_and_grad_sums = tiled_value_and_grad_sums

    def make_step_fn(accum_sh: Any, fused_plan: Any = None, comp_specs: Any = None) -> Callable:
        """The step body, closed over the accumulator shardings (the
        mirror of the param shardings — ``accumulator_shardings``) so the
        scan carry is PINNED to the param layout: under FSDP each
        device's accumulator holds exactly its gradient shard, gradients
        reduce-scatter straight into it, and the fp32 tree never
        replicates.  ``accum_sh=None`` (abstract callers without resolved
        shardings) leaves the layout to GSPMD.  ``fused_plan`` routes the
        optimizer tail to the fused Pallas apply (None = optax chain)."""

        def step_fn(state: TrainState, batch: dict, rng: jax.Array | None = None) -> tuple[TrainState, dict]:
            if compress and state.ef is None:
                raise ValueError(
                    "grad_compression='int8' needs the error-feedback tree: "
                    "build the state with create_train_state(..., "
                    "grad_compression='int8', workers=N)"
                )
            if grad_accum_steps > 1:
                b = jax.tree.leaves(batch)[0].shape[0]
                if b % grad_accum_steps:
                    raise ValueError(
                        f"global batch {b} is not divisible by "
                        f"grad_accum_steps={grad_accum_steps}"
                    )
                # Shard-local microbatch grouping: row r joins microbatch
                # r mod N (reshape to (B/N, N, ...) then swap), NOT the
                # contiguous slab r // (B/N).  With the batch sharded
                # contiguously over devices on dim 0, each device's rows
                # land wholly inside its own shard of every microbatch —
                # the slab grouping would instead scatter each microbatch
                # across device boundaries and GSPMD would pay an
                # all-to-all per step.  Loss and gradient sums are
                # additive over rows, so any grouping yields the same
                # optimizer step.
                micro = jax.tree.map(
                    lambda x: jnp.swapaxes(
                        x.reshape(x.shape[0] // grad_accum_steps, grad_accum_steps, *x.shape[1:]),
                        0,
                        1,
                    ),
                    batch,
                )
                micro = jax.lax.with_sharding_constraint(
                    micro, jax.tree.map(lambda _: micro_sharding, batch)
                )

                def pin(g_acc: Any) -> Any:
                    if accum_sh is None:
                        return g_acc
                    return jax.lax.with_sharding_constraint(g_acc, accum_sh)

                def body(carry, mb):
                    lsum_acc, tok_acc, g_acc, i = carry
                    r = jax.random.fold_in(rng, i) if rng is not None else None
                    lsum, tokens, grads = value_and_grad_sums(state.params, mb, r)
                    g_acc = pin(
                        jax.tree.map(
                            lambda a, g: a + g.astype(jnp.float32), g_acc, grads
                        )
                    )
                    return (lsum_acc + lsum, tok_acc + tokens, g_acc, i + 1), None

                zero_g = pin(
                    jax.tree.map(
                        lambda p: jnp.zeros(
                            ((workers,) + p.shape) if compress else p.shape,
                            jnp.float32,
                        ),
                        state.params,
                    )
                )
                (lsum, tokens, grads, _), _ = jax.lax.scan(
                    body,
                    (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), zero_g, 0),
                    micro,
                )
            else:
                lsum, tokens, grads = value_and_grad_sums(state.params, batch, rng)
            if compress:
                # the quantized cross-replica reduction, ONCE per optimizer
                # step (under accumulation the scan above summed fp32 TILED
                # partials — EF and the s8 wire apply at the step boundary);
                # stochastic rounding keys off the step RNG, folded with the
                # step counter so rng-less runs still draw fresh bits
                from distributed_llms_example_tpu.ops.quant_collectives import (
                    quantized_tree_reduce,
                )

                sr_base = rng if rng is not None else jax.random.PRNGKey(0x6e7)
                sr_key = jax.random.fold_in(
                    jax.random.fold_in(sr_base, 0x51ab), state.step
                )
                grads, new_ef = quantized_tree_reduce(
                    grads, state.ef, sr_key, mesh=mesh, param_specs=comp_specs,
                )
            else:
                new_ef = state.ef
            return optimizer_apply_block(
                state, tx, schedule, lsum, tokens, grads, health=health,
                fused=fused_plan, ef=new_ef,
            )

        return step_fn

    # shardings: state per rules; batch over (data, fsdp) with lengths over
    # sequence under context parallelism; rng replicated
    rules = rules or default_rules()
    bsh = batch_sharding(mesh, sequence_sharded=seq_sharded)
    repl = NamedSharding(mesh, P())

    metric_keys = ("loss", "learning_rate", "grad_norm", "target_tokens") + (
        HEALTH_METRIC_KEYS if health else ()
    )

    def jit_it(state_sh: Any, abstract_params: Any = None) -> Callable:
        from distributed_llms_example_tpu.train.optim import resolve_fused_plan

        metrics_sh = {k: repl for k in metric_keys}
        # the fp32 gradient accumulators mirror the param shardings leaf
        # for leaf — the weight-update-sharding contract the spec lint
        # checks and the compiled-carry test pins; the fused-plan
        # resolution (the --optim-impl dispatch) is the SHARED
        # train/optim.py resolver so the step and the budget probe can
        # never pick different impls
        comp_specs = None
        accum_pin_sh = None
        if grad_accum_steps > 1:
            accum_pin_sh = accumulator_shardings(state_sh.params)
        if compress:
            from distributed_llms_example_tpu.ops.quant_collectives import (
                error_feedback_shardings,
            )

            comp_specs = jax.tree.map(
                lambda sh: getattr(sh, "spec", None), state_sh.params
            )
            if grad_accum_steps > 1:
                # the scan carry holds TILED partial sums: worker dim over
                # the replica axes, inner dims still the param mirror
                accum_pin_sh = error_feedback_shardings(state_sh.params, mesh)
        step_fn = make_step_fn(
            accum_pin_sh,
            resolve_fused_plan(
                optim_spec, optim_impl, tx, state_sh, mesh,
                abstract_params=abstract_params,
                pipelined=hasattr(model, "num_microbatches"),
            ),
            comp_specs,
        )
        in_shardings = (state_sh, {"input_ids": bsh, "attention_mask": bsh, "labels": bsh})
        if with_dropout:
            jitted = jax.jit(
                step_fn,
                in_shardings=(*in_shardings, repl),
                out_shardings=(state_sh, metrics_sh),
                donate_argnums=(0,) if donate else (),
            )
        else:
            jitted = jax.jit(
                lambda s, b: step_fn(s, b, None),
                in_shardings=in_shardings,
                out_shardings=(state_sh, metrics_sh),
                donate_argnums=(0,) if donate else (),
            )

        # tracing must see the mesh so the models' activation constraints
        # (parallel/activation.py) bake into the compiled program
        def run(*args):
            with activation_mesh(mesh):
                return jitted(*args)

        run.jitted = jitted  # AOT access (bench.py cost analysis, memory audits)
        run.mesh = mesh
        return run

    def build(state: TrainState) -> tuple[Callable, Any]:
        sh = state_shardings(state, mesh, rules)
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state.params
        )
        return jit_it(sh, abstract), sh

    return build


def make_optimizer_probe(
    tx: optax.GradientTransformation,
    schedule: optax.Schedule,
    state_sh: Any,
    mesh: Mesh,
    *,
    optim_spec: Any = None,
    optim_impl: str | None = None,
    health: bool = False,
    abstract_params: Any = None,
) -> Callable[[TrainState], Any]:
    """A jitted stand-alone run of ``optimizer_apply_block`` for the
    budget layer's cadenced optimizer-apply timing (obs/budget.py
    ``probe_optimizer``): the SAME impl dispatch as the train step
    (``train.optim.resolve_fused_plan`` — one resolver, so the probe can
    never stamp a fused sample for a step that actually ran xla; pass
    ``abstract_params`` so an unparseable chain falls back with the same
    logged ``fused_optim_fallback`` instead of raising at the first
    cadence), fed a zeros gradient tree built in-program, with the
    outputs reduced to one replicated scalar so XLA must execute the
    full elementwise update (returning the new state would allocate a
    second full state per probe).  The output writes fuse into the
    reductions, so the sample reads as the apply's arithmetic + operand
    traffic — a slightly write-light but componentwise-faithful wall
    sample.  The caller times it at the LOG CADENCE only; nothing here
    runs on non-cadence steps."""
    from distributed_llms_example_tpu.train.optim import resolve_fused_plan

    plan = resolve_fused_plan(
        optim_spec, optim_impl, tx, state_sh, mesh,
        abstract_params=abstract_params,
    )

    def probe(state: TrainState):
        grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        )
        new_state, _metrics = optimizer_apply_block(
            state, tx, schedule, jnp.zeros((), jnp.float32),
            jnp.ones((), jnp.float32), grads, health=health, fused=plan,
            ef=state.ef,
        )
        total = jnp.zeros((), jnp.float32)
        # the EF tree only passes THROUGH the apply — folding its W x
        # params fp32 leaves into the reduction would bill the probe for
        # reads the real apply never does, inflating optimizer_apply_ms
        # on compressed runs
        for leaf in jax.tree.leaves(new_state.replace(ef=None)):
            total = total + jnp.sum(leaf).astype(jnp.float32)
        return total

    jitted = jax.jit(
        probe,
        in_shardings=(state_sh,),
        out_shardings=NamedSharding(mesh, P()),
    )

    def run(state: TrainState):
        with activation_mesh(mesh):
            return jitted(state)

    return run


def put_batch(batch: dict, mesh: Mesh, *, sequence_sharded: bool = False) -> dict:
    """Host-local numpy batch → global sharded arrays.

    Single-process: a plain device_put onto the (data, fsdp) sharding.
    Multi-host: ``make_array_from_process_local_data`` assembles the global
    array from each host's slice (the analog of DDP's per-rank loaders).
    ``sequence_sharded``: also split lengths over the ``sequence`` axis
    (train batches under context parallelism; generation keeps lengths
    whole because decode steps are length-1).
    """
    sh = batch_sharding(mesh, sequence_sharded=sequence_sharded)
    if jax.process_count() == 1:  # pod-agreed: process_count() is pod-uniform; single-host fast path
        return {k: jax.device_put(v, sh) for k, v in batch.items()}
    return {k: jax.make_array_from_process_local_data(sh, v) for k, v in batch.items()}
