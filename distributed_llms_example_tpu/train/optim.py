"""Optimizer and LR schedule factory.

Parity with the reference, but with the dead knobs made live:

- AdamW lr 5e-5 (train-accelerator.py:187) with the linear
  warmup-then-decay schedule of HF ``get_scheduler('linear')``
  (train-accelerator.py:200-205) — except ``--warmup-steps`` is actually
  honored (the reference hardcodes ``num_warmup_steps=1``,
  train-accelerator.py:204);
- the no-decay parameter split (train-accelerator.py:174-186) — except it
  actually decays the decay group (the reference sets both groups to
  weight_decay 0.0, making the split vestigial).  No-decay = every
  parameter of rank < 2: biases and norm scales;
- global-norm gradient clipping at 1.0, the HF Trainer default the
  torchrun variant inherits.
"""

from __future__ import annotations

from typing import Any

import jax
import optax


def linear_schedule_with_warmup(lr: float, warmup_steps: int, total_steps: int) -> optax.Schedule:
    warmup_steps = max(0, int(warmup_steps))
    decay_steps = max(1, int(total_steps) - warmup_steps)
    warm = optax.linear_schedule(0.0, lr, max(1, warmup_steps))
    decay = optax.linear_schedule(lr, 0.0, decay_steps)
    return optax.join_schedules([warm, decay], [warmup_steps])


def decay_mask(params: Any) -> Any:
    """True (decay) for matrices/embeddings, False for biases & norm scales.

    Checks the leaf *name* as well as rank: under pipeline parallelism the
    blocks are stacked with a leading layer dim, which makes norm scales
    (L, d) — rank alone would silently start decaying them."""
    def is_decay(path, p) -> bool:
        leaf = str(path[-1].key) if path and hasattr(path[-1], "key") else ""
        return p.ndim >= 2 and leaf not in ("scale", "bias")

    return jax.tree_util.tree_map_with_path(is_decay, params)


def multisteps_reference(
    tx: optax.GradientTransformation, accum_steps: int
) -> optax.GradientTransformation:
    """The ``optax.MultiSteps`` twin of the in-step scan accumulation —
    the cross-check oracle for tests (tests/test_train_step.py).

    ``use_grad_mean=False`` so MultiSteps accumulates the gradient SUM in
    the same order the scan does (zeros, then += microbatch grads one at
    a time) and applies the inner transformation exactly once on the
    k-th microbatch — the same single-apply contract as
    ``train/step.py optimizer_apply_block``.  Fed the identical
    normalized gradient stream, its inner apply is bit-equal to ours
    (same optax ``tx``, same inputs); fed raw per-microbatch gradients
    it converges to the same params up to fp32 summation-distribution
    error (the scan divides the sum once, MultiSteps sums pre-divided
    terms)."""
    return optax.MultiSteps(
        tx, every_k_schedule=int(accum_steps), use_grad_mean=False
    )


def make_optimizer(
    *,
    learning_rate: float = 5e-5,
    weight_decay: float = 0.01,
    warmup_steps: int = 500,
    total_steps: int = 10_000,
    max_grad_norm: float = 1.0,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    schedule = linear_schedule_with_warmup(learning_rate, warmup_steps, total_steps)
    tx = optax.chain(
        optax.clip_by_global_norm(max_grad_norm) if max_grad_norm > 0 else optax.identity(),
        optax.adamw(schedule, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay, mask=decay_mask),
    )
    return tx, schedule
