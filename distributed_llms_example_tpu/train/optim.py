"""Optimizer and LR schedule factory.

Parity with the reference, but with the dead knobs made live:

- AdamW lr 5e-5 (train-accelerator.py:187) with the linear
  warmup-then-decay schedule of HF ``get_scheduler('linear')``
  (train-accelerator.py:200-205) — except ``--warmup-steps`` is actually
  honored (the reference hardcodes ``num_warmup_steps=1``,
  train-accelerator.py:204);
- the no-decay parameter split (train-accelerator.py:174-186) — except it
  actually decays the decay group (the reference sets both groups to
  weight_decay 0.0, making the split vestigial).  No-decay = every
  parameter of rank < 2: biases and norm scales;
- global-norm gradient clipping at 1.0, the HF Trainer default the
  torchrun variant inherits.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax


def linear_schedule_with_warmup(lr: float, warmup_steps: int, total_steps: int) -> optax.Schedule:
    warmup_steps = max(0, int(warmup_steps))
    decay_steps = max(1, int(total_steps) - warmup_steps)
    warm = optax.linear_schedule(0.0, lr, max(1, warmup_steps))
    decay = optax.linear_schedule(lr, 0.0, decay_steps)
    return optax.join_schedules([warm, decay], [warmup_steps])


def decay_mask(params: Any) -> Any:
    """True (decay) for matrices/embeddings, False for biases & norm scales.

    Checks the leaf *name* as well as rank: under pipeline parallelism the
    blocks are stacked with a leading layer dim, which makes norm scales
    (L, d) — rank alone would silently start decaying them."""
    def is_decay(path, p) -> bool:
        leaf = str(path[-1].key) if path and hasattr(path[-1], "key") else ""
        return p.ndim >= 2 and leaf not in ("scale", "bias")

    return jax.tree_util.tree_map_with_path(is_decay, params)


def multisteps_reference(
    tx: optax.GradientTransformation, accum_steps: int
) -> optax.GradientTransformation:
    """The ``optax.MultiSteps`` twin of the in-step scan accumulation —
    the cross-check oracle for tests (tests/test_train_step.py).

    ``use_grad_mean=False`` so MultiSteps accumulates the gradient SUM in
    the same order the scan does (zeros, then += microbatch grads one at
    a time) and applies the inner transformation exactly once on the
    k-th microbatch — the same single-apply contract as
    ``train/step.py optimizer_apply_block``.  Fed the identical
    normalized gradient stream, its inner apply is bit-equal to ours
    (same optax ``tx``, same inputs); fed raw per-microbatch gradients
    it converges to the same params up to fp32 summation-distribution
    error (the scan divides the sum once, MultiSteps sums pre-divided
    terms)."""
    return optax.MultiSteps(
        tx, every_k_schedule=int(accum_steps), use_grad_mean=False
    )


@dataclasses.dataclass(frozen=True)
class OptimizerSpec:
    """The clip+AdamW hyperparameters as DATA — ``make_optimizer`` turns
    them into the opaque optax chain (the ``xla`` impl), and the fused
    Pallas apply (``ops/fused_optim.py``, ``--optim-impl fused``) reads
    them directly: an opaque ``GradientTransformation`` cannot be fused,
    so the spec is the one description both impls derive from (pinned
    against each other: identical op sequence, equal up to XLA float
    contraction)."""

    learning_rate: float = 5e-5
    weight_decay: float = 0.01
    warmup_steps: int = 500
    total_steps: int = 10_000
    max_grad_norm: float = 1.0
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8


def make_optimizer(
    *,
    learning_rate: float = 5e-5,
    weight_decay: float = 0.01,
    warmup_steps: int = 500,
    total_steps: int = 10_000,
    max_grad_norm: float = 1.0,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    tx, schedule, _ = make_optimizer_bundle(
        learning_rate=learning_rate, weight_decay=weight_decay,
        warmup_steps=warmup_steps, total_steps=total_steps,
        max_grad_norm=max_grad_norm, b1=b1, b2=b2, eps=eps,
    )
    return tx, schedule


def make_optimizer_bundle(
    **kw: Any,
) -> tuple[optax.GradientTransformation, optax.Schedule, OptimizerSpec]:
    """(tx, schedule, spec): the optax chain plus the :class:`OptimizerSpec`
    it was built from — callers that want the fused apply
    (``make_train_step(..., optim_spec=spec)``) use this form so the two
    impls cannot be built from diverging hyperparameters."""
    spec = OptimizerSpec(**kw)
    schedule = linear_schedule_with_warmup(
        spec.learning_rate, spec.warmup_steps, spec.total_steps
    )
    tx = optax.chain(
        optax.clip_by_global_norm(spec.max_grad_norm)
        if spec.max_grad_norm > 0
        else optax.identity(),
        optax.adamw(
            schedule, b1=spec.b1, b2=spec.b2, eps=spec.eps,
            weight_decay=spec.weight_decay, mask=decay_mask,
        ),
    )
    return tx, schedule, spec


def optimizer_update(
    tx: optax.GradientTransformation, grads: Any, opt_state: Any, params: Any
) -> tuple[Any, Any, Any]:
    """THE ``xla``-impl apply: ``tx.update`` + ``optax.apply_updates`` —
    the one home of the raw optax apply (scripts/repo_lint.py rule 8
    forbids it elsewhere in models/ and train/, so no call site can
    bypass the ``--optim-impl`` dispatch in ``optimizer_apply_block``).
    Returns (new_params, new_opt_state, updates)."""
    updates, new_opt = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), new_opt, updates


# ---------------------------------------------------------------------------
# The fused (--optim-impl fused) apply: same optax state pytree, same math,
# one Pallas pass per leaf-shard (ops/fused_optim.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FusedOptimPlan:
    """Everything ``optimizer_apply_block`` needs to run the fused apply:
    the hyperparameter spec, the mesh (per-shard ``shard_map`` dispatch),
    and the params' resolved PartitionSpecs (mu/nu/grad-accumulators all
    mirror them — the PR 5 layout contract the spec lint checks)."""

    spec: OptimizerSpec
    mesh: Any = None
    param_specs: Any = None


def _safe_int32_increment(count: jnp.ndarray) -> jnp.ndarray:
    # optax.numerics.safe_int32_increment, replicated so the fused count
    # bits match the chain's
    max_int32 = jnp.iinfo(jnp.int32).max
    one = jnp.array(1, dtype=jnp.int32)
    return jnp.where(count < max_int32, count + one, max_int32)


def parse_adamw_state(opt_state: Any) -> tuple[Any, list[Any]]:
    """Locate the single ``ScaleByAdamState`` (count/mu/nu) and every
    ``ScaleByScheduleState`` inside the optax chain state, WITHOUT
    assuming the exact chain nesting.  Raises ValueError when the
    structure is not a recognizable single-AdamW chain — callers fall
    back to the xla impl then."""
    adams: list[Any] = []
    scheds: list[Any] = []

    def walk(node: Any) -> None:
        if isinstance(node, optax.ScaleByAdamState):
            adams.append(node)
            return
        if isinstance(node, optax.ScaleByScheduleState):
            scheds.append(node)
            return
        if isinstance(node, tuple):  # chain tuples AND NamedTuple states
            for child in node:
                walk(child)

    walk(opt_state)
    if len(adams) != 1:
        raise ValueError(
            f"fused optimizer apply needs exactly one ScaleByAdamState in "
            f"the chain state, found {len(adams)} — is this the "
            "make_optimizer chain?"
        )
    return adams[0], scheds


def rebuild_adamw_state(opt_state: Any, new_adam: Any) -> Any:
    """The SAME optax pytree with the adam state replaced and every
    schedule count incremented — checkpoints written by the fused impl
    restore under xla (and vice versa) because the layout never forks."""

    def walk(node: Any) -> Any:
        if isinstance(node, optax.ScaleByAdamState):
            return new_adam
        if isinstance(node, optax.ScaleByScheduleState):
            return optax.ScaleByScheduleState(
                count=_safe_int32_increment(node.count)
            )
        if isinstance(node, tuple):
            rebuilt = [walk(child) for child in node]
            if hasattr(node, "_replace") and hasattr(node, "_fields"):
                return type(node)(*rebuilt)
            return tuple(rebuilt)
        return node

    return walk(opt_state)


def validate_fused_chain(
    tx: optax.GradientTransformation, abstract_params: Any
) -> str | None:
    """Build-time check that the chain state is fused-parseable (shape
    only — ``eval_shape`` of ``tx.init``).  Returns None when OK, else
    the reason string (the caller logs it and stays on xla)."""
    try:
        parse_adamw_state(jax.eval_shape(tx.init, abstract_params))
        return None
    except Exception as e:  # noqa: BLE001 — any parse failure means "not ours"
        return str(e)[:300]


def fused_optimizer_apply(
    plan: FusedOptimPlan,
    schedule: optax.Schedule,
    params: Any,
    opt_state: Any,
    grads: Any,
) -> tuple[Any, Any, jnp.ndarray, Any]:
    """The fused clip+AdamW step on a whole tree: parse the optax state,
    compute the step scalars with the chain's own expressions (global
    grad-norm = the two-stage per-shard-sumsq + psum reduction GSPMD
    inserts; clip trigger; bias corrections; -lr), run the per-leaf
    Pallas apply in place, and rebuild the identical state pytree.

    ``grads`` is the token-normalized fp32 tree (the
    ``optimizer_apply_block`` contract).  Returns
    (new_params, new_opt_state, grad_norm, stats_tree) where
    ``stats_tree`` carries each leaf's (param_sumsq, update_sumsq,
    nonfinite) partial sums from the kernel pass — the ``--health``
    numerics source, no extra reduction pass."""
    from distributed_llms_example_tpu.ops.fused_optim import (
        SCALARS,
        _S_BC1,
        _S_BC2,
        _S_GNORM,
        _S_NEG_LR,
        _S_TRIGGER,
        adamw_tree_apply,
    )

    spec = plan.spec
    adam, scheds = parse_adamw_state(opt_state)
    # stage 1+2 of the global-norm reduction: optax.global_norm's exact
    # expression — per-leaf sum of squares, summed across leaves; on a
    # sharded tree the partitioner computes per-shard partials and psums
    gnorm = optax.global_norm(grads)
    count_inc = _safe_int32_increment(adam.count)
    bc1 = (1 - spec.b1**count_inc).astype(jnp.float32)
    bc2 = (1 - spec.b2**count_inc).astype(jnp.float32)
    sched_count = scheds[0].count if scheds else adam.count
    # optax scale_by_learning_rate: step_size = -1 * schedule(count)
    neg_lr = jnp.asarray(-1 * schedule(sched_count), jnp.float32)
    trigger = (
        (gnorm < spec.max_grad_norm).astype(jnp.float32)
        if spec.max_grad_norm > 0
        else jnp.ones((), jnp.float32)
    )
    scal = jnp.zeros((SCALARS,), jnp.float32)
    scal = scal.at[_S_GNORM].set(gnorm)
    scal = scal.at[_S_TRIGGER].set(trigger)
    scal = scal.at[_S_BC1].set(bc1)
    scal = scal.at[_S_BC2].set(bc2)
    scal = scal.at[_S_NEG_LR].set(neg_lr)
    new_params, new_mu, new_nu, stats = adamw_tree_apply(
        params, adam.mu, adam.nu, grads, scal,
        b1=spec.b1, b2=spec.b2, eps=spec.eps,
        max_norm=spec.max_grad_norm, weight_decay=spec.weight_decay,
        decay_tree=decay_mask(params),
        mesh=plan.mesh, param_specs=plan.param_specs,
    )
    new_adam = optax.ScaleByAdamState(count=count_inc, mu=new_mu, nu=new_nu)
    return new_params, rebuild_adamw_state(opt_state, new_adam), gnorm, stats


def resolve_fused_plan(
    optim_spec: "OptimizerSpec | None",
    optim_impl: str | None,
    tx: optax.GradientTransformation,
    state_sh: Any,
    mesh: Any,
    *,
    abstract_params: Any = None,
    pipelined: bool = False,
) -> "FusedOptimPlan | None":
    """THE ``--optim-impl`` dispatch, shared by ``make_train_step`` and
    ``make_optimizer_probe`` so the step and the budget probe can never
    resolve to different impls: a FusedOptimPlan when a spec was
    supplied, the (process-default-resolved) impl is ``fused``, and the
    caller is not pipelined (stage>1 adapters stay on xla); None
    otherwise — including when the chain fails validation (logged
    ``fused_optim_fallback``)."""
    if optim_spec is None or pipelined:
        return None
    from distributed_llms_example_tpu.ops.fused_optim import resolve_impl

    if resolve_impl(optim_impl) != "fused":
        return None
    return build_fused_plan(
        optim_spec, tx, state_sh, mesh, abstract_params=abstract_params
    )


def build_fused_plan(
    optim_spec: OptimizerSpec,
    tx: optax.GradientTransformation,
    state_sh: Any,
    mesh: Any,
    *,
    abstract_params: Any = None,
) -> FusedOptimPlan | None:
    """Resolve the fused-apply plan at step-build time, or None (with a
    logged reason) when the chain state is not fused-parseable — the
    step then stays on the xla impl instead of failing at trace time."""
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    reason = (
        validate_fused_chain(tx, abstract_params)
        if abstract_params is not None
        else None
    )
    if reason is not None:
        log_json({
            "event": "fused_optim_fallback",
            "reason": reason,
        })
        return None
    param_specs = None
    if state_sh is not None:
        param_specs = jax.tree.map(
            lambda s: getattr(s, "spec", None), state_sh.params
        )
    return FusedOptimPlan(spec=optim_spec, mesh=mesh, param_specs=param_specs)
