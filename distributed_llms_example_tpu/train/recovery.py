"""In-run rewind-and-retry recovery — the other half of the watchdog.

PRs 2–3 built fault *detection*: in-graph numerics, a pod-agreed anomaly
watchdog, a flight recorder.  But every policy ended the run —
``--on-anomaly checkpoint`` saves and stops, and resuming costs a full
process restart (scheduler round-trip, weight reload, recompile).  At
pod scale most anomalies are cheaper than that: a poison batch or a
transient numeric fault costs at most ``save_every_steps`` optimizer
steps IF the run can rewind in-process.  ``--on-anomaly rewind`` does
exactly that:

1. **Rewind**: restore the newest VERIFIED checkpoint strictly older
   than the anomaly step (``io/checkpoint.py`` ``restore_before`` — a
   checkpoint saved at/after the anomaly may already hold poisoned
   state), reset the data cursor via the O(1) index-level epoch
   fast-forward, and restore the dropout RNG snapshot taken at save
   time, so the replay is bit-identical to the original steps.
2. **Quarantine**: the anomaly is attributed to an exact step by the
   watchdog, and the flight recorder holds that step's batch
   fingerprint (shapes + crc32s + the deterministic (epoch, epoch_step)
   plan position).  The batch is quarantined by plan position — a
   pod-consistent key, since every host computes the same batch plan —
   and the replay SKIPS it (crc-checked on the way past), so a poison
   batch cannot re-trip the same anomaly.
3. **Escalation**: rewind → skip-batch → halt.  Rewinds are bounded by
   ``--max-rewinds``.  When the budget is exhausted and the state is
   still finite (a loss spike / grad explosion, not NaN), one degraded
   ``skip_batch`` attempt quarantines the batch and continues WITHOUT
   restoring; anything beyond that — or an anomaly recurring on a batch
   already quarantined (the data hypothesis is refuted) — halts.

Pod consistency: every decision here derives only from pod-agreed
inputs (the agreed anomaly record, the shared checkpoint directory, the
deterministic batch plan, counters that advance identically on every
rank), so all ranks rewind to the same step without any extra
collective; the restore itself is orbax's usual collaborative restore.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

from distributed_llms_example_tpu.obs import sink as sink_mod

# escalation actions, in order
ACTIONS = ("rewind", "skip_batch", "halt")


@dataclasses.dataclass(frozen=True)
class Decision:
    action: str  # one of ACTIONS
    reason: str


class RecoveryController:
    """The rewind state machine: budget, quarantine set, save snapshots.

    One instance per Trainer; its counters and quarantine keys advance
    identically on every process (all inputs are pod-agreed), which is
    what makes the escalation itself agreement-free.
    """

    def __init__(self, *, max_rewinds: int = 2):
        self.max_rewinds = int(max_rewinds)
        self.rewinds_done = 0
        self.skips_done = 0
        # (epoch, epoch_step) → quarantine record (crc32s for verification)
        self.quarantined: dict[tuple[int, int], dict[str, Any]] = {}
        # checkpoint step → host-side extras orbax does not hold: the
        # dropout RNG key and the (epoch, pos) data cursor at save time
        self._snapshots: dict[int, dict[str, Any]] = {}

    # -- save-time bookkeeping ------------------------------------------

    def note_save(self, step: int, *, rng: Any, epoch: int, pos: int) -> None:
        """Record the host-side state a bit-exact in-process rewind needs
        alongside the checkpoint at ``step``: the dropout key (restored
        so replayed steps split the identical stream) and the data
        cursor (epoch, iterator items consumed — NOT the global step:
        quarantine skips make the two diverge)."""
        self._snapshots[int(step)] = {"rng": rng, "epoch": int(epoch), "pos": int(pos)}

    def snapshot_for(self, step: int) -> dict[str, Any] | None:
        return self._snapshots.get(int(step))

    # -- quarantine ------------------------------------------------------

    def quarantine(
        self, epoch: int, epoch_step: int, fingerprint: Mapping[str, Any], *, reason: str
    ) -> None:
        """Quarantine one batch-plan position; emits the ``quarantine``
        event (once — replay skips are silent ``quarantine_skip``s)."""
        key = (int(epoch), int(epoch_step))
        record = {
            "input_ids_crc32": fingerprint.get("input_ids_crc32"),
            "labels_crc32": fingerprint.get("labels_crc32"),
            "reason": reason,
        }
        self.quarantined[key] = record
        sink_mod.emit(
            {
                "event": "quarantine",
                "epoch": key[0],
                "epoch_step": key[1],
                **{k: v for k, v in record.items() if v is not None},
            },
            local=True,
        )

    def should_skip(self, epoch: int, epoch_step: int, batch: Mapping[str, Any]) -> bool:
        """Replay-time check: is this batch-plan position quarantined?
        The local crc is re-checked against the quarantine record — a
        mismatch means the deterministic plan did NOT reproduce the
        poisoned batch (seed/data drift), which is worth a loud event,
        but the position is skipped either way (the pod-consistent key
        is the position, not the per-host bytes)."""
        record = self.quarantined.get((int(epoch), int(epoch_step)))
        if record is None:
            return False
        expected = record.get("input_ids_crc32")
        if expected is not None:
            import zlib

            import numpy as np

            v = batch.get("input_ids")
            got = (
                zlib.crc32(np.ascontiguousarray(v).tobytes()) & 0xFFFFFFFF
                if v is not None
                else None
            )
            if got != expected:
                sink_mod.emit(
                    {
                        "event": "quarantine_crc_mismatch",
                        "epoch": int(epoch),
                        "epoch_step": int(epoch_step),
                        "expected_crc32": expected,
                        "got_crc32": got,
                    },
                    local=True,
                )
        sink_mod.emit(
            {
                "event": "quarantine_skip",
                "epoch": int(epoch),
                "epoch_step": int(epoch_step),
            },
            local=True,
        )
        return True

    # -- escalation ------------------------------------------------------

    def decide(
        self,
        anomaly: Mapping[str, Any],
        *,
        fingerprint: Mapping[str, Any] | None,
    ) -> Decision:
        """Pick the escalation stage for one agreed anomaly.  Inputs are
        pod-agreed (anomaly code/step; the fingerprint's plan position is
        deterministic), so every rank returns the same Decision."""
        key = None
        if fingerprint is not None:
            key = (int(fingerprint["epoch"]), int(fingerprint["epoch_step"]))
        if key is not None and key in self.quarantined:
            return Decision(
                "halt",
                f"anomaly recurred at already-quarantined batch {key} — "
                "not the data; rewinding again cannot help",
            )
        if self.rewinds_done < self.max_rewinds:
            self.rewinds_done += 1
            return Decision(
                "rewind",
                f"rewind {self.rewinds_done}/{self.max_rewinds}",
            )
        if (
            anomaly.get("code") != "nonfinite"
            and key is not None
            and self.skips_done == 0
        ):
            # degraded mode: the state is still finite, so dropping the
            # suspect batch and continuing loses nothing more — one try
            self.skips_done += 1
            return Decision(
                "skip_batch",
                "rewind budget exhausted; state finite — quarantining the "
                "batch and continuing without restore",
            )
        return Decision(
            "halt",
            f"rewind budget exhausted ({self.rewinds_done}/{self.max_rewinds})",
        )
